"""Sharded train-state checkpointing with exact resume.

Reference: ``veomni/checkpoint/dcp_checkpointer.py`` (torch DCP + async save
on a side gloo group, EP-placement normalization, extra_state pickles).
TPU translation: **Orbax** async checkpointing of the sharded TrainState —
every process writes its own shards (OCDBT/TensorStore), restore re-shards to
the current topology automatically, so the reference's EP save/restore
placement dance (``_apply_extra_parallel_dim``) is unnecessary: Orbax
restores to whatever NamedSharding the new run requests.

extra_state (dataloader cursor, meter, python RNG, global step) is a JSON
blob saved alongside, mirroring ``_save_extra_state``.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

import jax
import orbax.checkpoint as ocp

from veomni_tpu.observability.flight_recorder import record as flight_record
from veomni_tpu.observability.metrics import get_registry
from veomni_tpu.observability.spans import span
from veomni_tpu.resilience.elastic import (
    ElasticRestoreError,
    capture_topology,
    classify_restore,
    merge_rank_states,
    mesh_incompat_reason,
    split_rank_state,
)
from veomni_tpu.resilience.faults import fault_point
from veomni_tpu.resilience.integrity import (
    QUARANTINE_DIR_RE,
    STEP_DIR_RE,
    VERIFY_MODES,
    CheckpointCorruptError,
    is_committed_dir,
    list_rank_sidecars,
    read_topology,
    verify_manifest,
    write_manifest,
)
from veomni_tpu.resilience.retry import RetryPolicy, retry_call
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# naming scheme lives in integrity.py (shared with scripts/verify_ckpt.py);
# quarantined generations: global_step_N.corrupt (rename collisions get a
# numeric suffix so a twice-quarantined step never blocks the rename)
_STEP_RE = STEP_DIR_RE
_CORRUPT_RE = QUARANTINE_DIR_RE


def _tree_bytes(tree: Any) -> int:
    """Payload size from array metadata (no device sync: nbytes is shape
    math, not a fetch)."""
    return sum(
        int(getattr(leaf, "nbytes", 0)) for leaf in jax.tree.leaves(tree)
    )


class Checkpointer:
    """save/load of {train_state, extra_state} under ckpt_dir/global_step_N.

    I/O resilience: every save/restore dispatch runs under a bounded
    deterministic-backoff retry (``io_retries``/``retry_base_s``), with
    ``ckpt.save``/``ckpt.restore`` fault points inside each attempt so the
    whole path is exercisable from a ``VEOMNI_FAULT_PLAN``. Async-save
    commit errors are probed at the next step boundary (``save()``/``wait()``)
    and the failed step is EVICTED from the dedupe set, so a later save of
    that step re-dispatches instead of being silently lost.

    Integrity (``resilience/integrity.py``): once a generation's commit is
    observed, rank 0 digests it into ``manifest.json``; ``load()`` verifies
    the manifest per ``verify_mode`` (``off|size|full``) BEFORE dispatching
    the Orbax restore, quarantines failing generations to
    ``global_step_N.corrupt``, and falls back to the next-newest
    committed-and-verified one.
    """

    def __init__(self, ckpt_dir: str, *, async_save: bool = True, max_to_keep: int = 0,
                 io_retries: int = 3, retry_base_s: float = 0.05,
                 verify_mode: str = "size", elastic: bool = False):
        if verify_mode not in VERIFY_MODES:
            raise ValueError(
                f"unknown ckpt verify mode {verify_mode!r}; choose from {VERIFY_MODES}"
            )
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self.async_save = async_save
        self.max_to_keep = max_to_keep
        self.verify_mode = verify_mode
        # elastic restore (train.ckpt_elastic / resilience/elastic.py):
        # allow restoring a checkpoint saved on a different data-parallel
        # topology — arrays reshard via the target NamedShardings, per-rank
        # cursor sidecars merge/split. Off (default): a topology mismatch is
        # an actionable error, never a silent partial cursor restore.
        self.elastic = elastic
        # source-topology docs for the manifest, captured from the state
        # tree at each save dispatch. Keyed BY STEP: the previous async
        # step's manifest is written from inside the NEXT save(), which has
        # already captured its own doc — and rank_state_files can differ
        # between saves, so "latest" would stamp the wrong census onto the
        # prior generation
        self._topology: Optional[Dict[str, Any]] = None
        self._step_topology: Dict[int, Dict[str, Any]] = {}
        self._retry_policy = RetryPolicy(retries=io_retries, base_delay_s=retry_base_s)
        self._saved_steps: set = set()
        self._inflight_step: Optional[int] = None
        # steps condemned by a failed verify THIS process: the dir rename is
        # rank-0's job, but every rank must stop offering the step locally
        # (a lagging shared fs may still show the old name for a beat)
        self._quarantined: set = set()
        # in-flight async manifest digest (rank 0 only): the full-tree CRC
        # re-reads every committed byte, so it runs off the hot save path
        self._manifest_thread: Optional[threading.Thread] = None
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        # startup is the only moment no save can be in flight anywhere, so
        # clear crashed-save debris here (never during save(): a lagging host
        # could rmtree a faster host's live tmp dir)
        self._clean_debris()

    def _clean_debris(self):
        import shutil

        if jax.process_index() != 0:  # same shared-fs race as _prune
            return
        for d in os.listdir(self.ckpt_dir):
            if not _STEP_RE.match(d):
                continue
            step_dir = os.path.join(self.ckpt_dir, d)
            if not os.path.isdir(os.path.join(step_dir, "train_state")):
                # crash before commit: only tmp payload/extra_state remain
                logger.warning_rank0("removing uncommitted checkpoint debris %s", d)
                shutil.rmtree(step_dir, ignore_errors=True)
            else:
                for sub in os.listdir(step_dir):
                    if ".orbax-checkpoint-tmp" in sub:
                        shutil.rmtree(os.path.join(step_dir, sub), ignore_errors=True)
        self._reap_quarantined()

    def _reap_quarantined(self):
        """Age out ``.corrupt`` quarantined generations beyond ``max_to_keep``
        (rank-0-gated like ``_prune``). Quarantine keeps the bytes around for
        post-mortem, but a flaky filesystem would otherwise leak disk forever;
        the newest ``max_to_keep`` corpses stay, older ones are reaped.
        ``max_to_keep == 0`` (keep-everything semantics, same as _prune)
        never reaps."""
        if not self.max_to_keep or jax.process_index() != 0:
            return
        import shutil

        corpses = []
        for d in os.listdir(self.ckpt_dir):
            m = _CORRUPT_RE.match(d)
            if m:
                corpses.append((int(m.group(1)), d))
        for _step, d in sorted(corpses)[: -self.max_to_keep]:
            shutil.rmtree(os.path.join(self.ckpt_dir, d), ignore_errors=True)
            logger.warning_rank0("reaped quarantined checkpoint %s", d)

    # ------------------------------------------------------------------ save
    def check_for_errors(self) -> Optional[BaseException]:
        """Step-boundary probe of the async commit thread. On failure the
        in-flight step is evicted from the dedupe set (so a later ``save()``
        of that step re-dispatches instead of silently skipping) and the
        error is returned for the caller to surface or absorb."""
        probe = getattr(self._ckptr, "check_for_errors", None)
        if probe is None:
            return None
        try:
            probe()
        except Exception as e:
            self._evict_inflight(e)
            return e
        return None

    def _evict_inflight(self, err: BaseException) -> None:
        if self._inflight_step is not None:
            self._saved_steps.discard(self._inflight_step)
            logger.error(
                "async checkpoint save of step %d FAILED: %s; step evicted — "
                "the next save() of it will retry", self._inflight_step, err,
            )
            self._inflight_step = None

    def _dispatch_save(self, path: str, train_state, step_dir: str,
                       extra_state, rank_state) -> None:
        """One save attempt (the retried unit): sidecar JSONs, then the
        payload dispatch. The JSON writes sit INSIDE the unit so a transient
        shared-fs error there is retried like any other I/O (re-writing them
        is idempotent), and BEFORE the payload so the atomic ``train_state``
        rename can never commit a checkpoint missing its cursor metadata.
        The serialization wait on the PREVIOUS async save lives in save(),
        outside this unit: a previous commit's failure must evict ITS step,
        not be retried away as a transient fault of this one. The sync-mode
        wait stays inside — that failure IS this step's, and re-dispatching
        is the right retry."""
        os.makedirs(step_dir, exist_ok=True)
        if extra_state is not None and jax.process_index() == 0:
            with open(os.path.join(step_dir, "extra_state.json"), "w") as f:
                json.dump(extra_state, f)
        if rank_state is not None:
            # per-process state (dataloader cursor + packing carry-over is
            # rank-local data!): every process writes its own file — restoring
            # rank 0's buffer everywhere would feed all ranks rank-0's samples
            fname = f"extra_state_rank{jax.process_index()}.json"
            with open(os.path.join(step_dir, fname), "w") as f:
                json.dump(rank_state, f)
        fault_point("ckpt.save")
        self._ckptr.save(path, args=ocp.args.StandardSave(train_state))
        if not self.async_save:
            self._ckptr.wait_until_finished()

    def save(self, step: int, train_state, extra_state: Optional[Dict[str, Any]] = None,
             rank_state: Optional[Dict[str, Any]] = None):
        # surface a failed PREVIOUS async save now (and evict its step) —
        # never inside the jitted loop, only at this step boundary
        self.check_for_errors()
        path = os.path.join(self.ckpt_dir, f"global_step_{step}", "train_state")
        # in-memory dedupe: async saves only materialize the dir at commit, so
        # isdir alone would race an in-flight save of the same step
        if step in self._saved_steps:
            logger.info_rank0("checkpoint for step %d already dispatched; skipping", step)
            return
        # a quarantined step is being SUPERSEDED by this save: the condemned
        # dir was renamed away by rank 0 — but if that rename itself failed
        # (flaky shared fs), the corpse still occupies the path and Orbax
        # would refuse the dispatch with an unretried "destination exists"
        if step in self._quarantined:
            self._clear_corpse(step)
            # every rank reaches this branch (_quarantined mutates in
            # lockstep), but the clear is rank 0's job — without a barrier
            # another rank's _dispatch_save could write its fresh rank-local
            # sidecar INTO the corpse dir while rank 0 is still renaming or
            # deleting it, losing that rank's cursor from the superseding
            # generation
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(
                    f"ckpt_clear_corpse_{step}"
                )
        elif os.path.isdir(path):
            logger.info_rank0("checkpoint for step %d already exists; skipping", step)
            return
        # serialize with any in-flight save BEFORE the retried dispatch: if
        # the previous async commit failed, the error raises here, belongs to
        # the previous step, and must evict that step — not be swallowed by
        # this step's retry loop
        # source topology for the manifest (mesh axis sizes, world size —
        # resilience/elastic.py): captured from the state tree's shardings
        # here, at dispatch, so the commit-time manifest writer (possibly a
        # daemon thread) never touches jax device state itself.
        # rank_state_files records how many cursor sidecars this save
        # writes: the restore gate checks the on-disk set against it, so
        # losing ALL sidecars to rot is as detectable as losing one (the
        # directory listing alone cannot tell "all lost" from "none saved")
        self._topology = dict(
            capture_topology(train_state),
            rank_state_files=(
                jax.process_count() if rank_state is not None else 0
            ),
        )
        self._step_topology[step] = self._topology
        # the span is the single timing source (histogram ``span.ckpt.save``
        # + goodput checkpoint attribution + chrome trace): async saves
        # measure the host-blocking dispatch (serialize-with-previous +
        # device->host copy), sync saves the full commit — either way, the
        # wall time the step loop lost
        with span("ckpt.save"):
            try:
                self._ckptr.wait_until_finished()
            except Exception as e:
                self._evict_inflight(e)
            else:
                # the PREVIOUS async save just committed: its bytes are now
                # final, so this is the earliest safe moment to digest them —
                # in the background, so the full-tree CRC read doesn't stall
                # this save boundary (joined at the next wait()/load())
                if self._inflight_step is not None:
                    flight_record("ckpt.commit", cid=str(self._inflight_step))
                    self._start_manifest(self._inflight_step)
                    self._inflight_step = None
            step_dir = os.path.join(self.ckpt_dir, f"global_step_{step}")
            retry_call(
                self._dispatch_save, path, train_state, step_dir,
                extra_state, rank_state,
                policy=self._retry_policy,
                description=f"checkpoint save (step {step})",
            )
        reg = get_registry()
        reg.counter("ckpt.saves").inc()
        reg.counter("ckpt.saved_bytes").inc(_tree_bytes(train_state))
        flight_record("ckpt.save", cid=str(step), async_save=self.async_save)
        # dedupe only records a SUCCESSFUL dispatch (on failure the raise
        # above leaves the set untouched, so a later attempt of this step —
        # e.g. the train-end final save — isn't silently skipped)
        self._saved_steps.add(step)
        # the fresh generation replaces any condemned one at this step:
        # list_steps/latest_step must offer it again once committed
        self._quarantined.discard(step)
        self._inflight_step = step if self.async_save else None
        if not self.async_save:  # sync: committed right here
            flight_record("ckpt.commit", cid=str(step))
            self._write_manifest(step)
        logger.info_rank0("checkpoint save dispatched: step %d -> %s", step, path)
        self._prune()

    def wait(self):
        with span("ckpt.wait"):
            try:
                self._ckptr.wait_until_finished()
            except Exception as e:
                self._evict_inflight(e)
                raise
            err = self.check_for_errors()
            if err is not None:
                raise err
            # wait() is the explicit durability barrier: the manifest must be
            # on disk when it returns, so the inflight digest runs inline
            self._join_manifest()
            if self._inflight_step is not None:
                flight_record("ckpt.commit", cid=str(self._inflight_step))
                self._write_manifest(self._inflight_step)
            self._inflight_step = None

    # ------------------------------------------------------------- integrity
    def _start_manifest(self, step: int) -> None:
        """Digest a just-committed async generation off the hot save path —
        a synchronous full-tree CRC would stall rank 0 at every save boundary
        and make it a straggler at the next collective, exactly the
        host-blocking async save exists to avoid. Serialized: any previous
        digest is joined first, so manifest fault hits stay deterministic."""
        self._join_manifest()
        if jax.process_index() != 0:
            return
        if self.verify_mode == "off":
            # no digests to compute — the topology-only manifest is an O(1)
            # write, so it runs inline instead of on a thread
            self._write_manifest(step)
            return
        t = threading.Thread(
            target=self._write_manifest, args=(step,),
            name=f"ckpt-manifest-{step}", daemon=True,
        )
        t.start()
        self._manifest_thread = t

    def _join_manifest(self) -> None:
        t = self._manifest_thread
        if t is not None:
            t.join()
            self._manifest_thread = None
    def _write_manifest(self, step: int) -> None:
        """Rank 0 digests the committed generation into ``manifest.json``
        (the verify gate's ground truth, written NEXT to the extra-state
        sidecars). Never fatal: a failed manifest write leaves an
        unverifiable-but-healthy checkpoint, which ``load()`` accepts with a
        warning — refusing it would turn the safety net into a data killer.

        ``verify_mode == 'off'`` skips the digest entirely: "trust the
        bytes" must not cost a full-tree read of every committed byte per
        save (inline for sync saves!) to record CRCs nothing will consume —
        but the SOURCE TOPOLOGY (mesh axis sizes, world size, jax versions;
        ``resilience/elastic.py``) is still recorded, an O(1) write, so
        every generation stays diagnosable and elastically restorable.
        ``size`` mode still records digests — its manifests feed the
        operator CLI's out-of-band ``--mode full`` sweep, not just its own
        gate."""
        if jax.process_index() != 0:
            return
        step_dir = os.path.join(self.ckpt_dir, f"global_step_{step}")
        if not self._is_committed(step):
            return
        try:
            with span("ckpt.manifest"):
                write_manifest(
                    step_dir,
                    topology=self._step_topology.pop(step, self._topology),
                    digests=self.verify_mode != "off",
                )
            # drill point: a corrupt-mode fault spec here damages the
            # just-committed generation AFTER its digests were recorded —
            # exactly the storage-rot timeline the verify gate exists for.
            # Inside the try: an exception-mode spec must stay never-fatal
            # like any manifest failure (sync saves call this inline, async
            # ones from a daemon thread where a raise would vanish)
            fault_point("ckpt.manifest", context={"dir": step_dir})
        except Exception as e:
            logger.warning_rank0(
                "manifest write for step %d failed: %s (generation stays "
                "restorable, just unverifiable)", step, e,
            )
            return

    def verify_step(self, step: int):
        """Manifest verification per ``self.verify_mode``. Returns the
        :class:`VerifyReport`, or None when verification is off or the
        generation has no readable manifest (unverifiable ≠ corrupt: a crash
        can land between payload commit and manifest write, and pre-integrity
        checkpoints have no manifest at all)."""
        if self.verify_mode == "off":
            return None
        step_dir = os.path.join(self.ckpt_dir, f"global_step_{step}")
        report = verify_manifest(step_dir, mode=self.verify_mode)
        if report is None:
            logger.warning_rank0(
                "checkpoint step %d has no readable manifest; restoring "
                "UNVERIFIED", step,
            )
            return None
        reg = get_registry()
        reg.histogram("integrity.verify_s").observe(report.elapsed_s)
        if report.passed:
            reg.counter("integrity.ckpt_verified").inc()
        return report

    def _verify_gate(self, step: int) -> None:
        """Restore gate: verify on rank 0 and share ONE verdict with every
        process, so the multi-process Orbax restore collective can never
        split across generations — rot landing between two ranks'
        independent verifies would let rank A pass step N while rank B
        quarantines it and walks back, wedging the collective instead of
        falling back cleanly. A single verify also keeps ``full`` mode from
        multiplying restore-time I/O by the process count (every rank would
        re-digest the same shared files). On a condemned generation EVERY
        rank quarantines locally and raises, so the fallback walk stays in
        lockstep."""
        if self.verify_mode == "off":
            return
        multi = jax.process_count() > 1
        report = None
        if not multi or jax.process_index() == 0:
            try:
                report = self.verify_step(step)
            except Exception as e:
                # verification must ALWAYS reach the broadcast below — an
                # exception escaping on rank 0 alone would leave the other
                # ranks blocked in it. An errored verify is unverifiable,
                # not corrupt: restore proceeds with a warning
                logger.warning_rank0(
                    "manifest verification of step %d errored: %s; "
                    "restoring UNVERIFIED", step, e,
                )
                report = None
        failed = report is not None and not report.passed
        if multi:
            import numpy as np
            from jax.experimental import multihost_utils

            failed = bool(multihost_utils.broadcast_one_to_all(
                np.int32(1 if failed else 0)
            ))
        if failed:
            reason = report.summary() if report is not None else (
                f"rank-0 manifest verification failed (mode={self.verify_mode})"
            )
            self._quarantine(step, reason)
            raise CheckpointCorruptError(
                f"checkpoint step {step} failed '{self.verify_mode}' "
                f"verification and was quarantined: {reason}",
                report,
            )

    def _quarantine(self, step: int, reason: str) -> None:
        """Condemn a generation that failed verification: atomic rename to
        ``global_step_N.corrupt`` (rank-0-gated like ``_prune``) so no later
        ``list_steps``/``latest_step`` can ever offer it again, while the
        bytes stay on disk for post-mortem until ``_reap_quarantined`` ages
        them out."""
        self._quarantined.add(step)
        # un-dedupe: a later legitimate save() of this step must dispatch a
        # fresh generation, not be skipped as "already dispatched"
        self._saved_steps.discard(step)
        get_registry().counter("integrity.ckpt_quarantined").inc()
        flight_record("ckpt.quarantine", cid=str(step), reason=reason[:200])
        logger.error("QUARANTINING checkpoint step %d: %s", step, reason)
        if jax.process_index() != 0:
            return  # rename is rank 0's job; the in-memory set covers this rank
        self._rename_corpse(step)

    def _rename_corpse(self, step: int) -> bool:
        """Rank 0: move ``global_step_N`` aside to ``global_step_N.corrupt``
        (collision-suffixed). Returns True iff the step path is gone after
        the attempt — a failed rename is logged, never raised, because the
        in-memory ``_quarantined`` set already excludes the step."""
        src = os.path.join(self.ckpt_dir, f"global_step_{step}")
        dst = src + ".corrupt"
        k = 0
        while os.path.exists(dst):
            k += 1
            dst = src + f".corrupt.{k}"
        try:
            os.rename(src, dst)
            logger.error("quarantined %s -> %s", src, dst)
            return True
        except OSError as e:
            logger.error(
                "quarantine rename of %s failed: %s (step stays excluded "
                "in-memory)", src, e,
            )
            return not os.path.exists(src)

    def _clear_corpse(self, step: int) -> None:
        """A condemned generation is being SUPERSEDED by a fresh ``save()``
        of the same step. Normally the quarantine rename already moved the
        dir aside and this is a no-op; if that rename failed (flaky shared
        fs), the corpse still occupies the path and the Orbax dispatch would
        die on an unretried "destination already exists". Retry the move
        now, falling back to deletion — the bytes were condemned anyway."""
        if jax.process_index() != 0:
            return
        src = os.path.join(self.ckpt_dir, f"global_step_{step}")
        if not os.path.isdir(src):
            return
        if self._rename_corpse(step):
            return
        import shutil

        shutil.rmtree(src, ignore_errors=True)
        if os.path.exists(src):
            logger.error(
                "could not clear condemned checkpoint dir %s; the "
                "superseding save of step %d may fail", src, step,
            )
        else:
            logger.warning_rank0(
                "deleted condemned checkpoint dir %s (quarantine rename had "
                "failed) to clear the path for a superseding save", src,
            )

    def _prune(self):
        if not self.max_to_keep:
            return
        # single-rank deletion: every process calls save(), but on a shared
        # filesystem N ranks racing rmtree over the same step dirs hit
        # ENOENT on each other's half-deleted trees (ignore_errors hides the
        # error but not a torn delete racing a concurrent lister)
        if jax.process_index() != 0:
            return
        steps = sorted(self.list_steps())
        for s in steps[: -self.max_to_keep]:
            import shutil

            shutil.rmtree(os.path.join(self.ckpt_dir, f"global_step_{s}"), ignore_errors=True)
        self._reap_quarantined()

    # ------------------------------------------------------------------ load
    def _dispatch_restore(self, path: str, abstract_state):
        """One restore attempt (the retried unit). Transient shared-fs
        failures retry here; a CORRUPT checkpoint keeps failing and falls
        through to ``load()``'s walk-back over earlier committed steps."""
        fault_point("ckpt.restore")
        return self._ckptr.restore(path, args=ocp.args.StandardRestore(abstract_state))

    def _is_committed(self, step: int) -> bool:
        """True iff the step's payload finished committing — the commit
        marker predicate lives in integrity.py (shared with write_manifest
        and scripts/verify_ckpt.py): a stale ``*.orbax-checkpoint-tmp-*``
        *sibling* from an earlier crashed save must not invalidate a later
        successful one."""
        return is_committed_dir(
            os.path.join(self.ckpt_dir, f"global_step_{step}")
        )

    def list_steps(self):
        out = []
        if os.path.isdir(self.ckpt_dir):
            for d in os.listdir(self.ckpt_dir):
                m = _STEP_RE.match(d)
                if not m:
                    continue
                s = int(m.group(1))
                # locally-condemned steps stay invisible even if the rank-0
                # quarantine rename hasn't propagated over the shared fs yet
                if s in self._quarantined:
                    continue
                if self._is_committed(s):
                    out.append(s)
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.list_steps()
        return steps[-1] if steps else None

    def load(self, abstract_state, step: Optional[int] = None,
             max_step: Optional[int] = None):
        """Restore into the sharding/dtype structure of ``abstract_state``
        (a pytree of sharded jax.ShapeDtypeStructs). Returns (state, extra).

        ``step=None`` walks newest-first over committed-and-verified
        generations (optionally capped at ``max_step`` — the supervisor's
        rollback uses this to stay before the anomalous window): a generation
        that fails manifest verification is QUARANTINED and the walk falls
        back to the next-newest one. If every generation fails verification
        the run aborts cleanly with the full quarantine history; any other
        restore failure (e.g. abstract_state no longer matching the run) is
        systemic and surfaces as-is."""
        if step is None:
            last_err = None
            all_corrupt = True
            candidates = [s for s in reversed(self.list_steps())
                          if max_step is None or s <= max_step]
            for i, cand in enumerate(candidates):
                try:
                    return self.load(abstract_state, step=cand)
                except Exception as e:
                    if getattr(e, "config_error", False):
                        # config-class topology error (elastic knob off on a
                        # resized world, model-parallel degree change):
                        # walking past it could land on a stale PRE-resize
                        # generation and silently lose every step since —
                        # strictly worse than this actionable error
                        raise
                    last_err = e
                    all_corrupt = all_corrupt and isinstance(
                        e, CheckpointCorruptError
                    )
                    if i + 1 < len(candidates):
                        # integrity.ckpt_fallbacks means "walked past storage
                        # rot" (/healthz + bench surface it next to the
                        # quarantine count) — a fallback past a transient
                        # restore failure is NOT an integrity incident and
                        # must not send an operator hunting for .corrupt
                        # dirs that don't exist
                        reg = get_registry()
                        reg.counter("ckpt.restore_fallbacks").inc()
                        flight_record(
                            "ckpt.fallback", cid=str(cand),
                            to=candidates[i + 1],
                            corrupt=isinstance(e, CheckpointCorruptError),
                        )
                        if isinstance(e, CheckpointCorruptError):
                            reg.counter("integrity.ckpt_fallbacks").inc()
                        logger.warning_rank0(
                            "restore of step %d failed: %s; falling back to "
                            "step %d", cand, e, candidates[i + 1],
                        )
                    else:
                        logger.warning_rank0(
                            "restore of step %d failed: %s; no earlier "
                            "committed generation remains", cand, e,
                        )
            if last_err is not None:
                if all_corrupt:
                    raise CheckpointCorruptError(
                        f"every committed checkpoint generation under "
                        f"{self.ckpt_dir} failed {self.verify_mode} "
                        f"verification (tried {candidates}; all quarantined "
                        f"as *.corrupt). The run has no trustworthy state to "
                        f"resume from — inspect the quarantined dirs with "
                        f"scripts/verify_ckpt.py, restore from off-site "
                        f"backup, or restart from scratch."
                    ) from last_err
                raise last_err
            return None, None
        self.wait()
        step_dir = os.path.join(self.ckpt_dir, f"global_step_{step}")
        path = os.path.join(step_dir, "train_state")
        # cheap topology classification FIRST: mismatches no verification
        # changes (model-parallel degree change; data-parallel resize with
        # elastic OFF) raise here on metadata alone — rank 0 classifies and
        # broadcasts ONE verdict on multi-process runs (see _classify_step)
        # — so the walk never pays a full-CRC verify per generation to
        # rediscover a config error
        verdict, reason, rank_files = self._classify_step(
            step_dir, abstract_state
        )
        # verification gates the restore: Orbax must never be handed bytes
        # the manifest condemns (its own failure modes on corrupt input are
        # not guaranteed to be loud). It also keeps quarantine precedence
        # over a sidecar-based "incompatible" verdict: a missing rank
        # sidecar is often just storage rot the digest manifest condemns,
        # and that generation must be quarantined, not merely refused
        self._verify_gate(step)
        if verdict == "incompatible":
            raise ElasticRestoreError(
                f"checkpoint step {step} cannot be restored onto this "
                f"topology: {reason}"
            )
        rank_extra, elastic_event = self._materialize_rank_state(
            step, step_dir, verdict, reason, rank_files
        )
        with span("ckpt.restore"):
            restored = retry_call(
                self._dispatch_restore, path, abstract_state,
                policy=self._retry_policy,
                description=f"checkpoint restore (step {step})",
            )
        reg = get_registry()
        reg.counter("ckpt.restores").inc()
        reg.counter("ckpt.restored_bytes").inc(_tree_bytes(restored))
        flight_record("ckpt.restore", cid=str(step))
        extra = None
        extra_path = os.path.join(step_dir, "extra_state.json")
        if os.path.exists(extra_path):
            with open(extra_path) as f:
                extra = json.load(f)
        if rank_extra is not None:
            if extra is None:
                extra = {}
            extra.update(rank_extra)
        if elastic_event is not None:
            # counted only AFTER the array restore landed: a restore that
            # reshards its cursors but then fails (and falls back) must not
            # read as a completed topology crossing in /healthz
            reg.counter("ckpt.elastic_restores").inc()
            flight_record("ckpt.reshard", cid=str(step), **elastic_event)
            logger.warning_rank0(
                "ELASTIC restore of checkpoint step %d: %s",
                step, elastic_event["reason"],
            )
        logger.info_rank0("checkpoint restored from step %d", step)
        return restored, extra

    # -------------------------------------------------------------- elastic
    def _reshard_rank_state(self, step_dir: str, rank_files: List[int],
                            world: int, rank: int) -> Dict[str, Any]:
        """One elastic merge/split attempt (the retried unit): read EVERY
        saved rank's sidecar, fold them into the world-size-agnostic doc,
        and derive this rank's cursor on the new world size
        (``resilience/elastic.py``). Deterministic on every rank — all
        processes read the same files and the merge/split is pure."""
        fault_point("ckpt.reshard", context={"dir": step_dir})
        states: Dict[int, Optional[Dict[str, Any]]] = {}
        for r in rank_files:
            with open(os.path.join(step_dir, f"extra_state_rank{r}.json")) as f:
                states[r] = json.load(f)
        return split_rank_state(merge_rank_states(states), world, rank)

    _VERDICT_CODES = {"none": 0, "ok": 1, "unknown": 2, "elastic": 3,
                      "incompatible": 4}

    def _classify_local(
        self, step_dir: str, abstract_state,
    ) -> "tuple[str, str, List[int], bool]":
        """``(verdict, reason, rank sidecar list, config_error)`` from
        metadata alone (manifest topology + directory listing; never the
        payload bytes). ``config_error`` marks the mismatches no amount of
        verification changes: a model-parallel degree change, or a
        data-parallel resize with ``elastic`` OFF — the knob error names
        the fix instead of the pre-elastic silent behavior (grown ranks
        left with empty cursors repeating/skipping samples, shrunk worlds
        dropping the missing ranks' records)."""
        rank_files = list_rank_sidecars(step_dir)
        saved_topo = read_topology(step_dir)
        if not rank_files and saved_topo is None:
            return "none", "", rank_files, False  # pre-cursor checkpoint
        current = capture_topology(abstract_state)
        verdict, reason = classify_restore(
            saved_topo, jax.process_count(),
            target_mesh=current.get("mesh"),
            rank_files=rank_files or None,
            target_device_count=current.get("device_count"),
        )
        if verdict == "incompatible" and mesh_incompat_reason(
            (saved_topo or {}).get("mesh"), current.get("mesh")
        ):
            # config-class subtype: a model-parallel degree change applies
            # to the run as a whole (the walk aborts), unlike
            # per-generation damage such as a torn sidecar set — the check
            # itself lives once, inside classify_restore; this call only
            # subtypes its verdict
            return "incompatible", (
                f"checkpoint in {step_dir} cannot be restored onto this "
                f"topology: {reason}"
            ), rank_files, True
        if verdict == "elastic" and not self.elastic:
            return "elastic", (
                f"checkpoint in {step_dir} was saved on a different "
                f"topology ({reason}) and elastic restore is OFF. Set "
                f"train.ckpt_elastic=true to reshard the arrays and "
                f"merge/split the per-rank data cursors onto this topology, "
                f"or resume on the saved one."
            ), rank_files, True
        return verdict, reason, rank_files, False

    def _classify_step(
        self, step_dir: str, abstract_state,
    ) -> "tuple[str, str, List[int]]":
        """Topology classification with ONE verdict for the whole
        collective: on multi-process runs rank 0 classifies and broadcasts
        — same altitude as ``_verify_gate``, and for the same reason: two
        ranks classifying from independent directory listings on a lagging
        shared fs could split between restoring a generation and falling
        back past it, wedging the Orbax restore collective instead of
        failing over cleanly. Config-class mismatches raise here (walk
        aborts); a sidecar-based ``incompatible`` verdict is RETURNED so
        the verify gate keeps quarantine precedence (a missing sidecar is
        often storage rot the digest manifest condemns)."""
        multi = jax.process_count() > 1
        verdict, reason, rank_files, config = "none", "", [], False
        if not multi or jax.process_index() == 0:
            verdict, reason, rank_files, config = self._classify_local(
                step_dir, abstract_state
            )
        if multi:
            import numpy as np
            from jax.experimental import multihost_utils

            vec = multihost_utils.broadcast_one_to_all(np.asarray(
                [self._VERDICT_CODES[verdict], int(config), len(rank_files)],
                np.int32,
            ))
            config = bool(vec[1])
            if jax.process_index() != 0:
                verdict = {v: k for k, v in self._VERDICT_CODES.items()}[
                    int(vec[0])
                ]
                # rank 0's verdict came with rank 0's listing: derive the
                # file set from the broadcast count so a lagging local
                # listing can't silently shrink the merge input (a file
                # rank 0 saw but this rank can't read yet fails LOUDLY in
                # the retried reshard read, not silently)
                rank_files = list(range(int(vec[2])))
                reason = (
                    "classified on rank 0 (one verdict for the whole "
                    "collective; config-level mismatches include a "
                    "model-parallel degree change or train.ckpt_elastic "
                    "off on a resized world) — see rank 0's log for detail"
                )
        if config:
            err = ElasticRestoreError(reason)
            err.config_error = True  # applies to the run, not one generation
            raise err
        return verdict, reason, rank_files

    def _materialize_rank_state(
        self, step: int, step_dir: str, verdict: str, reason: str,
        rank_files: List[int],
    ) -> "tuple[Optional[Dict[str, Any]], Optional[Dict[str, Any]]]":
        """``(per-rank extra state, elastic event-or-None)`` for this
        process. Same topology: this rank's own sidecar, byte-exact. An
        ``elastic`` verdict (knob already checked in ``_classify_step``):
        merge/split of all saved sidecars — the returned event is counted
        by ``load()`` only once the array restore lands, so a resize whose
        restore then fails never reads as a completed topology crossing."""
        if verdict == "none":
            return None, None
        if verdict in ("ok", "unknown"):
            if verdict == "unknown":
                logger.warning_rank0(
                    "checkpoint step %d: %s", step, reason,
                )
            return self._read_own_sidecar(step_dir), None
        # verdict == "elastic"
        world = jax.process_count()
        rank = jax.process_index()
        if not rank_files:
            # mesh-only resize with no cursor sidecars: arrays reshard via
            # the target NamedShardings; there is no cursor to bridge
            resolved = None
        else:
            resolved = retry_call(
                self._reshard_rank_state, step_dir, rank_files, world, rank,
                policy=self._retry_policy,
                description=f"elastic cursor reshard (step {step})",
            )
        event = {
            "saved_world": len(rank_files)
            or (read_topology(step_dir) or {}).get("world_size"),
            "world": world,
            "reason": reason[:200],
        }
        return resolved, event

    def _read_own_sidecar(self, step_dir: str) -> Optional[Dict[str, Any]]:
        rank_path = os.path.join(
            step_dir, f"extra_state_rank{jax.process_index()}.json"
        )
        if not os.path.exists(rank_path):
            return None
        with open(rank_path) as f:
            return json.load(f)

    def close(self):
        self._ckptr.wait_until_finished()
        self._join_manifest()
        # same contract as wait(): a final async save committed by this
        # close must not leave the newest — most likely to be restored —
        # generation without its manifest (or without its ckpt.commit flight
        # event — a post-mortem must not show it saved-but-never-committed)
        if self._inflight_step is not None:
            flight_record("ckpt.commit", cid=str(self._inflight_step))
            self._write_manifest(self._inflight_step)
            self._inflight_step = None
        self._ckptr.close()


def build_checkpointer(ckpt_dir: str, ckpt_manager: str = "orbax", **kwargs) -> Checkpointer:
    """Reference ``build_checkpointer`` (checkpoint/checkpointer.py:30)."""
    if ckpt_manager not in ("orbax", "dcp"):
        raise ValueError(f"unknown ckpt_manager {ckpt_manager!r}")
    return Checkpointer(ckpt_dir, **kwargs)
