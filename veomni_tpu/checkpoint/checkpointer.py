"""Sharded train-state checkpointing with exact resume.

Reference: ``veomni/checkpoint/dcp_checkpointer.py`` (torch DCP + async save
on a side gloo group, EP-placement normalization, extra_state pickles).
TPU translation: **Orbax** async checkpointing of the sharded TrainState —
every process writes its own shards (OCDBT/TensorStore), restore re-shards to
the current topology automatically, so the reference's EP save/restore
placement dance (``_apply_extra_parallel_dim``) is unnecessary: Orbax
restores to whatever NamedSharding the new run requests.

extra_state (dataloader cursor, meter, python RNG, global step) is a JSON
blob saved alongside, mirroring ``_save_extra_state``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

from veomni_tpu.observability.metrics import get_registry
from veomni_tpu.observability.spans import span
from veomni_tpu.resilience.faults import fault_point
from veomni_tpu.resilience.retry import RetryPolicy, retry_call
from veomni_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_STEP_RE = re.compile(r"^global_step_(\d+)$")


def _tree_bytes(tree: Any) -> int:
    """Payload size from array metadata (no device sync: nbytes is shape
    math, not a fetch)."""
    return sum(
        int(getattr(leaf, "nbytes", 0)) for leaf in jax.tree.leaves(tree)
    )


class Checkpointer:
    """save/load of {train_state, extra_state} under ckpt_dir/global_step_N.

    I/O resilience: every save/restore dispatch runs under a bounded
    deterministic-backoff retry (``io_retries``/``retry_base_s``), with
    ``ckpt.save``/``ckpt.restore`` fault points inside each attempt so the
    whole path is exercisable from a ``VEOMNI_FAULT_PLAN``. Async-save
    commit errors are probed at the next step boundary (``save()``/``wait()``)
    and the failed step is EVICTED from the dedupe set, so a later save of
    that step re-dispatches instead of being silently lost.
    """

    def __init__(self, ckpt_dir: str, *, async_save: bool = True, max_to_keep: int = 0,
                 io_retries: int = 3, retry_base_s: float = 0.05):
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self.async_save = async_save
        self.max_to_keep = max_to_keep
        self._retry_policy = RetryPolicy(retries=io_retries, base_delay_s=retry_base_s)
        self._saved_steps: set = set()
        self._inflight_step: Optional[int] = None
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        # startup is the only moment no save can be in flight anywhere, so
        # clear crashed-save debris here (never during save(): a lagging host
        # could rmtree a faster host's live tmp dir)
        self._clean_debris()

    def _clean_debris(self):
        import shutil

        if jax.process_index() != 0:  # same shared-fs race as _prune
            return
        for d in os.listdir(self.ckpt_dir):
            if not _STEP_RE.match(d):
                continue
            step_dir = os.path.join(self.ckpt_dir, d)
            if not os.path.isdir(os.path.join(step_dir, "train_state")):
                # crash before commit: only tmp payload/extra_state remain
                logger.warning_rank0("removing uncommitted checkpoint debris %s", d)
                shutil.rmtree(step_dir, ignore_errors=True)
            else:
                for sub in os.listdir(step_dir):
                    if ".orbax-checkpoint-tmp" in sub:
                        shutil.rmtree(os.path.join(step_dir, sub), ignore_errors=True)

    # ------------------------------------------------------------------ save
    def check_for_errors(self) -> Optional[BaseException]:
        """Step-boundary probe of the async commit thread. On failure the
        in-flight step is evicted from the dedupe set (so a later ``save()``
        of that step re-dispatches instead of silently skipping) and the
        error is returned for the caller to surface or absorb."""
        probe = getattr(self._ckptr, "check_for_errors", None)
        if probe is None:
            return None
        try:
            probe()
        except Exception as e:
            self._evict_inflight(e)
            return e
        return None

    def _evict_inflight(self, err: BaseException) -> None:
        if self._inflight_step is not None:
            self._saved_steps.discard(self._inflight_step)
            logger.error(
                "async checkpoint save of step %d FAILED: %s; step evicted — "
                "the next save() of it will retry", self._inflight_step, err,
            )
            self._inflight_step = None

    def _dispatch_save(self, path: str, train_state, step_dir: str,
                       extra_state, rank_state) -> None:
        """One save attempt (the retried unit): sidecar JSONs, then the
        payload dispatch. The JSON writes sit INSIDE the unit so a transient
        shared-fs error there is retried like any other I/O (re-writing them
        is idempotent), and BEFORE the payload so the atomic ``train_state``
        rename can never commit a checkpoint missing its cursor metadata.
        The serialization wait on the PREVIOUS async save lives in save(),
        outside this unit: a previous commit's failure must evict ITS step,
        not be retried away as a transient fault of this one. The sync-mode
        wait stays inside — that failure IS this step's, and re-dispatching
        is the right retry."""
        os.makedirs(step_dir, exist_ok=True)
        if extra_state is not None and jax.process_index() == 0:
            with open(os.path.join(step_dir, "extra_state.json"), "w") as f:
                json.dump(extra_state, f)
        if rank_state is not None:
            # per-process state (dataloader cursor + packing carry-over is
            # rank-local data!): every process writes its own file — restoring
            # rank 0's buffer everywhere would feed all ranks rank-0's samples
            fname = f"extra_state_rank{jax.process_index()}.json"
            with open(os.path.join(step_dir, fname), "w") as f:
                json.dump(rank_state, f)
        fault_point("ckpt.save")
        self._ckptr.save(path, args=ocp.args.StandardSave(train_state))
        if not self.async_save:
            self._ckptr.wait_until_finished()

    def save(self, step: int, train_state, extra_state: Optional[Dict[str, Any]] = None,
             rank_state: Optional[Dict[str, Any]] = None):
        # surface a failed PREVIOUS async save now (and evict its step) —
        # never inside the jitted loop, only at this step boundary
        self.check_for_errors()
        path = os.path.join(self.ckpt_dir, f"global_step_{step}", "train_state")
        # in-memory dedupe: async saves only materialize the dir at commit, so
        # isdir alone would race an in-flight save of the same step
        if step in self._saved_steps:
            logger.info_rank0("checkpoint for step %d already dispatched; skipping", step)
            return
        if os.path.isdir(path):
            logger.info_rank0("checkpoint for step %d already exists; skipping", step)
            return
        # serialize with any in-flight save BEFORE the retried dispatch: if
        # the previous async commit failed, the error raises here, belongs to
        # the previous step, and must evict that step — not be swallowed by
        # this step's retry loop
        # the span is the single timing source (histogram ``span.ckpt.save``
        # + goodput checkpoint attribution + chrome trace): async saves
        # measure the host-blocking dispatch (serialize-with-previous +
        # device->host copy), sync saves the full commit — either way, the
        # wall time the step loop lost
        with span("ckpt.save"):
            try:
                self._ckptr.wait_until_finished()
            except Exception as e:
                self._evict_inflight(e)
            step_dir = os.path.join(self.ckpt_dir, f"global_step_{step}")
            retry_call(
                self._dispatch_save, path, train_state, step_dir,
                extra_state, rank_state,
                policy=self._retry_policy,
                description=f"checkpoint save (step {step})",
            )
        reg = get_registry()
        reg.counter("ckpt.saves").inc()
        reg.counter("ckpt.saved_bytes").inc(_tree_bytes(train_state))
        # dedupe only records a SUCCESSFUL dispatch (on failure the raise
        # above leaves the set untouched, so a later attempt of this step —
        # e.g. the train-end final save — isn't silently skipped)
        self._saved_steps.add(step)
        self._inflight_step = step if self.async_save else None
        logger.info_rank0("checkpoint save dispatched: step %d -> %s", step, path)
        self._prune()

    def wait(self):
        with span("ckpt.wait"):
            try:
                self._ckptr.wait_until_finished()
            except Exception as e:
                self._evict_inflight(e)
                raise
            err = self.check_for_errors()
            if err is not None:
                raise err
            self._inflight_step = None

    def _prune(self):
        if not self.max_to_keep:
            return
        # single-rank deletion: every process calls save(), but on a shared
        # filesystem N ranks racing rmtree over the same step dirs hit
        # ENOENT on each other's half-deleted trees (ignore_errors hides the
        # error but not a torn delete racing a concurrent lister)
        if jax.process_index() != 0:
            return
        steps = sorted(self.list_steps())
        for s in steps[: -self.max_to_keep]:
            import shutil

            shutil.rmtree(os.path.join(self.ckpt_dir, f"global_step_{s}"), ignore_errors=True)

    # ------------------------------------------------------------------ load
    def _dispatch_restore(self, path: str, abstract_state):
        """One restore attempt (the retried unit). Transient shared-fs
        failures retry here; a CORRUPT checkpoint keeps failing and falls
        through to ``load()``'s walk-back over earlier committed steps."""
        fault_point("ckpt.restore")
        return self._ckptr.restore(path, args=ocp.args.StandardRestore(abstract_state))

    def _is_committed(self, step: int) -> bool:
        """True iff the step's train_state payload finished committing.

        A crash during an async Orbax save leaves the step dir with only the
        uncommitted ``*.orbax-checkpoint-tmp-*`` payload (and possibly an
        eagerly-written extra_state.json). Orbax renames the tmp dir to its
        final name atomically on commit, so the final ``train_state`` dir
        existing IS the commit marker — a stale tmp *sibling* from an earlier
        crashed save must not invalidate a later successful one.
        """
        step_dir = os.path.join(self.ckpt_dir, f"global_step_{step}")
        return os.path.isdir(os.path.join(step_dir, "train_state"))

    def list_steps(self):
        out = []
        if os.path.isdir(self.ckpt_dir):
            for d in os.listdir(self.ckpt_dir):
                m = _STEP_RE.match(d)
                if m and self._is_committed(int(m.group(1))):
                    out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.list_steps()
        return steps[-1] if steps else None

    def load(self, abstract_state, step: Optional[int] = None):
        """Restore into the sharding/dtype structure of ``abstract_state``
        (a pytree of sharded jax.ShapeDtypeStructs). Returns (state, extra)."""
        if step is None:
            # walk back through committed steps so a corrupt latest checkpoint
            # still resumes; if EVERY step fails the failure is systemic (e.g.
            # abstract_state no longer matches the run) and must surface
            last_err = None
            for cand in reversed(self.list_steps()):
                try:
                    return self.load(abstract_state, step=cand)
                except Exception as e:
                    last_err = e
                    logger.warning_rank0(
                        "restore of step %d failed: %s; trying previous step", cand, e
                    )
            if last_err is not None:
                raise last_err
            return None, None
        self.wait()
        step_dir = os.path.join(self.ckpt_dir, f"global_step_{step}")
        path = os.path.join(step_dir, "train_state")
        with span("ckpt.restore"):
            restored = retry_call(
                self._dispatch_restore, path, abstract_state,
                policy=self._retry_policy,
                description=f"checkpoint restore (step {step})",
            )
        reg = get_registry()
        reg.counter("ckpt.restores").inc()
        reg.counter("ckpt.restored_bytes").inc(_tree_bytes(restored))
        extra = None
        extra_path = os.path.join(step_dir, "extra_state.json")
        if os.path.exists(extra_path):
            with open(extra_path) as f:
                extra = json.load(f)
        rank_path = os.path.join(
            step_dir, f"extra_state_rank{jax.process_index()}.json"
        )
        if os.path.exists(rank_path):
            with open(rank_path) as f:
                rank_extra = json.load(f)
            if extra is None:
                extra = {}
            extra.update(rank_extra)
        elif any(f.startswith("extra_state_rank") for f in os.listdir(step_dir)):
            # the checkpoint HAS per-rank files, just not for this rank
            # (process count changed between save and resume). Plain
            # per-process warning: this condition only occurs on ranks > 0
            # when the process count GREW, so rank0-gated logging would
            # never print.
            logger.warning(
                "no per-rank extra state for process %d of %d (topology "
                "changed?); dataloader resume may repeat or skip rank-local "
                "samples",
                jax.process_index(), jax.process_count(),
            )
        logger.info_rank0("checkpoint restored from step %d", step)
        return restored, extra

    def close(self):
        self._ckptr.wait_until_finished()
        self._ckptr.close()


def build_checkpointer(ckpt_dir: str, ckpt_manager: str = "orbax", **kwargs) -> Checkpointer:
    """Reference ``build_checkpointer`` (checkpoint/checkpointer.py:30)."""
    if ckpt_manager not in ("orbax", "dcp"):
        raise ValueError(f"unknown ckpt_manager {ckpt_manager!r}")
    return Checkpointer(ckpt_dir, **kwargs)
