from veomni_tpu.checkpoint.checkpointer import Checkpointer, build_checkpointer

__all__ = ["Checkpointer", "build_checkpointer"]
