"""Aux subsystems: channel loss accounting, MoE router monitor, determinism
shim, remat policies."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu.arguments import VeOmniArguments

TOY = {
    "model_type": "qwen3",
    "vocab_size": 256,
    "hidden_size": 64,
    "intermediate_size": 128,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "head_dim": 16,
    "qk_norm": True,
}


def test_channel_loss_e2e(tmp_path):
    from veomni_tpu.trainer import TextTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for i in range(128):
            f.write(json.dumps({
                "input_ids": rng.integers(0, 256, int(rng.integers(16, 60))).tolist(),
                "channel": "web" if i % 2 else "code",
            }) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = dict(TOY)
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 128
    args.data.channel_list = ["code", "web"]
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 100
    trainer = TextTrainer(args)
    cb = [c for c in trainer.callbacks if type(c).__name__ == "ChannelLossCallback"][0]
    trainer.train()
    assert sum(cb._counts) > 0, "no channel tokens accounted"
    assert all(c > 0 for c in cb._counts), f"channel counts {cb._counts}"
    trainer.checkpointer.close()


def test_moe_router_capture():
    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.utils.moe_monitor import capture_router_stats

    cfg = TransformerConfig(
        **{**TOY, "model_type": "qwen3_moe"},
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        dtype=jnp.float32,
    )
    model = build_foundation_model(config=cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = {
        "input_ids": jnp.ones((1, 32), jnp.int32),
        "position_ids": jnp.broadcast_to(jnp.arange(32), (1, 32)),
        "segment_ids": jnp.ones((1, 32), jnp.int32),
    }
    stats = capture_router_stats(model, params, batch)
    assert stats["expert_load"].shape == (2, 4)  # 2 moe layers, 4 experts
    np.testing.assert_allclose(stats["expert_load"].sum(1), 1.0, rtol=1e-6)


def test_remat_policies_run():
    from veomni_tpu.models import TransformerConfig, build_foundation_model

    for policy in ("nothing", "dots"):
        cfg = TransformerConfig(**TOY, dtype=jnp.float32, remat_policy=policy)
        model = build_foundation_model(config=cfg)
        params = model.init(jax.random.PRNGKey(0))
        batch = {
            "input_ids": jnp.ones((1, 16), jnp.int32),
            "labels": jnp.ones((1, 16), jnp.int32),
            "position_ids": jnp.broadcast_to(jnp.arange(16), (1, 16)),
            "segment_ids": jnp.ones((1, 16), jnp.int32),
        }
        g = jax.grad(lambda p: model.loss_fn(p, batch)[0])(params)
        assert np.isfinite(float(jax.tree.leaves(g)[0].sum()))


def test_batch_invariant_shim():
    from veomni_tpu.utils.determinism import set_batch_invariant_mode

    with set_batch_invariant_mode(True):
        pass
