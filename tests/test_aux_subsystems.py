"""Aux subsystems: channel loss accounting, MoE router monitor, determinism
shim, remat policies."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.arguments import VeOmniArguments

TOY = {
    "model_type": "qwen3",
    "vocab_size": 256,
    "hidden_size": 64,
    "intermediate_size": 128,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "head_dim": 16,
    "qk_norm": True,
}

TOY_MOE = {
    **TOY,
    "model_type": "qwen3_moe",
    "num_experts": 4,
    "num_experts_per_tok": 2,
    "moe_intermediate_size": 32,
}


def test_channel_loss_e2e(tmp_path):
    from veomni_tpu.trainer import TextTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for i in range(128):
            f.write(json.dumps({
                "input_ids": rng.integers(0, 256, int(rng.integers(16, 60))).tolist(),
                "channel": "web" if i % 2 else "code",
            }) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = dict(TOY)
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 128
    args.data.channel_list = ["code", "web"]
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 100
    trainer = TextTrainer(args)
    cb = [c for c in trainer.callbacks if type(c).__name__ == "ChannelLossCallback"][0]
    trainer.train()
    assert sum(cb._counts) > 0, "no channel tokens accounted"
    assert all(c > 0 for c in cb._counts), f"channel counts {cb._counts}"
    trainer.checkpointer.close()


def test_moe_router_capture():
    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.utils.moe_monitor import capture_router_stats

    cfg = TransformerConfig(
        **{**TOY, "model_type": "qwen3_moe"},
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        dtype=jnp.float32,
    )
    model = build_foundation_model(config=cfg)
    params = model.init(jax.random.PRNGKey(0))
    batch = {
        "input_ids": jnp.ones((1, 32), jnp.int32),
        "position_ids": jnp.broadcast_to(jnp.arange(32), (1, 32)),
        "segment_ids": jnp.ones((1, 32), jnp.int32),
    }
    stats = capture_router_stats(model, params, batch)
    assert stats["expert_load"].shape == (2, 4)  # 2 moe layers, 4 experts
    np.testing.assert_allclose(stats["expert_load"].sum(1), 1.0, rtol=1e-6)


def test_remat_policies_run():
    from veomni_tpu.models import TransformerConfig, build_foundation_model

    for policy in ("nothing", "dots"):
        cfg = TransformerConfig(**TOY, dtype=jnp.float32, remat_policy=policy)
        model = build_foundation_model(config=cfg)
        params = model.init(jax.random.PRNGKey(0))
        batch = {
            "input_ids": jnp.ones((1, 16), jnp.int32),
            "labels": jnp.ones((1, 16), jnp.int32),
            "position_ids": jnp.broadcast_to(jnp.arange(16), (1, 16)),
            "segment_ids": jnp.ones((1, 16), jnp.int32),
        }
        g = jax.grad(lambda p: model.loss_fn(p, batch)[0])(params)
        assert np.isfinite(float(jax.tree.leaves(g)[0].sum()))


def test_batch_invariant_shim():
    from veomni_tpu.utils.determinism import set_batch_invariant_mode

    with set_batch_invariant_mode(True):
        pass


def test_checkpointer_skips_uncommitted_step(tmp_path):
    """A crash mid-async-save leaves only the orbax tmp payload; resume must
    fall back to the last committed step (ADVICE r1: checkpointer.py:87)."""
    import os

    from veomni_tpu.checkpoint import build_checkpointer

    ckptr = build_checkpointer(str(tmp_path), async_save=False)
    state = {"w": jnp.arange(4, dtype=jnp.float32)}
    ckptr.save(2, state, {"global_step": 2})
    ckptr.wait()
    # fake a crashed save of step 4: tmp dir + eager extra_state, no payload
    crashed = tmp_path / "global_step_4"
    os.makedirs(crashed / "train_state.orbax-checkpoint-tmp-123")
    (crashed / "extra_state.json").write_text('{"global_step": 4}')
    assert ckptr.list_steps() == [2]
    assert ckptr.latest_step() == 2
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
    )
    restored, extra = ckptr.load(abstract)
    assert extra["global_step"] == 2
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(4))

    # re-saving step 4 after the crash must commit and become visible even
    # though stale tmp debris existed (review r2: stale sibling must not
    # permanently mask a later successful save)
    ckptr.save(4, state, {"global_step": 4})
    ckptr.wait()
    assert ckptr.latest_step() == 4
    restored, extra = ckptr.load(abstract)
    assert extra["global_step"] == 4
    ckptr.close()


def test_hf_config_roundtrip_moe_keys():
    """to_hf_config must emit the expert-count key + activation spelling HF
    transformers expects for each MoE dialect (ADVICE r1: config.py:202)."""
    from veomni_tpu.models.config import TransformerConfig

    for mt, hf_key in [
        ("qwen3_moe", "num_experts"),
        ("deepseek_v3", "n_routed_experts"),
        ("gpt_oss", "num_local_experts"),
    ]:
        cfg = TransformerConfig(
            model_type=mt, num_experts=8, num_experts_per_tok=2,
            moe_intermediate_size=32,
            hidden_act="gpt_oss_glu" if mt == "gpt_oss" else "silu",
        )
        hf = cfg.to_hf_config()
        assert hf.get(hf_key) == 8, (mt, hf)
        assert hf["hidden_act"] in ("silu",), (mt, hf["hidden_act"])
        back = TransformerConfig.from_hf_config(hf)
        assert back.num_experts == 8, mt
        if mt == "gpt_oss":
            assert back.hidden_act == "gpt_oss_glu"


def test_ep_capacity_drop_metric():
    """Capacity-mode EP surfaces a nonzero dropped-assignment fraction while
    dropless reports exactly zero (ADVICE r1: moe.py:65)."""
    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state

    from veomni_tpu.models import TransformerConfig

    destroy_parallel_state()
    try:
        ps = init_parallel_state(ep_size=2)  # 4 devices: ep2 x fsdp2
        batch = {
            "input_ids": jnp.ones((4, 32), jnp.int32),
            "labels": jnp.ones((4, 32), jnp.int32),
            "position_ids": jnp.broadcast_to(jnp.arange(32), (4, 32)),
            "segment_ids": jnp.ones((4, 32), jnp.int32),
        }
        # identical tokens route identically -> tight capacity guarantees drops
        cfg = TransformerConfig(
            **dict(TOY_MOE, dtype=jnp.float32, moe_capacity_factor=0.25)
        )
        model = build_foundation_model(config=cfg)
        params = model.init(jax.random.PRNGKey(0))
        with use_parallel_state(ps):
            _, metrics = model.loss_fn(params, batch)
        assert float(metrics["moe_dropped_frac"]) > 0.0

        cfg2 = TransformerConfig(**dict(TOY_MOE, dtype=jnp.float32))
        model2 = build_foundation_model(config=cfg2)
        params2 = model2.init(jax.random.PRNGKey(0))
        with use_parallel_state(ps):
            _, metrics2 = model2.loss_fn(params2, batch)
        assert float(metrics2["moe_dropped_frac"]) == 0.0
    finally:
        destroy_parallel_state()


def test_trim_safetensor_layers(tmp_path):
    """scripts/trim_safetensor_layers.py: layer filter + index + config patch."""
    import json
    import subprocess
    import sys

    import numpy as np
    from safetensors.numpy import save_file

    src = tmp_path / "full"
    src.mkdir()
    tensors = {"model.embed_tokens.weight": np.ones((8, 4), np.float32)}
    for i in range(4):
        tensors[f"model.layers.{i}.mlp.w"] = np.full((2, 2), float(i), np.float32)
    save_file(tensors, str(src / "model.safetensors"))
    with open(src / "config.json", "w") as f:
        json.dump({"num_hidden_layers": 4, "text_config": {"num_hidden_layers": 4}}, f)

    out = tmp_path / "trim"
    r = subprocess.run(
        [sys.executable, "scripts/trim_safetensor_layers.py",
         "--model_dir", str(src), "--out_dir", str(out), "--num_layers", "2"],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert r.returncode == 0, r.stderr
    from safetensors import safe_open

    with open(out / "model.safetensors.index.json") as f:
        wm = json.load(f)["weight_map"]
    assert "model.layers.1.mlp.w" in wm and "model.layers.2.mlp.w" not in wm
    with safe_open(str(out / next(iter(set(wm.values())))), framework="np") as f:
        assert set(f.keys()) == set(wm)
    with open(out / "config.json") as f:
        cfg = json.load(f)
    assert cfg["num_hidden_layers"] == 2
    assert cfg["text_config"]["num_hidden_layers"] == 2


def test_merge_chrome_trace(tmp_path):
    import json
    import subprocess
    import sys

    for i in range(2):
        with open(tmp_path / f"t{i}.json", "w") as f:
            json.dump({"traceEvents": [
                {"pid": 1, "tid": 1, "name": "process_name", "ph": "M",
                 "args": {"name": "dev"}},
                {"pid": 1, "tid": 1, "name": "op", "ph": "X", "ts": i, "dur": 1},
            ]}, f)
    out = tmp_path / "merged.json"
    r = subprocess.run(
        [sys.executable, "scripts/merge_chrome_trace.py", str(out),
         str(tmp_path / "t0.json"), str(tmp_path / "t1.json")],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert r.returncode == 0, r.stderr
    with open(out) as f:
        ev = json.load(f)["traceEvents"]
    assert len(ev) == 4
    assert {e["pid"] for e in ev} == {1, 3}  # hosts offset apart


def test_channel_loss_omni_family():
    """Per-channel CE hooks the omni thinkers' merged-hidden preamble (was
    a NotImplementedError scope guard through r4): channel sums must add up
    to the total loss on a text-only batch."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.models.auto import build_config
    from veomni_tpu.train.channel_loss import (
        make_channel_loss_fn,
        supports_channel_loss,
    )

    cfg = build_config(
        "qwen2_5_omni",
        text=dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, head_dim=16,
                  rope_scaling={"type": "mrope", "mrope_section": [2, 3, 3]},
                  dtype="float32", param_dtype="float32"),
        vision=None, audio=None,
        image_token_id=9, video_token_id=10, vision_start_token_id=8,
        audio_token_id=11,
    )
    model = build_foundation_model(config=cfg)
    assert supports_channel_loss(model)
    model.init(jax.random.PRNGKey(0))
    loss_fn = make_channel_loss_fn(model, num_channels=2)

    rng = np.random.default_rng(0)
    b, s = 2, 16
    ids = rng.integers(12, 256, (b, s))
    pos = np.broadcast_to(np.arange(s), (3, b, s)).transpose(1, 0, 2)
    batch = {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "labels": jnp.asarray(ids, jnp.int32),
        "position_ids": jnp.asarray(pos.copy(), jnp.int32),
        "segment_ids": jnp.ones((b, s), jnp.int32),
        "channel_ids": jnp.asarray(
            np.where(np.arange(s)[None] < s // 2, 0, 1), jnp.int32
        ).repeat(b, 0).reshape(b, s),
    }
    loss_sum, metrics = loss_fn(model.params, batch)
    ch = np.asarray(metrics["channel_loss_sums"])
    counts = np.asarray(metrics["channel_token_counts"])
    assert ch.shape == (2,) and np.all(ch > 0)
    assert counts.sum() == b * s
    assert float(ch.sum()) == pytest.approx(float(loss_sum), rel=1e-5)
