"""DeepSeek-V4 dialect: structure, packing equivalence, mHC invariants,
hash/topk routing, HF io round-trip.

No torch oracle exists for this family (transformers ships only
deepseek_v2/v3; the reference's modeling file is ByteDance-internal), so the
suite leans on *internal invariants* the architecture must satisfy:
packing-equivalence exercises every segment-aware code path (sliding mask,
HCA/CSA window alignment, indexer causality), which is where a sparse
implementation breaks first."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veomni_tpu.models.deepseek_v4 import (
    DeepseekV4Config,
    forward_logits,
    init_params,
    loss_fn,
)

CFG = dict(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=32,
    num_hidden_layers=3,
    num_attention_heads=2,
    head_dim=16,
    q_lora_rank=16,
    o_groups=2,
    o_lora_rank=8,
    sliding_window=8,
    layer_types=("sliding_attention", "compressed_sparse_attention",
                 "heavily_compressed_attention"),
    mlp_layer_types=("hash_moe", "topk_moe", "topk_moe"),
    compress_rate_hca=8,
    compress_rate_csa=4,
    index_n_heads=2,
    index_head_dim=8,
    index_topk=3,
    hc_mult=2,
    num_experts=4,
    num_experts_per_tok=2,
    rope_parameters={
        "main": {"rope_theta": 10000.0, "partial_rotary_factor": 0.5},
        "compress": {"rope_theta": 5000.0, "partial_rotary_factor": 0.5},
    },
    dtype=jnp.float32,
    param_dtype=jnp.float32,
    remat=False,
)


@pytest.fixture(scope="module")
def model():
    cfg = DeepseekV4Config(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    # give the hash router a non-trivial frozen table
    rng = np.random.default_rng(0)
    params["runs"][0]["mlp"]["tid2eid"] = jnp.asarray(
        rng.integers(0, cfg.num_experts,
                     (1, cfg.vocab_size, cfg.num_experts_per_tok)),  # [L=1,V,K]
        jnp.int32,
    )
    return cfg, params


def _batch(cfg, rng, rows, seq):
    ids = rng.integers(1, cfg.vocab_size, (rows, seq)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)
    labels[:, -1] = -100
    return {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(labels),
        "position_ids": jnp.broadcast_to(jnp.arange(seq), (rows, seq)).astype(jnp.int32),
        "segment_ids": jnp.ones((rows, seq), jnp.int32),
    }


def test_forward_finite_and_grads(model):
    cfg, params = model
    batch = _batch(cfg, np.random.default_rng(1), 2, 32)
    total, metrics = loss_fn(params, cfg, batch)
    assert np.isfinite(float(total))
    assert int(metrics["ntokens"]) == 2 * 31

    # allow_int: the frozen hash table (tid2eid, int32) rides in params
    grads = jax.grad(lambda p: loss_fn(p, cfg, batch)[0], allow_int=True)(params)
    # every trainable leaf gets gradient signal, EXCEPT: the frozen hash
    # table (int, non-diff) and the lightning indexer (it only drives the
    # non-differentiable top-k selection; the reference trains it with a
    # separate alignment objective, not the LM loss)
    flat = jax.tree_util.tree_leaves_with_path(grads)
    dead = [jax.tree_util.keystr(p) for p, g in flat
            if g.dtype.kind == "f" and float(jnp.abs(g).sum()) == 0.0]
    # e_score_correction_bias shifts only the (non-diff) top-k choice —
    # deepseek updates it with the noaux-tc balance rule, not gradients
    allowed_dead = ("tid2eid", "indexer", "e_score_correction_bias")
    assert not [d for d in dead if not any(a in d for a in allowed_dead)], dead


def test_packing_equivalence(model):
    """Loss of two sequences packed into one row (segment ids 1/2) must equal
    the sum of their standalone losses — exercises sliding mask, HCA/CSA
    window alignment, overlap windows, and indexer causality under packing."""
    cfg, params = model
    rng = np.random.default_rng(2)
    la, lb = 24, 16
    ids_a = rng.integers(1, cfg.vocab_size, la).astype(np.int32)
    ids_b = rng.integers(1, cfg.vocab_size, lb).astype(np.int32)

    def solo(ids):
        n = len(ids)
        lab = np.concatenate([ids[1:], [-100]]).astype(np.int32)
        batch = {
            "input_ids": jnp.asarray(ids)[None],
            "labels": jnp.asarray(lab)[None],
            "position_ids": jnp.arange(n, dtype=jnp.int32)[None],
            "segment_ids": jnp.ones((1, n), jnp.int32),
        }
        total, m = loss_fn(params, cfg, batch)
        return float(m["loss_sum"]), int(m["ntokens"])

    sa, na = solo(ids_a)
    sb, nb = solo(ids_b)

    packed_ids = np.concatenate([ids_a, ids_b])
    packed_lab = np.concatenate(
        [ids_a[1:], [-100], ids_b[1:], [-100]]
    ).astype(np.int32)
    packed = {
        "input_ids": jnp.asarray(packed_ids)[None],
        "labels": jnp.asarray(packed_lab)[None],
        "position_ids": jnp.concatenate(
            [jnp.arange(la), jnp.arange(lb)]
        ).astype(jnp.int32)[None],
        "segment_ids": jnp.concatenate(
            [jnp.ones(la, jnp.int32), jnp.full(lb, 2, jnp.int32)]
        )[None],
    }
    _, mp = loss_fn(params, cfg, packed)
    assert int(mp["ntokens"]) == na + nb
    np.testing.assert_allclose(float(mp["loss_sum"]), sa + sb, rtol=2e-5)


def test_padding_invariance(model):
    """Appending padding (segment 0) must not change the loss."""
    cfg, params = model
    rng = np.random.default_rng(3)
    batch = _batch(cfg, rng, 1, 24)
    _, m0 = loss_fn(params, cfg, batch)

    pad = 8
    batch_p = {
        "input_ids": jnp.pad(batch["input_ids"], ((0, 0), (0, pad))),
        "labels": jnp.pad(batch["labels"], ((0, 0), (0, pad)), constant_values=-100),
        "position_ids": jnp.pad(batch["position_ids"], ((0, 0), (0, pad))),
        "segment_ids": jnp.pad(batch["segment_ids"], ((0, 0), (0, pad))),
    }
    _, m1 = loss_fn(params, cfg, batch_p)
    np.testing.assert_allclose(float(m1["loss_sum"]), float(m0["loss_sum"]), rtol=1e-5)
    assert int(m1["ntokens"]) == int(m0["ntokens"])


def test_mhc_doubly_stochastic(model):
    """The Sinkhorn-projected comb matrix must be (approximately) doubly
    stochastic — the mHC manifold constraint."""
    from veomni_tpu.models.deepseek_v4 import _hyper_connection

    cfg, params = model
    rng = np.random.default_rng(4)
    streams = jnp.asarray(rng.standard_normal((2, 8, cfg.hc_mult, cfg.hidden_size)),
                          jnp.float32)
    lp_hc = jax.tree.map(lambda x: x[0], params["runs"][0]["attn_hc"])
    post, comb, collapsed = _hyper_connection(lp_hc, cfg, streams)
    rows = np.asarray(comb.sum(-1))
    cols = np.asarray(comb.sum(-2))
    np.testing.assert_allclose(rows, 1.0, atol=5e-3)
    np.testing.assert_allclose(cols, 1.0, atol=5e-3)
    assert post.shape == (2, 8, cfg.hc_mult)
    assert collapsed.shape == (2, 8, cfg.hidden_size)


def test_hash_router_uses_frozen_table(model):
    """Hash-MoE expert selection must follow tid2eid exactly (selection is
    static; only the mixing weights are learned)."""
    from veomni_tpu.models.deepseek_v4 import _dsv4_moe

    cfg, params = model
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((6, cfg.hidden_size)), jnp.float32)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, 6), jnp.int32)
    lp = jax.tree.map(lambda a: a[0], params["runs"][0]["mlp"])

    out1, _ = _dsv4_moe(lp, cfg, x, ids, "hash_moe")
    # permuting the frozen table for the used ids changes the output
    tbl = np.asarray(lp["tid2eid"])
    tbl2 = tbl.copy()
    tbl2[np.asarray(ids)] = (tbl2[np.asarray(ids)] + 1) % cfg.num_experts
    lp2 = dict(lp, tid2eid=jnp.asarray(tbl2))
    out2, _ = _dsv4_moe(lp2, cfg, x, ids, "hash_moe")
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_sliding_window_layer_masks(model):
    """A pure-sliding config must not attend beyond the window: moving a
    distant token (outside every window + no compressed path) must leave the
    last-token logits unchanged."""
    cfg0 = dict(CFG)
    cfg0.update(layer_types=("sliding_attention",) * 3,
                mlp_layer_types=("topk_moe",) * 3, sliding_window=4)
    cfg = DeepseekV4Config(**cfg0)
    params = init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(6)
    s = 16
    ids = rng.integers(1, cfg.vocab_size, s).astype(np.int32)
    ids2 = ids.copy()
    ids2[0] = (ids2[0] + 1) % cfg.vocab_size or 1
    pos = jnp.arange(s, dtype=jnp.int32)[None]

    l1 = forward_logits(params, cfg, jnp.asarray(ids)[None], pos)
    l2 = forward_logits(params, cfg, jnp.asarray(ids2)[None], pos)
    np.testing.assert_allclose(
        np.asarray(l1[0, -1]), np.asarray(l2[0, -1]), atol=1e-5
    )
    # sanity: within the window, changing a token does change the logits
    ids3 = ids.copy()
    ids3[-2] = (ids3[-2] + 1) % cfg.vocab_size or 1
    l3 = forward_logits(params, cfg, jnp.asarray(ids3)[None], pos)
    assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l3[0, -1]), atol=1e-5)


def test_hca_reaches_beyond_window(model):
    """An HCA layer must carry long-range signal: with sliding_window=4 and
    one HCA layer, changing a token in a *completed compression window* far
    outside the sliding window must change the last-token logits."""
    cfg0 = dict(CFG)
    cfg0.update(layer_types=("heavily_compressed_attention",),
                mlp_layer_types=("topk_moe",), num_hidden_layers=1,
                sliding_window=4, compress_rate_hca=4)
    cfg = DeepseekV4Config(**cfg0)
    params = init_params(jax.random.PRNGKey(2), cfg)
    rng = np.random.default_rng(7)
    s = 24
    ids = rng.integers(1, cfg.vocab_size, s).astype(np.int32)
    ids2 = ids.copy()
    ids2[1] = (ids2[1] + 1) % cfg.vocab_size or 1  # inside window 0 (complete)
    pos = jnp.arange(s, dtype=jnp.int32)[None]
    l1 = forward_logits(params, cfg, jnp.asarray(ids)[None], pos)
    l2 = forward_logits(params, cfg, jnp.asarray(ids2)[None], pos)
    assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]), atol=1e-6)


def test_registry_and_hf_roundtrip(model, tmp_path):
    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.models.auto import MODEL_REGISTRY

    cfg, params = model
    fam = MODEL_REGISTRY.get("deepseek_v4")
    out = tmp_path / "hf"
    fam.save_hf_checkpoint(params, cfg, str(out))

    m2 = build_foundation_model(str(out))
    assert m2.config.model_type == "deepseek_v4"
    assert m2.config.layer_types == cfg.layer_types
    p2 = m2.load_hf(str(out))
    flat_a = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(params)}
    flat_b = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(p2)}
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(
            np.asarray(flat_a[k]), np.asarray(flat_b[k]), err_msg=k
        )
