"""LTX-2 AV DiT: structural self-tests (reference ltx_core transformer; no
torch oracle in this environment — ltx_core isn't installed)."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veomni_tpu.models.ltx2 import (
    LTX2Config, hf_to_params, init_params, loss_fn, ltx2_forward, params_to_hf,
)

TINY = dict(
    num_attention_heads=2,
    attention_head_dim=24,   # rope ladder 24/(2*3)=4 freqs per axis
    in_channels=8,
    out_channels=8,
    num_layers=2,
    cross_attention_dim=48,
    caption_channels=32,
    with_audio=True,
    audio_num_attention_heads=2,
    audio_attention_head_dim=12,
    audio_in_channels=6,
    audio_out_channels=6,
    video_shape=(2, 4, 4),
    audio_len=8,
    dtype=jnp.float32,
    param_dtype=jnp.float32,
    remat=False,
)


@pytest.fixture(scope="module")
def model():
    cfg = LTX2Config(**TINY)
    params = init_params(jax.random.PRNGKey(0), cfg)
    # zero-init gates would freeze the attention contributions; nudge the
    # scale-shift tables so every pathway is live for the probes
    rng = np.random.default_rng(0)
    for k in ("scale_shift_table", "audio_scale_shift_table",
              "scale_shift_table_a2v_ca_video", "scale_shift_table_a2v_ca_audio"):
        params["blocks"][k] = jnp.asarray(
            rng.standard_normal(params["blocks"][k].shape) * 0.3, jnp.float32
        )
    return cfg, params


def _inputs(cfg, rng):
    nv = int(np.prod(cfg.video_shape))
    v = jnp.asarray(rng.standard_normal((2, nv, cfg.in_channels)), jnp.float32)
    a = jnp.asarray(rng.standard_normal((2, cfg.audio_len, cfg.audio_in_channels)),
                    jnp.float32)
    t = jnp.asarray([0.3, 0.8], jnp.float32)
    text = jnp.asarray(rng.standard_normal((2, 5, cfg.caption_channels)), jnp.float32)
    return v, a, t, text


def test_forward_shapes_and_conditioning(model):
    cfg, params = model
    rng = np.random.default_rng(1)
    v, a, t, text = _inputs(cfg, rng)
    vo, ao = ltx2_forward(params, cfg, v, t, text, audio_latents=a)
    assert vo.shape == (2, v.shape[1], cfg.out_channels)
    assert ao.shape == (2, cfg.audio_len, cfg.audio_out_channels)
    # timestep / text conditioning are live
    vo2, _ = ltx2_forward(params, cfg, v, t * 0.1, text, audio_latents=a)
    assert np.abs(np.asarray(vo) - np.asarray(vo2)).max() > 1e-6
    vo3, _ = ltx2_forward(params, cfg, v, t, text * -1.0, audio_latents=a)
    assert np.abs(np.asarray(vo) - np.asarray(vo3)).max() > 1e-6


def test_av_cross_coupling(model):
    """Audio must influence the video prediction (and vice versa) through
    the gated A/V cross attention."""
    cfg, params = model
    rng = np.random.default_rng(2)
    v, a, t, text = _inputs(cfg, rng)
    vo, ao = ltx2_forward(params, cfg, v, t, text, audio_latents=a)
    vo2, ao2 = ltx2_forward(params, cfg, v, t, text, audio_latents=a * -1.0)
    assert np.abs(np.asarray(vo) - np.asarray(vo2)).max() > 1e-7
    vo3, ao3 = ltx2_forward(params, cfg, v * -1.0, t, text, audio_latents=a)
    assert np.abs(np.asarray(ao) - np.asarray(ao3)).max() > 1e-7


def test_video_only_config(model):
    cfg0 = dict(TINY, with_audio=False)
    cfg = LTX2Config(**cfg0)
    params = init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(3)
    nv = int(np.prod(cfg.video_shape))
    v = jnp.asarray(rng.standard_normal((1, nv, cfg.in_channels)), jnp.float32)
    text = jnp.asarray(rng.standard_normal((1, 4, cfg.caption_channels)), jnp.float32)
    vo, ao = ltx2_forward(params, cfg, v, jnp.asarray([0.5]), text)
    assert vo.shape == (1, nv, cfg.out_channels) and ao is None
    assert "audio_attn1" not in params["blocks"]


def test_loss_and_grads(model):
    cfg, params = model
    rng = np.random.default_rng(4)
    v, a, t, text = _inputs(cfg, rng)
    batch = {
        "latents": v, "timestep": t * 1000.0, "text_states": text,
        "text_mask": jnp.ones((2, 5), jnp.int32),
        "target": jnp.asarray(rng.standard_normal(v.shape), jnp.float32),
        "audio_latents": a,
        "audio_target": jnp.asarray(rng.standard_normal(a.shape), jnp.float32),
    }
    total, metrics = loss_fn(params, cfg, batch)
    assert np.isfinite(float(total))
    grads = jax.grad(lambda p: loss_fn(p, cfg, batch)[0])(params)
    # both streams and the A/V cross projections receive signal
    for key in ("patchify_proj", "audio_patchify_proj"):
        assert float(jnp.abs(grads[key]).sum()) > 0.0
    assert float(jnp.abs(grads["blocks"]["audio_to_video_attn"]["to_q"]).sum()) > 0.0


def test_hf_roundtrip(model, tmp_path):
    from safetensors.numpy import save_file

    cfg, params = model
    sd = params_to_hf(params, cfg)
    assert "transformer_blocks.0.audio_to_video_attn.to_q.weight" in sd
    assert "adaln_single.emb.timestep_embedder.linear_1.weight" in sd
    save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
              str(tmp_path / "model.safetensors"))
    loaded = hf_to_params(str(tmp_path), cfg)
    flat_a = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(params)}
    flat_b = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(loaded)}
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(
            np.asarray(flat_a[k]), np.asarray(flat_b[k]), err_msg=k
        )


def test_dit_trainer_e2e(tmp_path):
    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer.dit_trainer import DiTTrainer

    rng = np.random.default_rng(0)
    nv = int(np.prod(TINY["video_shape"]))
    rows = []
    for _ in range(12):
        rows.append({
            "latents": rng.standard_normal((nv, TINY["in_channels"])).tolist(),
            "text_states": rng.standard_normal((5, TINY["caption_channels"])).tolist(),
            "audio_latents": rng.standard_normal(
                (TINY["audio_len"], TINY["audio_in_channels"])).tolist(),
        })
    with open(tmp_path / "data.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "ltx2", **TINY,
        "dtype": "float32", "param_dtype": "float32",
        "latent_shape": (nv, TINY["in_channels"]), "text_len": 8,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 2
    args.train.bf16 = False
    args.train.async_save = False
    args.train.log_steps = 100
    destroy_parallel_state()
    try:
        trainer = DiTTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 2
        assert np.isfinite(ctl.metrics["loss"])
        trainer.checkpointer.close()
    finally:
        destroy_parallel_state()
