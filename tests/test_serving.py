"""Continuous-batching engine: paging, scheduling, and token parity.

The load-bearing guarantee is **greedy parity**: the engine serving N
staggered requests over the paged cache must emit exactly the tokens
``models/decode.py::greedy_generate`` produces for each request in
isolation — including across recompute preemption — while the jitted
decode step compiles a bounded (bucket-count) number of times regardless
of how many requests flow through.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.models import decode as decode_mod
from veomni_tpu.models.decode import greedy_generate
from veomni_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    KVBlockManager,
    Request,
    SamplingParams,
    Scheduler,
    SequenceState,
)

QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)
# sinks + alternating sliding windows: covers the paged attend's window
# masking and sink softmax-denominator math
GPT_OSS_ISH = dict(
    model_type="gpt_oss", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=4, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, attention_sinks=True,
    attention_bias=True, o_bias=True, sliding_window=8,
    layer_types=["sliding_attention", "full_attention"] * 2,
    hidden_act="gpt_oss_glu",
)
QWEN3_MOE = dict(
    model_type="qwen3_moe", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True, num_experts=4,
    num_experts_per_tok=2, moe_intermediate_size=32,
)


@pytest.fixture(scope="module")
def qwen3():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


def _prompts(lengths, seed=0, vocab=128):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, vocab, n)] for n in lengths]


# --------------------------------------------------------------- block manager
def test_block_manager_alloc_grow_free():
    bm = KVBlockManager(num_blocks=6, block_size=4)
    assert bm.num_free == 5  # block 0 reserved as the null block
    assert bm.blocks_for(1) == 1 and bm.blocks_for(4) == 1
    assert bm.blocks_for(5) == 2
    t = bm.allocate("a", 2)
    assert len(t) == 2 and KVBlockManager.NULL_BLOCK not in t
    assert bm.num_allocated("a") == 2 and bm.num_free == 3
    bm.grow("a")
    assert bm.num_allocated("a") == 3
    assert bm.utilization() == pytest.approx(3 / 5)
    with pytest.raises(ValueError):
        bm.allocate("a", 1)  # double-allocate
    assert bm.free_seq("a") == 3
    assert bm.num_free == 5 and bm.free_seq("a") == 0  # idempotent
    with pytest.raises(ValueError):
        KVBlockManager(num_blocks=8, block_size=6)  # not a power of two


def test_block_manager_exhaustion():
    bm = KVBlockManager(num_blocks=4, block_size=4)
    bm.allocate("a", 3)
    assert not bm.can_allocate(1)
    with pytest.raises(RuntimeError):
        bm.grow("a")
    with pytest.raises(RuntimeError):
        bm.allocate("b", 1)
    bm.free_seq("a")
    assert bm.can_allocate(3)


# ------------------------------------------------------------------- scheduler
def _seq(rid, n_prompt):
    return SequenceState(
        request=Request(prompt_ids=list(range(1, n_prompt + 1)),
                        request_id=rid)
    )


def test_scheduler_fifo_head_of_line_and_self_preempt():
    bm = KVBlockManager(num_blocks=4, block_size=4)  # 3 usable
    sched = Scheduler(2, bm)
    a, b = _seq("a", 8), _seq("b", 4)
    sched.add(a)
    sched.add(b)
    assert [s.seq_id for s in sched.admit()] == ["a"]  # idle: no headroom
    # b needs 1+1 (headroom) but only 1 block is free -> head-of-line blocked
    assert sched.admit() == []
    a.pos = 8  # crosses into block 3
    assert sched.ensure_decode_capacity() == []
    assert bm.num_allocated("a") == 3
    a.pos = 12  # needs a 4th block: pool dry, a is the only victim
    preempted = sched.ensure_decode_capacity()
    assert preempted == [a] and a.slot == -1 and a.preemptions == 1
    # recompute requeue lands at the FRONT (FIFO order preserved)
    assert [s.seq_id for s in sched.waiting] == ["a", "b"]
    assert bm.num_free == 3


def test_scheduler_lifo_preemption():
    bm = KVBlockManager(num_blocks=5, block_size=4)  # 4 usable
    sched = Scheduler(2, bm)
    a, b = _seq("a", 4), _seq("b", 4)
    sched.add(a)
    sched.add(b)
    assert len(sched.admit()) == 2
    a.pos, b.pos = 4, 4
    sched.ensure_decode_capacity()  # both grow; pool now dry
    a.pos = 8
    preempted = sched.ensure_decode_capacity()
    # a needed a block; the LATEST admission (b) is the victim
    assert preempted == [b] and b.slot == -1
    assert bm.num_allocated("a") == 3
    assert sched.waiting[0] is b


# ---------------------------------------------------------------- engine parity
def test_engine_greedy_parity_staggered(qwen3):
    """The acceptance gate: staggered arrivals through 2 slots, outputs
    token-identical to isolated generation; TTFT + finish metadata set."""
    params, cfg = qwen3
    prompts = _prompts((5, 9, 17, 12), seed=0)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=6)))
           for p in prompts[:2]]
    events = []
    for _ in range(2):  # let the first wave start decoding, then add load
        events += eng.step()
    ids += [eng.submit(Request(prompt_ids=p,
                               sampling=SamplingParams(max_new_tokens=6)))
            for p in prompts[2:]]
    for ev in eng.generate():
        events.append(ev)
    outs = eng.run()
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert outs[rid].token_ids == want, (rid, outs[rid].token_ids, want)
        assert outs[rid].finished and outs[rid].finish_reason == "length"
        assert outs[rid].ttft_s is not None and outs[rid].ttft_s >= 0
    # the event stream carries every token exactly once, in order
    for rid in ids:
        stream = [ev.token for ev in events if ev.request_id == rid]
        assert stream == outs[rid].token_ids
        assert [ev for ev in events if ev.request_id == rid][-1].finished


def test_engine_decode_trace_count_bounded(qwen3):
    """Compile count of the batched decode step is bounded by the
    block-table-width buckets (<= log2), NOT by the number of requests in a
    mixed-length stream."""
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    base = dict(decode_mod.TRACE_COUNTS)
    first = _prompts((5, 9, 17, 21, 33, 7), seed=3)
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=5))
             for p in first])
    delta = decode_mod.TRACE_COUNTS["paged_decode"] - base["paged_decode"]
    # max_model_len 64 / block 8 -> table-width buckets {1,2,4,8}
    assert 1 <= delta <= 4, delta
    # doubling the request count with lengths inside the same buckets must
    # not add a single compile
    mid = dict(decode_mod.TRACE_COUNTS)
    more = _prompts((6, 10, 18, 22, 34, 8, 12, 30), seed=4)
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=5))
             for p in more])
    assert decode_mod.TRACE_COUNTS["paged_decode"] == mid["paged_decode"]


def test_engine_preemption_recompute_parity(qwen3):
    """A pool too small for the full load forces preemption; recompute must
    resume every greedy stream exactly."""
    params, cfg = qwen3
    prompts = _prompts((9, 11, 7), seed=1)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, num_blocks=8,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=10)))
           for p in prompts]
    outs = eng.run()
    assert eng.scheduler.preemption_count > 0
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=10)[len(p):]
        assert outs[rid].token_ids == want
    # every block returned to the pool at the end
    assert eng.blocks.num_used == 0


def test_engine_per_slot_sampling(qwen3):
    """One batch mixing greedy and sampled requests: the greedy stream is
    unaffected by its batch-mates; the sampled stream is reproducible per
    seed and changes with the seed."""
    params, cfg = qwen3
    prompts = _prompts((9, 11), seed=2)

    def run(seed):
        eng = InferenceEngine(params, cfg, EngineConfig(
            num_slots=2, block_size=8, max_model_len=64,
        ))
        g = eng.submit(Request(prompt_ids=prompts[0],
                               sampling=SamplingParams(max_new_tokens=8)))
        s = eng.submit(Request(
            prompt_ids=prompts[1],
            sampling=SamplingParams(temperature=0.8, top_k=10, top_p=0.9,
                                    max_new_tokens=8, seed=seed),
        ))
        outs = eng.run()
        return outs[g].token_ids, outs[s].token_ids

    g1, s1 = run(7)
    g2, s2 = run(7)
    _, s3 = run(8)
    want = greedy_generate(params, cfg, prompts[0],
                           max_new_tokens=8)[len(prompts[0]):]
    assert g1 == g2 == want
    assert s1 == s2  # per-seed reproducible
    assert s1 != s3  # seed actually threads through
    assert all(0 <= t < cfg.vocab_size for t in s1)


def test_engine_eos_and_validation(qwen3):
    params, cfg = qwen3
    prompt = _prompts((9,), seed=5)[0]
    full = greedy_generate(params, cfg, prompt, max_new_tokens=8)[len(prompt):]
    eos = full[3]  # force an early stop on a token greedy actually emits
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    rid = eng.submit(Request(prompt_ids=prompt, sampling=SamplingParams(
        max_new_tokens=8, eos_id=eos,
    )))
    out = eng.run()[rid]
    assert out.finish_reason == "eos"
    assert out.token_ids == full[: full.index(eos) + 1]
    with pytest.raises(ValueError):
        eng.submit(Request(prompt_ids=[], sampling=SamplingParams()))
    with pytest.raises(ValueError):  # prompt + max_new over max_model_len
        eng.submit(Request(prompt_ids=prompt,
                           sampling=SamplingParams(max_new_tokens=64)))
    with pytest.raises(ValueError):  # unsupported dialect fails fast
        InferenceEngine(params, TransformerConfig(
            model_type="deepseek_v3", vocab_size=64, hidden_size=64,
            num_hidden_layers=1, num_attention_heads=4, kv_lora_rank=16,
            qk_nope_head_dim=8, qk_rope_head_dim=8, v_head_dim=8,
        ))


@pytest.mark.parametrize("spec", ["gpt_oss_ish", "qwen3_moe"])
def test_engine_dialect_parity(spec):
    """Paged decode matches isolated decode on the dialect extremes: learned
    sinks + alternating sliding windows, and MoE MLP segments."""
    conf = {"gpt_oss_ish": GPT_OSS_ISH, "qwen3_moe": QWEN3_MOE}[spec]
    cfg = TransformerConfig(dtype=jnp.float32, **conf)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    prompts = _prompts((9, 13), seed=6)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=6)))
           for p in prompts]
    outs = eng.run()
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert outs[rid].token_ids == want


# --------------------------------------------------------------------- metrics
def test_engine_metrics_are_host_floats(qwen3):
    from veomni_tpu.trainer.callbacks import WandbCallback
    from veomni_tpu.utils.helper import host_floats

    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    eng.run([Request(prompt_ids=_prompts((9,), seed=7)[0],
                     sampling=SamplingParams(max_new_tokens=4))])
    m = eng.metrics()
    assert m and all(isinstance(v, (int, float)) for v in m.values())
    assert 0.0 <= m["block_utilization"] <= 1.0
    assert m["generated_tokens"] == 4.0
    assert m["ttft_avg_s"] > 0 and m["queue_depth"] == 0.0
    # the filter is the SHARED util (WandbCallback delegates to it): device
    # futures are dropped, host scalars pass
    mixed = dict(m, device_val=jnp.ones(()))
    assert "device_val" not in host_floats(mixed)
    assert WandbCallback._host_floats(mixed) == host_floats(mixed)
