"""Continuous-batching engine: paging, scheduling, and token parity.

The load-bearing guarantee is **greedy parity**: the engine serving N
staggered requests over the paged cache must emit exactly the tokens
``models/decode.py::greedy_generate`` produces for each request in
isolation — including across recompute preemption — while the jitted
decode step compiles a bounded (bucket-count) number of times regardless
of how many requests flow through.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.models import decode as decode_mod
from veomni_tpu.models.decode import greedy_generate
from veomni_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    KVBlockManager,
    PrefixCache,
    Request,
    SamplingParams,
    Scheduler,
    SequenceState,
)

QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)
# sinks + alternating sliding windows: covers the paged attend's window
# masking and sink softmax-denominator math
GPT_OSS_ISH = dict(
    model_type="gpt_oss", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=4, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, attention_sinks=True,
    attention_bias=True, o_bias=True, sliding_window=8,
    layer_types=["sliding_attention", "full_attention"] * 2,
    hidden_act="gpt_oss_glu",
)
QWEN3_MOE = dict(
    model_type="qwen3_moe", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True, num_experts=4,
    num_experts_per_tok=2, moe_intermediate_size=32,
)


@pytest.fixture(scope="module")
def qwen3():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


def _prompts(lengths, seed=0, vocab=128):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, vocab, n)] for n in lengths]


# --------------------------------------------------------------- block manager
def test_block_manager_alloc_grow_free():
    bm = KVBlockManager(num_blocks=6, block_size=4)
    assert bm.num_free == 5  # block 0 reserved as the null block
    assert bm.blocks_for(1) == 1 and bm.blocks_for(4) == 1
    assert bm.blocks_for(5) == 2
    t = bm.allocate("a", 2)
    assert len(t) == 2 and KVBlockManager.NULL_BLOCK not in t
    assert bm.num_allocated("a") == 2 and bm.num_free == 3
    bm.grow("a")
    assert bm.num_allocated("a") == 3
    assert bm.utilization() == pytest.approx(3 / 5)
    with pytest.raises(ValueError):
        bm.allocate("a", 1)  # double-allocate
    assert bm.free_seq("a") == 3
    assert bm.num_free == 5 and bm.free_seq("a") == 0  # idempotent
    with pytest.raises(ValueError):
        KVBlockManager(num_blocks=8, block_size=6)  # not a power of two


def test_block_manager_exhaustion():
    bm = KVBlockManager(num_blocks=4, block_size=4)
    bm.allocate("a", 3)
    assert not bm.can_allocate(1)
    with pytest.raises(RuntimeError):
        bm.grow("a")
    with pytest.raises(RuntimeError):
        bm.allocate("b", 1)
    bm.free_seq("a")
    assert bm.can_allocate(3)


def test_block_manager_unknown_seq_errors_are_actionable():
    """grow()/table() on an unknown sequence name the sequence and the
    valid transition instead of a bare KeyError (satellite bugfix)."""
    bm = KVBlockManager(num_blocks=6, block_size=4)
    bm.allocate("a", 1)
    with pytest.raises(KeyError, match=r"ghost.*grow\(\) is only valid"):
        bm.grow("ghost")
    with pytest.raises(KeyError, match=r"ghost.*table\(\) is only valid"):
        bm.table("ghost")
    # the message lists what IS allocated, so the operator can see the typo
    with pytest.raises(KeyError, match=r"'a'"):
        bm.table("ghost")


def test_block_manager_refcounts_shared_and_cow():
    """Shared allocation refcounts blocks; free_seq releases references,
    not blocks; the copy-on-write source is pinned through allocation."""
    bm = KVBlockManager(num_blocks=8, block_size=4)
    t_a, fresh_a = bm.allocate_shared("a", [], 3)
    assert t_a == fresh_a and all(bm.refcount(b) == 1 for b in t_a)
    # b shares a's first two blocks and adds one of its own
    t_b, fresh_b = bm.allocate_shared("b", t_a[:2], 1)
    assert t_b[:2] == t_a[:2] and len(fresh_b) == 1
    assert bm.refcount(t_a[0]) == 2 and bm.refcount(t_a[2]) == 1
    assert bm.num_used == 4  # 3 + 1 shared-suffix block
    bm.free_seq("a")
    # shared blocks survive a's release (b still references them); a's
    # exclusive third block is back on the free list (no cache attached)
    assert bm.refcount(t_a[0]) == 1 and bm.refcount(t_a[2]) == 0
    assert bm.num_used == 3
    # CoW: the pinned source keeps a reference until released
    t_c, fresh_c = bm.allocate_shared("c", t_b[:1], 1, cow_src=t_b[1])
    assert bm.cow_count == 1 and bm.refcount(t_b[1]) == 2
    bm.release_block(t_b[1])
    assert bm.refcount(t_b[1]) == 1  # b's own reference remains
    bm.free_seq("b")
    bm.free_seq("c")
    assert bm.num_used == 0 and bm.num_free == 7


def test_prefix_cache_match_insert_refcount_gated_eviction():
    bm = KVBlockManager(num_blocks=10, block_size=2)
    cache = PrefixCache(bm)
    toks = [1, 2, 3, 4, 5, 6, 7]  # 3 full blocks + 1 partial token
    table, _ = bm.allocate_shared("a", [], 4)
    assert cache.match(toks) == []  # cold
    assert cache.insert(toks[:6], table[:3]) == 3  # full blocks only
    assert cache.match(toks) == table[:3]
    assert cache.match([1, 2, 3, 99]) == table[:1]  # divergence mid-stream
    assert cache.match([9, 9, 9, 9]) == []
    # a still references everything -> nothing evictable
    assert cache.num_evictable() == 0 and bm.num_free == 5
    bm.free_seq("a")
    # refcounts dropped to 0: cached blocks are warm AND count as free
    assert cache.num_evictable() == 3 and bm.num_free == 9
    assert bm.num_used == 0
    # eviction is leaf-first (deepest block goes first), LRU-ordered
    assert cache.evict_lru() == table[2]
    assert cache.match(toks) == table[:2]
    # a match bumps LRU recency but refcount-0 blocks stay evictable
    assert cache.num_evictable() == 2
    # re-referencing a cached block removes it from the evictable set
    bm.allocate_shared("b", table[:1], 0)
    assert cache.num_evictable() == 1
    assert cache.evict_lru() == table[1]  # only the unreferenced leaf
    assert cache.evict_lru() is None  # table[0] is referenced by b
    bm.free_seq("b")
    assert cache.evict_lru() == table[0]
    assert len(cache) == 0


def test_block_manager_pool_pressure_evicts_before_exhaustion():
    """free ∪ evictable: allocation reclaims refcount-0 cached blocks LRU
    instead of failing (the engine-level counterpart: eviction before any
    preemption fires)."""
    bm = KVBlockManager(num_blocks=6, block_size=2)
    cache = PrefixCache(bm)
    table, _ = bm.allocate_shared("a", [], 3)
    cache.insert([1, 2, 3, 4, 5, 6], table)
    bm.free_seq("a")
    assert bm.num_free == 5 and bm.num_free_uncached == 2
    # needs 4 blocks: 2 free + 2 evicted from the cache (leaf-first)
    t_b, _ = bm.allocate_shared("b", [], 4)
    assert len(t_b) == 4 and bm.evictions == 2
    assert cache.match([1, 2, 3, 4, 5, 6]) == table[:1]  # root survived
    with pytest.raises(RuntimeError, match="out of KV blocks"):
        bm.grow("b", 2)  # 1 evictable + 0 free < 2


# ------------------------------------------------------------------- scheduler
def _seq(rid, n_prompt):
    return SequenceState(
        request=Request(prompt_ids=list(range(1, n_prompt + 1)),
                        request_id=rid)
    )


def test_scheduler_fifo_head_of_line_and_self_preempt():
    bm = KVBlockManager(num_blocks=4, block_size=4)  # 3 usable
    sched = Scheduler(2, bm)
    a, b = _seq("a", 8), _seq("b", 4)
    sched.add(a)
    sched.add(b)
    assert [s.seq_id for s in sched.admit()] == ["a"]  # idle: no headroom
    # b needs 1+1 (headroom) but only 1 block is free -> head-of-line blocked
    assert sched.admit() == []
    a.prefilling = False  # engine contract: prefill completed
    a.pos = 8  # crosses into block 3
    assert sched.ensure_decode_capacity() == []
    assert bm.num_allocated("a") == 3
    a.pos = 12  # needs a 4th block: pool dry, a is the only victim
    preempted = sched.ensure_decode_capacity()
    assert preempted == [a] and a.slot == -1 and a.preemptions == 1
    # recompute requeue lands at the FRONT (FIFO order preserved)
    assert [s.seq_id for s in sched.waiting] == ["a", "b"]
    assert bm.num_free == 3


def test_scheduler_lifo_preemption():
    bm = KVBlockManager(num_blocks=5, block_size=4)  # 4 usable
    sched = Scheduler(2, bm)
    a, b = _seq("a", 4), _seq("b", 4)
    sched.add(a)
    sched.add(b)
    assert len(sched.admit()) == 2
    a.prefilling = b.prefilling = False  # engine contract: prefill completed
    a.pos, b.pos = 4, 4
    sched.ensure_decode_capacity()  # both grow; pool now dry
    a.pos = 8
    preempted = sched.ensure_decode_capacity()
    # a needed a block; the LATEST admission (b) is the victim
    assert preempted == [b] and b.slot == -1
    assert bm.num_allocated("a") == 3
    assert sched.waiting[0] is b


# ---------------------------------------------------------------- engine parity
def test_engine_greedy_parity_staggered(qwen3):
    """The acceptance gate: staggered arrivals through 2 slots, outputs
    token-identical to isolated generation; TTFT + finish metadata set."""
    params, cfg = qwen3
    prompts = _prompts((5, 9, 17, 12), seed=0)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=6)))
           for p in prompts[:2]]
    events = []
    for _ in range(2):  # let the first wave start decoding, then add load
        events += eng.step()
    ids += [eng.submit(Request(prompt_ids=p,
                               sampling=SamplingParams(max_new_tokens=6)))
            for p in prompts[2:]]
    for ev in eng.generate():
        events.append(ev)
    outs = eng.run()
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert outs[rid].token_ids == want, (rid, outs[rid].token_ids, want)
        assert outs[rid].finished and outs[rid].finish_reason == "length"
        assert outs[rid].ttft_s is not None and outs[rid].ttft_s >= 0
    # the event stream carries every token exactly once, in order
    for rid in ids:
        stream = [ev.token for ev in events if ev.request_id == rid]
        assert stream == outs[rid].token_ids
        assert [ev for ev in events if ev.request_id == rid][-1].finished


def test_engine_decode_trace_count_bounded(qwen3):
    """Compile count of the batched decode step is bounded by the
    block-table-width buckets (<= log2), NOT by the number of requests in a
    mixed-length stream."""
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    base = dict(decode_mod.TRACE_COUNTS)
    first = _prompts((5, 9, 17, 21, 33, 7), seed=3)
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=5))
             for p in first])
    delta = decode_mod.TRACE_COUNTS["paged_decode"] - base["paged_decode"]
    # max_model_len 64 / block 8 -> table-width buckets {1,2,4,8}
    assert 1 <= delta <= 4, delta
    # doubling the request count with lengths inside the same buckets must
    # not add a single compile
    mid = dict(decode_mod.TRACE_COUNTS)
    more = _prompts((6, 10, 18, 22, 34, 8, 12, 30), seed=4)
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=5))
             for p in more])
    assert decode_mod.TRACE_COUNTS["paged_decode"] == mid["paged_decode"]


def test_engine_preemption_recompute_parity(qwen3):
    """A pool too small for the full load forces preemption; recompute must
    resume every greedy stream exactly."""
    params, cfg = qwen3
    prompts = _prompts((9, 11, 7), seed=1)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, num_blocks=8,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=10)))
           for p in prompts]
    outs = eng.run()
    assert eng.scheduler.preemption_count > 0
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=10)[len(p):]
        assert outs[rid].token_ids == want
    # every block returned to the pool at the end
    assert eng.blocks.num_used == 0


def test_engine_per_slot_sampling(qwen3):
    """One batch mixing greedy and sampled requests: the greedy stream is
    unaffected by its batch-mates; the sampled stream is reproducible per
    seed and changes with the seed."""
    params, cfg = qwen3
    prompts = _prompts((9, 11), seed=2)

    def run(seed):
        eng = InferenceEngine(params, cfg, EngineConfig(
            num_slots=2, block_size=8, max_model_len=64,
        ))
        g = eng.submit(Request(prompt_ids=prompts[0],
                               sampling=SamplingParams(max_new_tokens=8)))
        s = eng.submit(Request(
            prompt_ids=prompts[1],
            sampling=SamplingParams(temperature=0.8, top_k=10, top_p=0.9,
                                    max_new_tokens=8, seed=seed),
        ))
        outs = eng.run()
        return outs[g].token_ids, outs[s].token_ids

    g1, s1 = run(7)
    g2, s2 = run(7)
    _, s3 = run(8)
    want = greedy_generate(params, cfg, prompts[0],
                           max_new_tokens=8)[len(prompts[0]):]
    assert g1 == g2 == want
    assert s1 == s2  # per-seed reproducible
    assert s1 != s3  # seed actually threads through
    assert all(0 <= t < cfg.vocab_size for t in s1)


def test_engine_eos_and_validation(qwen3):
    params, cfg = qwen3
    prompt = _prompts((9,), seed=5)[0]
    full = greedy_generate(params, cfg, prompt, max_new_tokens=8)[len(prompt):]
    eos = full[3]  # force an early stop on a token greedy actually emits
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    rid = eng.submit(Request(prompt_ids=prompt, sampling=SamplingParams(
        max_new_tokens=8, eos_id=eos,
    )))
    out = eng.run()[rid]
    assert out.finish_reason == "eos"
    assert out.token_ids == full[: full.index(eos) + 1]
    with pytest.raises(ValueError):
        eng.submit(Request(prompt_ids=[], sampling=SamplingParams()))
    with pytest.raises(ValueError):  # prompt + max_new over max_model_len
        eng.submit(Request(prompt_ids=prompt,
                           sampling=SamplingParams(max_new_tokens=64)))
    with pytest.raises(ValueError):  # unsupported dialect fails fast
        InferenceEngine(params, TransformerConfig(
            model_type="deepseek_v3", vocab_size=64, hidden_size=64,
            num_hidden_layers=1, num_attention_heads=4, kv_lora_rank=16,
            qk_nope_head_dim=8, qk_rope_head_dim=8, v_head_dim=8,
        ))


@pytest.mark.parametrize("spec", ["gpt_oss_ish", "qwen3_moe"])
def test_engine_dialect_parity(spec):
    """Paged decode matches isolated decode on the dialect extremes: learned
    sinks + alternating sliding windows, and MoE MLP segments."""
    conf = {"gpt_oss_ish": GPT_OSS_ISH, "qwen3_moe": QWEN3_MOE}[spec]
    cfg = TransformerConfig(dtype=jnp.float32, **conf)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    prompts = _prompts((9, 13), seed=6)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=6)))
           for p in prompts]
    outs = eng.run()
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert outs[rid].token_ids == want


def test_scheduler_admission_headroom_excludes_matched_blocks():
    """Regression: matched cached blocks leave the evictable set the moment
    admission references them, so they must not double-count as claimable
    headroom — a fully-cached tight pool head-of-line waits cleanly instead
    of exploding inside allocate_shared."""
    bm = KVBlockManager(num_blocks=6, block_size=4)  # 5 usable
    cache = PrefixCache(bm)
    sched = Scheduler(2, bm, prefix_cache=cache)
    r = _seq("r", 8)  # running seq holds 2 blocks
    sched.add(r)
    assert sched.admit() == [r]
    toks = list(range(100, 112))  # 12 tokens = 3 full blocks
    table, _ = bm.allocate_shared("x", [], 3)
    cache.insert(toks, table)
    bm.free_seq("x")  # 3 cached evictable, free list empty
    y = SequenceState(request=Request(prompt_ids=toks, request_id="y"))
    sched.add(y)
    # full-match CoW admission needs 1 fresh block + 1 headroom, but every
    # "free" block is a matched block about to be pinned -> must WAIT
    assert sched.admit() == []
    assert sched.waiting[0] is y and bm.cow_count == 0
    sched.finish(r)  # releases 2 uncached blocks to the free list
    admitted = sched.admit()
    assert admitted == [y] and bm.cow_count == 1
    assert y.cow_src == table[2] and y.cached_tokens == 11  # P-1
    assert bm.refcount(y.cow_src) == 1  # pinned until the engine's copy


# ----------------------------------------------------- prefix cache + chunking
def test_engine_shared_prefix_parity_and_hit_rate(qwen3):
    """Staggered arrivals sharing a common system prompt, cache ON +
    chunked prefill ON: token-exact greedy parity, and later arrivals are
    admitted against cached prompt blocks (charged only the suffix)."""
    params, cfg = qwen3
    rng = np.random.default_rng(11)
    system = [int(t) for t in rng.integers(1, cfg.vocab_size, 19)]
    prompts = [system + [int(t) for t in rng.integers(1, cfg.vocab_size, n)]
               for n in (5, 9, 2, 13)]
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=96,
        prefix_cache=True, prefill_chunk=8,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=6)))
           for p in prompts[:2]]
    for _ in range(3):  # let the first wave cache its prompt blocks
        eng.step()
    ids += [eng.submit(Request(prompt_ids=p,
                               sampling=SamplingParams(max_new_tokens=6)))
            for p in prompts[2:]]
    outs = eng.run()
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert outs[rid].token_ids == want, (rid, outs[rid].token_ids, want)
    # the late arrivals hit the cached 19-token system prompt: two full
    # 8-token blocks of it are shared, never recomputed
    assert all(outs[r].cached_tokens >= 16 for r in ids[2:]), [
        outs[r].cached_tokens for r in ids
    ]
    m = eng.metrics()
    assert m["prefix_hit_rate"] > 0 and m["cached_tokens"] >= 32
    assert m["prefill_chunks"] > 0


def test_engine_cow_divergence_mid_block_parity(qwen3):
    """Copy-on-write matrix: an exact block-aligned replay of a cached
    prompt (full match -> CoW the divergence block, recompute only the last
    token) and a prompt diverging mid-block both stay token-exact, and the
    shared cached block is never corrupted for a third replay."""
    params, cfg = qwen3
    rng = np.random.default_rng(12)
    base = [int(t) for t in rng.integers(1, cfg.vocab_size, 16)]  # 2 blocks
    diverged = base[:12] + [int(t) for t in rng.integers(1, 128, 4)]
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64, prefix_cache=True,
    ))
    r1 = eng.submit(Request(prompt_ids=base,
                            sampling=SamplingParams(max_new_tokens=5)))
    eng.run()
    assert eng.blocks.cow_count == 0
    # exact replay: both blocks cached -> CoW on block 2, 1-token prefill
    r2 = eng.submit(Request(prompt_ids=base,
                            sampling=SamplingParams(max_new_tokens=5)))
    # mid-block divergence: block 1 shared, block 2 recomputed fresh
    r3 = eng.submit(Request(prompt_ids=diverged,
                            sampling=SamplingParams(max_new_tokens=5)))
    outs = eng.run()
    assert eng.blocks.cow_count == 1
    assert outs[r2].cached_tokens == 15  # P-1: everything but the last token
    assert outs[r3].cached_tokens == 8  # the shared first block only
    # a third replay still matches the ORIGINAL cached blocks (the CoW
    # write landed in a private copy, not the shared block)
    r4 = eng.submit(Request(prompt_ids=base,
                            sampling=SamplingParams(max_new_tokens=5)))
    outs4 = eng.run()
    for rid, p, o in ((r2, base, outs[r2]), (r3, diverged, outs[r3]),
                      (r4, base, outs4[r4])):
        want = greedy_generate(params, cfg, p, max_new_tokens=5)[len(p):]
        assert o.token_ids == want, (rid, o.token_ids, want)


def test_engine_preemption_cached_readmission(qwen3):
    """A preempted sequence's blocks stay cached: re-admission matches them
    and recomputes only the tail instead of the whole recompute prompt —
    while parity holds exactly."""
    params, cfg = qwen3
    prompts = _prompts((9, 11, 7), seed=13)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, num_blocks=8,
        prefix_cache=True,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=10)))
           for p in prompts]
    outs = eng.run()
    assert eng.scheduler.preemption_count > 0
    # at least one re-admission was a cache hit (the preempted sequence's
    # own blocks) — the LIFO-recompute cost collapsed to the uncached tail
    assert eng._cached_tokens_total > 0
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=10)[len(p):]
        assert outs[rid].token_ids == want
    assert eng.blocks.num_used == 0


def test_engine_eviction_reclaims_cache_before_preemption(qwen3):
    """Pool pressure: refcount-0 cached blocks are evicted LRU to satisfy
    admissions/growth BEFORE any running sequence is preempted."""
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=1, block_size=8, max_model_len=40, num_blocks=6,
        prefix_cache=True,
    ))
    prompts = _prompts((17, 19, 18), seed=14)
    for p in prompts:  # sequential: each run leaves its blocks cached
        eng.run([Request(prompt_ids=p,
                         sampling=SamplingParams(max_new_tokens=8))])
    assert eng.blocks.evictions > 0  # dry free list was refilled by LRU
    assert eng.scheduler.preemption_count == 0  # ... never by preemption
    assert eng.blocks.num_used == 0


def test_engine_cache_off_matches_seed_behavior(qwen3):
    """prefix_cache=False restores the pre-cache engine: exclusive blocks,
    monolithic prefill, zero cache accounting, all blocks truly freed."""
    params, cfg = qwen3
    prompts = _prompts((9, 9), seed=15)  # identical prompts: maximal overlap
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64, prefix_cache=False,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=6)))
           for p in prompts]
    outs = eng.run()
    assert eng.prefix_cache is None
    m = eng.metrics()
    assert m["prefix_hit_rate"] == 0 and m["cached_tokens"] == 0
    assert all(outs[r].cached_tokens == 0 for r in ids)
    assert eng.blocks.num_cached == 0
    assert eng.blocks.num_free_uncached == eng.config.num_blocks - 1
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert outs[rid].token_ids == want


def test_engine_chunked_prefill_interleaves_decode(qwen3):
    """A long prompt arriving mid-stream no longer stalls a running
    request: with prefill_chunk set, the running sequence keeps emitting a
    token on ticks where the new arrival is still prefilling chunks."""
    params, cfg = qwen3
    short, long = _prompts((5, 60), seed=16)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=128,
        prefix_cache=True, prefill_chunk=16,
    ))
    a = eng.submit(Request(prompt_ids=short,
                           sampling=SamplingParams(max_new_tokens=20)))
    eng.step()  # a prefilled + first token
    b = eng.submit(Request(prompt_ids=long,
                           sampling=SamplingParams(max_new_tokens=4)))
    # 60 tokens / 16-chunk = 4 chunk ticks; a must produce a token on each
    interleaved = 0
    while not eng._outputs[b].token_ids:
        got_a = any(ev.request_id == a for ev in eng.step())
        if not eng._outputs[b].token_ids:
            interleaved += got_a
    assert interleaved >= 3, interleaved
    outs = eng.run()
    for rid, p, n in ((a, short, 20), (b, long, 4)):
        want = greedy_generate(params, cfg, p, max_new_tokens=n)[len(p):]
        assert outs[rid].token_ids == want


def test_engine_prefill_trace_count_bounded(qwen3):
    """Compile-count gate for the chunked-prefill path: TRACE_COUNTS
    ["paged_prefill"] is bounded by (chunk bucket x table-width bucket),
    never per-request or per-chunk-position, across staggered arrivals and
    a preemption storm."""
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=128,
        prefix_cache=True, prefill_chunk=16,
    ))
    base = dict(decode_mod.TRACE_COUNTS)
    first = _prompts((5, 21, 40, 60, 33, 9), seed=17)
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=4))
             for p in first])
    delta = decode_mod.TRACE_COUNTS["paged_prefill"] - base["paged_prefill"]
    # chunk buckets {16} + final-chunk remainders {16} x table-width
    # buckets {1,2,4,8,16}: comfortably O(log2 x log2), never O(requests)
    assert 1 <= delta <= 10, delta
    # doubling the request count inside the same buckets adds ZERO compiles
    mid = dict(decode_mod.TRACE_COUNTS)
    more = _prompts((6, 22, 41, 61, 34, 10, 50, 13), seed=18)
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=4))
             for p in more])
    assert decode_mod.TRACE_COUNTS["paged_prefill"] == mid["paged_prefill"]
    # a preemption storm (tiny pool) re-admits through the SAME buckets
    eng2 = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, num_blocks=8,
        prefix_cache=True, prefill_chunk=16,
    ))
    pre = dict(decode_mod.TRACE_COUNTS)
    eng2.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=10))
              for p in _prompts((9, 11, 7), seed=19)])
    assert eng2.scheduler.preemption_count > 0
    storm = decode_mod.TRACE_COUNTS["paged_prefill"] - pre["paged_prefill"]
    assert storm <= 6, storm  # bucket-bounded, not per-(re)admission


def test_engine_no_block_leaks_after_drain(qwen3):
    """After run() drains: every non-cached block is on the free list,
    every cached block's refcount is 0, and the accounting identity
    free + cached == pool holds."""
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefix_cache=True, prefill_chunk=8,
    ))
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=6))
             for p in _prompts((5, 9, 17, 12), seed=20)])
    bm = eng.blocks
    assert bm.num_used == 0
    assert bm.num_free_uncached + bm.num_cached == bm.num_blocks - 1
    cache = eng.prefix_cache
    assert all(bm.refcount(b) == 0 for b in cache._by_block)
    assert cache.num_evictable() == len(cache)


@pytest.mark.parametrize("spec", ["gpt_oss_ish", "qwen3_moe"])
def test_engine_dialect_parity_cached_chunked(spec):
    """The dialect extremes (sinks + alternating sliding windows, MoE MLP
    segments) through the chunked-prefill + prefix-cache path: shared
    prompts, cache hits, still token-exact."""
    conf = {"gpt_oss_ish": GPT_OSS_ISH, "qwen3_moe": QWEN3_MOE}[spec]
    cfg = TransformerConfig(dtype=jnp.float32, **conf)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(21)
    system = [int(t) for t in rng.integers(1, cfg.vocab_size, 17)]
    prompts = [system + [int(t) for t in rng.integers(1, cfg.vocab_size, n)]
               for n in (5, 9)]
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefix_cache=True, prefill_chunk=8,
    ))
    ids, outs = [], {}
    for p in prompts:  # sequential drains so the second hits the cache
        ids.append(eng.submit(Request(
            prompt_ids=p, sampling=SamplingParams(max_new_tokens=6))))
        outs.update(eng.run())
    assert outs[ids[1]].cached_tokens >= 16
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert outs[rid].token_ids == want


# --------------------------------------------------------------------- metrics
def test_engine_metrics_are_host_floats(qwen3):
    from veomni_tpu.trainer.callbacks import WandbCallback
    from veomni_tpu.utils.helper import host_floats

    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    eng.run([Request(prompt_ids=_prompts((9,), seed=7)[0],
                     sampling=SamplingParams(max_new_tokens=4))])
    m = eng.metrics()
    assert m and all(isinstance(v, (int, float)) for v in m.values())
    assert 0.0 <= m["block_utilization"] <= 1.0
    assert m["generated_tokens"] == 4.0
    assert m["ttft_avg_s"] > 0 and m["queue_depth"] == 0.0
    # the filter is the SHARED util (WandbCallback delegates to it): device
    # futures are dropped, host scalars pass
    mixed = dict(m, device_val=jnp.ones(()))
    assert "device_val" not in host_floats(mixed)
    assert WandbCallback._host_floats(mixed) == host_floats(mixed)


def test_engine_ttft_is_window_scoped(qwen3):
    """Satellite bugfix: ttft_avg_s resets with the metrics window like
    decode_tokens_per_sec; the lifetime average lives under its own key."""
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    prompt = _prompts((9,), seed=22)[0]
    eng.run([Request(prompt_ids=prompt,
                     sampling=SamplingParams(max_new_tokens=4))])
    m1 = eng.metrics()  # resets the window
    assert m1["ttft_avg_s"] > 0
    assert m1["ttft_avg_lifetime_s"] == pytest.approx(m1["ttft_avg_s"])
    m2 = eng.metrics()  # fresh window: no TTFT observed since the reset
    assert "ttft_avg_s" not in m2
    assert m2["ttft_avg_lifetime_s"] == pytest.approx(
        m1["ttft_avg_lifetime_s"])
    # a peek must not clobber the window another consumer owns
    eng.run([Request(prompt_ids=_prompts((5,), seed=23)[0],
                     sampling=SamplingParams(max_new_tokens=4))])
    peek = eng.metrics(reset_window=False)
    assert peek["ttft_avg_s"] > 0
    again = eng.metrics()
    assert again["ttft_avg_s"] == pytest.approx(peek["ttft_avg_s"])
