"""VLM: ViT forward, feature merge, e2e VLM training on a CPU mesh.

Reference tests: vlm model-patch tests + ``tests/train_scripts/train_vlm_test.py``.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest


VISION = dict(image_size=28, patch_size=7, hidden_size=32, intermediate_size=64,
              num_hidden_layers=2, num_attention_heads=2, spatial_merge_size=2)
TEXT = dict(model_type="qwen2", vocab_size=512, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, attention_bias=True)


def _vlm_config():
    from veomni_tpu.models.auto import build_config

    return build_config("slot_vlm", text=dict(TEXT, dtype=jnp.float32),
                        vision=VISION, image_token_id=500)


def test_vit_shapes():
    from veomni_tpu.models.vision import ViTConfig, init_vit_params, vit_forward

    cfg = ViTConfig(**VISION, out_hidden_size=64)
    params = init_vit_params(jax.random.PRNGKey(0), cfg)
    patches = jnp.ones((3, cfg.grid ** 2, cfg.num_channels * cfg.patch_size ** 2))
    feats = vit_forward(params, cfg, patches)
    assert feats.shape == (3, cfg.tokens_per_image, 64)


def test_feature_merge_positions():
    from veomni_tpu.models.vlm import merge_image_features

    b, s, h, t_img = 1, 10, 4, 2
    embeds = jnp.zeros((b, s, h))
    ids = jnp.array([[1, 500, 500, 2, 500, 500, 3, 4, 5, 6]])
    feats = jnp.arange(b * 2 * t_img * h, dtype=jnp.float32).reshape(b, 2, t_img, h)
    mask = jnp.array([[True, True]])
    out = merge_image_features(embeds, ids, feats, mask, 500)
    np.testing.assert_allclose(np.asarray(out[0, 1]), np.asarray(feats[0, 0, 0]))
    np.testing.assert_allclose(np.asarray(out[0, 5]), np.asarray(feats[0, 1, 1]))
    np.testing.assert_allclose(np.asarray(out[0, 0]), np.zeros(h))  # text untouched


def test_vlm_loss_and_grads():
    from veomni_tpu.models import build_foundation_model

    cfg = _vlm_config()
    model = build_foundation_model(config=cfg)
    params = model.init(jax.random.PRNGKey(0))
    vcfg = cfg.vision
    t_img = vcfg.tokens_per_image
    s = 32
    ids = np.full((2, s), 7, np.int32)
    ids[:, :t_img] = 500  # one image leading each row
    batch = {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(ids),
        "position_ids": jnp.broadcast_to(jnp.arange(s), (2, s)),
        "segment_ids": jnp.ones((2, s), jnp.int32),
        "pixel_patches": jnp.ones(
            (2, 1, vcfg.grid ** 2, vcfg.num_channels * vcfg.patch_size ** 2), jnp.float32
        ),
        "image_mask": jnp.ones((2, 1), bool),
    }
    loss, metrics = jax.jit(model.loss_fn)(params, batch)
    assert np.isfinite(float(loss))
    g = jax.grad(lambda p: model.loss_fn(p, batch)[0])(params)
    assert float(jnp.abs(g["vision_tower"]["patch_embed"]).sum()) > 0


def test_vlm_trainer_e2e(tmp_path):
    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.trainer.vlm_trainer import VLMTrainer

    rng = np.random.default_rng(0)
    rows = []
    for _ in range(64):
        n_img = int(rng.integers(0, 3))
        rows.append({
            "input_ids": rng.integers(0, 499, int(rng.integers(10, 40))).tolist(),
            "images": [rng.random((28, 28, 3)).tolist() for _ in range(n_img)],
        })
    with open(tmp_path / "data.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "slot_vlm", "text": dict(TEXT), "vision": dict(VISION),
        "image_token_id": 500,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.max_seq_len = 128
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    trainer = VLMTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert (tmp_path / "out" / "hf_ckpt" / "model.safetensors").exists()
    trainer.checkpointer.close()
