"""Pallas grouped-matmul numerics vs the XLA ragged_dot reference (interpret
mode on CPU), forward + backward, incl. empty groups and boundary tiles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.ops.group_gemm import _group_gemm_ragged
from veomni_tpu.ops.pallas.grouped_gemm import pallas_group_gemm


def _inputs(m=512, k=128, n=256, e=4, sizes=None, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2)
    lhs = jax.random.normal(ks[0], (m, k), jnp.float32)
    rhs = jax.random.normal(ks[1], (e, k, n), jnp.float32)
    if sizes is None:
        sizes = [m // e] * e
    assert sum(sizes) == m
    return lhs, rhs, jnp.asarray(sizes, jnp.int32)


@pytest.mark.parametrize("sizes", [
    None,                       # even groups (tile-aligned)
    [100, 156, 0, 256],         # boundary-crossing + empty group
    [512, 0, 0, 0],             # everything in one expert
], ids=["even", "ragged", "single"])
def test_gmm_forward_matches_ragged(sizes):
    lhs, rhs, gs = _inputs(sizes=sizes)
    ref = _group_gemm_ragged(lhs, rhs, gs)
    got = pallas_group_gemm(lhs, rhs, gs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_gmm_backward_matches_ragged():
    lhs, rhs, gs = _inputs(sizes=[100, 156, 0, 256])

    def loss_p(lhs, rhs):
        return (pallas_group_gemm(lhs, rhs, gs) ** 2).sum()

    def loss_r(lhs, rhs):
        return (_group_gemm_ragged(lhs, rhs, gs) ** 2).sum()

    gp = jax.grad(loss_p, argnums=(0, 1))(lhs, rhs)
    gr = jax.grad(loss_r, argnums=(0, 1))(lhs, rhs)
    np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(gr[0]), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gp[1]), np.asarray(gr[1]), rtol=2e-4, atol=2e-4)


def test_gmm_fallback_unaligned():
    lhs, rhs, gs = _inputs(m=200, k=64, n=96, e=4, sizes=[50, 50, 50, 50])
    ref = _group_gemm_ragged(lhs, rhs, gs)
    got = pallas_group_gemm(lhs, rhs, gs)  # falls back to ragged path
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)
