"""Speculative decoding (draft-then-verify) on the paged serving engine.

The load-bearing guarantee is unchanged from the non-speculative engine:
**token-exact parity** — greedy AND seeded sampling — with isolated
``greedy_generate`` and with the one-token engine, across staggered
arrivals, preemption/recompute, prefix-cache hits, and the dialect
extremes. Speculation may only change *when* tokens land (several per
verify tick), never *which* tokens. On top of that: the verify program's
compile count is bucket-bounded, speculative block claims roll back without
leaking (or freeing anything shared), and the request tracer's TPOT stays
correct when one tick emits many tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.models import decode as decode_mod
from veomni_tpu.models.decode import greedy_generate
from veomni_tpu.ops.kernel_registry import KERNEL_REGISTRY
from veomni_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    KVBlockManager,
    PrefixCache,
    Request,
    SamplingParams,
    Scheduler,
    SequenceState,
)
from veomni_tpu.serving.spec_decode import (
    draft_ngram,
    draft_off,
    resolve_draft_fn,
)

QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)
GPT_OSS_ISH = dict(
    model_type="gpt_oss", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=4, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, attention_sinks=True,
    attention_bias=True, o_bias=True, sliding_window=8,
    layer_types=["sliding_attention", "full_attention"] * 2,
    hidden_act="gpt_oss_glu",
)
QWEN3_MOE = dict(
    model_type="qwen3_moe", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True, num_experts=4,
    num_experts_per_tok=2, moe_intermediate_size=32,
)


@pytest.fixture(scope="module")
def qwen3():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


def _prompts(lengths, seed=0, vocab=128):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, vocab, n)] for n in lengths]


def _loopy_prompts(lengths, seed=0, vocab=128, period=8):
    """Prompts whose tail n-grams recur (a repeated block), so the ngram
    prompt-lookup drafter actually proposes continuations."""
    rng = np.random.default_rng(seed)
    base = [int(t) for t in rng.integers(1, vocab, period)]
    out = []
    for n in lengths:
        reps = base * (n // period + 2)
        uniq = [int(t) for t in rng.integers(1, vocab, 2)]
        out.append((reps[: max(0, n - 2)] + uniq)[:n])
    return out


class _registered_draft:
    """Register a throwaway spec_draft impl for one test, cleanly removed
    afterwards (the registry is process-global)."""

    def __init__(self, name, fn):
        self.name, self.fn = name, fn

    def __enter__(self):
        KERNEL_REGISTRY.register("spec_draft", self.name)(self.fn)
        return self.name

    def __exit__(self, *exc):
        KERNEL_REGISTRY._ops["spec_draft"].pop(self.name, None)
        KERNEL_REGISTRY.resolve.cache_clear()


# ------------------------------------------------------------------ drafting
def test_draft_ngram_prompt_lookup():
    # tail [7,8] recurred earlier; the most recent occurrence is followed
    # by [9, 1] — that continuation is the proposal
    ctx = [1, 2, 7, 8, 3, 4, 7, 8, 9, 1, 5, 7, 8]
    assert draft_ngram(ctx, 4) == [9, 1, 5, 7]
    assert draft_ngram(ctx, 2) == [9, 1]  # k caps the proposal
    # no recurrence of any tail n-gram -> no proposal (slot degrades to 0)
    assert draft_ngram([1, 2, 3, 4, 5], 4) == []
    assert draft_ngram([1, 2], 0) == []
    assert draft_ngram([], 4) == []
    # the trivial strategy never proposes
    assert draft_off(ctx, 4) == []
    # a 1-token context can't have an earlier occurrence
    assert draft_ngram([5], 4) == []


def test_draft_ngram_prefers_longest_match():
    # the 2-gram tail [7, 8] matches at position 1 (-> 9 follows); the
    # 1-gram tail [8] ALSO matches at position 5 (-> 6 follows): the longer
    # n-gram wins because it is more specific
    ctx = [1, 7, 8, 9, 2, 8, 6, 7, 8]
    assert draft_ngram(ctx, 1) == [9]


def test_resolve_draft_fn_validates_and_honors_pin():
    assert resolve_draft_fn("ngram") is draft_ngram
    assert resolve_draft_fn("off") is draft_off
    with pytest.raises(ValueError, match="unknown spec_draft"):
        resolve_draft_fn("nope")
    # an ops-config pin outranks the engine knob (ulysses-dispatch rules)
    KERNEL_REGISTRY.pin("spec_draft", "off")
    try:
        assert resolve_draft_fn("ngram") is draft_off
    finally:
        KERNEL_REGISTRY.clear_pins()


# ------------------------------------------------- block manager / scheduler
def test_block_manager_shrink_rollback():
    bm = KVBlockManager(num_blocks=8, block_size=4)
    t = bm.allocate("a", 2)
    grown = bm.grow("a", 3)  # returns the full 5-entry table
    released = bm.shrink("a", 2)
    assert released == list(reversed(grown[2:]))  # tail first
    assert bm.table("a") == t
    assert bm.num_free == 5
    assert bm.shrink("a", 2) == []  # idempotent at the target
    assert bm.shrink("a", 99) == []  # never grows
    with pytest.raises(ValueError):
        bm.shrink("a", 0)  # a live sequence keeps >= 1 block
    with pytest.raises(KeyError, match="ghost"):
        bm.shrink("ghost", 1)


def test_block_manager_shrink_never_strands_shared_blocks():
    """A trailing block shared with another sequence (or cached) survives
    one sequence's rollback: shrink drops a REFERENCE, not the block."""
    bm = KVBlockManager(num_blocks=8, block_size=4)
    cache = PrefixCache(bm)
    t_a, _ = bm.allocate_shared("a", [], 3)
    t_b, _ = bm.allocate_shared("b", t_a, 0)  # b shares all of a's blocks
    released = bm.shrink("a", 1)
    assert released == [t_a[2], t_a[1]]
    # b still references them: NOT freed, refcount simply dropped to 1
    assert bm.refcount(t_a[1]) == 1 and bm.refcount(t_a[2]) == 1
    assert bm.num_free == 4  # nothing actually returned to the pool
    bm.free_seq("b")
    assert bm.num_free == 6  # now they are
    # cached (refcount-0-bound) trailing block: rollback re-enters it into
    # the evictable set via the cache, not the raw free list
    t_c, _ = bm.allocate_shared("c", [], 2)
    cache.insert(list(range(100, 108)), t_c)
    bm.shrink("c", 1)
    assert cache.has_block(t_c[1]) and cache.num_evictable() == 1
    assert bm.num_free_uncached + bm.num_cached == bm.num_free


def test_scheduler_claim_speculative_degrades_never_preempts():
    bm = KVBlockManager(num_blocks=6, block_size=4)  # 5 usable
    sched = Scheduler(2, bm)
    a = SequenceState(request=Request(prompt_ids=list(range(1, 9)),
                                      request_id="a"))
    b = SequenceState(request=Request(prompt_ids=list(range(1, 9)),
                                      request_id="b"))
    sched.add(a)
    sched.add(b)
    assert len(sched.admit()) == 2  # 2 blocks each, 1 free
    a.prefilling = b.prefilling = False
    a.pos = b.pos = 8
    # a wants 4 drafted positions = cover position 12 -> needs block 4, but
    # only ONE block is free: k degrades to what the claimed coverage holds
    k, claimed = sched.claim_speculative(a, 4)
    assert len(claimed) == 1 and k == 3  # coverage [0,12): pos 8 + 3 drafts
    assert bm.num_free == 0
    # the pool is dry: b's claim degrades all the way to 0 — NO preemption
    k_b, claimed_b = sched.claim_speculative(b, 4)
    assert (k_b, claimed_b) == (0, []) and sched.preemption_count == 0
    # rollback returns a's claim; b can then claim it
    bm.shrink("a", 2)
    assert sched.claim_speculative(b, 2)[0] > 0


def test_spec_admission_headroom_accounts_for_k_growth():
    """With speculation on, admission keeps ceil(spec_k/bs) extra blocks
    free per tick so a fresh admission doesn't starve every claim."""

    def build(spec_headroom):
        bm = KVBlockManager(num_blocks=8, block_size=4)  # 7 usable
        bm.allocate("x", 1)  # 6 free
        sched = Scheduler(2, bm, spec_headroom_blocks=spec_headroom)
        a = SequenceState(request=Request(prompt_ids=list(range(1, 9)),
                                          request_id="a"))
        b = SequenceState(request=Request(prompt_ids=list(range(1, 13)),
                                          request_id="b"))
        sched.add(a)
        sched.add(b)
        return bm, sched, a, b

    # WITHOUT spec headroom both admit in one pass: a (idle, no headroom,
    # 2 blocks), then b (3 blocks + 1 base headroom = 4 <= 4 free)
    _, sched0, a0, b0 = build(0)
    assert sched0.admit() == [a0, b0]
    # WITH one spec-headroom block b must wait: 3 + (1 + 1) = 5 > 4 free
    bm, sched, a, b = build(1)
    assert sched.admit() == [a]
    assert sched.admit() == []  # still head-of-line blocked on headroom
    bm.free_seq("x")  # one more free block covers the spec headroom
    assert sched.admit() == [b]


def test_spec_enabled_honors_registry_pin(qwen3):
    """The ops-config pin outranks the engine knob for the ON/OFF decision
    too: a pinned `off` releases the admission headroom and the per-tick
    draft calls, a pinned strategy enables speculation over spec_draft=
    'off' (spec_k still gates)."""
    params, cfg = qwen3
    ec = dict(num_slots=1, block_size=8, max_model_len=64)
    KERNEL_REGISTRY.pin("spec_draft", "off")
    try:
        eng = InferenceEngine(params, cfg, EngineConfig(spec_k=4, **ec))
        assert not eng._spec_enabled
        assert eng.scheduler.spec_headroom_blocks == 0
        assert eng._verify_step is None
    finally:
        KERNEL_REGISTRY.clear_pins()
    KERNEL_REGISTRY.pin("spec_draft", "ngram")
    try:
        eng = InferenceEngine(params, cfg, EngineConfig(
            spec_k=4, spec_draft="off", **ec))
        assert eng._spec_enabled and eng._draft_fn is draft_ngram
    finally:
        KERNEL_REGISTRY.clear_pins()


# ------------------------------------------------------------- engine parity
def test_spec_engine_greedy_parity_staggered(qwen3):
    """The acceptance gate: staggered arrivals through a spec_k=4 engine
    emit exactly the tokens isolated generation produces — and on a
    loopy-prompt workload the drafter actually gets tokens accepted."""
    params, cfg = qwen3
    prompts = _loopy_prompts((21, 17, 26, 19), seed=0)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=96, spec_k=4,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=8)))
           for p in prompts[:2]]
    events = []
    for _ in range(2):
        events += eng.step()
    ids += [eng.submit(Request(prompt_ids=p,
                               sampling=SamplingParams(max_new_tokens=8)))
            for p in prompts[2:]]
    for ev in eng.generate():
        events.append(ev)
    outs = eng.run()
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=8)[len(p):]
        assert outs[rid].token_ids == want, (rid, outs[rid].token_ids, want)
        assert outs[rid].finished
    # the event stream carries every token exactly once, in order, even
    # when one verify tick emitted several
    for rid in ids:
        stream = [ev.token for ev in events if ev.request_id == rid]
        assert stream == outs[rid].token_ids
        idxs = [ev.index for ev in events if ev.request_id == rid]
        assert idxs == list(range(len(stream)))
    # speculation did something: drafts were proposed AND accepted
    m = eng.metrics()
    assert m["spec_proposed"] > 0 and m["spec_accepted"] > 0
    assert sum(outs[r].spec_accepted_tokens for r in ids) == int(
        m["spec_accepted"]
    )


def test_spec_engine_sampled_parity_vs_nonspec(qwen3):
    """Seeded sampling through forced verify steps is token-identical to
    the one-token engine: the verify path replays the exact per-token PRNG
    key schedule, so even 100%-rejected drafts change nothing."""
    params, cfg = qwen3

    def junk(context, k):
        # deterministic junk: forces real verify steps with ~zero
        # acceptance, the worst case for parity
        return [(int(context[-1]) + 37 + i) % 127 + 1 for i in range(k)]

    prompts = _prompts((9, 13, 7), seed=1)
    sampling = SamplingParams(temperature=0.8, top_k=20, top_p=0.9,
                              max_new_tokens=9, seed=5)

    def run(spec_k, draft="ngram"):
        eng = InferenceEngine(params, cfg, EngineConfig(
            num_slots=2, block_size=8, max_model_len=64,
            spec_k=spec_k, spec_draft=draft,
        ))
        ids = [eng.submit(Request(prompt_ids=list(p), sampling=sampling))
               for p in prompts]
        outs = eng.run()
        return [outs[r].token_ids for r in ids], eng

    base, _ = run(0)
    with _registered_draft("__test_junk", junk) as name:
        spec, eng = run(3, name)
        assert eng.metrics()["spec_proposed"] > 0  # verify really ran
    assert spec == base


@pytest.mark.parametrize("spec", ["gpt_oss_ish", "qwen3_moe"])
def test_spec_dialect_parity(spec):
    """Verify-step parity on the dialect extremes: learned sinks +
    alternating sliding windows (the verify rows must window-mask per
    position exactly like single-token decode), and MoE MLP segments."""
    conf = {"gpt_oss_ish": GPT_OSS_ISH, "qwen3_moe": QWEN3_MOE}[spec]
    cfg = TransformerConfig(dtype=jnp.float32, **conf)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    prompts = _loopy_prompts((17, 21), seed=6)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64, spec_k=3,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=6)))
           for p in prompts]
    outs = eng.run()
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert outs[rid].token_ids == want, (rid, outs[rid].token_ids, want)


def test_spec_preemption_recompute_parity(qwen3):
    """A pool too small for the full load forces preemption mid-speculation;
    recompute must resume every greedy stream exactly, and drafted-block
    rollback must leave no block behind."""
    params, cfg = qwen3
    prompts = _loopy_prompts((9, 11, 7), seed=7)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, num_blocks=8,
        spec_k=3,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=10)))
           for p in prompts]
    outs = eng.run()
    assert eng.scheduler.preemption_count > 0
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=10)[len(p):]
        assert outs[rid].token_ids == want
    assert eng.blocks.num_used == 0


def test_spec_prefix_cache_parity_and_hits(qwen3):
    """Speculation composes with the prefix cache + chunked prefill: shared
    system prompts still hit, and the combined path stays token-exact."""
    params, cfg = qwen3
    rng = np.random.default_rng(11)
    system = [int(t) for t in rng.integers(1, cfg.vocab_size, 19)]
    prompts = [system + [int(t) for t in rng.integers(1, cfg.vocab_size, n)]
               for n in (5, 9, 2, 13)]
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=96,
        prefix_cache=True, prefill_chunk=8, spec_k=4,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=6)))
           for p in prompts[:2]]
    for _ in range(3):
        eng.step()
    ids += [eng.submit(Request(prompt_ids=p,
                               sampling=SamplingParams(max_new_tokens=6)))
            for p in prompts[2:]]
    outs = eng.run()
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert outs[rid].token_ids == want, (rid, outs[rid].token_ids, want)
    assert all(outs[r].cached_tokens >= 16 for r in ids[2:])


def test_spec_cow_replay_parity(qwen3):
    """Exact block-aligned replay of a cached prompt: the full-match CoW
    admission (recompute only the last token into a copied divergence
    block) composes with speculative decode ticks, token-exact, and the
    shared cached blocks survive rollback untouched."""
    params, cfg = qwen3
    rng = np.random.default_rng(14)
    base = [int(t) for t in rng.integers(1, cfg.vocab_size, 8)] * 2
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
        prefix_cache=True, spec_k=4,
    ))
    r1 = eng.submit(Request(prompt_ids=list(base),
                            sampling=SamplingParams(max_new_tokens=5)))
    eng.run()
    r2 = eng.submit(Request(prompt_ids=list(base),
                            sampling=SamplingParams(max_new_tokens=5)))
    outs = eng.run()
    assert eng.blocks.cow_count == 1
    assert outs[r2].cached_tokens == 15  # P-1: all but the last token
    want = greedy_generate(params, cfg, base, max_new_tokens=5)[len(base):]
    assert outs[r2].token_ids == want
    # a third replay still matches the ORIGINAL cached blocks
    r3 = eng.submit(Request(prompt_ids=list(base),
                            sampling=SamplingParams(max_new_tokens=5)))
    assert eng.run()[r3].token_ids == want
    bm = eng.blocks
    assert bm.num_used == 0
    assert bm.num_free_uncached + bm.num_cached == bm.num_blocks - 1


def test_spec_k0_path_byte_identical(qwen3):
    """spec_k=0 (the default) IS the PR 9 engine: the verify program is
    never built, never traced, and outputs are identical — same for an
    explicit spec_draft='off' with k > 0."""
    params, cfg = qwen3
    prompts = _loopy_prompts((9, 13), seed=8)

    def run(**kw):
        eng = InferenceEngine(params, cfg, EngineConfig(
            num_slots=2, block_size=8, max_model_len=64, **kw,
        ))
        ids = [eng.submit(Request(prompt_ids=list(p),
                                  sampling=SamplingParams(max_new_tokens=6)))
               for p in prompts]
        outs = eng.run()
        return [outs[r].token_ids for r in ids], eng

    before = decode_mod.TRACE_COUNTS["paged_verify"]
    base, eng0 = run()
    off, eng_off = run(spec_k=4, spec_draft="off")
    assert decode_mod.TRACE_COUNTS["paged_verify"] == before
    assert eng0._verify_step is None and eng_off._verify_step is None
    assert not eng0._spec_enabled and not eng_off._spec_enabled
    assert base == off
    assert eng0.metrics()["spec_proposed"] == 0.0
    spec, _ = run(spec_k=4)
    assert spec == base  # and the speculative path agrees token-for-token


def test_spec_verify_trace_count_bounded(qwen3):
    """Compile-count gate: TRACE_COUNTS["paged_verify"] is bounded by
    (verify-width bucket x table-width bucket), never per-request — across
    staggered arrivals, and a same-bucket re-run adds ZERO compiles, and a
    preemption storm re-admits through the SAME buckets."""
    params, cfg = qwen3
    # cache OFF so a re-run of the identical batch replays the exact same
    # tick/draft trajectory (with the cache on, warm prompt blocks change
    # admissions — and bucket SEQUENCES — between runs)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64, spec_k=4,
        prefix_cache=False,
    ))
    base = dict(decode_mod.TRACE_COUNTS)
    first = _loopy_prompts((5, 21, 40, 33, 9, 14), seed=17)
    batch = lambda: [Request(prompt_ids=p,
                             sampling=SamplingParams(max_new_tokens=6))
                     for p in first]
    eng.run(batch())
    delta = decode_mod.TRACE_COUNTS["paged_verify"] - base["paged_verify"]
    # verify-width buckets {2,4,8} x table-width buckets {1,2,4,8}:
    # O(log2 k x log2 width), never O(requests)
    assert 1 <= delta <= 12, delta
    # the SAME request set again: same buckets, ZERO new compiles
    mid = dict(decode_mod.TRACE_COUNTS)
    eng.run(batch())
    assert decode_mod.TRACE_COUNTS["paged_verify"] == mid["paged_verify"]
    assert decode_mod.TRACE_COUNTS["paged_decode"] == mid["paged_decode"]
    # more requests with lengths inside the same prompt buckets: the
    # verify-bucket PRODUCT space stays the cumulative bound — compile
    # count tracks buckets, never request count
    more = _loopy_prompts((6, 22, 41, 34, 10, 15, 28, 13), seed=18)
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=6))
             for p in more])
    total = decode_mod.TRACE_COUNTS["paged_verify"] - base["paged_verify"]
    assert total <= 12, total
    # preemption storm (tiny pool): rollback/recompute stays in-bucket
    eng2 = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, num_blocks=8,
        spec_k=3,
    ))
    pre = dict(decode_mod.TRACE_COUNTS)
    # per-prompt repetition (drafting stays active) but NO cross-request
    # sharing: the prefix cache must not absorb the pool pressure the
    # storm needs
    storm = [_loopy_prompts((n,), seed=40 + n)[0] for n in (9, 11, 7)]
    eng2.run([Request(prompt_ids=p,
                      sampling=SamplingParams(max_new_tokens=10))
              for p in storm])
    assert eng2.scheduler.preemption_count > 0
    storm = decode_mod.TRACE_COUNTS["paged_verify"] - pre["paged_verify"]
    assert storm <= 8, storm


def test_spec_no_block_leak_after_rollback(qwen3):
    """The accounting identity free_uncached + cached == pool holds after
    a run whose every verify tick rejected drafts (maximal rollback),
    including rejection mid-shared-block — and at no point does a block
    referenced by one sequence sit on the free list."""
    params, cfg = qwen3

    def junk(context, k):
        return [(int(context[-1]) + 53 + i) % 127 + 1 for i in range(k)]

    rng = np.random.default_rng(21)
    system = [int(t) for t in rng.integers(1, cfg.vocab_size, 16)]
    prompts = [system + [int(t) for t in rng.integers(1, cfg.vocab_size, n)]
               for n in (3, 5, 9)]
    with _registered_draft("__test_junk_leak", junk) as name:
        eng = InferenceEngine(params, cfg, EngineConfig(
            num_slots=2, block_size=8, max_model_len=96,
            prefix_cache=True, prefill_chunk=8, spec_k=4, spec_draft=name,
        ))
        for p in prompts:
            eng.submit(Request(prompt_ids=p,
                               sampling=SamplingParams(max_new_tokens=6)))
        bm = eng.blocks
        while eng.has_work:
            eng.step()
            free = set(bm._free)
            for sid in list(bm._tables):
                for b in bm._tables[sid]:
                    assert b not in free, (sid, b)
                    assert bm.refcount(b) >= 1
        assert eng.metrics()["spec_proposed"] > 0
    assert bm.num_used == 0
    assert bm.num_free_uncached + bm.num_cached == bm.num_blocks - 1
    cache = eng.prefix_cache
    assert all(bm.refcount(b) == 0 for b in cache._by_block)
    assert cache.num_evictable() == len(cache)


def test_spec_eos_mid_verify_stops_exactly(qwen3):
    """When an accepted draft IS the eos token, emission stops there: no
    post-eos tokens leak out of a multi-token verify tick."""
    params, cfg = qwen3
    prompt = _loopy_prompts((17,), seed=9)[0]
    full = greedy_generate(params, cfg, prompt,
                           max_new_tokens=8)[len(prompt):]
    eos = full[4]
    want = full[: full.index(eos) + 1]

    def oracle(context, k):
        g = len(context) - len(prompt)
        return full[g:g + k]  # the true greedy continuation: full accept

    with _registered_draft("__test_oracle_eos", oracle) as name:
        eng = InferenceEngine(params, cfg, EngineConfig(
            num_slots=2, block_size=8, max_model_len=64,
            spec_k=4, spec_draft=name,
        ))
        rid = eng.submit(Request(prompt_ids=prompt, sampling=SamplingParams(
            max_new_tokens=8, eos_id=eos,
        )))
        out = eng.run()[rid]
    assert out.finish_reason == "eos"
    assert out.token_ids == want
    assert eng.blocks.num_used == 0
    # accepted-token rollup counts SAVED decode steps: the truncated tick
    # emitted len(want)-1 tokens (prefill gave the first), one of which is
    # the tick's own step — not inflated by post-eos accepted drafts
    assert out.spec_accepted_tokens == len(want) - 2


# ---------------------------------------------------------- tracer / metrics
def test_spec_tpot_counts_multi_token_ticks(qwen3):
    """Satellite regression: with forced k-acceptance (oracle drafter) a
    request finishes in a handful of verify ticks; serve.tpot_s must
    divide by the per-tick RECORDED token counts, and the timeline must
    carry the verify_emit marks + the spec_accepted_tokens rollup."""
    params, cfg = qwen3
    prompt = _prompts((9,), seed=10)[0]
    n_new = 12
    full = greedy_generate(params, cfg, prompt,
                           max_new_tokens=n_new)[len(prompt):]

    def oracle(context, k):
        g = len(context) - len(prompt)
        return full[g:g + k]

    with _registered_draft("__test_oracle", oracle) as name:
        eng = InferenceEngine(params, cfg, EngineConfig(
            num_slots=1, block_size=8, max_model_len=64,
            spec_k=4, spec_draft=name,
        ))
        rid = eng.submit(Request(prompt_ids=prompt, sampling=SamplingParams(
            max_new_tokens=n_new,
        )))
        out = eng.run()[rid]
    assert out.token_ids == full
    # full acceptance: every verify tick emitted k+1 tokens
    assert out.spec_accepted_tokens > 0
    tl = eng.tracer.get(rid)
    assert tl is not None and tl.decode_tokens == n_new - 1
    assert tl.spec_accepted_tokens == out.spec_accepted_tokens
    marks = [(s, d) for _, s, d in tl.marks if s == "verify_emit"]
    assert marks and all(d["tokens"] > 1 for _, d in marks)
    assert sum(d["tokens"] for _, d in marks) >= tl.spec_accepted_tokens
    assert out.tpot_s is not None and out.tpot_s >= 0
    doc = tl.to_doc()
    assert doc["spec_accepted_tokens"] == out.spec_accepted_tokens


def test_tracer_tpot_uses_recorded_tick_counts():
    """Direct unit pin of the bugfix: when the per-tick counts disagree
    with ``tokens - 1`` (the old assumption of one token per decode tick),
    the recorded counts win."""
    from veomni_tpu.observability.metrics import MetricsRegistry
    from veomni_tpu.observability.request_trace import RequestTracer

    tracer = RequestTracer(1, registry=MetricsRegistry())
    tracer.on_queued("r")
    tracer.on_admitted("r", 0)
    tracer.on_first_token("r")
    # one verify tick emitted 4 tokens (3 accepted drafts + bonus)
    tracer.on_decode_tokens("r", 4, spec_accepted=3)
    tl = tracer.on_finished("r", "length", tokens=5)
    assert tl is not None and tl.tpot_s is not None
    wall = tl.finished_t - tl.first_token_t
    assert tl.tpot_s == pytest.approx(wall / 4)
    assert tl.spec_accepted_tokens == 3
    # fallback: an engine that never reports tick counts keeps the old
    # (tokens - 1) denominator
    tracer.on_queued("s")
    tracer.on_admitted("s", 0)
    tracer.on_first_token("s")
    tl2 = tracer.on_finished("s", "length", tokens=3)
    wall2 = tl2.finished_t - tl2.first_token_t
    assert tl2.tpot_s == pytest.approx(wall2 / 2)


def test_spec_metrics_and_acceptance_window(qwen3):
    """serve.spec_* counters/gauge: lifetime totals monotone, the
    acceptance-rate gauge is window-scoped like decode_tokens_per_sec."""
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=96, spec_k=4,
    ))
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=8))
             for p in _loopy_prompts((21, 17), seed=12)])
    m1 = eng.metrics()  # resets the window
    assert m1["spec_proposed"] > 0
    assert 0.0 < m1["spec_acceptance_rate"] <= 1.0
    assert m1["spec_accepted"] <= m1["spec_proposed"]
    m2 = eng.metrics()  # fresh window: rate zeroed, totals persist
    assert m2["spec_acceptance_rate"] == 0.0
    assert m2["spec_proposed"] == m1["spec_proposed"]
    from veomni_tpu.observability.metrics import get_registry

    names = {name for name, _ in get_registry().items_snapshot()}
    assert {"serve.spec_proposed", "serve.spec_accepted",
            "serve.spec_acceptance_rate"} <= names
