"""Elastic checkpoints: restore across a different mesh shape / world size.

The universal checkpoint layout (``resilience/elastic.py`` +
``checkpoint/checkpointer.py``): every generation's ``manifest.json`` records
the source topology (even under ``ckpt_verify=off``); restore classifies the
target topology (``ok`` / ``elastic`` / ``incompatible``) before touching
the arrays; global arrays reshard onto the target ``NamedSharding``s; and
the per-rank data cursors — streaming consumed-prefix maps, poison-skip
histories, collator carry-overs — merge (N→M, M<N) or split (M>N)
deterministically.

Acceptance drills (subprocess, CPU virtual devices, mirroring the PR 3/5
bit-exact drills): train + save on a 4-device mesh, resume on 2 and on 8
devices with the global batch held constant — the loss trajectory must be
BIT-identical to the uninterrupted 4-device control; and the composition
with PR 5 integrity — corrupt the newest generation, fall back one, AND
resume on a different mesh under ``ckpt_verify=full`` with streaming
skip-budget accounting replayed identically.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _disarm_fault_plan():
    yield
    from veomni_tpu.resilience.faults import disarm_faults

    disarm_faults()
    os.environ.pop("VEOMNI_FAULT_PLAN", None)


# ---------------------------------------------------------------------------
# classify_restore: the one verdict shared by the restore gate and the CLI
# ---------------------------------------------------------------------------

def test_classify_restore_matrix():
    from veomni_tpu.resilience.elastic import classify_restore

    topo4 = {"world_size": 4, "device_count": 4,
             "mesh": {"fsdp": 4, "tp": 1}}
    # same world, sidecars complete -> ok
    assert classify_restore(topo4, 4, rank_files=[0, 1, 2, 3])[0] == "ok"
    # data-parallel world resize with complete sidecars -> elastic (both ways)
    assert classify_restore(topo4, 2, rank_files=[0, 1, 2, 3])[0] == "elastic"
    assert classify_restore(topo4, 8, rank_files=[0, 1, 2, 3])[0] == "elastic"
    # missing sidecars make a resize unmergeable
    verdict, reason = classify_restore(topo4, 2, rank_files=[0, 2, 3])
    assert verdict == "incompatible" and "ranks [1] are missing" in reason
    # model-parallel degree change: refused with the axis named
    verdict, reason = classify_restore(
        {"world_size": 4, "mesh": {"fsdp": 2, "tp": 2}}, 4,
        target_mesh={"fsdp": 1, "tp": 4}, rank_files=[0, 1, 2, 3])
    assert verdict == "incompatible" and "'tp' changed 2 -> 4" in reason
    # mesh-only resize (same world size): arrays still need a reshard
    verdict, _ = classify_restore(
        {"world_size": 1, "device_count": 4, "mesh": {"fsdp": 4}}, 1,
        target_mesh={"fsdp": 2}, target_device_count=2, rank_files=[0])
    assert verdict == "elastic"
    # pre-elastic checkpoint: world inferred from the sidecar set — same
    # world restores, but a RESIZE is refused (the inference cannot prove
    # the set is complete: a lost highest-rank sidecar is undetectable)
    assert classify_restore(None, 1, rank_files=[0])[0] == "ok"
    assert classify_restore(None, 2, rank_files=[0, 1])[0] == "ok"
    verdict, reason = classify_restore(None, 1, rank_files=[0, 1])
    assert verdict == "incompatible" and "no recorded topology" in reason
    # nothing recorded at all: unknown, never a hard failure
    assert classify_restore(None, 4)[0] == "unknown"
    # torn sidecar set at the same world size
    assert classify_restore({"world_size": 1}, 1,
                            rank_files=[7])[0] == "incompatible"
    # the save recorded how many sidecars it wrote: losing ALL of them is
    # as detectable as losing one (a bare listing can't tell "all lost"
    # from "none saved")
    topo_rs = {"world_size": 2, "rank_state_files": 2}
    assert classify_restore(topo_rs, 1, rank_files=None)[0] == "incompatible"
    assert classify_restore(topo_rs, 1, rank_files=[0])[0] == "incompatible"
    assert classify_restore(topo_rs, 1, rank_files=[0, 1])[0] == "elastic"


# ---------------------------------------------------------------------------
# merge/split: native loader cursors + collator carry-over
# ---------------------------------------------------------------------------

def _native_state(cursor, pending, epoch=0, seed=1, dropped=0):
    return {"dataloader": {
        "epoch": epoch, "cursor": cursor, "seed": seed,
        "dp_rank": 0, "dp_size": 2,
        "collator": {"pending": pending, "dropped_oversized": dropped},
    }}


def test_merge_split_native_loader_states():
    from veomni_tpu.resilience.elastic import (
        merge_rank_states,
        split_rank_state,
    )

    merged = merge_rank_states({
        0: _native_state(10, ["a", "b"], dropped=1),
        1: _native_state(12, ["c"]),
    })
    assert merged["saved_world_size"] == 2
    assert merged["dataloader"]["global_cursor"] == 22
    # same-world split is a bit-exact passthrough of the original docs
    assert split_rank_state(merged, 2, 0) == _native_state(10, ["a", "b"], dropped=1)
    assert split_rank_state(merged, 2, 1) == _native_state(12, ["c"])
    # 2 -> 1: global position preserved, carry-over concatenated, drop count kept
    one = split_rank_state(merged, 1, 0)["dataloader"]
    assert one["cursor"] == 22
    assert one["collator"]["pending"] == ["a", "b", "c"]
    assert one["collator"]["dropped_oversized"] == 1
    # 2 -> 4: carry-over redistributes round-robin, nothing lost/duplicated;
    # the cursor split is remainder-preserving (sums back to exactly 22)
    quarters = [split_rank_state(merged, 4, r)["dataloader"] for r in range(4)]
    assert [q["cursor"] for q in quarters] == [6, 6, 5, 5]
    got = [s for q in quarters for s in q["collator"]["pending"]]
    assert sorted(got) == ["a", "b", "c"]
    assert sum(q["collator"]["dropped_oversized"] for q in quarters) == 1

    # a torn rank set refuses to merge
    from veomni_tpu.resilience.elastic import ElasticRestoreError

    with pytest.raises(ElasticRestoreError, match="torn sidecar set"):
        merge_rank_states({0: _native_state(1, []), 2: _native_state(1, [])})

    # a stateful loader schema the merge does not understand (the dynamic
    # batcher's knapsack buffer) must refuse a RESIZE — silently dropping
    # the buffer would lose training samples — while a same-world split
    # (mesh-only resize) still passes the original docs through byte-exact
    dyn = _native_state(5, [])
    dyn["dataloader"]["buffer"] = {"buffer": ["sample"]}
    dyn["dataloader"]["batches_emitted"] = 3
    m_dyn = merge_rank_states({0: dyn, 1: _native_state(5, [])})
    assert split_rank_state(m_dyn, 2, 0) == dyn  # passthrough: exact
    with pytest.raises(ElasticRestoreError, match="buffer"):
        split_rank_state(m_dyn, 1, 0)

    # a nested dataset state present on only SOME ranks is torn — merging
    # just the survivors would drop the others' consumed records
    with_ds = _native_state(5, [])
    with_ds["dataloader"]["dataset"] = {"epoch": 0, "consumed": {"00": 3},
                                        "skipped": []}
    m_torn_ds = merge_rank_states({0: with_ds, 1: _native_state(5, [])})
    with pytest.raises(ElasticRestoreError, match="nested dataset state"):
        split_rank_state(m_torn_ds, 1, 0)

    # epoch skew: a rank already rolled into the next epoch had its cursor
    # RESET at rollover, so a resize cannot tell which records its old
    # block covered — merging would re-train that whole block. Refused on
    # resize; same-world passthrough stays exact.
    ahead = _native_state(2, ["z"], epoch=1)
    m_skew = merge_rank_states({0: _native_state(90, ["a"]), 1: ahead})
    assert split_rank_state(m_skew, 2, 1) == ahead
    with pytest.raises(ElasticRestoreError, match="epoch rollover"):
        split_rank_state(m_skew, 4, 0)


# ---------------------------------------------------------------------------
# streaming cursors: globally keyed, EXACT across a world resize
# ---------------------------------------------------------------------------

def _shard_corpus(tmp_path, n_shards=4, per_shard=6):
    d = tmp_path / "shards"
    d.mkdir(exist_ok=True)
    uid = 0
    for s in range(n_shards):
        with open(d / f"{s:02d}.jsonl", "w") as f:
            for _ in range(per_shard):
                f.write(json.dumps({"uid": uid}) + "\n")
                uid += 1
    return str(d), uid


def _stream(path, rank, world, **kw):
    from veomni_tpu.data.streaming import StreamingShardDataset

    return StreamingShardDataset(path, shuffle=True, seed=11, dp_rank=rank,
                                 dp_size=world, retry_base_s=0.001, **kw)


@pytest.mark.parametrize("target_world", [1, 4])
def test_streaming_elastic_resume_is_set_exact(tmp_path, target_world):
    """Mid-epoch 2-rank cursors merged and resumed on 1 and on 4 ranks: the
    union of records consumed before + after the resize is EXACTLY one epoch
    — nothing repeated, nothing skipped — because the consumed map is keyed
    by (shard, prefix-in-global-permuted-order), not by rank position."""
    from veomni_tpu.resilience.elastic import (
        merge_rank_states,
        split_rank_state,
    )

    path, total = _shard_corpus(tmp_path)
    # unequal progress: rank 0 consumed 5, rank 1 consumed 3 (ranks pack
    # different sample mixes, so equal lockstep can't be assumed)
    first = []
    states = {}
    for rank, k in ((0, 5), (1, 3)):
        ds = _stream(path, rank, 2)
        it = iter(ds)
        first += [next(it)["uid"] for _ in range(k)]
        states[rank] = {"dataloader": ds.state_dict()}
    assert len(set(first)) == len(first)

    merged = merge_rank_states(states)
    rest = []
    for r in range(target_world):
        ds = _stream(path, r, target_world)
        ds.load_state_dict(
            split_rank_state(merged, target_world, r)["dataloader"])
        rest += [row["uid"] for row in ds]  # one epoch from the cursor
    assert sorted(first + rest) == list(range(total)), (
        "elastic resume must consume exactly the records the original "
        "2-rank run had left"
    )


def test_streaming_elastic_merges_skip_history(tmp_path):
    """Poison-skip accounting survives the resize: the resumed world carries
    the full union of per-rank skip histories, so replay consumes no fresh
    budget wherever the poisoned shard lands."""
    from veomni_tpu.resilience.elastic import (
        merge_rank_states,
        split_rank_state,
    )

    path, total = _shard_corpus(tmp_path)
    # poison one record in each of two different shards
    for shard, line in (("00.jsonl", 2), ("03.jsonl", 4)):
        p = os.path.join(path, shard)
        lines = open(p).read().splitlines()
        lines[line] = "{rot"
        open(p, "w").write("\n".join(lines) + "\n")

    states = {}
    consumed = []
    for rank in (0, 1):
        ds = _stream(path, rank, 2, skip_budget=2)
        consumed += [row["uid"] for row in ds]  # full epoch, skipping poison
        states[rank] = {"dataloader": ds.state_dict()}
    all_skips = sorted(
        tuple(e) for s in states.values()
        for e in s["dataloader"]["skipped"]
    )
    assert len(all_skips) == 2  # one poison hit per rank

    merged = merge_rank_states(states)
    out = split_rank_state(merged, 1, 0)["dataloader"]
    assert sorted(tuple(e) for e in out["skipped"]) == all_skips
    # the resumed dataset replays the identical skips without new budget
    ds = _stream(path, 0, 1, skip_budget=2)
    ds.load_state_dict(out)
    epoch2 = [row["uid"] for row in ds]  # cursor was at epoch end -> epoch 2
    assert len(epoch2) == total - 2
    assert len(ds.state_dict()["skipped"]) == 2  # no fresh budget consumed


def test_streaming_record_striding_refuses_mid_epoch_merge(tmp_path):
    """Fewer shards than ranks strides RECORDS over ranks — per-shard
    consumption is no longer a prefix, so a mid-epoch world resize must
    refuse with the actionable re-shard message instead of corrupting the
    accounting. Both directions: SAVED states in the stride regime refuse
    at merge; a resize INTO the stride regime (target ranks > shard count,
    where every saved state was prefix-clean) refuses when the merged
    cursor reaches the target dataset."""
    from veomni_tpu.resilience.elastic import (
        ElasticRestoreError,
        merge_rank_states,
        split_rank_state,
    )

    path, _ = _shard_corpus(tmp_path, n_shards=1, per_shard=12)
    states = {}
    for rank in (0, 1):
        ds = _stream(path, rank, 2)
        it = iter(ds)
        next(it)
        states[rank] = {"dataloader": ds.state_dict()}
    assert states[0]["dataloader"]["stride_records"]
    merged1 = merge_rank_states(states)  # deferred: passthrough stays legal
    assert split_rank_state(merged1, 2, 1) == states[1]
    with pytest.raises(ElasticRestoreError, match="fewer shards than"):
        split_rank_state(merged1, 4, 0)

    # target-side: save on 2 ranks over 4 shards (no striding, mid-epoch),
    # resume on 8 ranks — the target would stride records, so the merged
    # consumed-prefix map is not addressable there and must be refused
    path4, _ = _shard_corpus(tmp_path, n_shards=4, per_shard=6)
    states4 = {}
    for rank in (0, 1):
        ds = _stream(path4, rank, 2)
        it = iter(ds)
        next(it)
        states4[rank] = {"dataloader": ds.state_dict()}
    merged = merge_rank_states(states4)  # saved side is prefix-clean
    target = _stream(path4, 3, 8)
    assert target._stride_records
    with pytest.raises(ElasticRestoreError, match="re-shard the corpus"):
        target.load_state_dict(split_rank_state(merged, 8, 3)["dataloader"])


# ---------------------------------------------------------------------------
# checkpointer: topology metadata + the restore gate + sidecar merge dispatch
# ---------------------------------------------------------------------------

def _mesh_state():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("fsdp",))
    sh = NamedSharding(mesh, P("fsdp"))
    return {"w": jax.device_put(jnp.arange(64, dtype=jnp.float32), sh)}


def test_manifest_records_topology_even_with_verify_off(tmp_path):
    from veomni_tpu.checkpoint import build_checkpointer
    from veomni_tpu.resilience.integrity import (
        read_manifest,
        read_topology,
        verify_manifest,
    )

    import jax

    state = _mesh_state()
    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            verify_mode="off")
    ck.save(1, state, extra_state={"global_step": 1})
    step_dir = os.path.join(ck.ckpt_dir, "global_step_1")
    topo = read_topology(step_dir)
    assert topo is not None
    assert topo["world_size"] == 1
    assert topo["mesh"] == {"fsdp": len(jax.devices())}
    assert topo["jax"]
    # off mode recorded NO digests: the generation is diagnosable but
    # UNVERIFIABLE — an empty file table must never read as verified-clean
    assert read_manifest(step_dir)["files"] == {}
    assert verify_manifest(step_dir, mode="full") is None
    ck.close()

    # digest-ful modes carry the same topology next to the CRCs
    ck2 = build_checkpointer(str(tmp_path / "ck2"), async_save=False,
                            verify_mode="size")
    ck2.save(1, state, extra_state={"global_step": 1})
    step_dir2 = os.path.join(ck2.ckpt_dir, "global_step_1")
    assert read_topology(step_dir2)["mesh"] == topo["mesh"]
    assert read_manifest(step_dir2)["files"]
    assert verify_manifest(step_dir2, mode="size").passed
    ck2.close()


def test_async_manifest_stamps_each_steps_own_sidecar_census(tmp_path):
    """The previous async step's manifest is written from inside the NEXT
    save(), which has already captured its own topology — the census must
    be the OWNING step's (a later cursor-less save must not stamp
    rank_state_files=0 onto a generation that has sidecars, which would
    defeat the all-sidecars-lost detection)."""
    from veomni_tpu.checkpoint import build_checkpointer
    from veomni_tpu.resilience.integrity import read_topology

    state = _mesh_state()
    ck = build_checkpointer(str(tmp_path / "ck"), async_save=True,
                            verify_mode="size")
    ck.save(1, state, extra_state={"global_step": 1},
            rank_state={"dataloader": None})
    ck.save(2, state, extra_state={"global_step": 2})  # no rank state
    ck.wait()
    t1 = read_topology(os.path.join(ck.ckpt_dir, "global_step_1"))
    t2 = read_topology(os.path.join(ck.ckpt_dir, "global_step_2"))
    assert t1["rank_state_files"] == 1
    assert t2["rank_state_files"] == 0
    ck.close()


def _patch_saved_world(step_dir, world):
    mpath = os.path.join(step_dir, "manifest.json")
    doc = json.load(open(mpath))
    doc["topology"]["world_size"] = world
    doc["topology"]["rank_state_files"] = world
    doc["topology"]["mesh"] = {}
    json.dump(doc, open(mpath, "w"))


def _abstract(state):
    import jax

    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding),
        state)


def _save_two_rank_ckpt(tmp_path, elastic=False, **kw):
    """A step-1 generation that claims world_size=2: rank 0's real sidecar
    plus a fabricated rank 1 sidecar with a different cursor."""
    from veomni_tpu.checkpoint import build_checkpointer

    state = _mesh_state()
    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            verify_mode="size", elastic=elastic, **kw)
    ck.save(1, state, extra_state={"global_step": 1},
            rank_state={"dataloader": {
                "epoch": 0, "cursor": 10, "seed": 7, "dp_rank": 0,
                "dp_size": 2,
                "collator": {"pending": ["p0"], "dropped_oversized": 0}}})
    step_dir = os.path.join(ck.ckpt_dir, "global_step_1")
    rank1 = {"dataloader": {
        "epoch": 0, "cursor": 14, "seed": 7, "dp_rank": 1, "dp_size": 2,
        "collator": {"pending": ["p1"], "dropped_oversized": 0}}}
    with open(os.path.join(step_dir, "extra_state_rank1.json"), "w") as f:
        json.dump(rank1, f)
    _patch_saved_world(step_dir, 2)
    return ck, state, step_dir


def test_world_shrink_without_elastic_fails_actionably(tmp_path):
    """The satellite bugfix: a topology mismatch must never silently restore
    partial cursor state (the pre-elastic behavior restored THIS rank's
    sidecar and dropped the other ranks' records on a shrink — and left
    grown ranks empty). With elastic off, a pinned-step load raises the
    knob-naming error."""
    from veomni_tpu.resilience.elastic import ElasticRestoreError

    ck, state, _ = _save_two_rank_ckpt(tmp_path, elastic=False)
    with pytest.raises(ElasticRestoreError, match="ckpt_elastic"):
        ck.load(_abstract(state), step=1)
    ck.close()


def test_elastic_restore_merges_sidecars_2_to_1(tmp_path):
    from veomni_tpu.checkpoint import build_checkpointer
    from veomni_tpu.observability.metrics import get_registry

    e0 = get_registry().counter("ckpt.elastic_restores").value
    ck, state, _ = _save_two_rank_ckpt(tmp_path)
    ck.close()
    ck2 = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                             verify_mode="size", elastic=True)
    restored, extra = ck2.load(_abstract(state), step=1)
    assert int(extra["global_step"]) == 1
    dl = extra["dataloader"]
    assert dl["cursor"] == 24  # 10 + 14: global epoch position preserved
    assert sorted(dl["collator"]["pending"]) == ["p0", "p1"]
    assert np.array_equal(np.asarray(restored["w"]),
                          np.asarray(state["w"]))
    assert get_registry().counter("ckpt.elastic_restores").value - e0 == 1
    ck2.close()


def test_ckpt_reshard_fault_survived_within_retry_budget(tmp_path):
    """Satellite: the resharding path drills under tier-1 like every other
    recovery path — an injected I/O fault inside the sidecar merge/split is
    retried and the elastic restore still lands."""
    from veomni_tpu.checkpoint import build_checkpointer
    from veomni_tpu.resilience.faults import configure_faults, fired_faults

    ck, state, _ = _save_two_rank_ckpt(tmp_path)
    ck.close()
    configure_faults([{"point": "ckpt.reshard", "mode": "exception",
                       "hit": 1, "times": 2}])
    ck2 = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                             verify_mode="size", elastic=True, io_retries=3,
                             retry_base_s=0.001)
    restored, extra = ck2.load(_abstract(state), step=1)
    assert extra["dataloader"]["cursor"] == 24
    assert len([a for a in fired_faults()
                if a.point == "ckpt.reshard"]) == 2
    ck2.close()

    # exhaustion: the fault keeps firing past the budget and surfaces
    configure_faults([{"point": "ckpt.reshard", "mode": "exception",
                       "times": 20}])
    ck3 = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                             verify_mode="size", elastic=True, io_retries=1,
                             retry_base_s=0.001)
    from veomni_tpu.resilience.faults import InjectedFault

    with pytest.raises(InjectedFault):
        ck3.load(_abstract(state), step=1)
    ck3.close()


def test_legacy_mid_epoch_streaming_cursor_refuses_resize():
    """A pre-elastic streaming cursor (rank-local shard_pos/rec_pos only,
    no consumed map) cannot be transferred: an empty map would silently
    restart the epoch. Same-world passthrough stays exact."""
    from veomni_tpu.resilience.elastic import (
        ElasticRestoreError,
        merge_rank_states,
        split_rank_state,
    )

    legacy = {"dataloader": {"epoch": 0, "shard_pos": 2, "rec_pos": 17,
                             "skipped": []}}
    merged = merge_rank_states({0: legacy, 1: {"dataloader": {
        "epoch": 0, "shard_pos": 1, "rec_pos": 3, "skipped": []}}})
    assert split_rank_state(merged, 2, 0) == legacy  # passthrough: exact
    with pytest.raises(ElasticRestoreError, match="before elastic keying"):
        split_rank_state(merged, 1, 0)


def test_config_error_aborts_fallback_walk(tmp_path):
    """With elastic OFF on a resized world, the restore walk must surface
    the actionable knob error instead of sliding past the newest (resized)
    generation onto a stale pre-resize one — silently losing every step in
    between would be worse than the error."""
    import jax

    from veomni_tpu.checkpoint import build_checkpointer
    from veomni_tpu.resilience.elastic import ElasticRestoreError

    state = _mesh_state()
    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            verify_mode="size")
    for step in (1, 2):
        ck.save(step, state, extra_state={"global_step": step},
                rank_state={"dataloader": {
                    "epoch": 0, "cursor": step, "seed": 7,
                    "dp_rank": 0, "dp_size": 2,
                    "collator": {"pending": [], "dropped_oversized": 0}}})
    # generation 2 claims a 2-process world; generation 1 still matches
    step2 = os.path.join(ck.ckpt_dir, "global_step_2")
    with open(os.path.join(step2, "extra_state_rank1.json"), "w") as f:
        json.dump({"dataloader": None}, f)
    _patch_saved_world(step2, 2)
    with pytest.raises(ElasticRestoreError, match="ckpt_elastic"):
        ck.load(_abstract(state))  # walk must NOT fall back to step 1
    ck.close()


def test_rotted_sidecar_is_quarantined_not_topology_refused(tmp_path):
    """Quarantine keeps precedence over the topology gate: a missing rank
    sidecar that the digest manifest condemns is storage rot — the
    generation must be quarantined (counted, renamed, walked past), not
    merely refused as an elastic incompatibility that would leave the
    rotted dir as the newest committed generation forever."""
    import jax

    from veomni_tpu.checkpoint import build_checkpointer
    from veomni_tpu.resilience import CheckpointCorruptError

    state = _mesh_state()
    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            verify_mode="size")
    ck.save(1, state, extra_state={"global_step": 1},
            rank_state={"dataloader": None})
    step_dir = os.path.join(ck.ckpt_dir, "global_step_1")
    os.remove(os.path.join(step_dir, "extra_state_rank0.json"))
    with pytest.raises(CheckpointCorruptError):
        ck.load(_abstract(state), step=1)
    assert os.path.isdir(os.path.join(ck.ckpt_dir, "global_step_1.corrupt"))
    ck.close()

    # with ckpt_verify=off there are no digests to condemn the loss, but
    # the topology's recorded sidecar count still catches it — losing ALL
    # sidecars must not classify as a cursor-less mesh resize
    from veomni_tpu.resilience.elastic import ElasticRestoreError

    ck2 = build_checkpointer(str(tmp_path / "ck2"), async_save=False,
                             verify_mode="off", elastic=True)
    ck2.save(1, state, extra_state={"global_step": 1},
             rank_state={"dataloader": None})
    os.remove(os.path.join(ck2.ckpt_dir, "global_step_1",
                           "extra_state_rank0.json"))
    with pytest.raises(ElasticRestoreError, match="torn or lost"):
        ck2.load(_abstract(state), step=1)
    ck2.close()


def test_model_parallel_degree_change_refused(tmp_path):
    from veomni_tpu.checkpoint import build_checkpointer
    from veomni_tpu.resilience.elastic import ElasticRestoreError

    state = _mesh_state()
    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            verify_mode="size", elastic=True)
    ck.save(1, state, extra_state={"global_step": 1},
            rank_state={"dataloader": None})
    step_dir = os.path.join(ck.ckpt_dir, "global_step_1")
    mpath = os.path.join(step_dir, "manifest.json")
    doc = json.load(open(mpath))
    doc["topology"]["mesh"] = {"fsdp": 1, "tp": 4}  # claim a TP=4 source
    json.dump(doc, open(mpath, "w"))
    # even WITH elastic on: a TP degree change is truly incompatible
    with pytest.raises(ElasticRestoreError, match="'tp' changed"):
        ck.load(_abstract(state), step=1)
    ck.close()


# ---------------------------------------------------------------------------
# operator CLI: topology printing + ELASTIC-OK/INCOMPATIBLE verdicts
# ---------------------------------------------------------------------------

def test_verify_ckpt_cli_topology_and_elastic_verdicts(tmp_path, capsys):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import verify_ckpt

    ck, state, step_dir = _save_two_rank_ckpt(tmp_path)
    ck.close()

    # world 2 saved (complete sidecars): ELASTIC-OK for 2 (same) and 1/4
    # (resize); after removing rank 1's sidecar the resize is INCOMPATIBLE
    rc = verify_ckpt.main([str(tmp_path / "ck"), "--mode", "size",
                           "--target-world-size", "4"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "topology: world_size=2" in out
    assert "ELASTIC-OK for world_size=4" in out

    os.remove(os.path.join(step_dir, "extra_state_rank1.json"))
    rc = verify_ckpt.main([str(tmp_path / "ck"), "--mode", "size",
                           "--target-world-size", "4"])
    out = capsys.readouterr().out
    # distinct exit code: intact bytes (not 1) but a scripted pre-resize
    # gate must still fail (not 0)
    assert rc == 3
    assert "INCOMPATIBLE for world_size=4" in out
    assert "1 elastically incompatible" in out


# ---------------------------------------------------------------------------
# subprocess acceptance drills: 4-device save -> 2/8-device resume, bit-exact
# ---------------------------------------------------------------------------

DENSE_TOY = {
    "model_type": "qwen3", "vocab_size": 256, "hidden_size": 32,
    "intermediate_size": 64, "num_hidden_layers": 2,
    "num_attention_heads": 2, "num_key_value_heads": 2, "head_dim": 16,
    "qk_norm": True,
}

_DRIVER = """\
import json, os, sys

cfg = json.load(open(sys.argv[1]))
sys.path.insert(0, cfg["repo"])

from veomni_tpu.arguments import VeOmniArguments
from veomni_tpu.trainer import TextTrainer
from veomni_tpu.trainer.callbacks import Callback

args = VeOmniArguments()
args.model.config_overrides = cfg["toy"]
args.data.train_path = cfg["data"]
args.data.data_type = "pretokenized"
args.data.max_seq_len = 64
if cfg.get("dataset_type"):
    args.data.dataset_type = cfg["dataset_type"]
t = args.train
t.output_dir = cfg["out"]
t.micro_batch_size = cfg["micro_batch_size"]
t.train_steps = cfg["train_steps"]
t.save_steps = cfg.get("save_steps", 0)
t.async_save = False
t.ckpt_verify = cfg.get("ckpt_verify", "size")
t.ckpt_elastic = bool(cfg.get("ckpt_elastic", False))
t.data_skip_budget = cfg.get("data_skip_budget", 0)
# constant LR: cosine bakes train_steps into every update and the legs
# train different horizons
t.lr_decay_style = "constant"
t.lr = 1e-3
t.bf16 = False
t.save_hf_weights = False
t.log_steps = 1

trainer = TextTrainer(args)


class Rec(Callback):
    def on_step_end(self, tr, state):
        if state.synced:
            with open(cfg["loss_log"], "a") as f:
                f.write(json.dumps({
                    "step": state.global_step,
                    "loss_hex": float(state.metrics["loss"]).hex(),
                }) + "\\n")


trainer.callbacks.append(Rec())
ctl = trainer.train()
trainer.checkpointer.close()
res = {"global_step": ctl.global_step,
       "elastic_restores": __import__(
           "veomni_tpu.observability.metrics", fromlist=["get_registry"]
       ).get_registry().counter("ckpt.elastic_restores").value}
if hasattr(trainer.dataset, "state_dict"):
    res["dataset_state"] = trainer.dataset.state_dict()
with open(cfg["result"], "w") as f:
    json.dump(res, f)
"""

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_driver(tmp_path, cfg, ndev, extra_env=None):
    """One training leg on an ``ndev``-device virtual CPU mesh. The device
    topology is pinned per leg (not inherited from the pytest process) —
    this IS the mesh resize under test."""
    driver = tmp_path / "driver.py"
    driver.write_text(_DRIVER)
    cfg = dict(cfg, repo=_REPO, toy=DENSE_TOY)
    cfg_path = tmp_path / (os.path.basename(cfg["loss_log"]) + ".cfg.json")
    cfg_path.write_text(json.dumps(cfg))
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", VEOMNI_LOG_LEVEL="WARNING",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={ndev}",
    )
    env.pop("VEOMNI_FAULT_PLAN", None)
    if extra_env:
        env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, str(driver), str(cfg_path)],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=600,
    )
    return proc


def _cfg(tmp_path, out_name, loss_log, micro_batch_size, **over):
    cfg = {
        "data": str(tmp_path / "data.jsonl"),
        "out": str(tmp_path / out_name),
        "loss_log": str(tmp_path / loss_log),
        "result": str(tmp_path / (loss_log + ".result.json")),
        "train_steps": 8,
        "micro_batch_size": micro_batch_size,
    }
    cfg.update(over)
    return cfg


def _losses(path):
    out = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            out[rec["step"]] = rec["loss_hex"]
    return out


def _write_data(path, n=96, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            f.write(json.dumps({
                "input_ids": rng.integers(0, vocab, int(rng.integers(16, 80))).tolist(),
            }) + "\n")


def test_subprocess_elastic_resume_on_smaller_and_larger_mesh(tmp_path):
    """THE acceptance drill: train + save on a 4-device mesh, resume on 2
    and on 8 devices (micro batch scaled inversely so the global batch —
    and with it the math — is constant). The resumed trajectory must be
    BIT-identical to an uninterrupted control ON THE TARGET MESH: the
    restored state is exact, so resuming on M devices is indistinguishable
    from having run on M devices all along. Against the 4-device control the
    trajectories agree to float32 reduction-order noise (~1 ULP creeps in
    after a few steps — XLA sums partial reductions in mesh-shaped order —
    which is why the bit-exact oracle is the mesh-matched control)."""
    _write_data(tmp_path / "data.jsonl")

    ctl4 = _cfg(tmp_path, "ctl4_out", "ctl4.jsonl", 2, save_steps=2)
    proc = _run_driver(tmp_path, ctl4, ndev=4)
    assert proc.returncode == 0, proc.stderr[-2000:]
    ref4 = _losses(ctl4["loss_log"])
    assert sorted(ref4) == list(range(1, 9))

    # leg 1: 4-device mesh, stops at step 4 with checkpoints at 2 and 4
    leg1 = _cfg(tmp_path, "elastic_out", "leg1.jsonl", 2,
                train_steps=4, save_steps=2)
    proc = _run_driver(tmp_path, leg1, ndev=4)
    assert proc.returncode == 0, proc.stderr[-2000:]
    leg1_losses = _losses(leg1["loss_log"])

    # resume the same run on 2 devices and (separately) on 8 — each from a
    # FRESH copy of leg 1's output (a resume's own train-end save would
    # otherwise become the next leg's resume point)
    for ndev, mb, log in ((2, 4, "resume2"), (8, 1, "resume8")):
        ctl_m = _cfg(tmp_path, f"ctl{ndev}_out", f"ctl_{log}.jsonl", mb)
        proc = _run_driver(tmp_path, ctl_m, ndev=ndev)
        assert proc.returncode == 0, proc.stderr[-2000:]
        ref_m = _losses(ctl_m["loss_log"])
        # the shared prefix (steps 1-4, before the resize) and the whole
        # mesh-matched control agree with the 4-device control to f32
        # reduction-order noise — cross-mesh math equivalence
        for step in range(1, 9):
            a, b = float.fromhex(ref4[step]), float.fromhex(ref_m[step])
            assert np.isclose(a, b, rtol=1e-5, atol=0), (step, a, b)
        assert all(ref4[s] == leg1_losses[s] for s in range(1, 5))

        out_m = str(tmp_path / f"elastic_out_{ndev}")
        shutil.copytree(leg1["out"], out_m)
        leg2 = _cfg(tmp_path, f"elastic_out_{ndev}", f"{log}.jsonl", mb,
                    save_steps=0, ckpt_elastic=True)
        proc = _run_driver(tmp_path, leg2, ndev=ndev)
        assert proc.returncode == 0, (
            f"resume on {ndev} devices failed:\n" + proc.stderr[-2000:]
        )
        result = json.load(open(leg2["result"]))
        assert result["global_step"] == 8
        assert result["elastic_restores"] >= 1  # the gate saw the resize
        got = _losses(leg2["loss_log"])
        assert sorted(got) == list(range(5, 9))  # resumed from step 4
        for step, hexloss in got.items():
            assert ref_m[step] == hexloss, (
                f"{ndev}-device resume, step {step}: loss {hexloss} != "
                f"{ndev}-device control {ref_m[step]}"
            )

    # without the knob, the mesh resize is refused with the actionable error
    noknob_out = str(tmp_path / "elastic_out_noknob")
    shutil.copytree(leg1["out"], noknob_out)
    noknob = _cfg(tmp_path, "elastic_out_noknob", "noknob.jsonl", 4,
                  save_steps=0)
    proc = _run_driver(tmp_path, noknob, ndev=2)
    assert proc.returncode != 0
    assert "ckpt_elastic" in proc.stderr


def test_subprocess_elastic_composes_with_integrity_fallback(tmp_path):
    """Satellite: elastic restore composed with PR 5 integrity — the newest
    generation rots (corrupt fault after its digests are recorded), the
    resumed run on a DIFFERENT mesh quarantines it under ckpt_verify=full,
    falls back one generation, and replays bit-exactly vs the control —
    streaming skip-budget accounting replayed identically across the
    topology change."""
    shard_dir = tmp_path / "stream_shards"
    shard_dir.mkdir()
    rng = np.random.default_rng(0)
    poison_idx = 7
    with open(shard_dir / "00.jsonl", "w") as f:
        for i in range(64):
            if i == poison_idx:
                f.write("{this is not json\n")
                continue
            f.write(json.dumps({
                "input_ids": rng.integers(
                    0, 256, int(rng.integers(16, 80))).tolist(),
            }) + "\n")

    common = dict(dataset_type="streaming", data_skip_budget=1,
                  ckpt_verify="full")
    # the bit-exact oracle shares the corrupt leg's MESH HISTORY (4-device
    # steps 1-2, elastic 2-device resume for 3-8, no corruption): both legs
    # restore the identical step-2 state, so the fallback must change
    # NOTHING about the trajectory. (A single-mesh control is only equal to
    # f32 reduction-order noise — see the mesh-resize drill above.)
    c1 = _cfg(tmp_path, "icc_out", "icc1.jsonl", 2,
              train_steps=2, save_steps=2, **common)
    c1["data"] = str(shard_dir)
    proc = _run_driver(tmp_path, c1, ndev=4)
    assert proc.returncode == 0, proc.stderr[-2000:]
    c2 = _cfg(tmp_path, "icc_out", "icc2.jsonl", 4, save_steps=0,
              ckpt_elastic=True, **common)
    c2["data"] = str(shard_dir)
    proc = _run_driver(tmp_path, c2, ndev=2)
    assert proc.returncode == 0, proc.stderr[-2000:]
    ref = {**_losses(c1["loss_log"]), **_losses(c2["loss_log"])}
    assert sorted(ref) == list(range(1, 9))
    assert json.load(open(c2["result"]))["dataset_state"]["skipped"] == [
        ["00.jsonl", poison_idx]]

    # leg 1 on 4 devices: checkpoints at 2 and 4; the ckpt.manifest corrupt
    # fault (hit 2 = the step-4 manifest) bitflips the step-4 payload AFTER
    # its digests were recorded — the storage-rot timeline
    leg1 = _cfg(tmp_path, "ivic_out", "ivic1.jsonl", 2,
                train_steps=4, save_steps=2, **common)
    leg1["data"] = str(shard_dir)
    plan = [{"point": "ckpt.manifest", "mode": "corrupt", "hit": 2,
             "op": "bitflip"}]
    proc = _run_driver(tmp_path, leg1, ndev=4,
                       extra_env={"VEOMNI_FAULT_PLAN": json.dumps(plan)})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert _losses(leg1["loss_log"])[2] == ref[2]  # shared 4-device prefix
    ck_dir = os.path.join(leg1["out"], "checkpoints")
    assert os.path.isdir(os.path.join(ck_dir, "global_step_4"))

    # leg 2 resumes on 2 devices with full verification: step 4 quarantined,
    # step 2 restored ONTO THE RESIZED MESH, steps 3-8 replayed bit-exactly
    leg2 = _cfg(tmp_path, "ivic_out", "ivic2.jsonl", 4, save_steps=0,
                ckpt_elastic=True, **common)
    leg2["data"] = str(shard_dir)
    proc = _run_driver(tmp_path, leg2, ndev=2)
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.load(open(leg2["result"]))
    assert result["global_step"] == 8
    assert result["elastic_restores"] >= 1
    assert result["dataset_state"]["skipped"] == [["00.jsonl", poison_idx]]
    assert os.path.isdir(os.path.join(ck_dir, "global_step_4.corrupt"))
    assert not os.path.isdir(os.path.join(ck_dir, "global_step_4"))
    got = _losses(leg2["loss_log"])
    assert sorted(got) == list(range(3, 9))  # fell back to step 2
    for step, hexloss in got.items():
        assert ref[step] == hexloss, (
            f"step {step}: elastic post-fallback loss {hexloss} != control "
            f"{ref[step]}"
        )
