"""Muon optimizer: NS orthogonalization properties + e2e training step."""

import jax
import jax.numpy as jnp
import numpy as np


def test_newton_schulz_orthogonalizes():
    from veomni_tpu.optim.muon import _newton_schulz

    g = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
    sv_in = np.linalg.svd(np.asarray(g), compute_uv=False)
    o = _newton_schulz(g, steps=10)
    sv = np.linalg.svd(np.asarray(o), compute_uv=False)
    # Muon's quintic NS squeezes singular values toward ~1 (approximately —
    # that's by design), from a wide input spread
    assert sv_in.max() / sv_in.min() > 3
    assert sv.min() > 0.55 and sv.max() < 1.45, sv


def test_muon_e2e_training(tmp_path):
    import json

    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.trainer import TextTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "d.jsonl", "w") as f:
        for _ in range(64):
            f.write(json.dumps(
                {"input_ids": rng.integers(0, 256, int(rng.integers(16, 60))).tolist()}
            ) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen3", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
        "qk_norm": True,
    }
    args.data.train_path = str(tmp_path / "d.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 128
    args.train.output_dir = str(tmp_path / "out")
    args.train.optimizer = "muon"
    args.train.lr = 1e-3
    args.train.micro_batch_size = 1
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 100
    trainer = TextTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert np.isfinite(ctl.metrics["loss"])
    trainer.checkpointer.close()
