"""Multi-process execution path: 2 processes x 4 virtual CPU devices.

Ports the reference's mp.spawn+gloo distributed test strategy (SURVEY §4):
jax.distributed.initialize via env vars, per-process data sharding,
make_array_from_process_local_data batch assembly, multihost Orbax
save/restore with exact loss-trajectory continuation after a restart.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

DRIVER = os.path.join(os.path.dirname(__file__), "tools", "multihost_train.py")


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(nproc, data, out, steps, stop_at=0, timeout=600):
    port = _free_port()
    procs = []
    for pid in range(nproc):
        env = dict(
            os.environ,
            VEOMNI_COORDINATOR_ADDRESS=f"localhost:{port}",
            VEOMNI_NUM_PROCESSES=str(nproc),
            VEOMNI_PROCESS_ID=str(pid),
        )
        env.pop("PYTEST_CURRENT_TEST", None)
        procs.append(subprocess.Popen(
            [sys.executable, DRIVER, data, out, str(steps), str(stop_at)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    results = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"rank failed:\n{stderr[-3000:]}"
        results.append(json.loads(stdout.strip().splitlines()[-1]))
    return sorted(results, key=lambda r: r["process"])


@pytest.fixture(scope="module")
def data_path(tmp_path_factory):
    path = tmp_path_factory.mktemp("mh") / "data.jsonl"
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for _ in range(512):
            ln = int(rng.integers(16, 100))
            f.write(json.dumps(
                {"input_ids": rng.integers(0, 256, ln).tolist()}) + "\n")
    return str(path)


def test_two_process_training_and_resume(data_path, tmp_path):
    out = str(tmp_path / "out")
    # uninterrupted 8-step reference run
    ref = _launch(2, data_path, str(tmp_path / "ref"), steps=8)
    assert ref[0]["devices"] == 8
    assert ref[0]["global_step"] == 8
    # both processes observe the same (globally reduced) loss
    assert ref[0]["losses"] == ref[1]["losses"]

    # preempted run: stop after 4 (checkpoint at 4), restart to 8
    first = _launch(2, data_path, out, steps=8, stop_at=4)
    assert first[0]["global_step"] == 4
    second = _launch(2, data_path, out, steps=8)
    assert second[0]["global_step"] == 8
    # trajectory after resume continues the uninterrupted run exactly
    assert second[0]["losses"] == ref[0]["losses"][4:], (
        f"resumed {second[0]['losses']} != ref tail {ref[0]['losses'][4:]}"
    )
