"""Multi-process execution path: 2 processes x 4 virtual CPU devices.

Ports the reference's mp.spawn+gloo distributed test strategy (SURVEY §4):
jax.distributed.initialize via env vars, per-process data sharding,
make_array_from_process_local_data batch assembly, multihost Orbax
save/restore with exact loss-trajectory continuation after a restart.
"""

import json
import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

# jaxlib 0.4.x CPU rejects cross-process programs outright
# ("Multiprocess computations aren't implemented on the CPU backend");
# the capability this suite exercises only exists on newer jaxlib.
pytestmark = pytest.mark.skipif(
    jax.__version_info__ < (0, 5, 0),
    reason="multiprocess CPU computations unsupported by jaxlib < 0.5",
)

DRIVER = os.path.join(os.path.dirname(__file__), "tools", "multihost_train.py")


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(nproc, data, out, steps, stop_at=0, timeout=600):
    port = _free_port()
    procs = []
    for pid in range(nproc):
        env = dict(
            os.environ,
            VEOMNI_COORDINATOR_ADDRESS=f"localhost:{port}",
            VEOMNI_NUM_PROCESSES=str(nproc),
            VEOMNI_PROCESS_ID=str(pid),
        )
        env.pop("PYTEST_CURRENT_TEST", None)
        procs.append(subprocess.Popen(
            [sys.executable, DRIVER, data, out, str(steps), str(stop_at)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    results = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"rank failed:\n{stderr[-3000:]}"
        results.append(json.loads(stdout.strip().splitlines()[-1]))
    return sorted(results, key=lambda r: r["process"])


@pytest.fixture(scope="module")
def data_path(tmp_path_factory):
    path = tmp_path_factory.mktemp("mh") / "data.jsonl"
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for _ in range(512):
            ln = int(rng.integers(16, 100))
            f.write(json.dumps(
                {"input_ids": rng.integers(0, 256, ln).tolist()}) + "\n")
    return str(path)


LOAD_DRIVER = os.path.join(os.path.dirname(__file__), "tools", "multihost_load.py")
VLM_DRIVER = os.path.join(os.path.dirname(__file__), "tools", "multihost_vlm.py")


def test_two_process_vlm_matches_single_process(tmp_path):
    """Packed-VLM multihost data assembly: a 2-process run (per-row patch
    budgets, each process assembles only its rows) reproduces the
    1-process (global packed buffer) loss trajectory exactly. Dataset size
    == global batch, so every step sees the same sample set in both
    layouts. Reference: per-rank multimodal slicing,
    ``data/data_collator.py:317-431``."""
    rng = np.random.default_rng(0)
    data = tmp_path / "vlm.jsonl"
    with open(data, "w") as f:
        for i in range(8):  # == global micro-batch (mb 1 x dp 8)
            f.write(json.dumps({
                "input_ids": rng.integers(11, 256, int(rng.integers(8, 24))).tolist(),
                "images": [rng.random((8 + 4 * (i % 2), 8, 3)).tolist()],
            }) + "\n")

    def launch(nproc, local_devices, out):
        port = _free_port()
        procs = []
        for pid in range(nproc):
            env = dict(os.environ)
            if nproc > 1:
                env.update(
                    VEOMNI_COORDINATOR_ADDRESS=f"localhost:{port}",
                    VEOMNI_NUM_PROCESSES=str(nproc),
                    VEOMNI_PROCESS_ID=str(pid),
                )
            env.pop("PYTEST_CURRENT_TEST", None)
            procs.append(subprocess.Popen(
                # one shared output_dir: orbax multiprocess saves coordinate
                # via global barriers keyed on the path
                [sys.executable, VLM_DRIVER, str(data), "3",
                 str(local_devices), out],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            ))
        results = []
        for p in procs:
            stdout, stderr = p.communicate(timeout=900)
            assert p.returncode == 0, f"rank failed:\n{stderr[-3000:]}"
            results.append(json.loads(stdout.strip().splitlines()[-1]))
        return results

    single = launch(1, 8, str(tmp_path / "s"))[0]
    double = launch(2, 4, str(tmp_path / "d"))
    assert single["devices"] == 8 and double[0]["devices"] == 8
    assert not single["per_row"] and double[0]["per_row"]
    assert double[0]["losses"] == double[1]["losses"]
    np.testing.assert_allclose(
        double[0]["losses"], single["losses"], rtol=2e-4,
    )


def test_two_process_ep_sliced_weight_load(tmp_path):
    """Streamed HF load on a 2-process EP mesh: each process must read only
    the expert rows its local devices hold (reference EP-sliced per-rank
    reads, ``module_utils.py:530,867``), and every placed shard must match
    the on-disk tensor bit-for-bit."""
    import jax
    import numpy as np

    from veomni_tpu.models import TransformerConfig, build_foundation_model

    cfg = TransformerConfig(
        model_type="qwen3_moe", vocab_size=256, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, qk_norm=True,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
    )
    model = build_foundation_model(config=cfg)
    params = model.init(jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "hf_ckpt")
    model.save_hf(ckpt, params=params)

    def run(extra):
        port = _free_port()
        procs = []
        for pid in range(2):
            env = dict(
                os.environ,
                VEOMNI_COORDINATOR_ADDRESS=f"localhost:{port}",
                VEOMNI_NUM_PROCESSES="2",
                VEOMNI_PROCESS_ID=str(pid),
            )
            env.pop("PYTEST_CURRENT_TEST", None)
            procs.append(subprocess.Popen(
                [sys.executable, LOAD_DRIVER, ckpt, "4"] + extra,
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            ))
        results = []
        for p in procs:
            stdout, stderr = p.communicate(timeout=600)
            assert p.returncode == 0, f"rank failed:\n{stderr[-3000:]}"
            results.append(json.loads(stdout.strip().splitlines()[-1]))
        return sorted(results, key=lambda r: r["process"])

    results = run([])
    # total expert bytes on disk: 3 tensors x L x E x (h x ffn) x f32
    total_expert = 3 * cfg.num_hidden_layers * cfg.num_experts * (
        cfg.hidden_size * cfg.moe_intermediate_size
    ) * 4
    for r in results:
        assert r["shards_match_disk"], r
        # ep=4 over 2 processes: each holds half the experts; a full-model
        # read (the failure mode this test exists to catch) would be ~2x
        assert r["expert_bytes"] <= 0.6 * total_expert, (
            r, total_expert,
        )
        assert r["expert_bytes"] >= 0.4 * total_expert, (
            r, total_expert,
        )

    # rank0-broadcast mode: replicated params are read once on process 0 and
    # shipped over the interconnect — rank 1's filesystem traffic drops
    bres = run(["broadcast"])
    for r in bres:
        assert r["shards_match_disk"], r
    assert bres[1]["other_bytes"] < results[1]["other_bytes"], (bres, results)


def test_two_process_training_and_resume(data_path, tmp_path):
    out = str(tmp_path / "out")
    # uninterrupted 8-step reference run
    ref = _launch(2, data_path, str(tmp_path / "ref"), steps=8)
    assert ref[0]["devices"] == 8
    assert ref[0]["global_step"] == 8
    # both processes observe the same (globally reduced) loss
    assert ref[0]["losses"] == ref[1]["losses"]

    # preempted run: stop after 4 (checkpoint at 4), restart to 8
    first = _launch(2, data_path, out, steps=8, stop_at=4)
    assert first[0]["global_step"] == 4
    second = _launch(2, data_path, out, steps=8)
    assert second[0]["global_step"] == 8
    # trajectory after resume continues the uninterrupted run exactly
    assert second[0]["losses"] == ref[0]["losses"][4:], (
        f"resumed {second[0]['losses']} != ref tail {ref[0]['losses'][4:]}"
    )
