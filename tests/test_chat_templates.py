"""Chat-template registry + multimodal DPO transform
(reference ``multimodal_chat_template.py`` TEMPLATES + ``chat_template.py``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.data.chat_template import (
    CHAT_TEMPLATE_REGISTRY,
    build_chat_template,
)
from veomni_tpu.models.auto import build_config


class FakeTok:
    """Char-level tokenizer: deterministic, no vocab files needed."""

    def __call__(self, text, add_special_tokens=False):
        return {"input_ids": [10 + (ord(c) % 200) for c in text]}


MESSAGES = [
    {"role": "system", "content": "be terse"},
    {"role": "user", "content": "hi"},
    {"role": "assistant", "content": "hello"},
]


def test_registry_covers_reference_names():
    for name in ("qwen2vl", "qwen2_5vl", "qwen3vl", "qwen2_5omni", "janus",
                 "chatml", "llama2"):
        assert name in CHAT_TEMPLATE_REGISTRY, name


@pytest.mark.parametrize("name", ["chatml", "llama2"])
def test_text_templates_supervise_assistant_only(name):
    tmpl = build_chat_template(name, FakeTok())
    enc = tmpl.encode_messages(MESSAGES)
    ids, labels = enc["input_ids"], enc["labels"]
    assert len(ids) == len(labels)
    sup = [l for l in labels if l != -100]
    assert 0 < len(sup) < len(ids)  # some supervised, prompt masked
    assert all(l == i for l, i in zip(labels, ids) if l != -100)


def test_model_type_resolution():
    cfg = build_config("qwen2_5_vl", **{
        "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "window_size": 8, "fullatt_block_indexes": [1],
            "out_hidden_size": 64,
        },
        "image_token_id": 9, "video_token_id": 10, "vision_start_token_id": 8,
    })
    tmpl = build_chat_template("default", FakeTok(), cfg)
    enc = tmpl.encode_messages([
        {"role": "user", "content": [
            {"type": "text", "text": "what is this?"},
            {"type": "image", "image": np.random.default_rng(0).random((8, 8, 3))},
        ]},
        {"role": "assistant", "content": "a square"},
    ])
    # the image expanded into vision_start + merged-token placeholders
    assert enc["input_ids"].count(9) == 4 * 4 // 4  # (8/2)^2 patches / 2^2
    assert 8 in enc["input_ids"]
    assert len(enc["vis_patches"]) == 1 and len(enc["vis_grids"]) == 1
    with pytest.raises(ValueError, match="unknown chat template"):
        build_chat_template("nope", FakeTok())


def test_vlm_dpo_transform_collate_and_logprobs():
    """vlm_dpo rows -> paired per-row-budget batch -> finite VLM logprobs;
    chosen/rejected share the prompt+media, differ in the response."""
    from veomni_tpu.data.data_transform import build_data_transform
    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.models.qwen2_5_vl import sequence_logprob_sums
    from veomni_tpu.trainer.dpo_trainer import VLMDPOPairCollator

    cfg = build_config("qwen2_5_vl", **{
        "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "window_size": 8, "fullatt_block_indexes": [1],
            "out_hidden_size": 64,
        },
        "image_token_id": 9, "video_token_id": 10, "vision_start_token_id": 8,
    })
    transform = build_data_transform(
        "vlm_dpo", tokenizer=FakeTok(), vlm_config=cfg, max_seq_len=64,
    )
    rng = np.random.default_rng(0)
    samples = [transform({
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "pick"},
            {"type": "image", "image": rng.random((8, 8, 3))},
        ]}],
        "chosen": "good answer",
        "rejected": "bad",
    }) for _ in range(2)]
    # prompt (incl. media placeholders) is masked in both branches
    s = samples[0]
    n_prompt_c = sum(1 for l in s["chosen_labels"] if l == -100)
    n_prompt_r = sum(1 for l in s["rejected_labels"] if l == -100)
    assert n_prompt_c == n_prompt_r > 0
    assert s["chosen_input_ids"][:n_prompt_c] == s["rejected_input_ids"][:n_prompt_r]

    col = VLMDPOPairCollator(seq_len=64, pairs=2, vlm_config=cfg, max_patches=128)
    batch = col(samples)
    assert batch["input_ids"].shape == (4, 64)
    assert batch["pixel_values"].ndim == 3  # per-row budget layout

    model = build_foundation_model(config=cfg)
    params = model.init(jax.random.PRNGKey(0))
    logps = sequence_logprob_sums(
        params, cfg, {k: jnp.asarray(v) for k, v in batch.items()}
    )
    assert logps.shape == (4,)
    assert np.all(np.isfinite(np.asarray(logps)))
    assert np.all(np.asarray(logps) < 0)


def _small_vl_cfg():
    return build_config("qwen2_5_vl", **{
        "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "window_size": 8, "fullatt_block_indexes": [1],
            "out_hidden_size": 64,
        },
        "image_token_id": 9, "video_token_id": 10, "vision_start_token_id": 8,
    })


def test_cap_resize_counts_still_image_patches_without_temporal_factor():
    """A still image yields t=1 patch rows — an image exactly at the budget
    must NOT be resized (the old tp-inflated count halved its resolution)."""
    from veomni_tpu.data.chat_template import qwen_vl_chat_template

    cfg = _small_vl_cfg()
    # 8x8 px, patch 2 -> (8/2)^2 = 16 patch rows exactly
    tmpl = qwen_vl_chat_template(FakeTok(), cfg, max_patches_per_sample=16)
    enc = tmpl.encode_messages([
        {"role": "user", "content": [
            {"type": "image", "image": np.random.default_rng(0).random((8, 8, 3))},
        ]},
    ])
    assert enc["vis_grids"][0] == (1, 4, 4)        # untouched grid
    assert enc["vis_patches"][0].shape[0] == 16    # not downscaled


def test_vlm_dpo_multi_image_row_respects_total_budget():
    """3 images in one preference row must fit the per-sample budget TOTAL
    (the per-item cap alone would overflow the collator's row budget 3x)."""
    from veomni_tpu.data.data_transform import build_data_transform

    cfg = _small_vl_cfg()
    budget = 48
    transform = build_data_transform(
        "vlm_dpo", tokenizer=FakeTok(), vlm_config=cfg, max_seq_len=256,
        max_patches_per_sample=budget,
    )
    rng = np.random.default_rng(1)
    out = transform({
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "compare"},
            *({"type": "image", "image": rng.random((32, 32, 3))}
              for _ in range(3)),
        ]}],
        "chosen": "first",
        "rejected": "second",
    })
    total = sum(p.shape[0] for p in out["vis_patches"])
    assert total <= budget, f"{total} patches exceed the {budget} budget"
    # all three images survived (downscaled, not dropped)
    assert len(out["vis_grids"]) == 3


def test_vlm_dpo_underflow_budget_drops_trailing_media():
    """When media_count * merge_block exceeds the per-sample budget, the
    per-item floor (one merge block each) would overflow it — the transform
    must drop trailing media instead, and must do so via the per-call
    budget (no shared template state mutated between rows)."""
    from veomni_tpu.data.data_transform import build_data_transform

    cfg = _small_vl_cfg()  # merge 2 -> min block = 4 patches
    budget = 8             # fits 2 items at the 4-patch floor, not 3
    transform = build_data_transform(
        "vlm_dpo", tokenizer=FakeTok(), vlm_config=cfg, max_seq_len=256,
        max_patches_per_sample=budget,
    )
    rng = np.random.default_rng(2)
    row = {
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "compare"},
            *({"type": "image", "image": rng.random((16, 16, 3))}
              for _ in range(3)),
        ]}],
        "chosen": "first",
        "rejected": "second",
    }
    out = transform(dict(row))
    assert len(out["vis_grids"]) == 2  # trailing image dropped
    total = sum(p.shape[0] for p in out["vis_patches"])
    assert total <= budget, f"{total} patches exceed the {budget} budget"
    # the input row's messages were not mutated
    assert sum(1 for p in row["messages"][0]["content"]
               if isinstance(p, dict) and p.get("type") == "image") == 3
    # a following single-image row sees the full budget again (per-call
    # budget, not leftover shared state from the 3-image row)
    out2 = transform({
        "messages": [{"role": "user", "content": [
            {"type": "image", "image": rng.random((16, 16, 3))},
        ]}],
        "chosen": "a", "rejected": "b",
    })
    assert len(out2["vis_grids"]) == 1
    assert sum(p.shape[0] for p in out2["vis_patches"]) <= budget
