"""Parallelism must not change math (reference test_e2e_parallel.py /
test_fsdp_equivalence.py): identical loss + grad_norm across mesh layouts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _toy_cfg(moe: bool = False):
    from veomni_tpu.models.config import TransformerConfig

    kw = dict(
        model_type="qwen3_moe" if moe else "qwen3",
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        qk_norm=True,
        dtype=jnp.float32,
    )
    if moe:
        kw.update(num_experts=4, num_experts_per_tok=2, moe_intermediate_size=64)
    return TransformerConfig(**kw)


def _batch(bsz=8, seq=64, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, (bsz, seq))
    seg = np.ones((bsz, seq), np.int32)
    seg[:, seq // 2:] = 2  # two packed segments per row
    pos = np.concatenate(
        [np.arange(seq // 2), np.arange(seq - seq // 2)]
    )
    return {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "labels": jnp.asarray(ids, jnp.int32),
        "position_ids": jnp.asarray(np.broadcast_to(pos, (bsz, seq)).copy(), jnp.int32),
        "segment_ids": jnp.asarray(seg),
    }


def _loss_and_gnorm(cfg, mesh_kwargs, batch):
    import optax

    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state

    destroy_parallel_state()
    ps = init_parallel_state(**mesh_kwargs)
    model = build_foundation_model(config=cfg)
    with use_parallel_state(ps):
        params = model.init(jax.random.PRNGKey(0))
        plan = model.get_parallel_plan()
        shardings = plan.resolve(params, ps)
        params = jax.jit(lambda p: p, out_shardings=shardings)(params)
        batch_sharding = {k: ps.batch_sharding() for k in batch}
        batch = {k: jax.device_put(v, batch_sharding[k]) for k, v in batch.items()}

        def norm_loss(p, b):
            loss_sum, metrics = model.loss_fn(p, b)
            return loss_sum / jnp.maximum(metrics["ntokens"], 1)

        loss, grads = jax.jit(jax.value_and_grad(norm_loss))(params, batch)
        gnorm = jax.jit(optax.global_norm)(grads)
        return float(loss), float(gnorm)


@pytest.mark.parametrize("moe", [False, True], ids=["dense", "moe"])
def test_sp_ep_equivalence(moe):
    """(sp, ep) in {1,2}x{1,2} all produce identical loss/grad_norm."""
    cfg = _toy_cfg(moe)
    batch = _batch()
    base = _loss_and_gnorm(cfg, dict(dp_shard_size=4), batch)
    layouts = [dict(ulysses_size=2, dp_shard_size=2)]
    if moe:
        layouts += [
            dict(ep_size=2, dp_shard_size=4),
            dict(ulysses_size=2, ep_size=2, dp_shard_size=2),
        ]
    for kw in layouts:
        got = _loss_and_gnorm(cfg, kw, batch)
        np.testing.assert_allclose(got[0], base[0], rtol=2e-5, err_msg=f"loss {kw}")
        np.testing.assert_allclose(got[1], base[1], rtol=2e-4, err_msg=f"gnorm {kw}")


def test_ulysses_attention_matches_local():
    """Ulysses a2a attention == single-device attention on the same inputs."""
    from veomni_tpu.ops.attention import _attention_xla
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.sequence_parallel import ulysses_attention

    rng = jax.random.PRNGKey(0)
    b, s, hq, hkv, d = 2, 32, 8, 4, 16
    qk, kk, vk = jax.random.split(rng, 3)
    q = jax.random.normal(qk, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(vk, (b, s, hkv, d), jnp.float32)
    seg = jnp.concatenate(
        [jnp.ones((b, s // 2), jnp.int32), jnp.full((b, s // 2), 2, jnp.int32)], axis=1
    )
    ref = _attention_xla(q, k, v, segment_ids=seg, causal=True)

    ps = init_parallel_state(ulysses_size=4, dp_shard_size=1)
    with use_parallel_state(ps):
        got = jax.jit(
            lambda *a: ulysses_attention(_attention_xla, *a, pstate=ps, causal=True)
        )(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
