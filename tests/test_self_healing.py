"""Self-healing fleet drills: wedge detection, resurrection, fencing.

The hang failure mode is the one the kill drill (test_router.py) cannot
produce: the replica does not die — its ``engine.step()`` simply never
returns (a wedged XLA collective, a stuck host callback), and before PR
18 the router's pump would block on it forever, holding every healthy
replica's tick hostage. These tests drive that exact scenario with the
fault layer's ``hang`` mode and pin the whole recovery arc:

* detection within the ``replica_stall_s`` budget — the router abandons
  the stuck pump thread while the zombie is *still sleeping inside the
  hang*, it never waits the hang out;
* correctness is untouched: every surviving output matches the bare
  greedy reference token-for-token, survivors leak zero KV blocks;
* resurrection attaches to the shared program bundle with ZERO new
  traces (``TRACE_COUNTS`` gate — a respawn that recompiles would stall
  a production fleet for minutes);
* probation: a respawned replica serves spill traffic cleanly before
  rejoining affinity rotation; budgets exhaust into loud permanent
  retirement; ``health()`` flips unhealthy under ``min_live`` and
  RECOVERS (a 503 here is a state, not a tombstone).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.models import decode as decode_mod
from veomni_tpu.models.decode import greedy_generate
from veomni_tpu.resilience.faults import configure_faults, disarm_faults
from veomni_tpu.serving import EngineConfig, Request, SamplingParams
from veomni_tpu.serving.replica import (
    STATE_LIVE,
    STATE_PROBATION,
    STATE_WEDGED,
)
from veomni_tpu.serving.router import Router, RouterConfig

QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)


@pytest.fixture(scope="module")
def qwen3():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    disarm_faults()


def _prompts(n, seed=0, length=8):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, 128, length)]
            for _ in range(n)]


def _reqs(prompts, n_new=8):
    return [Request(prompt_ids=list(p),
                    sampling=SamplingParams(max_new_tokens=n_new))
            for p in prompts]


def _pool_identity(eng):
    bm = eng.blocks
    assert bm.num_used == 0
    assert bm.num_free_uncached + bm.num_cached == bm.num_blocks - 1


def _engine_cfg(**kw):
    kw.setdefault("num_blocks", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_model_len", 128)
    return EngineConfig(**kw)


def _drain(router, timeout_s=30.0):
    deadline = time.perf_counter() + timeout_s
    while router.has_work and time.perf_counter() < deadline:
        router.step()
    assert not router.has_work, "router failed to drain"


def _restore_fleet(router, probe_prompt, timeout_s=30.0):
    """Drive respawns to landing and probation replicas to parole with
    identical-prefix probe bursts (they saturate one affinity target so
    the spill reaches the probationer). Returns probe request ids."""
    probes = []
    deadline = time.perf_counter() + timeout_s
    n_cfg = router.config.replicas
    while time.perf_counter() < deadline:
        probation = [h for h in router.replicas.values()
                     if h.state == STATE_PROBATION]
        if (len(router.live_replicas()) >= n_cfg
                and not router._pending_respawns and not probation
                and not router.has_work):
            return probes
        if router.has_work or router._pending_respawns:
            router.step()
            continue
        burst = router.config.spill_queue_depth + 1 + sum(
            router.config.probation_requests for _ in probation)
        for req in _reqs([probe_prompt] * burst, n_new=4):
            probes.append(router.submit(req))
    raise AssertionError("fleet did not restore in time")


# ------------------------------------------------------------ the hang drill
def test_wedge_detection_respawn_and_parity_mid_storm(qwen3):
    """One replica hangs mid-storm inside ``engine.step()``. The router
    must declare it WEDGED within the stall budget (NOT wait out the
    hang), keep the healthy replicas' ticks fast, keep every output
    token-exact, respawn the victim with zero new traces, and walk it
    through probation back to rotation."""
    params, cfg = qwen3
    stall_s, stall_ticks, hang_s = 0.5, 2, 4.0
    r = Router(params, cfg, _engine_cfg(), RouterConfig(
        replicas=3, replica_stall_s=60.0, replica_stall_ticks=stall_ticks,
        max_respawns=2, respawn_backoff_s=0.05, respawn_backoff_max_s=0.2,
        probation_requests=2))
    prompts = _prompts(18, seed=3)
    refs = {tuple(p): greedy_generate(params, cfg, p,
                                      max_new_tokens=8)[len(p):]
            for p in prompts}
    # warm EVERY shape the storm will touch (incl. the cache-hit chunked
    # prefill) under the forgiving deadline, then tighten it
    r.run(_reqs(prompts))
    r.run(_reqs(prompts))
    r.config.replica_stall_s = stall_s

    # ---- fault-free replay: per-tick latency baseline + quiet check
    ids = [r.submit(q) for q in _reqs(prompts)]
    ff_durs = []
    while r.has_work:
        t0 = time.perf_counter()
        r.step()
        ff_durs.append(time.perf_counter() - t0)
    assert r._wedged_total == 0 and r._respawn_total == 0
    for rid, p in zip(ids, prompts):
        out = r.pop_output(rid)
        assert out.token_ids == refs[tuple(p)], rid
    p99_ff = float(np.percentile(ff_durs, 99))

    trace_base = dict(decode_mod.TRACE_COUNTS)
    # ---- the hang: one decode tick, somewhere in the fleet, sleeps 4s
    configure_faults([{"point": "serve.decode_tick", "mode": "hang",
                       "hit": 5, "times": 1, "seconds": hang_s}])
    ids = [r.submit(q) for q in _reqs(prompts)]
    t_start = time.perf_counter()
    t_wedge = None
    post_wedge_durs = []
    while r.has_work:
        t0 = time.perf_counter()
        r.step()
        dt = time.perf_counter() - t0
        if t_wedge is None and r._wedged_total >= 1:
            t_wedge = time.perf_counter()
        elif t_wedge is not None:
            post_wedge_durs.append(dt)
    disarm_faults()
    assert r._wedged_total == 1, "the hang must read as exactly one wedge"
    assert t_wedge is not None
    # detection bound: stall_s per strike tick + scheduling slack — and
    # strictly before the 4s hang would have returned on its own
    assert t_wedge - t_start < stall_s * stall_ticks + 1.5
    assert t_wedge - t_start < hang_s
    # healthy replicas' ticks were never held hostage after abandonment
    if len(post_wedge_durs) >= 5:
        assert float(np.percentile(post_wedge_durs, 99)) <= max(
            2.0 * p99_ff, 0.15)
    # token integrity for every storm output: completed requests (incl.
    # the stranded-then-redispatched, which restart from scratch) match
    # greedy exactly; requests that had already streamed tokens on the
    # wedged replica are terminal ``cancelled`` keeping the delivered
    # greedy PREFIX — exactly-once, never duplicated, never corrupted
    n_done = n_cancelled = 0
    for rid, p in zip(ids, prompts):
        out = r.pop_output(rid)
        ref = refs[tuple(p)]
        assert out is not None and out.finished, rid
        if out.finish_reason in ("eos", "length"):
            n_done += 1
            assert out.token_ids == ref, rid
        else:
            assert out.finish_reason == "cancelled", rid
            n_cancelled += 1
            assert out.token_ids == ref[:len(out.token_ids)], rid
    # only requests actually RUNNING on the wedged engine can cancel —
    # bounded by its slot count; everything else must complete
    assert n_cancelled <= r.engine_config.num_slots
    assert n_done + n_cancelled == len(prompts)
    # ---- resurrection: lands in probation, paroles on clean spill work
    probes = _restore_fleet(r, prompts[0])
    assert r._respawn_total == 1 and r._probation_total == 1
    assert len(r.live_replicas()) == 3
    for rid in probes:
        out = r.pop_output(rid)
        if out is not None:
            assert out.finish_reason in ("eos", "length", "rejected")
    # zero new traces across wedge + respawn + probation: the respawned
    # engine attached to the SAME shared program bundle
    assert dict(decode_mod.TRACE_COUNTS) == trace_base
    # zero leaked blocks on every quiescent engine (survivors + respawn)
    for h in r.replicas.values():
        if h.engine_quiescent:
            _pool_identity(h.engine)


# ------------------------------------------------- total loss and recovery
def test_total_fleet_loss_recovers_and_health_flips(qwen3):
    """Killing EVERY replica drops ``health()`` to unhealthy; respawns
    land, queued work completes, and health recovers — the 503 is a
    state the fleet exits, not a tombstone."""
    params, cfg = qwen3
    r = Router(params, cfg, _engine_cfg(num_slots=2), RouterConfig(
        replicas=2, min_live=2, max_respawns=2, respawn_backoff_s=0.05,
        respawn_backoff_max_s=0.2, probation_requests=0))
    prompts = _prompts(4, seed=9)
    r.run(_reqs(prompts))
    r.run(_reqs(prompts))
    assert r.health()["healthy"]
    for rid in [h.rid for h in r.live_replicas()]:
        r.kill_replica(rid, reason="drill: total loss")
    h = r.health()
    assert not h["healthy"] and h["replicas_live"] == 0
    assert h["pending_respawns"] == 2
    # queued work survives the outage: submitted while NOTHING is live
    ids = [r.submit(q) for q in _reqs(prompts, n_new=4)]
    _drain(r)
    for rid, p in zip(ids, prompts):
        out = r.pop_output(rid)
        assert out.finish_reason in ("eos", "length")
        want = greedy_generate(params, cfg, p, max_new_tokens=4)[len(p):]
        assert out.token_ids == want
    h = r.health()
    assert h["healthy"] and h["replicas_live"] == 2
    assert r._respawn_total == 2


def test_respawn_budget_exhausts_into_permanent_retirement(qwen3):
    """A lineage that keeps dying burns its ``max_respawns`` budget and
    is retired for good: capacity stays reduced, health says so, and the
    survivor keeps serving."""
    params, cfg = qwen3
    r = Router(params, cfg, _engine_cfg(num_slots=2), RouterConfig(
        replicas=2, min_live=2, max_respawns=1, respawn_backoff_s=0.05,
        respawn_backoff_max_s=0.1, probation_requests=0))
    prompts = _prompts(3, seed=11)
    r.run(_reqs(prompts))
    victim = r.live_replicas()[0].rid
    r.kill_replica(victim, reason="drill: kill 1")
    deadline = time.perf_counter() + 10.0
    while (len(r.live_replicas()) < 2
           and time.perf_counter() < deadline):
        r.step()
    assert len(r.live_replicas()) == 2, "first respawn must land"
    r.kill_replica(victim, reason="drill: kill 2")
    # budget (1) already spent: no pending respawn, lineage retired
    assert not r._pending_respawns
    assert victim in r._retired_lineages
    h = r.health()
    assert not h["healthy"]  # live 1 < min_live 2, permanently
    assert victim in h["retired_lineages"]
    # the survivor still serves correctly
    ids = [r.submit(q) for q in _reqs(prompts, n_new=4)]
    _drain(r)
    for rid, p in zip(ids, prompts):
        out = r.pop_output(rid)
        want = greedy_generate(params, cfg, p, max_new_tokens=4)[len(p):]
        assert out.token_ids == want


# --------------------------------------------------------- dispatch bounce
def test_admit_fault_bounces_request_not_replica(qwen3):
    """An exception at ``serve.admit`` (engine.submit) is the REQUEST's
    problem: it gets a terminal rejected output, the replica stays in
    rotation, and the next request sails through."""
    params, cfg = qwen3
    r = Router(params, cfg, _engine_cfg(num_slots=2),
               RouterConfig(replicas=2, max_respawns=0))
    prompts = _prompts(2, seed=13)
    r.run(_reqs(prompts))
    configure_faults([{"point": "serve.admit", "mode": "exception",
                       "hit": 1, "times": 1}])
    rid_bounced = r.submit(_reqs(prompts[:1], n_new=4)[0])
    _drain(r)
    out = r.pop_output(rid_bounced)
    assert out is not None and out.finish_reason == "rejected"
    assert len(r.live_replicas()) == 2  # nobody died for this
    disarm_faults()
    rid_ok = r.submit(_reqs(prompts[1:], n_new=4)[0])
    _drain(r)
    out = r.pop_output(rid_ok)
    want = greedy_generate(params, cfg, prompts[1],
                           max_new_tokens=4)[len(prompts[1]):]
    assert out.finish_reason in ("eos", "length") and out.token_ids == want


# -------------------------------------------------------- zombie write fence
def test_metrics_fence_drops_zombie_writes():
    """``LabelledRegistry.revoke()`` turns a labelled view's instruments
    into write-dropping proxies — the abandoned pump thread's late writes
    vanish — while a successor view over the SAME label writes normally
    and the unlabelled identity view is untouched."""
    from veomni_tpu.observability.metrics import (
        LabelledRegistry,
        MetricsRegistry,
    )

    base = MetricsRegistry()
    view = LabelledRegistry(base, "r0")
    c = view.counter("serve.requests")
    g = view.gauge("serve.queue_depth")
    c.inc()
    g.set(7.0)
    assert c.value == 1.0
    view.revoke()
    c.inc(5)          # zombie write: dropped
    g.set(99.0)       # zombie write: dropped
    assert c.value == 1.0 and g.value == 7.0
    # reads still delegate; the successor (fresh view, same label, same
    # base registry -> same underlying instrument) writes normally
    succ = LabelledRegistry(base, "r0")
    succ.counter("serve.requests").inc()
    assert succ.counter("serve.requests").value == 2.0
    assert c.value == 2.0  # the zombie can still READ the shared truth
    c.inc()  # still fenced even after the successor took over
    assert succ.counter("serve.requests").value == 2.0
    # identity view: bare instruments, revoke is a no-op shape-wise
    ident = LabelledRegistry(base, "")
    ic = ident.counter("standalone.count")
    ident.revoke()
    ic.inc()
    assert ic.value == 1.0


def test_engine_revoke_metrics_fences_labelled_only(qwen3):
    """``InferenceEngine.revoke_metrics()``: labelled engines stop
    writing, unlabelled engines are untouched (single-engine serving has
    no respawn and must keep its metrics)."""
    from dataclasses import replace

    from veomni_tpu.observability.metrics import get_registry
    from veomni_tpu.serving import InferenceEngine

    params, cfg = qwen3
    ec = _engine_cfg(num_slots=2, metrics_label="fencetest")
    eng = InferenceEngine(params, cfg, ec)
    p = _prompts(1, seed=17)[0]
    eng.run(_reqs([p], n_new=4))
    reg = get_registry()
    # the labelled view scopes serve.requests -> serve.fencetest.requests
    after_first = reg.counter("serve.fencetest.requests").value
    assert after_first >= 1.0
    eng.revoke_metrics()
    out = eng.run(_reqs([p], n_new=4))  # steps happily, writes dropped
    assert all(o.finish_reason in ("eos", "length") for o in out.values())
    assert reg.counter("serve.fencetest.requests").value == after_first
    # unlabelled: revoke_metrics is a no-op, metrics keep flowing
    eng2 = InferenceEngine(params, cfg, replace(ec, metrics_label=""))
    eng2.revoke_metrics()
    base_reqs = reg.counter("serve.requests").value
    eng2.run(_reqs([p], n_new=4))
    assert reg.counter("serve.requests").value == base_reqs + 1.0


# ------------------------------------------------------------- pump beats
def test_pump_workers_write_replica_heartbeats(qwen3, tmp_path):
    """With ``heartbeat_dir`` set, every pump worker beats as
    ``heartbeat-<rid>.json`` (phase serve_pump) — the wedged-replica
    diagnosis artifact scripts/fleet.py merges."""
    from veomni_tpu.observability.fleet import read_heartbeats

    params, cfg = qwen3
    r = Router(params, cfg, _engine_cfg(num_slots=2), RouterConfig(
        replicas=2, max_respawns=0, heartbeat_dir=str(tmp_path)))
    prompts = _prompts(6, seed=19)
    r.run(_reqs(prompts, n_new=4))
    beats = read_heartbeats(str(tmp_path))
    rids = {h.rid for h in r.replicas.values()}
    assert {b["rank"] for b in beats} == rids
    for b in beats:
        assert b["phase"] == "serve_pump"
        assert b["replica"] in rids
        assert b["state"] in (STATE_LIVE, STATE_PROBATION, STATE_WEDGED)
