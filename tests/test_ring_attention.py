"""Ring-attention CP correctness: cp layouts must match local attention and
the cp=1 training math exactly (the reference stubs CP — parallel_state.py:81
— so the oracle is our own single-device path, equivalence-style like
reference test_e2e_parallel.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _qkv(b=2, s=64, hq=8, hkv=4, d=16, seed=0):
    rng = jax.random.PRNGKey(seed)
    qk, kk, vk = jax.random.split(rng, 3)
    q = jax.random.normal(qk, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(vk, (b, s, hkv, d), jnp.float32)
    seg = jnp.concatenate(
        [jnp.ones((b, s // 2), jnp.int32), jnp.full((b, s // 2), 2, jnp.int32)],
        axis=1,
    )
    return q, k, v, seg


def _sp_case(layout, causal=True, sliding_window=None, seg=True, mask_mod=None,
             **qkv_kw):
    from veomni_tpu.ops.attention import _attention_xla
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.parallel.sequence_parallel import sp_attention

    q, k, v, segs = _qkv(**qkv_kw)
    segs = segs if seg else None
    ref = _attention_xla(
        q, k, v, segment_ids=segs, causal=causal, sliding_window=sliding_window,
        mask_mod=mask_mod,
    )
    destroy_parallel_state()
    ps = init_parallel_state(**layout)
    with use_parallel_state(ps):
        got = jax.jit(
            lambda *a: sp_attention(
                _attention_xla, *a, pstate=ps, causal=causal,
                sliding_window=sliding_window, mask_mod=mask_mod,
            )
        )(q, k, v, segs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize(
    "layout",
    [
        dict(cp_size=2, dp_shard_size=2),
        dict(cp_size=4, dp_shard_size=1),
        dict(cp_size=2, ulysses_size=2, dp_shard_size=1),
    ],
    ids=["cp2", "cp4", "cp2xu2"],
)
def test_ring_matches_local(layout):
    _sp_case(layout)


def test_ring_non_causal():
    _sp_case(dict(cp_size=4, dp_shard_size=1), causal=False)


def test_ring_sliding_window():
    _sp_case(dict(cp_size=4, dp_shard_size=1), sliding_window=24)


def test_ring_no_segments():
    _sp_case(dict(cp_size=4, dp_shard_size=1), seg=False)


def _doc_mask(q_idx, k_idx):
    """Block-diagonal 'document' flex mask (width 16) — positional only, so
    it must see GLOBAL indices to survive sequence sharding."""
    return (q_idx // 16) == (k_idx // 16)


@pytest.mark.parametrize(
    "layout",
    [
        dict(ulysses_size=4, dp_shard_size=1),
        dict(cp_size=4, dp_shard_size=1),
        dict(cp_size=2, ulysses_size=2, dp_shard_size=1),
    ],
    ids=["u4", "cp4", "cp2xu2"],
)
def test_mask_mod_under_sp(layout):
    """Flex masks compose with ulysses/ring SP on global positions
    (reference flex x Ulysses, ops/kernels/attention/__init__.py:30-86)."""
    _sp_case(layout, mask_mod=_doc_mask)
    _sp_case(layout, causal=False, mask_mod=_doc_mask)


def test_mask_mod_sp_via_facade():
    """The public attention() facade routes mask_mod through the ambient
    parallel state instead of raising."""
    from veomni_tpu.ops.attention import attention
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state

    q, k, v, seg = _qkv()
    destroy_parallel_state()
    ref = attention(q, k, v, segment_ids=seg, causal=True, mask_mod=_doc_mask)
    ps = init_parallel_state(cp_size=2, ulysses_size=2, dp_shard_size=1)
    with use_parallel_state(ps):
        got = jax.jit(
            lambda *a: attention(*a, causal=True, mask_mod=_doc_mask)
        )(q, k, v, seg)
    destroy_parallel_state()
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_batch_mask_mod_rejected_under_sp():
    """Batch-dependent flex masks can't ride the shard_map closure; the
    facade rejects them with a clear error (not a deep trace failure)."""
    from veomni_tpu.ops.attention import attention
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state

    q, k, v, seg = _qkv()
    doc_ids = jnp.asarray(np.arange(q.shape[0])[:, None] * jnp.ones(
        (1, q.shape[1]), jnp.int32))

    def batch_mask(q_idx, k_idx):
        return doc_ids[:, q_idx[:, 0]][:, :, None] == doc_ids[:, k_idx[0]][:, None, :]

    destroy_parallel_state()
    ps = init_parallel_state(cp_size=2, dp_shard_size=2)
    with use_parallel_state(ps):
        with pytest.raises(NotImplementedError, match="batch-dependent"):
            attention(q, k, v, segment_ids=seg, causal=True,
                      mask_mod=batch_mask)
    destroy_parallel_state()


def test_ring_grads_match_local():
    """AD through the ring scan + ppermute == local attention grads."""
    from veomni_tpu.ops.attention import _attention_xla
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.parallel.sequence_parallel import sp_attention

    q, k, v, seg = _qkv()

    def local(q, k, v):
        return (_attention_xla(q, k, v, segment_ids=seg, causal=True) ** 2).sum()

    ref = jax.grad(local, argnums=(0, 1, 2))(q, k, v)

    destroy_parallel_state()
    ps = init_parallel_state(cp_size=4, dp_shard_size=1)
    with use_parallel_state(ps):

        def ring(q, k, v):
            out = sp_attention(_attention_xla, q, k, v, seg, pstate=ps, causal=True)
            return (out ** 2).sum()

        got = jax.jit(jax.grad(ring, argnums=(0, 1, 2)))(q, k, v)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=5e-5, atol=5e-5)


def test_cp_training_equivalence():
    """Full train-math equivalence: loss/grad_norm identical at cp=2 vs fsdp=4
    (mirrors test_parallel_equivalence but exercising the ring path)."""
    from tests.test_parallel_equivalence import _batch, _loss_and_gnorm, _toy_cfg

    cfg = _toy_cfg()
    batch = _batch()
    base = _loss_and_gnorm(cfg, dict(dp_shard_size=4), batch)
    for kw in (
        dict(cp_size=2, dp_shard_size=2),
        dict(cp_size=2, ulysses_size=2, dp_shard_size=1),
    ):
        got = _loss_and_gnorm(cfg, kw, batch)
        np.testing.assert_allclose(got[0], base[0], rtol=2e-5, err_msg=f"loss {kw}")
        np.testing.assert_allclose(got[1], base[1], rtol=2e-4, err_msg=f"gnorm {kw}")
