"""Numerical parity vs HuggingFace transformers (torch CPU).

This is the reference's core oracle (``tests/test_models_patch.py``: VeOmni
modeling must produce identical loss/grads to upstream HF). Here: build a
tiny HF model, save_pretrained, load through our HF importer, and compare
token-mean loss (f32) on the same batch.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import transformers  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

DIMS = dict(
    vocab_size=257, hidden_size=64, intermediate_size=112,
    num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=256, tie_word_embeddings=False,
)


def _hf_model(tmp_path, kind):
    torch.manual_seed(0)
    if kind == "llama":
        cfg = transformers.LlamaConfig(**DIMS, rope_theta=10000.0)
        m = transformers.LlamaForCausalLM(cfg)
    elif kind == "llama31":
        cfg = transformers.LlamaConfig(
            **DIMS, rope_theta=500000.0,
            rope_scaling={"rope_type": "llama3", "factor": 8.0,
                          "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                          "original_max_position_embeddings": 64},
        )
        m = transformers.LlamaForCausalLM(cfg)
    elif kind == "qwen2":
        cfg = transformers.Qwen2Config(**DIMS)
        m = transformers.Qwen2ForCausalLM(cfg)
    elif kind == "qwen3":
        cfg = transformers.Qwen3Config(**DIMS, head_dim=16)
        m = transformers.Qwen3ForCausalLM(cfg)
    elif kind == "qwen3_moe":
        cfg = transformers.Qwen3MoeConfig(
            **DIMS, head_dim=16, num_experts=4, num_experts_per_tok=2,
            moe_intermediate_size=48, norm_topk_prob=True,
            decoder_sparse_step=1, mlp_only_layers=[],
            router_aux_loss_coef=0.0, output_router_logits=False,
        )
        m = transformers.Qwen3MoeForCausalLM(cfg)
    elif kind == "gemma3":
        cfg = transformers.Gemma3TextConfig(
            **{k: v for k, v in DIMS.items() if k != "tie_word_embeddings"},
            head_dim=16, query_pre_attn_scalar=16,
            sliding_window=16, rope_local_base_freq=10000.0, rope_theta=1000000.0,
            layer_types=["sliding_attention", "sliding_attention", "full_attention"],
        )
        m = transformers.Gemma3ForCausalLM(cfg)
    elif kind == "deepseek_v3":
        cfg = transformers.DeepseekV3Config(
            **{k: v for k, v in DIMS.items() if k not in ("num_key_value_heads",)},
            num_key_value_heads=DIMS["num_attention_heads"],
            q_lora_rank=24, kv_lora_rank=16,
            qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
            n_routed_experts=8, num_experts_per_tok=2, moe_intermediate_size=32,
            n_shared_experts=1, n_group=2, topk_group=1,
            routed_scaling_factor=1.5, scoring_func="sigmoid", norm_topk_prob=True,
            first_k_dense_replace=1,  # rope_interleave defaults True (real ckpts)
        )
        m = transformers.DeepseekV3ForCausalLM(cfg)
    elif kind == "gpt_oss":
        cfg = transformers.GptOssConfig(
            **{k: v for k, v in DIMS.items()},
            head_dim=16, num_local_experts=4, num_experts_per_tok=2,
            sliding_window=16,
            layer_types=["sliding_attention", "full_attention", "sliding_attention"],
            router_aux_loss_coef=0.0, output_router_logits=False,
        )
        m = transformers.GptOssForCausalLM(cfg)
    elif kind == "seed_oss":
        cfg = transformers.SeedOssConfig(
            **DIMS, head_dim=16, attention_bias=True, attention_out_bias=True,
            attention_dropout=0.0, residual_dropout=0.0,
        )
        m = transformers.SeedOssForCausalLM(cfg)
    elif kind == "glm4_moe":
        cfg = transformers.Glm4MoeConfig(
            **DIMS, head_dim=16, partial_rotary_factor=0.5, use_qk_norm=True,
            n_routed_experts=8, num_experts_per_tok=2, moe_intermediate_size=32,
            n_shared_experts=1, n_group=2, topk_group=1,
            routed_scaling_factor=1.5, norm_topk_prob=True,
            first_k_dense_replace=1,
        )
        m = transformers.Glm4MoeForCausalLM(cfg)
    elif kind == "qwen3_next":
        cfg = transformers.Qwen3NextConfig(
            **{k: v for k, v in DIMS.items() if k != "num_hidden_layers"},
            head_dim=16, partial_rotary_factor=0.25,
            linear_num_value_heads=4, linear_num_key_heads=2,
            linear_key_head_dim=16, linear_value_head_dim=16,
            linear_conv_kernel_dim=4, full_attention_interval=4,
            num_hidden_layers=4,
            num_experts=4, num_experts_per_tok=2, moe_intermediate_size=48,
            shared_expert_intermediate_size=32, decoder_sparse_step=1,
            norm_topk_prob=True, mlp_only_layers=[],
            router_aux_loss_coef=0.0, output_router_logits=False,
        )
        m = transformers.Qwen3NextForCausalLM(cfg)
    elif kind == "deepseek_v2":
        cfg = transformers.DeepseekV2Config(
            **{k: v for k, v in DIMS.items() if k != "num_key_value_heads"},
            num_key_value_heads=DIMS["num_attention_heads"],
            q_lora_rank=24, kv_lora_rank=16,
            qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
            n_routed_experts=8, num_experts_per_tok=2, moe_intermediate_size=32,
            n_shared_experts=1, topk_method="greedy", norm_topk_prob=False,
            routed_scaling_factor=1.0, aux_loss_alpha=0.0,
            first_k_dense_replace=1,
        )
        m = transformers.DeepseekV2ForCausalLM(cfg)
    else:
        raise ValueError(kind)
    d = tmp_path / kind
    m.save_pretrained(d)
    return m.eval(), str(d)


def _batch(seq=48, bsz=2, vocab=257, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (bsz, seq)).astype(np.int64)


def _hf_loss(model, ids):
    with torch.no_grad():
        out = model(input_ids=torch.tensor(ids), labels=torch.tensor(ids))
    return float(out.loss)


def _our_loss(model_dir, ids):
    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(model_dir, dtype=jnp.float32)
    params = model.load_hf(model_dir)
    b, s = ids.shape
    labels = np.concatenate(
        [ids[:, 1:], np.full((b, 1), -100)], axis=1
    ).astype(np.int32)
    batch = {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "labels": jnp.asarray(labels),
        "position_ids": jnp.broadcast_to(jnp.arange(s), (b, s)),
        "segment_ids": jnp.ones((b, s), jnp.int32),
    }
    loss_sum, metrics = jax.jit(model.loss_fn)(params, batch)
    return float(loss_sum / metrics["ntokens"])


ALL_KINDS = ["llama", "llama31", "qwen2", "qwen3", "qwen3_moe",
             "gemma3", "deepseek_v3", "gpt_oss",
             "seed_oss", "glm4_moe", "deepseek_v2", "qwen3_next"]


@pytest.mark.parametrize("kind", ALL_KINDS)
def test_loss_parity_vs_hf(tmp_path, kind):
    hf, model_dir = _hf_model(tmp_path, kind)
    ids = _batch()
    expected = _hf_loss(hf, ids)
    got = _our_loss(model_dir, ids)
    np.testing.assert_allclose(got, expected, rtol=2e-4,
                               err_msg=f"{kind}: ours {got} vs HF {expected}")


def _hf_grads(model, ids):
    """(grad_norm, embed_grad, final_norm_grad) of the token-mean loss."""
    model.zero_grad()
    out = model(input_ids=torch.tensor(ids), labels=torch.tensor(ids))
    out.loss.backward()
    sq = 0.0
    for p in model.parameters():
        if p.grad is not None:
            sq += float((p.grad.double() ** 2).sum())
    base = model.model if hasattr(model, "model") else model
    return (
        sq ** 0.5,
        base.embed_tokens.weight.grad.numpy().copy(),
        base.norm.weight.grad.numpy().copy(),
    )


def _our_grads(model_dir, ids):
    import optax

    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(model_dir, dtype=jnp.float32)
    params = model.load_hf(model_dir)
    b, s = ids.shape
    labels = np.concatenate(
        [ids[:, 1:], np.full((b, 1), -100)], axis=1
    ).astype(np.int32)
    batch = {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "labels": jnp.asarray(labels),
        "position_ids": jnp.broadcast_to(jnp.arange(s), (b, s)),
        "segment_ids": jnp.ones((b, s), jnp.int32),
    }

    def norm_loss(p, x):
        loss_sum, metrics = model.loss_fn(p, x)
        return loss_sum / jnp.maximum(metrics["ntokens"], 1)

    grads = jax.jit(jax.grad(norm_loss))(params, batch)
    return (
        float(jax.jit(optax.global_norm)(grads)),
        np.asarray(grads["embed_tokens"]),
        np.asarray(grads["norm"]),
    )


# a representative spread: dense, GQA+qk-norm, stacked-expert MoE, MLA+
# sigmoid routing, fused-expert + sinks, partial-rotary MoE. The backward of
# every custom-VJP op (chunked CE, grouped GEMM, chunked attention) is on
# these paths — a wrong-but-loss-preserving backward fails here.
@pytest.mark.parametrize(
    "kind", ["llama31", "qwen3", "qwen3_moe", "deepseek_v3", "gpt_oss",
             "glm4_moe", "qwen3_next"],
)
def test_grad_parity_vs_hf(tmp_path, kind):
    hf, model_dir = _hf_model(tmp_path, kind)
    ids = _batch()
    ref_gnorm, ref_embed, ref_norm = _hf_grads(hf, ids)
    got_gnorm, got_embed, got_norm = _our_grads(model_dir, ids)
    np.testing.assert_allclose(got_gnorm, ref_gnorm, rtol=1e-3,
                               err_msg=f"{kind} grad_norm")
    # per-tensor check on relative Frobenius error: a wrong backward shows up
    # as an O(1) relative error. Bound measured against an f64 gold: OUR f32
    # grads sit at ~2e-7 from it while HF's own f32 deepseek grads carry
    # ~3.2e-3 of cast-churn noise (routing verified identical) — the bound
    # accommodates the reference's noise, not ours.
    tol = 5e-3 if kind.startswith("deepseek") else 2e-3
    for name, got, ref in (("embed", got_embed, ref_embed),
                           ("final-norm", got_norm, ref_norm)):
        rel = np.linalg.norm(got - ref) / max(np.linalg.norm(ref), 1e-12)
        assert rel < tol, f"{kind} {name} grad relative error {rel:.2e}"


def test_streamed_shard_aligned_load(tmp_path):
    """hf_to_params with target_shardings must produce bit-identical values
    to the unsharded load, via per-slice callback reads (EP-sliced expert
    tensors included)."""
    import numpy as np

    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.models.hf_io import hf_to_params, save_hf_checkpoint
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.train.train_step import resolve_state_shardings

    cfg = TransformerConfig(
        model_type="qwen3_moe", vocab_size=128, hidden_size=32,
        intermediate_size=64, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=8, qk_norm=True,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=16,
        dtype=jnp.float32,
    )
    model = build_foundation_model(config=cfg)
    params = model.init(jax.random.PRNGKey(0))
    out = str(tmp_path / "hf")
    save_hf_checkpoint(params, cfg, out)

    plain = hf_to_params(out, cfg)
    destroy_parallel_state()
    try:
        ps = init_parallel_state(ep_size=2)
        with use_parallel_state(ps):
            shardings = resolve_state_shardings(
                jax.eval_shape(lambda: plain), model.get_parallel_plan(), ps
            )
            sharded = hf_to_params(out, cfg, target_shardings=shardings)
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(plain),
            jax.tree_util.tree_leaves_with_path(sharded),
        ):
            assert pa == pb
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=str(pa)
            )
    finally:
        destroy_parallel_state()


def test_bf16_loss_parity_vs_hf(tmp_path):
    """bf16 compute path vs HF bf16 (loose tolerance: bf16 has ~3 decimal
    digits; catches dtype-handling breaks, not ulp noise)."""
    hf, model_dir = _hf_model(tmp_path, "qwen3")
    hf = hf.to(torch.bfloat16)
    ids = _batch()
    with torch.no_grad():
        expected = float(hf(input_ids=torch.tensor(ids),
                            labels=torch.tensor(ids)).loss)

    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(model_dir, dtype=jnp.bfloat16)
    params = model.load_hf(model_dir)
    b, s = ids.shape
    labels = np.concatenate(
        [ids[:, 1:], np.full((b, 1), -100)], axis=1
    ).astype(np.int32)
    batch = {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "labels": jnp.asarray(labels),
        "position_ids": jnp.broadcast_to(jnp.arange(s), (b, s)),
        "segment_ids": jnp.ones((b, s), jnp.int32),
    }
    loss_sum, metrics = jax.jit(model.loss_fn)(params, batch)
    got = float(loss_sum / metrics["ntokens"])
    np.testing.assert_allclose(got, expected, rtol=2e-2,
                               err_msg=f"bf16: ours {got} vs HF {expected}")
