"""glm_moe_dsa: MLA + DSA lightning-indexer sparse attention + sigmoid-MoE.

No torch oracle (the family is not in transformers), so the suite tests the
invariants the DSA machinery must satisfy: with ``index_topk >= seq_len`` the
sparse path must EQUAL the dense MLA path (selection keeps everything); with
a small top-k the output must differ from dense yet stay packing-consistent;
"shared" indexer layers must reuse the previous layer's selection; and the
indexer must receive no gradient from the LM loss (reference
``GlmMoeDsaIndexer.forward`` is @torch.no_grad)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veomni_tpu.models.config import TransformerConfig
from veomni_tpu.models import transformer

BASE = dict(
    model_type="glm_moe_dsa",
    vocab_size=128,
    hidden_size=48,
    intermediate_size=64,
    moe_intermediate_size=32,
    num_hidden_layers=3,
    num_attention_heads=4,
    num_key_value_heads=4,
    q_lora_rank=24,
    kv_lora_rank=16,
    qk_nope_head_dim=8,
    qk_rope_head_dim=8,
    v_head_dim=8,
    rope_interleave=True,
    num_experts=4,
    num_experts_per_tok=2,
    scoring_func="sigmoid",
    n_group=2,
    topk_group=1,
    norm_topk_prob=True,
    n_shared_experts=1,
    first_k_dense_replace=1,
    index_n_heads=2,
    index_head_dim=16,
    index_topk=4,
    dtype=jnp.float32,
    param_dtype=jnp.float32,
    remat=False,
)


def _mk(cfg_kw):
    cfg = TransformerConfig(**cfg_kw)
    params = transformer.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _batch(cfg, rng, rows, seq):
    ids = rng.integers(1, cfg.vocab_size, (rows, seq)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)
    labels[:, -1] = -100
    return {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(labels),
        "position_ids": jnp.broadcast_to(jnp.arange(seq), (rows, seq)).astype(jnp.int32),
        "segment_ids": jnp.ones((rows, seq), jnp.int32),
    }


def test_topk_full_equals_dense():
    """index_topk >= S selects every causal position -> identical to the
    dense MLA model with the same weights minus the indexer."""
    rng = np.random.default_rng(0)
    s = 16
    kw = dict(BASE, index_topk=s)
    cfg, params = _mk(kw)
    batch = _batch(cfg, rng, 2, s)
    sparse_total, sparse_m = transformer.loss_fn(params, cfg, batch)

    dense_kw = dict(BASE)
    for k in ("index_n_heads", "index_head_dim", "index_topk"):
        dense_kw.pop(k)
    dense_cfg = TransformerConfig(**dense_kw)
    dense_params = jax.tree.map(lambda x: x, params)
    for tree_name in ("dense_layers", "layers"):
        dense_params[tree_name] = {
            k: v for k, v in params[tree_name].items() if k != "indexer"
        }
    dense_total, dense_m = transformer.loss_fn(dense_params, dense_cfg, batch)
    np.testing.assert_allclose(
        float(sparse_m["loss_sum"]), float(dense_m["loss_sum"]), rtol=1e-6
    )


def test_small_topk_differs_and_packs():
    rng = np.random.default_rng(1)
    cfg, params = _mk(BASE)

    # sparse != dense-selection (top-k actually bites)
    s = 16
    batch = _batch(cfg, rng, 1, s)
    _, m_small = transformer.loss_fn(params, cfg, batch)
    cfg_full = TransformerConfig(**dict(BASE, index_topk=s))
    _, m_full = transformer.loss_fn(params, cfg_full, batch)
    assert abs(float(m_small["loss_sum"]) - float(m_full["loss_sum"])) > 1e-6

    # packing equivalence: two segments in one row == two standalone rows
    la, lb = 12, 8
    ids_a = rng.integers(1, cfg.vocab_size, la).astype(np.int32)
    ids_b = rng.integers(1, cfg.vocab_size, lb).astype(np.int32)

    def solo(ids):
        n = len(ids)
        lab = np.concatenate([ids[1:], [-100]]).astype(np.int32)
        b = {
            "input_ids": jnp.asarray(ids)[None],
            "labels": jnp.asarray(lab)[None],
            "position_ids": jnp.arange(n, dtype=jnp.int32)[None],
            "segment_ids": jnp.ones((1, n), jnp.int32),
        }
        _, m = transformer.loss_fn(params, cfg, b)
        return float(m["loss_sum"])

    packed = {
        "input_ids": jnp.asarray(np.concatenate([ids_a, ids_b]))[None],
        "labels": jnp.asarray(np.concatenate(
            [ids_a[1:], [-100], ids_b[1:], [-100]]).astype(np.int32))[None],
        "position_ids": jnp.asarray(
            np.concatenate([np.arange(la), np.arange(lb)]).astype(np.int32))[None],
        "segment_ids": jnp.asarray(np.concatenate(
            [np.ones(la, np.int32), np.full(lb, 2, np.int32)]))[None],
    }
    _, mp = transformer.loss_fn(params, cfg, packed)
    np.testing.assert_allclose(
        float(mp["loss_sum"]), solo(ids_a) + solo(ids_b), rtol=2e-5
    )


def test_shared_indexer_reuses_selection():
    """With indexer_types full/shared/shared, perturbing the LAST layer's own
    indexer weights must not change the loss (its selection comes from layer
    1); perturbing layer 1's indexer must."""
    rng = np.random.default_rng(2)
    kw = dict(BASE, first_k_dense_replace=0,
              indexer_types=("full", "shared", "shared"))
    cfg, params = _mk(kw)
    batch = _batch(cfg, rng, 1, 16)
    base_loss = float(transformer.loss_fn(params, cfg, batch)[1]["loss_sum"])

    def bump(layer):
        # re-randomize the layer's indexer query projection: a fresh matrix
        # re-ranks the relu scores (a mere scale would preserve the top-k)
        p2 = jax.tree.map(lambda x: x, params)
        idx = dict(p2["layers"]["indexer"])
        wq = np.asarray(idx["wq_b"]).copy()
        wq[layer] = np.random.default_rng(99).standard_normal(wq[layer].shape) * 0.5
        idx["wq_b"] = jnp.asarray(wq)
        p2["layers"] = dict(p2["layers"], indexer=idx)
        return float(transformer.loss_fn(p2, cfg, batch)[1]["loss_sum"])

    assert bump(2) == base_loss            # shared layer: own indexer unused
    assert bump(0) != base_loss            # provider layer: selection shifts


def test_indexer_gets_no_lm_gradient():
    rng = np.random.default_rng(3)
    cfg, params = _mk(BASE)
    batch = _batch(cfg, rng, 1, 16)
    grads = jax.grad(lambda p: transformer.loss_fn(p, cfg, batch)[0])(params)
    for tree in ("dense_layers", "layers"):
        for leaf in jax.tree.leaves(grads[tree]["indexer"]):
            assert float(jnp.abs(leaf).max()) == 0.0


def test_hf_roundtrip(tmp_path):
    from veomni_tpu.models import build_foundation_model, hf_io

    cfg, params = _mk(BASE)
    out = tmp_path / "hf"
    hf_io.save_hf_checkpoint(params, cfg, str(out))
    m2 = build_foundation_model(str(out))
    assert m2.config.model_type == "glm_moe_dsa"
    assert m2.config.use_dsa and m2.config.index_topk == cfg.index_topk
    p2 = m2.load_hf(str(out))
    flat_a = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(params)}
    flat_b = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(p2)}
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(
            np.asarray(flat_a[k]), np.asarray(flat_b[k]), err_msg=k
        )
