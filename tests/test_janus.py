"""Janus composite: understanding + generation pathways, VQ invariants,
HF io round-trip (reference ``janus/modeling_janus.py``; no torch oracle —
the family isn't in transformers)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veomni_tpu.models.janus import (
    JanusConfig,
    decode_code,
    gen_vision_encode,
    init_params,
    loss_fn,
)

TEXT = dict(model_type="llama", vocab_size=600, hidden_size=64,
            intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, dtype=jnp.float32,
            param_dtype=jnp.float32, remat=False)
VISION = dict(width=32, layers=2, heads=2, patch_size=8, image_size=32,
              mlp_ratio=2.0)
GEN = dict(codebook_size=32, codebook_embed_dim=6, ch=8,
           encoder_ch_mult=(1, 2), decoder_ch_mult=(1, 2), num_res_blocks=1,
           z_channels=4, image_size=8, num_groups=4)
IMG_ID, GEN_ID = 510, 512


@pytest.fixture(scope="module")
def model():
    cfg = JanusConfig(text=dict(TEXT), vision=dict(VISION), gen_vision=dict(GEN),
                      image_token_id=IMG_ID, image_gen_token_id=GEN_ID,
                      gen_head_embed=48)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _batch(cfg, with_images=True, with_gen=True):
    rng = np.random.default_rng(0)
    s = 64
    t_img = cfg.vision.tokens_per_image       # 16
    t_gen = cfg.gen_vision.tokens_per_image   # 16
    ids = rng.integers(1, 500, (2, s)).astype(np.int32)
    if with_images:
        ids[0, :t_img] = IMG_ID
    if with_gen:
        ids[0, 24:24 + t_gen] = GEN_ID
    labels = np.roll(ids, -1, 1).astype(np.int32)
    labels[:, -1] = -100
    labels[np.roll(ids, -1, 1) >= 500] = -100  # no text CE on placeholders
    batch = {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(labels),
        "position_ids": jnp.broadcast_to(jnp.arange(s), (2, s)).astype(jnp.int32),
        "segment_ids": jnp.ones((2, s), jnp.int32),
    }
    if with_images:
        px = rng.random((2, 1, 32, 32, 3), np.float32)
        mask = np.zeros((2, 1), bool)
        mask[0, 0] = True
        batch["pixel_values"] = jnp.asarray(px)
        batch["image_mask"] = jnp.asarray(mask)
    if with_gen:
        gp = rng.random((2, 1, 8, 8, 3), np.float32) * 2 - 1
        gmask = np.zeros((2, 1), bool)
        gmask[0, 0] = True
        batch["gen_pixels"] = jnp.asarray(gp)
        batch["gen_image_mask"] = jnp.asarray(gmask)
    return batch


def test_loss_paths_live(model):
    cfg, params = model
    batch = _batch(cfg)
    total, metrics = loss_fn(params, cfg, batch)
    assert np.isfinite(float(total))
    assert int(metrics["gen_ntokens"]) == cfg.gen_vision.tokens_per_image

    # understanding tower is live: changing the image changes the loss
    b2 = dict(batch)
    b2["pixel_values"] = batch["pixel_values"] * -1.0
    assert float(loss_fn(params, cfg, b2)[0]) != float(total)
    # frozen VQ: gen_vision gets zero grads; gen head/aligner get signal
    grads = jax.grad(lambda p: loss_fn(p, cfg, batch)[0])(params)
    assert all(float(jnp.abs(g).max()) == 0.0
               for g in jax.tree.leaves(grads["gen_vision"]))
    assert float(jnp.abs(grads["gen_head"]["fc2"]).sum()) > 0.0
    assert float(jnp.abs(grads["gen_embed"]).sum()) > 0.0


def test_gen_loss_trains(model):
    cfg, params = model
    batch = _batch(cfg, with_images=False)

    import optax

    # adam on the generation head/aligner only (sum-space loss makes raw SGD
    # scale-sensitive on a toy codebook; the trainer uses adamw anyway)
    trainable = {k: params[k] for k in ("gen_aligner", "gen_head")}
    opt = optax.adam(3e-3)
    opt_state = opt.init(trainable)

    @jax.jit
    def step(tr, opt_state):
        def f(tr_):
            return loss_fn({**params, **tr_}, cfg, batch)

        (_, m), g = jax.value_and_grad(f, has_aux=True)(tr)
        updates, opt_state = opt.update(g, opt_state, tr)
        return optax.apply_updates(tr, updates), opt_state, m

    trainable, opt_state, m0 = step(trainable, opt_state)
    for _ in range(10):
        trainable, opt_state, m = step(trainable, opt_state)
    gl0 = float(m0["gen_loss_sum"]) / float(m0["gen_ntokens"])
    gl1 = float(m["gen_loss_sum"]) / float(m["gen_ntokens"])
    assert gl1 < gl0 - 0.05, (gl0, gl1)


def test_vq_roundtrip_and_l2(model):
    cfg, params = model
    gv = params["gen_vision"]
    rng = np.random.default_rng(1)
    px = jnp.asarray(rng.random((2, 8, 8, 3), np.float32) * 2 - 1)
    z_q, idx, vq = gen_vision_encode(gv, cfg.gen_vision, px)
    assert idx.shape == (2, 4, 4) and vq.shape == (2,)
    # straight-through value equals the (l2-normed) codebook entry
    rec = decode_code(gv, cfg.gen_vision, idx.reshape(2, -1))
    assert rec.shape == (2, 8, 8, 3)
    from veomni_tpu.models.janus import gen_vision_decode

    rec2 = gen_vision_decode(gv, cfg.gen_vision, z_q)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(rec2), atol=1e-5)


def test_hf_roundtrip(model, tmp_path):
    from veomni_tpu.models import build_foundation_model

    cfg, params = model
    from veomni_tpu.models.auto import MODEL_REGISTRY

    fam = MODEL_REGISTRY.get("janus")
    out = tmp_path / "hf"
    fam.save_hf_checkpoint(params, cfg, str(out))
    m2 = build_foundation_model(str(out))
    assert m2.config.model_type == "janus"
    assert m2.config.gen_vision.codebook_size == cfg.gen_vision.codebook_size
    p2 = m2.load_hf(str(out))
    flat_a = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(params)}
    flat_b = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(p2)}
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_allclose(
            np.asarray(flat_a[k]).astype(np.float32),
            np.asarray(flat_b[k]).astype(np.float32), atol=0, err_msg=k,
        )


def test_janus_trainer_e2e(tmp_path):
    """Trainer drive: understanding + generation images through the omni
    task path (JanusCollator, registry family, replicated VQ plan)."""
    import json

    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer.omni_trainer import OmniTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for i in range(24):
            row = {"input_ids": rng.integers(1, 500, int(rng.integers(10, 24))).tolist()}
            if i % 2:
                row["images"] = [rng.random((32, 32, 3)).tolist()]
            if i % 3 == 0:
                row["gen_images"] = [rng.random((8, 8, 3)).tolist()]
            f.write(json.dumps(row) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "janus",
        "text": dict(TEXT),
        "vision": dict(VISION),
        "gen_vision": dict(GEN),
        "image_token_id": IMG_ID, "image_gen_token_id": GEN_ID,
        "gen_head_embed": 48,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.max_seq_len = 96
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.log_steps = 100
    destroy_parallel_state()
    try:
        trainer = OmniTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 3
        assert np.isfinite(ctl.metrics["loss"])
        trainer.checkpointer.close()
    finally:
        destroy_parallel_state()
