"""Subprocess drive of the real CLI entrypoint (SURVEY §4 test strategy:
the reference spawns its training scripts under torchrun and parses the
emitted metrics; here the script runs on a fresh process with a virtual
CPU mesh + SP/EP so registry/config wiring is exercised from a cold
import, not the warmed test process)."""

import json
import os
import re
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_train_text_cli(tmp_path):
    rng = np.random.default_rng(0)
    data = tmp_path / "data.jsonl"
    with open(data, "w") as f:
        for _ in range(64):
            f.write(json.dumps(
                {"input_ids": rng.integers(0, 256, int(rng.integers(16, 48))).tolist()}
            ) + "\n")
    yaml = tmp_path / "toy.yaml"
    yaml.write_text(f"""
model:
  config_overrides:
    model_type: qwen3_moe
    vocab_size: 256
    hidden_size: 64
    intermediate_size: 128
    num_hidden_layers: 2
    num_attention_heads: 4
    num_key_value_heads: 2
    head_dim: 16
    qk_norm: true
    num_experts: 4
    num_experts_per_tok: 2
    moe_intermediate_size: 64
data:
  train_path: {data}
  data_type: pretokenized
  max_seq_len: 64
train:
  platform: cpu
  num_virtual_devices: 4
  ulysses_parallel_size: 2
  expert_parallel_size: 2
  output_dir: {tmp_path}/out
  micro_batch_size: 2
  train_steps: 3
  bf16: false
  async_save: false
  log_steps: 1
""")
    env = dict(os.environ)
    env["VEOMNI_LOG_LEVEL"] = "INFO"  # conftest silences INFO in-process
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tasks", "train_text.py"), str(yaml)],
        capture_output=True, text=True, timeout=900, cwd=REPO, env=env,
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-3000:]
    # parse the emitted per-step metrics like the reference's log scraping
    losses = [float(m) for m in re.findall(r"step \d+/3 \| loss=([0-9.]+)", out)]
    assert len(losses) == 3, out[-3000:]
    assert all(np.isfinite(losses))
    assert os.path.exists(f"{tmp_path}/out/checkpoints/global_step_3")
