"""Numerics & training-health observatory (ISSUE 14).

* ``tree_health`` unit semantics: RMS/absmax/non-finite counts, per-layer
  vectors for scan-stacked subtrees, update/weight ratio, overflow-margin
  bits, deterministic group-cardinality capping;
* the instrumented sibling step: its own ``numerics_step`` cost-census
  site + ``TRACE_COUNTS`` key, provenance ordering (param beats grad);
* cost-census hygiene: ``CostWindow`` excludes the numerics bucket from
  the MFU math;
* knob-off byte-identical trajectory drill + trace-count gate (exactly one
  extra compiled program when the tier is on, zero steady-state retraces);
* the ``step.params`` nan-fault drill: the supervisor's anomaly re-run
  produces a post-mortem whose provenance doc NAMES the injected group;
* the step_ok window-accumulation gate on the channel-loss accumulators
  (regression for the PR 3 ``step.loss`` nan fault polluting averages);
* ``/debug/numerics`` exporter endpoint.
"""

import json
import os
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from veomni_tpu.arguments import VeOmniArguments


@pytest.fixture(autouse=True)
def _disarm_and_clear():
    yield
    from veomni_tpu.observability.numerics import set_active_monitor
    from veomni_tpu.resilience.faults import disarm_faults

    disarm_faults()
    set_active_monitor(None)
    os.environ.pop("VEOMNI_FAULT_PLAN", None)


# ---------------------------------------------------------------------------
# tree_health unit semantics
# ---------------------------------------------------------------------------

def test_tree_health_stats_and_stacked_groups():
    from veomni_tpu.observability.numerics import NumericsMonitor, tree_health

    L = 3
    params = {
        "layers": {"w": jnp.full((L, 2, 2), 2.0, jnp.float32)},
        "embed": jnp.full((4,), 1.0, jnp.float32),
    }
    grads = {
        # layer 1's grads carry one inf; magnitudes are per-layer distinct
        "layers": {"w": jnp.stack([
            jnp.full((2, 2), 0.5), jnp.full((2, 2), jnp.inf),
            jnp.full((2, 2), 4.0),
        ])},
        "embed": jnp.full((4,), 3.0, jnp.float32),
    }
    updates = {
        "layers": {"w": jnp.full((L, 2, 2), 0.2, jnp.float32)},
        "embed": jnp.full((4,), 0.1, jnp.float32),
    }
    health = tree_health(params, grads, updates)
    assert sorted(health) == ["embed", "layers.w"]

    emb = {k: float(v) for k, v in health["embed"].items()}
    assert emb["grad_rms"] == pytest.approx(3.0)
    assert emb["grad_absmax"] == pytest.approx(3.0)
    assert emb["param_rms"] == pytest.approx(1.0)
    assert emb["update_ratio"] == pytest.approx(0.1, rel=1e-5)
    assert emb["grad_nonfinite"] == 0.0
    # f32 leaf: log2(f32max) - log2(3) = 128 - log2(3)
    assert emb["overflow_margin_bits"] == pytest.approx(
        128 - np.log2(3.0), abs=0.01)

    lw = {k: np.asarray(v) for k, v in health["layers.w"].items()}
    # stacked subtree -> per-layer vectors
    assert lw["grad_rms"].shape == (L,)
    np.testing.assert_allclose(lw["grad_rms"], [0.5, 0.0, 4.0])  # inf masked
    np.testing.assert_allclose(lw["grad_nonfinite"], [0.0, 4.0, 0.0])
    np.testing.assert_allclose(lw["param_rms"], [2.0, 2.0, 2.0])
    np.testing.assert_allclose(lw["update_ratio"], [0.1] * L, rtol=1e-5)

    # host-side provenance ordering: grads bad in layers.w only -> grad kind
    doc = NumericsMonitor._to_doc(health)
    first = NumericsMonitor.first_nonfinite(doc)
    assert first == {"group": "layers.w", "kind": "grad",
                     "nonfinite_count": 4.0, "layer": 1}

    # param beats grad: poison a param too, in a group that sorts EARLIER
    params2 = dict(params)
    params2["embed"] = params["embed"].at[0].set(jnp.nan)
    doc2 = NumericsMonitor._to_doc(tree_health(params2, grads, updates))
    first2 = NumericsMonitor.first_nonfinite(doc2)
    assert first2["group"] == "embed" and first2["kind"] == "param"


def test_build_groups_cap_is_deterministic():
    from veomni_tpu.observability.numerics import REST_GROUP, build_groups

    tree = {f"mod{i:03d}": {"a": 0.0, "b": 1.0} for i in range(40)}
    paths = [p for p, _ in jax.tree_util.tree_leaves_with_path(tree)]
    # uncapped: full leaf-path granularity
    full = build_groups(paths, max_groups=100)
    assert len(full) == 80 and full[0][0] == "mod000.a"
    # capped below the leaf count but above the subtree count: coarsens to
    # the 40 subtree roots (deterministic, no rest bucket)
    coarse = build_groups(paths, max_groups=50)
    assert [n for n, _ in coarse][:2] == ["mod000", "mod001"]
    assert len(coarse) == 40
    assert all(len(m) == 2 for _, m in coarse)
    # capped below even that: sorted head survives, tail merges into rest
    capped = build_groups(paths, max_groups=8)
    names = [n for n, _ in capped]
    assert len(names) == 8 and REST_GROUP in names
    assert names[:3] == [REST_GROUP, "mod000", "mod001"]
    # deterministic across calls
    assert capped == build_groups(paths, max_groups=8)
    # degenerate caps hold EXACTLY: 1 (everything in the rest bucket) and
    # 0 (clamped to 1) — a keep-head of max(1, cap-1) would emit 2 groups
    for cap in (1, 0):
        tiny = build_groups(paths, max_groups=cap)
        assert [n for n, _ in tiny] == [REST_GROUP]
        assert sorted(i for _, m in tiny for i in m) == list(range(80))


# ---------------------------------------------------------------------------
# instrumented sibling step: own census site, own trace counter
# ---------------------------------------------------------------------------

def test_numerics_sibling_step_site_and_counts(monkeypatch):
    from veomni_tpu.observability.cost import get_cost_census
    from veomni_tpu.observability.numerics import NumericsSpec
    from veomni_tpu.train import build_train_state, build_train_step
    from veomni_tpu.train.train_step import TRACE_COUNTS

    monkeypatch.setenv("VEOMNI_DONATE_STATE", "1")  # sibling must ignore it

    def loss_fn(params, micro):
        loss = (params["w"] * micro["x"]).sum() * micro["scale"][0]
        return loss, {"ntokens": jnp.int32(micro["x"].size)}

    opt = optax.adam(0.1)
    state = build_train_state({"w": jnp.ones((4,), jnp.float32)}, opt)
    step = build_train_step(loss_fn, opt, None, skip_nonfinite=True,
                            numerics_spec=NumericsSpec())

    def batch(scale):
        return {"x": jnp.ones((1, 4), jnp.float32),
                "scale": jnp.full((1, 1), scale, jnp.float32)}

    t0 = TRACE_COUNTS["numerics_step"]
    # the census is process-global: other tests may already have a
    # train_step/1x4 record — the sibling must not bump ITS call count
    hot = get_cost_census().get("train_step", "1x4")
    hot_calls = hot.calls if hot is not None else 0
    st2, metrics, health = step(state, batch(1.0))
    assert bool(metrics["step_ok"]) and "w" in health
    # no donation: the input state must still be alive and re-steppable
    st3, m3, h3 = step(state, batch(float("nan")))
    assert not bool(m3["step_ok"])
    assert float(h3["w"]["grad_nonfinite"]) > 0
    assert TRACE_COUNTS["numerics_step"] == t0 + 1  # one program, two calls
    rec = get_cost_census().get("numerics_step", "1x4")
    assert rec is not None and rec.calls >= 2
    # the hot site is untouched by the sibling's compiles and calls
    hot = get_cost_census().get("train_step", "1x4")
    assert (hot.calls if hot is not None else 0) == hot_calls


def test_costwindow_excludes_numerics_site():
    from veomni_tpu.observability.cost import CostCensus, CostWindow
    from veomni_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry()
    census = CostCensus(registry=reg)
    census.record("train_step", "b", flops=100.0, bytes_accessed=10.0)
    census.record("numerics_step", "b", flops=1e9, bytes_accessed=1e9)
    window = CostWindow(census)
    window.begin()
    for _ in range(4):
        census.note_call("train_step", "b")
        census.note_call("numerics_step", "b")
    out = window.end()
    # achieved FLOPs counted the train-step program only: the diagnostic
    # site's 1e9-FLOPs program must not inflate the window
    assert out["census_tflops_s"] * 1e12 * out["census_window_s"] == \
        pytest.approx(400.0, rel=1e-6)
    # an explicit allowlist overrides the exclusion
    w2 = CostWindow(census, sites=("numerics_step",))
    w2.begin()
    census.note_call("numerics_step", "b")
    out2 = w2.end()
    assert out2["census_tflops_s"] > 0


# ---------------------------------------------------------------------------
# e2e: knob-off byte-identical trajectory + trace-count gate
# ---------------------------------------------------------------------------

DENSE_TOY = {
    "model_type": "qwen3", "vocab_size": 256, "hidden_size": 32,
    "intermediate_size": 64, "num_hidden_layers": 2,
    "num_attention_heads": 2, "num_key_value_heads": 2, "head_dim": 16,
    "qk_norm": True,
}


def _write_data(path, n=96, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            f.write(json.dumps({
                "input_ids": rng.integers(
                    0, vocab, int(rng.integers(16, 80))).tolist(),
            }) + "\n")


def _dense_args(tmp_path, out_name="out", **train_overrides):
    args = VeOmniArguments()
    args.model.config_overrides = dict(DENSE_TOY)
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.train.output_dir = str(tmp_path / out_name)
    args.train.micro_batch_size = 2
    args.train.train_steps = 6
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 1
    for k, v in train_overrides.items():
        setattr(args.train, k, v)
    return args


def _run(args):
    from veomni_tpu.trainer import TextTrainer
    from veomni_tpu.trainer.callbacks import Callback

    trainer = TextTrainer(args)
    losses = {}

    class Rec(Callback):
        def on_step_end(self, t, state):
            if state.synced:
                losses[state.global_step] = float(state.metrics["loss"]).hex()

    trainer.callbacks.append(Rec())
    ctl = trainer.train()
    params = jax.tree.map(np.asarray, trainer.train_state.params)
    trainer.checkpointer.close()
    return ctl, losses, params, trainer


def test_knob_off_byte_identical_and_trace_count_gate(tmp_path):
    from veomni_tpu.observability.cost import get_cost_census
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.train.train_step import TRACE_COUNTS

    _write_data(tmp_path / "data.jsonl")

    n0, t0 = TRACE_COUNTS["numerics_step"], TRACE_COUNTS["train_step"]
    _ctl_off, losses_off, params_off, _ = _run(_dense_args(tmp_path, "off"))
    destroy_parallel_state()
    # knob off (the default): the tier contributes NOTHING — no sibling
    # program exists, the hot step compiled exactly once
    assert TRACE_COUNTS["numerics_step"] == n0
    assert TRACE_COUNTS["train_step"] == t0 + 1

    _ctl_on, losses_on, params_on, trainer_on = _run(
        _dense_args(tmp_path, "on", observability_numerics_interval=2)
    )
    # trace-count gate: the tier costs exactly ONE extra compiled program
    # (the sibling), zero steady-state retraces of either site
    assert TRACE_COUNTS["numerics_step"] == n0 + 1
    assert TRACE_COUNTS["train_step"] == t0 + 2
    # with interval=2 over 6 steps the sibling ran on steps 2/4/6
    rec = get_cost_census().latest("numerics_step")
    assert rec is not None and rec.calls >= 3

    # the instrumented sibling computes the SAME update math: trajectory
    # and final params are bit-identical to the knob-off run
    assert losses_on == losses_off
    la, lb = jax.tree.leaves(params_off), jax.tree.leaves(params_on)
    assert all(np.array_equal(x, y) for x, y in zip(la, lb))

    # the interval cadence published numerics gauges + filled the history
    from veomni_tpu.observability.metrics import get_registry

    names = [n for n, _ in get_registry().items_snapshot()
             if n.startswith("numerics.")]
    assert any(".grad_rms" in n for n in names)
    assert any(".update_ratio" in n for n in names)
    assert trainer_on._numerics is not None
    assert len(trainer_on._numerics.snapshot()["history"]) == 3


# ---------------------------------------------------------------------------
# e2e: step.params nan drill -> post-mortem names the injected group
# ---------------------------------------------------------------------------

def test_step_params_drill_postmortem_names_injected_group(tmp_path):
    from veomni_tpu.resilience import AnomalyBudgetExceeded
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(
        tmp_path, train_steps=8,
        observability_numerics_interval=100,  # tier armed; cadence unused
        resilience_anomaly_budget=1, resilience_rollback_after=10,
    )
    configure_faults([{"point": "step.params", "mode": "nan", "hit": 3,
                       "group": "layers.q_proj"}])
    with pytest.raises(AnomalyBudgetExceeded):
        _run(args)

    pm = json.load(open(os.path.join(args.train.output_dir,
                                     "postmortem-0.json")))
    assert pm["reason"] == "exception:AnomalyBudgetExceeded"
    prov = pm["numerics"]["provenance"]
    first = prov["first_nonfinite"]
    # the provenance doc NAMES the injected group — and classifies it as a
    # PARAM problem (upstream of the NaN grads it caused everywhere else)
    assert first["group"] == "layers.q_proj"
    assert first["kind"] == "param"
    assert first["layer"] == 0
    assert prov["groups"]["layers.q_proj"]["param_nonfinite"][0] > 0
    # flight recorder carries the same attribution
    evs = [e for e in pm["events"] if e.get("kind") == "numerics.nonfinite"]
    assert evs and evs[0]["payload"]["group"] == "layers.q_proj"


def test_fault_plan_step_params_grammar():
    from veomni_tpu.resilience import faults

    # nan mode now covers step.params, carrying the group on the action
    faults.configure_faults([{"point": "step.params", "mode": "nan",
                              "group": "layers.mlp"}])
    act = faults.fault_point("step.params")
    assert act is not None and act.mode == "nan"
    assert act.target == "layers.mlp"
    faults.disarm_faults()
    # ...but stays rejected anywhere else
    with pytest.raises(ValueError, match="step.params"):
        faults.configure_faults([{"point": "ckpt.save", "mode": "nan"}])


def test_poison_param_group_targets_match():
    from veomni_tpu.observability.numerics import poison_param_group

    params = {
        "embed": jnp.ones((4,), jnp.float32),
        "layers": {"q_proj": jnp.ones((2, 3), jnp.float32),
                   "tid": jnp.ones((2,), jnp.int32)},
    }
    poisoned, target = poison_param_group(params, "q_proj")
    assert target == "layers.q_proj"
    assert not np.isfinite(np.asarray(poisoned["layers"]["q_proj"])).all()
    assert np.isfinite(np.asarray(poisoned["embed"])).all()
    # empty pattern: first float leaf in sorted-path order; int leaves are
    # never poisoned
    _, t2 = poison_param_group(params, "")
    assert t2 == "embed"
    same, t3 = poison_param_group(params, "tid")
    assert t3 == "" and same is params


# ---------------------------------------------------------------------------
# step_ok window-accumulation gate (satellite bugfix)
# ---------------------------------------------------------------------------

def _channel_cb_step(cb, metrics, step=1):
    from veomni_tpu.trainer.callbacks import TrainerControlState

    state = TrainerControlState(global_step=step)
    state.metrics = metrics
    cb.on_step_end(None, state)


def test_channel_loss_accumulation_gated_on_step_ok():
    from veomni_tpu.train.channel_loss import ChannelLossCallback

    cb = ChannelLossCallback(["a", "b"], log_steps=100)
    sums = jnp.asarray([2.0, 4.0])
    counts = jnp.asarray([1.0, 2.0])

    # host-flag False (sync step / injected drill): contribution dropped
    _channel_cb_step(cb, {"channel_loss_sums": sums,
                          "channel_token_counts": counts,
                          "step_ok": False})
    assert cb._acc_sums is None

    # device-array False (async step): masked lazily to zeros, loop stays
    # async (no fetch happened here)
    _channel_cb_step(cb, {"channel_loss_sums": sums * jnp.nan,
                          "channel_token_counts": counts,
                          "step_ok": jnp.asarray(False)})
    np.testing.assert_allclose(np.asarray(cb._acc_sums), [0.0, 0.0])

    # ok steps accumulate as before
    _channel_cb_step(cb, {"channel_loss_sums": sums,
                          "channel_token_counts": counts,
                          "step_ok": jnp.asarray(True)})
    _channel_cb_step(cb, {"channel_loss_sums": sums,
                          "channel_token_counts": counts,
                          "step_ok": 1.0})
    cb._fold()
    np.testing.assert_allclose(cb._sums, [4.0, 8.0])
    np.testing.assert_allclose(cb._counts, [2.0, 4.0])


def test_channel_loss_e2e_excludes_injected_nan_step(tmp_path):
    """PR 3 ``step.loss`` nan-fault regression: the injected anomalous
    step's per-channel sums/counts must NOT pollute the window
    accumulators — lifetime channel token counts equal the sum over the
    OK steps only."""
    from veomni_tpu.resilience.faults import configure_faults
    from veomni_tpu.trainer import TextTrainer
    from veomni_tpu.trainer.callbacks import Callback

    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for i in range(96):
            f.write(json.dumps({
                "input_ids": rng.integers(
                    0, 256, int(rng.integers(16, 60))).tolist(),
                "channel": "web" if i % 2 else "code",
            }) + "\n")
    args = _dense_args(tmp_path, train_steps=4)
    args.data.channel_list = ["code", "web"]
    configure_faults([{"point": "step.loss", "mode": "nan", "hit": 2}])

    trainer = TextTrainer(args)
    per_step_tokens = {}

    class Rec(Callback):
        def on_step_end(self, t, state):
            if state.synced:
                per_step_tokens[state.global_step] = float(
                    state.metrics["ntokens"])

    # BEFORE ChannelLossCallback in hook order: it pops the channel metrics
    trainer.callbacks.insert(0, Rec())
    ctl = trainer.train()
    trainer.checkpointer.close()
    assert ctl.resilience["anomaly_steps"] == [2]
    cb = [c for c in trainer.callbacks
          if type(c).__name__ == "ChannelLossCallback"][0]
    cb._fold()
    expected = sum(v for s, v in per_step_tokens.items() if s != 2)
    assert sum(cb._counts) == pytest.approx(expected)
    assert all(np.isfinite(s) for s in cb._sums)


# ---------------------------------------------------------------------------
# exporter endpoint + post-mortem attach
# ---------------------------------------------------------------------------

def test_debug_numerics_endpoint():
    from veomni_tpu.observability.exporter import MetricsExporter
    from veomni_tpu.observability.numerics import (
        NumericsMonitor,
        set_active_monitor,
        tree_health,
    )

    exp = MetricsExporter(port=0, host="127.0.0.1")
    port = exp.start()
    try:
        def get():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/numerics") as r:
                return json.loads(r.read())

        set_active_monitor(None)
        doc = get()
        assert doc["enabled"] is False and "interval" in doc["hint"]

        mon = NumericsMonitor()
        set_active_monitor(mon)
        params = {"w": jnp.ones((2,), jnp.float32)}
        grads = {"w": jnp.asarray([jnp.nan, 1.0])}
        health = tree_health(params, grads, params)
        mon.observe(7, health)
        mon.diagnose(7, health)
        doc = get()
        assert doc["enabled"] is True
        assert doc["latest"]["step"] == 7
        assert doc["provenance"]["first_nonfinite"]["group"] == "w"
        assert doc["provenance"]["first_nonfinite"]["kind"] == "grad"
    finally:
        exp.stop()
        set_active_monitor(None)


def test_attach_numerics_extra_tolerates_no_monitor():
    from veomni_tpu.observability.numerics import (
        attach_numerics_extra,
        set_active_monitor,
    )

    set_active_monitor(None)
    extra = {}
    attach_numerics_extra(extra)
    assert "numerics" not in extra
