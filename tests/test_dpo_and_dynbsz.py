"""DPO trainer + dynamic batching tests."""

import json

import numpy as np
import pytest

from veomni_tpu.arguments import VeOmniArguments

TOY = {
    "model_type": "qwen2",
    "vocab_size": 256,
    "hidden_size": 64,
    "intermediate_size": 128,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "head_dim": 16,
    "attention_bias": True,
}


def test_dpo_trainer_e2e(tmp_path):
    from veomni_tpu.trainer.dpo_trainer import TextDPOTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "dpo.jsonl", "w") as f:
        for _ in range(64):
            f.write(json.dumps({
                "prompt": rng.integers(0, 256, int(rng.integers(4, 16))).tolist(),
                "chosen": rng.integers(0, 256, int(rng.integers(4, 24))).tolist(),
                "rejected": rng.integers(0, 256, int(rng.integers(4, 24))).tolist(),
            }) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = dict(TOY)
    args.data.train_path = str(tmp_path / "dpo.jsonl")
    args.data.data_type = "dpo"
    args.data.max_seq_len = 64
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 100
    trainer = TextDPOTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert np.isfinite(ctl.metrics["loss"])
    trainer.checkpointer.close()


def test_dyn_bsz_buffer_knapsack():
    from veomni_tpu.data.dynamic_batching import DynBszBuffer

    buf = DynBszBuffer(token_budget=100, buffer_size=10)
    for n in (60, 50, 40, 30, 10):
        buf.put({"input_ids": list(range(n))})
    batch = buf.pop_batch()
    total = sum(len(s["input_ids"]) for s in batch)
    assert total <= 100 and total >= 90  # 60+40 or 60+30+10
    assert len(buf) == 5 - len(batch)


def test_dynamic_dataloader_resume(tmp_path):
    from veomni_tpu.data.data_collator import TextPackingCollator
    from veomni_tpu.data.dataset import MappingDataset
    from veomni_tpu.data.dynamic_batching import DynamicBatchDataloader

    rng = np.random.default_rng(0)
    rows = [{"input_ids": rng.integers(0, 99, int(rng.integers(10, 60))).tolist()}
            for _ in range(128)]
    ds = MappingDataset(rows=rows)

    def make():
        return DynamicBatchDataloader(
            ds, TextPackingCollator(seq_len=128, micro_batch_size=2),
            token_budget=256, grad_accum_steps=1, buffer_size=16, seed=3,
        )

    dl = make()
    it = iter(dl)
    for _ in range(3):
        next(it)
    state = dl.state_dict()
    a = next(it)

    dl2 = make()
    dl2.load_state_dict(state)
    b = next(iter(dl2))
    np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
