"""Import-time device hygiene (TPU analogue of the reference's
``tests/special_sanity/check_device_api_usage.py`` .cuda()-literal gate).

On this stack the portable-device sin is *initializing a JAX backend at
import time*: under the axon relay a backend init is a (possibly blocking,
exclusive) TPU chip claim, so any module that calls jax.devices() /
jax.device_count() at import turns `import veomni_tpu.x` into a second chip
claimant — see BENCH_NOTES r5 "parse-time backend-init hazard". Every
veomni_tpu module must import cleanly with backend construction forbidden.

Runs in a SUBPROCESS: in a full-suite run earlier tests have already
imported (and cached in sys.modules) nearly every module, which would make
an in-process walk vacuous.
"""

import subprocess
import sys

_WALK = r"""
import importlib, pkgutil, sys
from jax._src import xla_bridge

def _forbidden(*a, **k):
    raise AssertionError("backend-init-at-import")

xla_bridge.backends = _forbidden
xla_bridge.get_backend = _forbidden

import veomni_tpu

failures = []
visited = []
for m in pkgutil.walk_packages(veomni_tpu.__path__, "veomni_tpu."):
    visited.append(m.name)
    try:
        importlib.import_module(m.name)
    except AssertionError:
        failures.append(m.name)
    except Exception:
        pass  # unrelated import errors (optional deps) are other tests' job
if failures:
    print("FAILURES:" + ",".join(failures))
    sys.exit(1)
# these packages must be part of the walk (a missing __init__.py would
# silently drop a whole subtree from this gate)
for required in ("veomni_tpu.serving", "veomni_tpu.serving.engine",
                 "veomni_tpu.resilience", "veomni_tpu.resilience.faults",
                 "veomni_tpu.resilience.integrity",
                 "veomni_tpu.resilience.retry", "veomni_tpu.resilience.supervisor",
                 "veomni_tpu.observability", "veomni_tpu.observability.metrics",
                 "veomni_tpu.observability.spans",
                 "veomni_tpu.observability.goodput",
                 "veomni_tpu.observability.exporter",
                 "veomni_tpu.observability.callback",
                 "veomni_tpu.observability.flight_recorder",
                 "veomni_tpu.observability.request_trace",
                 "veomni_tpu.observability.cost",
                 "veomni_tpu.observability.numerics",
                 "veomni_tpu.observability.devmem",
                 "veomni_tpu.observability.comm",
                 "veomni_tpu.observability.fleet"):
    if required not in visited:
        print("MISSING:" + required)
        sys.exit(1)
print("CLEAN")
"""


def test_no_backend_init_at_import():
    p = subprocess.run(
        [sys.executable, "-c", _WALK], capture_output=True, text=True,
        cwd="/root/repo", timeout=600,
    )
    assert p.returncode == 0 and "CLEAN" in p.stdout, (
        f"backend init at import: {p.stdout}\n{p.stderr[-500:]}"
    )
