"""Import-time device hygiene (TPU analogue of the reference's
``tests/special_sanity/check_device_api_usage.py`` .cuda()-literal gate).

On this stack the portable-device sin is *initializing a JAX backend at
import time*: under the axon relay a backend init is a (possibly blocking,
exclusive) TPU chip claim, so any module that calls jax.devices() /
jax.device_count() at import turns `import veomni_tpu.x` into a second chip
claimant — see BENCH_NOTES r5 "parse-time backend-init hazard". Every
veomni_tpu module must import cleanly with backend construction forbidden.
"""

import importlib
import pkgutil

import pytest


def _walk_modules():
    import veomni_tpu

    for m in pkgutil.walk_packages(veomni_tpu.__path__, "veomni_tpu."):
        yield m.name


@pytest.mark.filterwarnings("ignore")
def test_no_backend_init_at_import(monkeypatch):
    from jax._src import xla_bridge

    def _forbidden(*a, **k):
        raise AssertionError(
            "JAX backend initialized at import time — on the axon relay "
            "this is a blocking exclusive TPU chip claim"
        )

    monkeypatch.setattr(xla_bridge, "backends", _forbidden)
    monkeypatch.setattr(xla_bridge, "get_backend", _forbidden)
    # jax.devices()/device_count()/local_devices() all route through these
    failures = []
    for name in _walk_modules():
        try:
            importlib.import_module(name)
        except AssertionError as e:
            failures.append((name, str(e).split(" — ")[0]))
        except Exception:
            # unrelated import errors (optional deps) are other tests' job
            pass
    assert not failures, f"backend init at import: {failures}"
