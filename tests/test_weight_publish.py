"""Crash-safe live weight publication drills.

The rolling publish (docs/serving.md "Versioned weight publication")
turns the router's version tag from a label on FUTURE replicas into a
live control surface over the RUNNING fleet. These tests pin its whole
contract:

* ``WeightStore``: monotonic sequence numbers, immutable version tags;
* the engine swap: drain-fenced (never mid-stream), structurally
  validated (an incongruent payload would silently retrace — refused),
  prefix cache flushed under a bumped ``cache_epoch`` with the
  block-manager no-leak identity conserved, and ZERO new jit traces;
* **token parity**: a published engine must produce byte-identical
  streams to a FRESH engine built on the new weights — any divergence
  means stale KV (or stale buffers) survived the swap;
* the roll: one replica at a time, ``min_live`` respected, nobody
  starved, respawns and late arrivals attach at the LATEST version even
  when the publish itself is what killed a replica (``serve.publish``
  fault drill);
* the checkpoint gate: corrupt or uncommitted generations are refused
  BEFORE any buffer is touched;
* chaos composition: publish events extend seeded plans without moving
  a single fault/kill draw of existing seeds, and a soak that schedules
  publishes without a ``publish_fn`` refuses to silently skip them.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.models import decode as decode_mod
from veomni_tpu.resilience.faults import configure_faults, disarm_faults
from veomni_tpu.resilience.integrity import (
    CheckpointCorruptError,
    write_manifest,
)
from veomni_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    Request,
    SamplingParams,
    WeightStore,
    load_published_params,
)
from veomni_tpu.serving.replica import STATE_PROBATION
from veomni_tpu.serving.router import Router, RouterConfig

QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)


@pytest.fixture(scope="module")
def qwen3():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    disarm_faults()


def _perturb(params, seed=7, scale=0.1):
    """A payload that is congruent but decisively DIFFERENT: per-leaf
    additive noise big enough to move greedy argmaxes, proving a swap is
    live rather than a no-op."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    rng = np.random.default_rng(seed)
    out = []
    for x in leaves:
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact):
            noise = rng.standard_normal(x.shape).astype(np.float32) * scale
            out.append(x + jnp.asarray(noise, dtype=x.dtype))
        else:
            out.append(x)
    return jax.tree_util.tree_unflatten(treedef, out)


def _prompts(n, seed=0, length=8, prefix=()):
    rng = np.random.default_rng(seed)
    return [list(prefix) + [int(t) for t in rng.integers(1, 128, length)]
            for _ in range(n)]


def _reqs(prompts, n_new=6):
    return [Request(prompt_ids=list(p),
                    sampling=SamplingParams(max_new_tokens=n_new))
            for p in prompts]


def _pool_identity(eng):
    bm = eng.blocks
    assert bm.num_used == 0
    assert bm.num_free_uncached + bm.num_cached == bm.num_blocks - 1


def _engine_cfg(**kw):
    kw.setdefault("num_blocks", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_model_len", 128)
    return EngineConfig(**kw)


def _drain(router, timeout_s=60.0):
    deadline = time.perf_counter() + timeout_s
    while router.has_work and time.perf_counter() < deadline:
        router.step()
    assert not router.has_work, "router failed to drain"


def _restore_fleet(router, probe_prompt, timeout_s=60.0):
    """Drive respawns to landing and probation replicas to parole (same
    idiom as test_self_healing.py). Returns probe request ids."""
    probes = []
    deadline = time.perf_counter() + timeout_s
    n_cfg = router.config.replicas
    while time.perf_counter() < deadline:
        probation = [h for h in router.replicas.values()
                     if h.state == STATE_PROBATION]
        if (len(router.live_replicas()) >= n_cfg
                and not router._pending_respawns and not probation
                and not router.has_work):
            return probes
        if router.has_work or router._pending_respawns:
            router.step()
            continue
        burst = router.config.spill_queue_depth + 1 + sum(
            router.config.probation_requests for _ in probation)
        for req in _reqs([probe_prompt] * burst, n_new=4):
            probes.append(router.submit(req))
    raise AssertionError("fleet did not restore in time")


# --------------------------------------------------------------- WeightStore
def test_weight_store_monotonic_seq_and_immutable_tags(qwen3):
    params, _ = qwen3
    store = WeightStore(params, "v0")
    assert store.latest.version == "v0" and store.latest.seq == 0
    rec = store.put("step-100", params)
    assert rec.seq == 1 and store.latest.version == "step-100"
    assert store.seq("v0") == 0 and store.seq("step-100") == 1
    assert store.seq("never-published") == -1
    assert store.versions() == ["v0", "step-100"]
    assert "v0" in store and "nope" not in store and len(store) == 2
    with pytest.raises(ValueError, match="immutable"):
        store.put("v0", params)  # retagging is a caught operator error
    with pytest.raises(ValueError, match="non-empty"):
        store.put("", params)
    assert store.get("v0").params is params


# ----------------------------------------------------------- the engine swap
def test_swap_refuses_busy_engine_and_incongruent_payloads(qwen3):
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, _engine_cfg())
    eng.submit(_reqs(_prompts(1), n_new=4)[0])
    with pytest.raises(RuntimeError, match="busy engine"):
        eng.swap_weights(_perturb(params))
    eng.run()  # drain; swaps are legal again
    # dtype change on every float leaf: congruence check must refuse it
    # BEFORE any state changes (it would silently retrace every program)
    half = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)
        else x, params)
    epoch_before = eng.cache_epoch
    with pytest.raises(ValueError, match="incongruent"):
        eng.swap_weights(half)
    assert eng.cache_epoch == epoch_before  # refusal changed nothing


def test_swap_flushes_prefix_cache_no_leak_identity(qwen3):
    """The cache-epoch invalidation: a swap flushes EVERY cached block
    back to the free pool (the no-leak identity holds across the flush),
    bumps the epoch, and the cache repopulates cleanly afterwards."""
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, _engine_cfg())
    shared = tuple(_prompts(1, seed=3, length=16)[0])
    eng.run(_reqs(_prompts(4, seed=4, prefix=shared), n_new=4))
    bm = eng.blocks
    cached_before = bm.num_cached
    assert cached_before > 0  # the swap has real cached KV to invalidate
    _pool_identity(eng)
    assert eng.cache_epoch == 0 and eng.prefix_cache.epoch == 0
    info = eng.swap_weights(_perturb(params))
    assert info["flushed_blocks"] == cached_before
    assert info["cache_epoch"] == 1
    assert eng.cache_epoch == 1 and eng.prefix_cache.epoch == 1
    assert bm.num_cached == 0  # stale KV is unreachable, not leaked
    _pool_identity(eng)
    # the flushed cache repopulates under the new weights
    eng.run(_reqs(_prompts(4, seed=5, prefix=shared), n_new=4))
    assert bm.num_cached > 0
    _pool_identity(eng)


def test_swap_token_parity_vs_fresh_engine_zero_traces(qwen3):
    """THE acceptance gate: after swapping perturbed weights into an
    engine with a hot prefix cache, its outputs must be token-identical
    to a FRESH engine built on the new weights (zero stale KV anywhere),
    must DIFFER from the old weights' streams (the swap is live), and
    the swap + post-swap serving must add zero jit traces."""
    params, cfg = qwen3
    new_params = _perturb(params)
    ecfg = _engine_cfg(num_slots=2)
    shared = tuple(_prompts(1, seed=9, length=16)[0])
    prompts = _prompts(4, seed=10, prefix=shared)
    eng = InferenceEngine(params, cfg, ecfg)
    old_outs = eng.run(_reqs(prompts))  # warm: cache hot, buckets traced
    _pool_identity(eng)
    base = dict(decode_mod.TRACE_COUNTS)
    eng.swap_weights(new_params)
    outs = eng.run(_reqs(prompts))  # same shapes -> same buckets
    assert decode_mod.TRACE_COUNTS == base, "weight swap must not retrace"
    fresh = InferenceEngine(new_params, cfg, ecfg)
    fresh_outs = fresh.run(_reqs(prompts))
    by_tokens = lambda outs: sorted(o.token_ids for o in outs.values())
    assert by_tokens(outs) == by_tokens(fresh_outs), \
        "published engine diverged from fresh engine on the same weights"
    assert by_tokens(outs) != by_tokens(old_outs), \
        "outputs unchanged after swap: the perturbed publish was a no-op"


# ------------------------------------------------------------ the rolling roll
def test_rolling_publish_respects_min_live_and_starves_nobody(qwen3):
    """A publish under load rolls ONE replica at a time, never drops the
    live fleet below min_live, and every request — submitted before,
    during and after the roll — reaches a clean terminal output."""
    params, cfg = qwen3
    r = Router(params, cfg, _engine_cfg(num_slots=2), RouterConfig(
        replicas=3, min_live=2))
    ids = [r.submit(q) for q in _reqs(_prompts(6, seed=20), n_new=5)]
    for _ in range(2):
        r.step()
    assert r.publish_weights(_perturb(params), "v1") == "v1"
    min_live_seen = len(r.live_replicas())
    max_publishing = 0
    ids += [r.submit(q) for q in _reqs(_prompts(4, seed=21), n_new=5)]
    deadline = time.perf_counter() + 60.0
    while r.has_work and time.perf_counter() < deadline:
        r.step()
        min_live_seen = min(min_live_seen, len(r.live_replicas()))
        max_publishing = max(max_publishing, sum(
            1 for h in r.replicas.values() if h.state == "publishing"))
    assert not r.has_work
    assert min_live_seen >= 2, "publish took the fleet below min_live"
    assert max_publishing <= 1, "roll must fence one replica at a time"
    assert not r.publish_in_progress
    assert all(h.weights_version == "v1" for h in r.live_replicas())
    outs = {i: r.pop_output(i) for i in ids}
    assert all(o is not None and o.finish_reason == "length"
               for o in outs.values()), "a request starved during the roll"


def test_kill_mid_publish_respawn_attaches_at_latest_version(qwen3):
    """The crash drill: ``serve.publish`` kills the first victim inside
    its swap window. Failure triage must run (no lost ids), the respawn
    must attach at the LATEST version (the satellite-1 bugfix pin — an
    ancestor-version respawn would freeze the fleet mixed forever), and
    the fleet still converges to one version with zero leaked blocks."""
    params, cfg = qwen3
    r = Router(params, cfg, _engine_cfg(), RouterConfig(
        replicas=3, min_live=1, probation_requests=1,
        respawn_backoff_s=0.05))
    probe = _prompts(1, seed=30)[0]
    ids = [r.submit(q) for q in _reqs(_prompts(3, seed=31), n_new=4)]
    _drain(r)
    configure_faults([{"point": "serve.publish", "mode": "exception",
                       "hit": 1, "times": 1}])
    r.publish_weights(_perturb(params), "v1")
    probes = _restore_fleet(r, probe)
    disarm_faults()
    _drain(r)
    assert not r.publish_in_progress
    replicas = list(r.replicas.values())
    assert len(r.live_replicas()) == 3
    assert all(h.weights_version == "v1" for h in replicas)
    died = [h for h in replicas if h.generation > 0]
    assert len(died) == 1, "exactly one replica dies in this drill"
    for i in ids + probes:  # nobody lost, nobody duplicated
        assert r.pop_output(i) is not None
        assert r.pop_output(i) is None
    for h in replicas:
        _pool_identity(h.engine)


def test_publish_then_respawn_parity_with_add_replica(qwen3):
    """Respawns and freshly-added replicas agree: both attach at the
    latest published version, not at the fleet's founding version."""
    params, cfg = qwen3
    r = Router(params, cfg, _engine_cfg(), RouterConfig(
        replicas=2, min_live=1, probation_requests=0,
        respawn_backoff_s=0.05))
    r.publish_weights(_perturb(params), "v1")
    _drain(r)  # converge the publish first
    victim = next(iter(r.live_replicas()))
    r.kill_replica(victim.rid)
    probe = _prompts(1, seed=40)[0]
    _restore_fleet(r, probe)
    _drain(r)
    assert r.replicas[victim.rid].generation == 1
    assert r.replicas[victim.rid].weights_version == "v1"
    added = r.add_replica()
    assert added.weights_version == "v1"
    assert all(h.weights_version == "v1" for h in r.replicas.values())


# ------------------------------------------------------- the checkpoint gate
def _fake_generation(tmp_path, name="global_step_7", payload=b"x" * 512):
    step_dir = tmp_path / name
    (step_dir / "train_state").mkdir(parents=True)
    (step_dir / "train_state" / "arrays.bin").write_bytes(payload)
    return str(step_dir)


def test_publish_from_checkpoint_integrity_gate(qwen3, tmp_path):
    """Corrupt and uncommitted generations are refused BEFORE the loader
    runs — the fleet's buffers and version history stay untouched."""
    params, cfg = qwen3
    new_params = _perturb(params)
    loads = []

    def loader(step_dir):
        loads.append(step_dir)
        return new_params

    r = Router(params, cfg, _engine_cfg(), RouterConfig(
        replicas=2))
    # clean generation: manifest written, loader runs, fleet converges
    good = _fake_generation(tmp_path, "global_step_7")
    write_manifest(good, subtrees=("train_state",))
    assert r.publish_from_checkpoint(good, loader) == "global_step_7"
    _drain(r)
    assert all(h.weights_version == "global_step_7"
               for h in r.live_replicas())
    assert loads == [good]
    # truncated payload: CORRUPT — refused, loader never called
    bad = _fake_generation(tmp_path, "global_step_8")
    write_manifest(bad, subtrees=("train_state",))
    os.truncate(os.path.join(bad, "train_state", "arrays.bin"), 1)
    with pytest.raises(CheckpointCorruptError, match="verification failed"):
        r.publish_from_checkpoint(bad, loader)
    # uncommitted dir (no train_state payload): refused, loader never ran
    empty = tmp_path / "global_step_9"
    empty.mkdir()
    with pytest.raises(CheckpointCorruptError, match="not a committed"):
        r.publish_from_checkpoint(str(empty), loader)
    assert loads == [good], "a refused generation must never be loaded"
    assert r.weights_version == "global_step_7"  # history untouched
    # verify_mode="off" still refuses uncommitted dirs
    with pytest.raises(CheckpointCorruptError):
        load_published_params(str(empty), loader, verify_mode="off")


# --------------------------------------------------------- chaos composition
def test_chaos_plan_publish_draws_deterministic_and_prefix_stable():
    """Adding publish events to a seeded plan must not move a single
    fault/kill draw (existing seeds stay repros), and the publish draws
    themselves are deterministic."""
    from veomni_tpu.resilience.chaos import build_chaos_plan

    base = build_chaos_plan(11, duration_s=10.0).to_doc()
    withpub = build_chaos_plan(11, duration_s=10.0, publishes=2).to_doc()
    assert withpub["faults"] == base["faults"]
    assert withpub["kills"] == base["kills"]
    assert base["publishes"] == [] and len(withpub["publishes"]) == 2
    again = build_chaos_plan(11, duration_s=10.0, publishes=2).to_doc()
    assert again == withpub
    for p in withpub["publishes"]:
        assert 0.15 * 10.0 <= p["at_s"] <= 0.70 * 10.0


def test_chaos_soak_publish_only_converges_and_requires_publish_fn(qwen3):
    """A publish-only storm (no faults, no kills) through the soak
    harness: every invariant incl. version convergence holds, and a plan
    that schedules publishes without a publish_fn is refused loudly."""
    from veomni_tpu.resilience.chaos import build_chaos_plan, run_chaos_soak

    params, cfg = qwen3
    plan = build_chaos_plan(5, duration_s=2.0, kills=0, hangs=0, delays=0,
                            exceptions=0, publishes=1)
    reqs = _reqs(_prompts(8, seed=50), n_new=4)
    arrivals = [0.2 * i for i in range(len(reqs))]

    def factory():
        r = Router(params, cfg, _engine_cfg(num_slots=2), RouterConfig(
            replicas=3))
        r.run(_reqs(_prompts(2, seed=51), n_new=2))  # warm the programs
        return r

    with pytest.raises(ValueError, match="publish_fn"):
        run_chaos_soak(router_factory=factory, requests=reqs,
                       arrivals=arrivals, plan=plan)
    report = run_chaos_soak(
        router_factory=factory, requests=reqs, arrivals=arrivals, plan=plan,
        publish_fn=lambda router, idx:
            router.publish_weights(_perturb(params), f"storm-v{idx + 1}"))
    assert report["publishes"] == 1
    assert report["published_versions"] == ["storm-v1"]
    assert report["version_converged"], report
    assert report["serving_versions"] == ["storm-v1"]
    assert report["publish_wall_s"] >= 0
    assert report["invariants_ok"], report
