"""Qwen2.5-VL parity vs HF transformers (tiny config, random weights).

The reference's headline VLM capability — training real Qwen-VL checkpoints —
oracle-tested the same way as text families in test_hf_parity.py: build a tiny
``Qwen2_5_VLForConditionalGeneration``, export HF-format safetensors, import
into our model, and assert identical vision features / loss on inputs with
text + two differently-sized images (exercising window attention, mrope, and
the patch merger).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

IMG_ID, VID_ID, VSTART_ID = 9, 10, 8


def _tiny_hf_model(tmp_path):
    import torch
    from transformers import Qwen2_5_VLConfig, Qwen2_5_VLForConditionalGeneration

    cfg = Qwen2_5_VLConfig(
        text_config=dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            max_position_embeddings=512,
            rope_theta=10000.0,
            rope_scaling={"type": "mrope", "mrope_section": [2, 3, 3]},
            tie_word_embeddings=False,
        ),
        vision_config=dict(
            depth=3,
            hidden_size=32,
            intermediate_size=64,
            num_heads=2,
            in_channels=3,
            patch_size=2,
            temporal_patch_size=2,
            spatial_merge_size=2,
            window_size=8,  # 2 merged patches per window side
            fullatt_block_indexes=[1],
            out_hidden_size=64,
            tokens_per_second=2,
        ),
        image_token_id=IMG_ID,
        video_token_id=VID_ID,
        vision_start_token_id=VSTART_ID,
    )
    torch.manual_seed(0)
    model = Qwen2_5_VLForConditionalGeneration(cfg).eval()
    out = tmp_path / "hf_ckpt"
    model.save_pretrained(out, safe_serialization=True)
    return model, cfg, str(out)


def _vision_inputs(rng, grids, patch_dim):
    n = sum(t * h * w for t, h, w in grids)
    pixel_values = rng.standard_normal((n, patch_dim)).astype(np.float32)
    return pixel_values, np.asarray(grids, np.int64)


@pytest.fixture(scope="module")
def hf_and_ours(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("q25vl")
    hf_model, hf_cfg, ckpt = _tiny_hf_model(tmp_path)

    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(ckpt, dtype="float32")
    params = model.load_hf(ckpt)
    return hf_model, hf_cfg, model, params


def test_vision_tower_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    grids = [(1, 4, 6), (1, 8, 4)]  # uneven grids: window padding paths
    rng = np.random.default_rng(0)
    pixel_values, grid_thw = _vision_inputs(rng, grids, cfg.vision.patch_dim)

    with torch.no_grad():
        ref = hf_model.model.visual(
            torch.from_numpy(pixel_values), torch.from_numpy(grid_thw)
        ).numpy()

    from veomni_tpu.models.qwen2_5_vl import vision_forward, vision_metadata

    meta = vision_metadata(grids, cfg.vision, n_pad_patches=pixel_values.shape[0] + 8)
    px = np.zeros((pixel_values.shape[0] + 8, pixel_values.shape[1]), np.float32)
    px[: pixel_values.shape[0]] = pixel_values
    got = vision_forward(
        params["vision_tower"], cfg.vision,
        jnp.asarray(px)[jnp.asarray(meta["patch_gather"])],
        jnp.asarray(meta["pos_hw"]), jnp.asarray(meta["seg_window"]),
        jnp.asarray(meta["seg_full"]), jnp.asarray(meta["reverse"]),
        dtype=jnp.float32,
    )
    got = np.asarray(got)[np.asarray(meta["merged_mask"])]
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_mrope_position_ids_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    grids = [(1, 4, 6), (1, 8, 4)]
    n_merged = [t * (h // 2) * (w // 2) for t, h, w in grids]
    rng = np.random.default_rng(1)

    ids = []
    for nm in n_merged:
        ids += [VSTART_ID] + [IMG_ID] * nm
    ids += list(rng.integers(11, 256, 7))
    input_ids = np.asarray([ids], np.int64)

    ref_pos, _ = hf_model.model.get_rope_index(
        torch.from_numpy(input_ids), torch.as_tensor(grids)
    )
    from veomni_tpu.models.qwen2_5_vl import mrope_position_ids

    got = mrope_position_ids(input_ids, grids, cfg)  # [B,3,S]
    np.testing.assert_array_equal(got[0], ref_pos[:, 0].numpy())


def test_full_loss_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    grids = [(1, 4, 6), (1, 8, 4)]
    n_merged = [t * (h // 2) * (w // 2) for t, h, w in grids]
    rng = np.random.default_rng(2)
    pixel_values, grid_thw = _vision_inputs(rng, grids, cfg.vision.patch_dim)

    ids = [VSTART_ID] + [IMG_ID] * n_merged[0] + list(rng.integers(11, 256, 5))
    ids += [VSTART_ID] + [IMG_ID] * n_merged[1] + list(rng.integers(11, 256, 6))
    input_ids = np.asarray([ids], np.int64)
    labels = input_ids.copy()
    labels[:, : n_merged[0] + 1] = -100  # mask the first image span

    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.from_numpy(input_ids),
            labels=torch.from_numpy(labels),
            pixel_values=torch.from_numpy(pixel_values),
            image_grid_thw=torch.from_numpy(grid_thw),
        )
    ref_loss = float(ref.loss)

    from veomni_tpu.models.qwen2_5_vl import mrope_position_ids, vision_metadata

    meta = vision_metadata(grids, cfg.vision, n_pad_patches=pixel_values.shape[0])
    pos = mrope_position_ids(input_ids, grids, cfg)
    # pre-shift labels to our collator contract (labels[t] = next token)
    shifted = np.full_like(labels, -100)
    shifted[:, :-1] = labels[:, 1:]
    batch = {
        "input_ids": jnp.asarray(input_ids, jnp.int32),
        "labels": jnp.asarray(shifted, jnp.int32),
        "position_ids": jnp.asarray(pos, jnp.int32),
        "segment_ids": jnp.ones_like(jnp.asarray(input_ids, jnp.int32)),
        "pixel_values": jnp.asarray(pixel_values)[jnp.asarray(meta["patch_gather"])],
        "vis_pos_hw": jnp.asarray(meta["pos_hw"]),
        "vis_seg_window": jnp.asarray(meta["seg_window"]),
        "vis_seg_full": jnp.asarray(meta["seg_full"]),
        "vis_reverse": jnp.asarray(meta["reverse"]),
        "vis_merged_mask": jnp.asarray(meta["merged_mask"]),
    }
    loss_sum, metrics = model.loss_fn(params, batch)
    got_loss = float(loss_sum) / float(metrics["ntokens"])
    np.testing.assert_allclose(got_loss, ref_loss, rtol=2e-4)


def test_qwen25_vl_trainer_e2e(tmp_path):
    """Full trainer drive: images -> patches/metadata -> mrope -> train steps
    (loss finite and decreasing-ish, checkpoint written)."""
    import json

    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer import VLMTrainer

    rng = np.random.default_rng(0)
    rows = []
    for i in range(24):
        rows.append({
            "input_ids": rng.integers(11, 256, int(rng.integers(8, 24))).tolist(),
            # 8x8 or 12x8 pixels -> 4x4 / 6x4 patch grids (patch 2, merge 2)
            "images": [rng.random((8 + 4 * (i % 2), 8, 3)).tolist()],
        })
    with open(tmp_path / "data.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen2_5_vl",
        "vocab_size": 256,
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "window_size": 8, "fullatt_block_indexes": [1],
            "out_hidden_size": 64,
        },
        "image_token_id": 9, "video_token_id": 10,
        "vision_start_token_id": 8,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.data.max_patches = 256  # 8 global rows (mb 2 x dp 4) x <=24 patches
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    destroy_parallel_state()
    try:
        trainer = VLMTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 3
        assert np.isfinite(ctl.metrics["loss"])
        trainer.checkpointer.close()
        # HF export exists and reimports
        import os

        hf_dir = os.path.join(args.train.output_dir, "hf_ckpt")
        assert os.path.exists(os.path.join(hf_dir, "model.safetensors"))
        from veomni_tpu.models import build_foundation_model

        m2 = build_foundation_model(hf_dir, dtype="float32")
        m2.load_hf(hf_dir)
    finally:
        destroy_parallel_state()


def test_qwen25_vl_sp_equivalence(hf_and_ours):
    """Heterogeneous SP: vision tower at sp=1 (scoped no-SP state) + LM at
    ulysses=2 must reproduce the unsharded loss exactly (fp32)."""
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.models.qwen2_5_vl import mrope_position_ids, vision_metadata

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    grids_row = [(1, 4, 6), (1, 8, 4)]
    n_merged = [t * (h // 2) * (w // 2) for t, h, w in grids_row]
    rng = np.random.default_rng(3)
    pixel_row, _ = _vision_inputs(rng, grids_row, cfg.vision.patch_dim)
    # two rows (batch divisible by the dp axes), images packed in row order
    grids = grids_row * 2
    pixel_values = np.concatenate([pixel_row, pixel_row])

    ids = [VSTART_ID] + [IMG_ID] * n_merged[0] + list(rng.integers(11, 256, 5))
    ids += [VSTART_ID] + [IMG_ID] * n_merged[1] + list(rng.integers(11, 256, 6))
    ids += [0] * (64 - len(ids))  # pad to an sp-divisible length
    input_ids = np.asarray([ids, ids], np.int64)
    labels = np.full_like(input_ids, -100)
    labels[:, n_merged[0] + 1: -1] = input_ids[:, n_merged[0] + 2:]

    meta = vision_metadata(grids, cfg.vision, n_pad_patches=pixel_values.shape[0])
    pos = mrope_position_ids(input_ids, grids, cfg)
    batch = {
        "input_ids": jnp.asarray(input_ids, jnp.int32),
        "labels": jnp.asarray(labels, jnp.int32),
        "position_ids": jnp.asarray(pos, jnp.int32),
        "segment_ids": jnp.asarray((input_ids != 0).astype(np.int32)),
        "pixel_values": jnp.asarray(pixel_values)[jnp.asarray(meta["patch_gather"])],
        "vis_pos_hw": jnp.asarray(meta["pos_hw"]),
        "vis_seg_window": jnp.asarray(meta["seg_window"]),
        "vis_seg_full": jnp.asarray(meta["seg_full"]),
        "vis_reverse": jnp.asarray(meta["reverse"]),
        "vis_merged_mask": jnp.asarray(meta["merged_mask"]),
    }
    destroy_parallel_state()
    ref_loss, ref_metrics = model.loss_fn(params, batch)
    ref = float(ref_loss) / float(ref_metrics["ntokens"])
    try:
        ps = init_parallel_state(ulysses_size=2, dp_shard_size=2)
        with use_parallel_state(ps):
            got_loss, got_metrics = jax.jit(model.loss_fn)(params, batch)
            got = float(got_loss) / float(got_metrics["ntokens"])
    finally:
        destroy_parallel_state()
    np.testing.assert_allclose(got, ref, rtol=1e-5)
