"""graftlint — the static-analysis subsystem (ISSUE 13).

Acceptance contract: the whole repo lints clean (every pass, zero
non-allowlisted findings) — THE tier-1 gate, mirrored by the fast lint
stage in ``scripts/tier1.sh``; the analyzer itself never imports jax; every
rule has a positive fixture proving it still fires (a rule without a
failing fixture silently rots); the allowlist round-trips (suppression,
mandatory justification, stale-entry and malformed-file detection); the
threaded modules carry their ``# guarded-by:`` annotations; and the lock
fixes this PR landed (locked instrument reads, locked flight-recorder
introspection) hold under a thread hammer.
"""

import json
import os
import re
import subprocess
import sys
import threading

import pytest

from veomni_tpu.analysis import run_lint
from veomni_tpu.analysis.core import Allowlist, RepoIndex

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "tools", "lint_fixtures")

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([a-z-]+/[a-z-]+)")


# ------------------------------------------------------------ the tier-1 gate
def test_repo_lints_clean():
    """Every pass over the whole repo: zero non-allowlisted findings.

    This is the gate ISSUE 13 ships green: real violations found while
    building it were either fixed (locked metric/recorder reads, serve.py
    health endpoint off live scheduler state, doc tables for every knob/
    op) or allowlisted with a justification."""
    result = run_lint(_REPO)
    assert result.ok, "\n".join(f.format() for f in result.findings)


def test_lint_cli_json_fast_and_jax_free(tmp_path):
    """The CLI exits 0 on the clean repo, emits the CI JSON artifact, and
    asserts internally that jax was never imported (the tier-1 lint stage
    depends on exactly that property to run in seconds)."""
    out = str(tmp_path / "lint.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "lint.py"),
         "--json", out],
        capture_output=True, text=True, timeout=120, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.load(open(out))
    assert doc["ok"] is True
    assert doc["findings"] == []
    assert doc["elapsed_s"] < 60.0
    assert "no JAX" in proc.stderr


def test_analysis_package_imports_without_jax():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import veomni_tpu.analysis, sys; "
         "assert 'jax' not in sys.modules, 'analysis pulled in jax'"],
        capture_output=True, text=True, timeout=60, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ------------------------------------------------------------------- fixtures
def _expectations(root):
    """{(relpath, line): rule} from # EXPECT: markers in a fixture tree."""
    out = {}
    for dirpath, _dirs, files in os.walk(root):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            for lineno, line in enumerate(open(full), 1):
                m = _EXPECT_RE.search(line)
                if m:
                    out[(rel, lineno)] = m.group(1)
    return out


def _assert_exact(findings, expected, rule_prefixes):
    """Every EXPECT fires on its line; no unexpected finding under the
    checked rule families (both directions — silent extra findings would
    mean the rule over-triggers on clean fixture code)."""
    got = {}
    for f in findings:
        if any(f.rule.startswith(p) for p in rule_prefixes):
            got.setdefault((f.path, f.line), set()).add(f.rule)
    missing = {
        k: rule for k, rule in expected.items()
        if any(rule.startswith(p) for p in rule_prefixes)
        and rule not in got.get(k, set())
    }
    assert not missing, f"fixture rules did not fire: {missing}; got {got}"
    unexpected = {
        k: rules for k, rules in got.items()
        if expected.get(k) not in rules
    }
    assert not unexpected, f"unexpected findings on clean lines: {unexpected}"


def test_purity_and_recompile_fixtures_fire():
    from veomni_tpu.analysis import purity, recompile

    root = os.path.join(_FIXTURES, "repo")
    index = RepoIndex.load(root)
    expected = _expectations(root)
    findings = purity.run(index) + recompile.run(index)
    _assert_exact(findings, expected, ("trace-purity", "recompile-hazard"))
    # the sanctioned TRACE_COUNTS bump line produced NO finding at all
    hot = open(os.path.join(root, "veomni_tpu", "hot.py")).read().splitlines()
    counts_line = next(i for i, l in enumerate(hot, 1)
                       if "TRACE_COUNTS[" in l)
    assert not any(f.line == counts_line for f in findings)


def test_lock_discipline_fixtures_fire():
    from veomni_tpu.analysis import locks

    root = os.path.join(_FIXTURES, "repo")
    index = RepoIndex.load(root)
    expected = _expectations(root)
    _assert_exact(locks.run(index), expected, ("lock-discipline",))


def test_drift_fixtures_fire():
    from veomni_tpu.analysis import drift

    root = os.path.join(_FIXTURES, "drift_repo")
    index = RepoIndex.load(root)
    expected = _expectations(root)
    findings = (drift.metric_findings(index) + drift.knob_findings(index)
                + drift.env_findings(index) + drift.fault_findings(index)
                + drift.registry_findings(index))
    _assert_exact(findings, expected, ("drift/",))


def test_traced_walk_reaches_known_roots():
    """The purity pass's sanity pins, asserted directly: losing a decode/
    engine/train-step root would make the whole family vacuous."""
    from veomni_tpu.analysis.callgraph import get_callgraph
    from veomni_tpu.analysis.purity import SANITY_TRACED

    index = RepoIndex.load(_REPO)
    seen = {
        (tf.func.sf.path, tf.func.qualname)
        for tf in get_callgraph(index).traced_functions().values()
    }
    missing = SANITY_TRACED - seen
    assert not missing, f"traced walk lost roots: {sorted(missing)}"


# ------------------------------------------------------------------ allowlist
def test_allowlist_roundtrip(tmp_path):
    from veomni_tpu.analysis import purity

    root = os.path.join(_FIXTURES, "repo")
    index = RepoIndex.load(root)
    target = next(f for f in purity.run(index)
                  if f.rule == "trace-purity/host-time")
    allow = tmp_path / "allow.toml"
    allow.write_text(
        "[[allow]]\n"
        f'rule = "{target.rule}"\n'
        f'path = "{target.path}"\n'
        'match = "impure_step"\n'
        'justification = "fixture roundtrip"\n'
    )
    al = Allowlist.load(str(allow))
    kept = al.filter([target])
    assert kept == [] and al.entries[0].hits == 1
    assert al.audit() == []  # matched + justified: no policy findings


def test_allowlist_stale_and_missing_justification(tmp_path):
    allow = tmp_path / "allow.toml"
    allow.write_text(
        "[[allow]]\n"
        'rule = "trace-purity/host-time"\n'
        'path = "veomni_tpu/nonexistent.py"\n'
        'justification = "excuses code that no longer exists"\n'
        "\n"
        "[[allow]]\n"
        'rule = "trace-purity/io"\n'
        'path = "veomni_tpu/also_missing.py"\n'
        'justification = ""\n'
    )
    al = Allowlist.load(str(allow))
    al.filter([])  # nothing matches anything
    rules = sorted(f.rule for f in al.audit())
    assert rules == ["allowlist/missing-justification",
                     "allowlist/stale-entry", "allowlist/stale-entry"]


def test_allowlist_malformed_fails_loudly(tmp_path):
    allow = tmp_path / "allow.toml"
    allow.write_text("[allow]\nrule = broken\n")
    al = Allowlist.load(str(allow))
    assert any(f.rule == "allowlist/malformed" for f in al.audit())


def test_repo_allowlist_policy():
    """The real allowlist parses, and every entry carries a justification
    (stale entries are covered by test_repo_lints_clean — a stale entry IS
    a finding)."""
    al = Allowlist.load(os.path.join(_REPO, "veomni_tpu", "analysis",
                                     "allowlist.toml"))
    assert not al.errors
    for e in al.entries:
        assert e.justification.strip(), f"{e.rule} @ {e.path} unjustified"


# ------------------------------------------- annotations + lock-fix regression
ANNOTATED_MODULES = (
    "veomni_tpu/observability/metrics.py",
    "veomni_tpu/observability/spans.py",
    "veomni_tpu/observability/flight_recorder.py",
    "veomni_tpu/observability/request_trace.py",
    "veomni_tpu/observability/fleet.py",
)


def test_threaded_modules_carry_guard_annotations():
    """ISSUE 13 satellite: the threaded observability modules declare their
    lock contracts. An annotation deleted along with a refactor silently
    removes its enforcement — this pins the coverage."""
    from veomni_tpu.analysis import locks

    index = RepoIndex.load(_REPO)
    for path in ANNOTATED_MODULES:
        anns = locks._comment_annotations(index.files[path])
        assert anns, f"{path} lost its # guarded-by: annotations"


def test_metrics_value_reads_are_locked_under_hammer():
    """Regression for the unlocked instrument reads the lock-discipline
    pass found: Counter.value / Histogram.count/sum and registry get() now
    take the shared lock, so a reader thread always observes a consistent
    (count, sum) pair mid-hammer."""
    from veomni_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram("hammer.h")
    stop = threading.Event()
    errs = []

    def writer():
        while not stop.is_set():
            h.observe(1.0)

    def reader():
        while not stop.is_set():
            c, s = h.count, h.sum
            # sum of N observations of exactly 1.0 can never exceed the
            # count observed AFTER it — torn reads would break this
            if s > h.count + 1e-9:
                errs.append((c, s))
            reg.get("hammer.h")
            reg.histogram_sum("hammer.h")

    threads = [threading.Thread(target=writer) for _ in range(2)] + [
        threading.Thread(target=reader) for _ in range(2)
    ]
    for t in threads:
        t.start()
    stop_timer = threading.Timer(0.5, stop.set)
    stop_timer.start()
    for t in threads:
        t.join(timeout=10)
    stop_timer.cancel()
    stop.set()
    assert not errs, f"torn histogram reads: {errs[:3]}"
    assert h.count == pytest.approx(h.sum)


def test_flight_recorder_len_dropped_consistent_under_hammer():
    """Regression for the unlocked ``__len__``/``dropped`` reads: with a
    ring of capacity N, a reader must never observe len > N, and the
    snapshot's (events, dropped) pair comes from one locked pass."""
    from veomni_tpu.observability.flight_recorder import FlightRecorder

    rec = FlightRecorder(max_events=64)
    stop = threading.Event()
    errs = []

    def writer():
        i = 0
        while not stop.is_set():
            rec.record("hammer", cid=str(i))
            i += 1

    def reader():
        while not stop.is_set():
            if len(rec) > 64:
                errs.append(len(rec))
            snap = rec.snapshot(limit=8)
            if snap["dropped"] < 0:
                errs.append(snap["dropped"])

    threads = [threading.Thread(target=writer) for _ in range(2)] + [
        threading.Thread(target=reader)
    ]
    for t in threads:
        t.start()
    stop_timer = threading.Timer(0.4, stop.set)
    stop_timer.start()
    for t in threads:
        t.join(timeout=10)
    stop_timer.cancel()
    stop.set()
    assert not errs
    assert len(rec) <= 64 and rec.dropped >= 0
