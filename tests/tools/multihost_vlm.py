"""Per-process driver for the multihost packed-VLM data-path test.

Runs VLMTrainer (qwen2_5_vl toy) on a (4-local x nproc) virtual CPU mesh and
prints the loss trajectory. With nproc=2 the trainer auto-selects the
per-row patch-budget collator (each process assembles only its rows); the
parent asserts the trajectory matches a single-process (packed-mode) run of
the same global batch — the reference contract of per-rank multimodal
slicing (``data/data_collator.py:317-431``).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def main():
    data_path, steps, local_devices = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    out_dir = sys.argv[4]

    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.trainer import VLMTrainer
    from veomni_tpu.trainer.callbacks import Callback

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen2_5_vl",
        "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "window_size": 8, "fullatt_block_indexes": [1],
            "out_hidden_size": 64,
        },
        "image_token_id": 9, "video_token_id": 10,
        "vision_start_token_id": 8,
    }
    args.data.train_path = data_path
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.data.max_patches = 256
    args.train.platform = "cpu"
    args.train.num_virtual_devices = local_devices
    args.train.output_dir = out_dir
    args.train.micro_batch_size = 1
    args.train.train_steps = steps
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 1  # sync every step: the test reads the series

    losses = []

    class Rec(Callback):
        def on_step_end(self, trainer, state):
            if "loss" in state.metrics:
                losses.append(round(float(state.metrics["loss"]), 6))

    trainer = VLMTrainer(args)
    trainer.callbacks.append(Rec())
    trainer.train()
    trainer.checkpointer.close()
    import jax

    print(json.dumps({
        "process": jax.process_index(),
        "devices": jax.device_count(),
        "per_row": trainer._vlm_per_row,
        "losses": losses,
    }), flush=True)


if __name__ == "__main__":
    main()
