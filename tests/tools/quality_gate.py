"""Pinned quality-gate bounds for non-bit-exact serving features.

The core scoring lives in ``veomni_tpu/serving/quality.py`` (the engine
and bench use it too); this helper pins the REPO-WIDE bounds and gives
tests a one-call assertion. Any future deliberately-non-bit-exact feature
(fp8 KV, quantized lm head, approximate attention) should certify itself
through :func:`assert_quality_gate` rather than inventing its own
tolerance — one gate, one place to argue about bounds.

Bound provenance (2026-08, CPU, f32 reference, fixed_corpus seed 0 over
the qwen3 / gpt_oss_ish / qwen3_moe tier-1 dialect trio): worst observed
``ppl_rel_delta`` was 2.5e-4 and worst ``topk_overlap`` 0.988 across
int8-KV, int8-weight, and combined modes. The pins below leave ~80x
headroom on perplexity and accept up to one swapped token per top-8
neighborhood — loose enough to survive BLAS/backend drift, tight enough
that a real quantization bug (wrong scale axis, garbage rows leaking into
the attend) blows through them immediately.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from veomni_tpu.serving.quality import fixed_corpus, quality_stats

#: max relative teacher-forced perplexity change vs the f32 path
PPL_REL_DELTA_BOUND = 0.02
#: min mean top-k overlap vs the f32 path (k = TOP_K)
TOPK_OVERLAP_BOUND = 0.90
#: neighborhood size the overlap bound is pinned against
TOP_K = 8


def assert_quality_gate(params, cfg, *, kv_quant: str = "none",
                        weight_quant: str = "none", block_size: int = 16,
                        corpus: Optional[Sequence[Sequence[int]]] = None,
                        ) -> Dict[str, float]:
    """Score the quantized path against the f32 reference on the fixed-seed
    corpus and assert the pinned bounds; returns the stats for the test to
    inspect/print. ``corpus=None`` uses the standard
    :func:`~veomni_tpu.serving.quality.fixed_corpus` for the config's
    vocab."""
    if corpus is None:
        corpus = fixed_corpus(cfg.vocab_size)
    stats = quality_stats(
        params, cfg, corpus, kv_quant=kv_quant, weight_quant=weight_quant,
        top_k=TOP_K, block_size=block_size,
    )
    assert stats["ppl_rel_delta"] <= PPL_REL_DELTA_BOUND, (
        f"quality gate: ppl_rel_delta {stats['ppl_rel_delta']:.5f} exceeds "
        f"{PPL_REL_DELTA_BOUND} (kv_quant={kv_quant}, "
        f"weight_quant={weight_quant}; ppl {stats['ppl_ref']:.4f} -> "
        f"{stats['ppl_quant']:.4f})"
    )
    assert stats["topk_overlap"] >= TOPK_OVERLAP_BOUND, (
        f"quality gate: top-{TOP_K} overlap {stats['topk_overlap']:.4f} "
        f"below {TOPK_OVERLAP_BOUND} (kv_quant={kv_quant}, "
        f"weight_quant={weight_quant})"
    )
    return stats
