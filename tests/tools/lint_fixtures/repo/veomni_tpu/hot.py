"""Positive fixtures: trace-purity + recompile-hazard rules.

Every marked line must fire exactly its rule; unmarked lines must stay
clean (the sanctioned TRACE_COUNTS bump below pins the negative case).
"""

import random
import time

import jax
import jax.numpy as jnp
import numpy as np

GLOBAL_STATE = {}
TRACE_COUNTS = {"step": 0}


def _bucket_pow2(n, floor=16):
    b = floor
    while b < n:
        b *= 2
    return b


def impure_step(x, scale):
    TRACE_COUNTS["step"] += 1  # sanctioned trace-counter pattern: clean
    GLOBAL_STATE["last"] = 1  # EXPECT: trace-purity/global-mutation
    t = time.time()  # EXPECT: trace-purity/host-time
    r = random.random()  # EXPECT: trace-purity/host-random
    print("tracing")  # EXPECT: trace-purity/io
    host = np.asarray(x)  # EXPECT: trace-purity/host-sync
    v = x.item()  # EXPECT: trace-purity/host-sync
    f = float(x)  # EXPECT: trace-purity/host-cast
    y = jnp.sum(x) * scale + t + r + f
    if y > 0:  # EXPECT: recompile-hazard/traced-branch
        y = y + 1
    return y, host, v


step = jax.jit(impure_step)

_jitted_entry = jax.jit(lambda tokens, bucket: tokens[:bucket],
                        static_argnums=(1,))


def caller(tokens):
    good = _jitted_entry(tokens, _bucket_pow2(len(tokens)))
    bad = _jitted_entry(tokens, len(tokens))  # EXPECT: recompile-hazard/unbucketed-static-arg
    return good, bad


class Engine:
    def __init__(self):
        self._step = self._build()

    def _build(self):
        return jax.jit(lambda a, width: a, static_argnums=(1,))

    def tick(self, xs):
        return self._step(xs, xs.shape[0])  # EXPECT: recompile-hazard/unbucketed-static-arg
