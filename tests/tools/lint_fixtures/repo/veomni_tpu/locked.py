"""Positive fixtures: lock-discipline rules.

Locked paths (``with``-block and acquire-style) must stay clean; the
unlocked read/write, the unknown lock, and the detached annotation must
each fire exactly their rule.
"""

import threading

_registry = {}  # guarded-by: _registry_lock
_registry_lock = threading.Lock()
_ghost = 0  # guarded-by: _missing_lock  # EXPECT: lock-discipline/unknown-lock


def put(k, v):
    with _registry_lock:
        _registry[k] = v


def peek():
    return dict(_registry)  # EXPECT: lock-discipline/unlocked-read


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock

    def add(self, x):
        with self._lock:
            self._items.append(x)
            self._count += 1

    def size(self):
        return self._count  # EXPECT: lock-discipline/unlocked-read

    def drop(self):
        self._items = []  # EXPECT: lock-discipline/unlocked-write

    def bounded_drop(self):
        if not self._lock.acquire(timeout=1.0):
            return
        try:
            self._items = []  # acquire-style evidence: clean
        finally:
            self._lock.release()


def shadowing_local():
    _registry = {"local": True}  # a LOCAL, not the guarded global: clean
    return _registry


def detached():  # guarded-by: _lock  # EXPECT: lock-discipline/bad-annotation
    return None
