"""Drift fixture: one documented and one undocumented train.* knob."""

from dataclasses import dataclass


@dataclass
class TrainingArguments:
    documented_knob: int = 1
    mystery_knob: int = 0  # EXPECT: drift/knob-undocumented
