"""Drift fixture: metric / env / registry-op surfaces, half undocumented."""

import os


class _Reg:
    def counter(self, name):
        return name

    def gauge(self, name):
        return name

    def set_gauges(self, prefix, values):
        return prefix

    def register(self, op, impl):
        def deco(fn):
            return fn

        return deco


reg = _Reg()
KERNEL_REGISTRY = _Reg()


def emit(kind):
    reg.counter("documented.count")
    reg.gauge("ghost.gauge")  # EXPECT: drift/metric-undocumented
    reg.counter(f"family.{kind}")
    reg.set_gauges("stats", {})
    os.environ.get("VEOMNI_DOCUMENTED")
    os.environ.get("VEOMNI_GHOST")  # EXPECT: drift/env-undocumented


@KERNEL_REGISTRY.register("documented_op", "xla")
def _op_a(x):
    return x


@KERNEL_REGISTRY.register("ghost_op", "xla")  # EXPECT: drift/registry-op-undocumented
def _op_b(x):
    return x
