"""Drift fixture: one documented and one undocumented fault point."""

KNOWN_POINTS = (
    "ckpt.save",
    "step.ghost",  # EXPECT: drift/fault-point-undocumented
)


def fault_point(name):
    return name


def site():
    fault_point("ckpt.save")
    fault_point("data.phantom")  # EXPECT: drift/fault-point-undocumented
