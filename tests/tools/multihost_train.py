"""Per-process driver for the multi-process CPU training test.

Launched by tests/test_multihost.py with VEOMNI_COORDINATOR_ADDRESS /
VEOMNI_NUM_PROCESSES / VEOMNI_PROCESS_ID set. Runs TextTrainer on a
(4 local x nproc) virtual CPU mesh and prints one JSON line with the loss
trajectory (the parent asserts cross-process agreement + exact resume).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def main():
    data_path = sys.argv[1]
    out_dir = sys.argv[2]
    train_steps = int(sys.argv[3])
    stop_at = int(sys.argv[4]) if len(sys.argv) > 4 else 0

    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.trainer import TextTrainer
    from veomni_tpu.trainer.callbacks import Callback

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen3", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
        "qk_norm": True,
    }
    args.data.train_path = data_path
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 128
    args.train.platform = "cpu"
    args.train.num_virtual_devices = 4  # per process
    args.train.output_dir = out_dir
    args.train.micro_batch_size = 2
    args.train.train_steps = train_steps
    args.train.save_steps = 4
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.log_steps = 100

    losses = []
    hashes = {}
    batch_hashes = []

    def _hash(trainer):
        import hashlib

        import jax
        import numpy as np

        md = hashlib.md5()
        for leaf in jax.tree.leaves(trainer.train_state.params):
            for sh in sorted(leaf.addressable_shards, key=lambda s: str(s.index)):
                md.update(np.ascontiguousarray(np.asarray(sh.data)).tobytes())
        return md.hexdigest()

    class Capture(Callback):
        def on_train_begin(self, trainer, state):
            hashes["begin"] = _hash(trainer)
            hashes["begin_step"] = state.global_step
            dl = trainer.dataloader
            hashes["loader"] = (
                dl.state_dict() if hasattr(dl, "state_dict") else None
            )
            hashes["dp_rank"] = getattr(dl, "dp_rank", None)
            hashes["dp_size"] = getattr(dl, "dp_size", None)
            if hasattr(dl, "_epoch_indices"):
                hashes["first_idxs"] = [int(i) for i in dl._epoch_indices()[:5]]

        def on_train_end(self, trainer, state):
            hashes["end"] = _hash(trainer)

        def on_step_begin(self, trainer, state):
            import hashlib

            import numpy as np

            md = hashlib.md5()
            for k in sorted(trainer.current_batch):
                md.update(np.ascontiguousarray(
                    np.asarray(trainer.current_batch[k])).tobytes())
            batch_hashes.append(md.hexdigest()[:12])

        def on_step_end(self, trainer, state):
            losses.append(round(float(state.metrics["loss"]), 8))
            if stop_at and state.global_step >= stop_at:
                state.should_stop = True

    trainer = TextTrainer(args)
    trainer.callbacks.append(Capture())
    import jax

    assert jax.process_count() == int(os.environ["VEOMNI_NUM_PROCESSES"])
    assert jax.device_count() == 4 * jax.process_count()
    ctl = trainer.train()
    trainer.checkpointer.close()
    print(json.dumps({
        "process": jax.process_index(),
        "global_step": ctl.global_step,
        "losses": losses,
        "devices": jax.device_count(),
        "hashes": hashes,
        "batch_hashes": batch_hashes,
    }), flush=True)


if __name__ == "__main__":
    main()
