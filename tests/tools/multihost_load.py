"""Per-process driver for the multihost streamed-weight-load test.

Launched by tests/test_multihost.py with the distributed env vars set. Loads
an HF MoE checkpoint with EP-sharded target shardings on a (4 local x nproc)
virtual CPU mesh, instrumenting safetensors slice reads, and prints one JSON
line with per-process read accounting + a correctness digest.

Proves the reference multihost contract (``module_utils.py:530,867`` —
EP-sliced per-rank reads instead of every rank reading every tensor): a
process must read only the expert rows its local devices hold.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def main():
    ckpt_dir = sys.argv[1]
    ep_size = int(sys.argv[2])
    if len(sys.argv) > 3 and sys.argv[3] == "broadcast":
        os.environ["VEOMNI_WEIGHTS_BROADCAST"] = "1"

    from veomni_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(4)  # per process

    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ["VEOMNI_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["VEOMNI_NUM_PROCESSES"]),
        process_id=int(os.environ["VEOMNI_PROCESS_ID"]),
    )

    import numpy as np

    from veomni_tpu.models import build_foundation_model, hf_io
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.train.train_step import resolve_state_shardings

    # instrument the lazy reader: tally UNIQUE (tensor, slice) reads so
    # replicated-dim repeats don't inflate the account
    reads = {}
    orig_slice = hf_io.LazyHFTensors.read_slice
    orig_read = hf_io.LazyHFTensors.read

    def counting_slice(self, name, idx):
        arr = orig_slice(self, name, idx)
        reads[(name, str(idx))] = arr.nbytes
        return arr

    def counting_read(self, name):
        arr = orig_read(self, name)
        reads[(name, "FULL")] = arr.nbytes
        return arr

    hf_io.LazyHFTensors.read_slice = counting_slice
    hf_io.LazyHFTensors.read = counting_read

    model = build_foundation_model(config_path=ckpt_dir)
    ps = init_parallel_state(ep_size=ep_size, dp_shard_size=-1)
    with use_parallel_state(ps):
        plan = model.get_parallel_plan()
        abs_params = model.abstract()
        shardings = resolve_state_shardings(abs_params, plan, ps)
        params = model.load_hf(ckpt_dir, target_shardings=shardings)

        expert_bytes = sum(
            v for (name, _), v in reads.items() if ".experts." in name
        )
        other_bytes = sum(
            v for (name, _), v in reads.items() if ".experts." not in name
        )
        # correctness: every addressable shard must equal the slice of the
        # full on-disk tensor it claims to be (checked via a second,
        # uninstrumented full read on the expert tensors)
        hf_io.LazyHFTensors.read_slice = orig_slice
        hf_io.LazyHFTensors.read = orig_read
        lazy = hf_io.LazyHFTensors(ckpt_dir)
        L = model.config.num_hidden_layers
        full = np.stack([
            np.stack([
                np.asarray(lazy.read_slice(
                    f"model.layers.{i}.mlp.experts.{e}.gate_proj.weight",
                    (slice(None),),
                )).T
                for e in range(model.config.num_experts)
            ])
            for i in range(L)
        ])  # [L, E, in, out] in our layout
        got = params["layers"]["experts"]["gate_proj"]
        ok = all(
            np.allclose(np.asarray(sh.data), full[sh.index], atol=1e-6)
            for sh in got.addressable_shards
        )

    print(json.dumps({
        "process": int(os.environ["VEOMNI_PROCESS_ID"]),
        "expert_bytes": int(expert_bytes),
        "other_bytes": int(other_bytes),
        "shards_match_disk": bool(ok),
    }), flush=True)


if __name__ == "__main__":
    main()
