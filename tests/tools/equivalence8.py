"""8-virtual-device equivalence driver (subprocess: own jax config).

Covers the round-1 gaps: HSDP (dp_replicate=2), ep=4, sp=4, the combined
2x2x2 layout, pure-DDP replication, and capacity-mode EP vs dropless.
Prints one JSON line with loss/grad_norm per layout.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import jax

from veomni_tpu.utils.jax_compat import set_virtual_cpu_devices

set_virtual_cpu_devices(8)
jax.config.update("jax_cpu_enable_async_dispatch", False)

import jax.numpy as jnp
import numpy as np


def toy_cfg(moe=False, capacity=0.0):
    from veomni_tpu.models.config import TransformerConfig

    kw = dict(
        model_type="qwen3_moe" if moe else "qwen3",
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        head_dim=16, qk_norm=True, dtype=jnp.float32,
        moe_capacity_factor=capacity,
    )
    if moe:
        kw.update(num_experts=8, num_experts_per_tok=2, moe_intermediate_size=64)
    return TransformerConfig(**kw)


def batch(bsz=8, seq=64, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, (bsz, seq))
    seg = np.ones((bsz, seq), np.int32)
    seg[:, seq // 2:] = 2
    pos = np.concatenate([np.arange(seq // 2), np.arange(seq - seq // 2)])
    return {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "labels": jnp.asarray(ids, jnp.int32),
        "position_ids": jnp.asarray(np.broadcast_to(pos, (bsz, seq)).copy(), jnp.int32),
        "segment_ids": jnp.asarray(seg),
    }


def run(cfg, mesh_kwargs, b):
    import optax

    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state

    destroy_parallel_state()
    ps = init_parallel_state(**mesh_kwargs)
    model = build_foundation_model(config=cfg)
    with use_parallel_state(ps):
        params = model.init(jax.random.PRNGKey(0))
        shardings = model.get_parallel_plan().resolve(params, ps)
        params = jax.jit(lambda p: p, out_shardings=shardings)(params)
        bs = {k: ps.batch_sharding() for k in b}
        bb = {k: jax.device_put(v, bs[k]) for k, v in b.items()}

        def norm_loss(p, x):
            loss_sum, metrics = model.loss_fn(p, x)
            return loss_sum / jnp.maximum(metrics["ntokens"], 1), metrics

        (loss, metrics), grads = jax.jit(
            jax.value_and_grad(norm_loss, has_aux=True)
        )(params, bb)
        gnorm = jax.jit(optax.global_norm)(grads)
        dropped = float(metrics.get("moe_dropped_frac", 0.0))
        return float(loss), float(gnorm), dropped


def main():
    out = {}
    for moe in (False, True):
        cfg = toy_cfg(moe)
        b = batch()
        name = "moe" if moe else "dense"
        out[f"{name}/base"] = run(cfg, dict(dp_shard_size=8), b)
        layouts = {
            "hsdp2": dict(dp_replicate_size=2, dp_shard_size=4),
            "ddp": dict(dp_replicate_size=-1, dp_shard_size=1),
            "sp4": dict(ulysses_size=4, dp_shard_size=2),
        }
        if moe:
            layouts.update({
                "ep4": dict(ep_size=4, dp_shard_size=8),
                "ep2sp2rep2": dict(dp_replicate_size=2, ep_size=2,
                                   dp_shard_size=2, ulysses_size=2),
            })
        for lname, kw in layouts.items():
            out[f"{name}/{lname}"] = run(cfg, kw, b)
    # capacity-mode EP: bounded loss delta vs dropless + visible drop metric
    cfg_cap = toy_cfg(True, capacity=1.0)
    out["moe/ep4_capacity"] = run(cfg_cap, dict(ep_size=4, dp_shard_size=8), batch())
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
