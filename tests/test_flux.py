"""FLUX.1 MMDiT: structural self-tests.

No diffusers oracle in this environment (the reference's flux wraps the
public FLUX.1 weights), so these tests pin the architecture's own contract:
double/single-stream flow, conditioning paths (timestep / pooled / guidance),
text-mask semantics, diffusers-format key layout round-trip, and a full
DiTTrainer drive."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veomni_tpu.models.flux import (
    FluxConfig, flux_forward, hf_to_params, init_params, loss_fn, params_to_hf,
)

TINY = dict(
    in_channels=8,
    num_layers=2,
    num_single_layers=2,
    attention_head_dim=24,   # rope axes 8/8/8
    num_attention_heads=2,
    joint_attention_dim=32,
    pooled_projection_dim=16,
    guidance_embeds=True,
    axes_dims_rope=(8, 8, 8),
    dtype=jnp.float32,
    param_dtype=jnp.float32,
    remat=False,
)


@pytest.fixture(scope="module")
def model():
    cfg = FluxConfig(**TINY)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_forward_shape_and_conditioning(model):
    cfg, params = model
    rng = np.random.default_rng(0)
    lat = jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32)  # 4x4 grid
    t = jnp.asarray([100.0, 700.0], jnp.float32)
    text = jnp.asarray(rng.standard_normal((2, 5, 32)), jnp.float32)
    pooled = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
    g = jnp.asarray([3.5, 3.5], jnp.float32)

    out = flux_forward(params, cfg, lat, t, text, pooled, guidance=g)
    assert out.shape == (2, 16, 8)
    np.testing.assert_array_equal(
        np.asarray(out),
        np.asarray(flux_forward(params, cfg, lat, t, text, pooled, guidance=g)),
    )
    # every conditioning stream is live
    for other in (
        flux_forward(params, cfg, lat, t * 0.1, text, pooled, guidance=g),
        flux_forward(params, cfg, lat, t, text * -1.0, pooled, guidance=g),
        flux_forward(params, cfg, lat, t, text, pooled * -1.0, guidance=g),
        flux_forward(params, cfg, lat, t, text, pooled, guidance=g * 2.0),
    ):
        assert np.abs(np.asarray(out) - np.asarray(other)).max() > 1e-6


def test_text_mask_blocks_padding(model):
    """Padded text tokens (mask 0) must not influence the image stream."""
    cfg, params = model
    rng = np.random.default_rng(1)
    lat = jnp.asarray(rng.standard_normal((1, 16, 8)), jnp.float32)
    t = jnp.asarray([500.0], jnp.float32)
    pooled = jnp.asarray(rng.standard_normal((1, 16)), jnp.float32)
    text = rng.standard_normal((1, 6, 32)).astype(np.float32)
    mask = np.asarray([[1, 1, 1, 0, 0, 0]], np.int32)
    out1 = flux_forward(params, cfg, lat, t, jnp.asarray(text), pooled,
                        text_mask=jnp.asarray(mask))
    text2 = text.copy()
    text2[:, 3:] = rng.standard_normal((1, 3, 32))
    out2 = flux_forward(params, cfg, lat, t, jnp.asarray(text2), pooled,
                        text_mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-5)


def test_loss_and_grads_finite(model):
    cfg, params = model
    rng = np.random.default_rng(2)
    batch = {
        "latents": jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32),
        "timestep": jnp.asarray([100.0, 900.0], jnp.float32),
        "text_states": jnp.asarray(rng.standard_normal((2, 5, 32)), jnp.float32),
        "pooled_text": jnp.asarray(rng.standard_normal((2, 16)), jnp.float32),
        "guidance": jnp.asarray([1.0, 1.0], jnp.float32),
        "target": jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32),
    }
    loss_sum, metrics = loss_fn(params, cfg, batch)
    assert np.isfinite(float(loss_sum))
    grads = jax.grad(lambda p: loss_fn(p, cfg, batch)[0])(params)
    for path, g in jax.tree_util.tree_leaves_with_path(grads):
        assert np.all(np.isfinite(np.asarray(g))), jax.tree_util.keystr(path)
    # single-stream params receive signal
    assert float(jnp.abs(grads["single_blocks"]["out_w"]).sum()) > 0.0


def test_diffusers_roundtrip(model, tmp_path):
    from safetensors.numpy import save_file

    cfg, params = model
    sd = params_to_hf(params, cfg)
    # diffusers-format names present
    assert "transformer_blocks.0.attn.add_q_proj.weight" in sd
    assert "single_transformer_blocks.1.proj_mlp.weight" in sd
    assert "time_text_embed.guidance_embedder.linear_1.weight" in sd
    save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
              str(tmp_path / "model.safetensors"))
    loaded = hf_to_params(str(tmp_path), cfg)
    flat_a = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(params)}
    flat_b = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(loaded)}
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(
            np.asarray(flat_a[k]), np.asarray(flat_b[k]), err_msg=k
        )


def test_dit_trainer_e2e(tmp_path):
    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer.dit_trainer import DiTTrainer

    rng = np.random.default_rng(0)
    rows = []
    for _ in range(16):
        rows.append({
            "latents": rng.standard_normal((16, 8)).tolist(),
            "text_states": rng.standard_normal((5, 32)).tolist(),
            "pooled_text": rng.standard_normal(16).tolist(),
        })
    with open(tmp_path / "data.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "flux", **TINY,
        "dtype": "float32", "param_dtype": "float32",
        "latent_shape": (16, 8), "text_len": 8,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 2
    args.train.bf16 = False
    args.train.async_save = False
    args.train.log_steps = 100
    destroy_parallel_state()
    try:
        trainer = DiTTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 2
        assert np.isfinite(ctl.metrics["loss"])
        trainer.checkpointer.close()
    finally:
        destroy_parallel_state()
