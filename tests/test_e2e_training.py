"""End-to-end training smoke: toy model, packed data, FSDP+SP mesh, resume.

Ports the reference's e2e strategy (``tests/e2e/test_e2e_training*.py`` +
``tests/checkpoints/test_trainer_saveload.py``): run real trainer steps on a
toy config and assert loss decreases and resume reproduces state.
"""

import json
import os

import numpy as np
import pytest

from veomni_tpu.arguments import VeOmniArguments


def _write_dummy_data(path, n=512, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        ln = int(rng.integers(16, 100))
        rows.append({"input_ids": rng.integers(0, vocab, ln).tolist()})
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


TOY = {
    "model_type": "qwen3",
    "vocab_size": 256,
    "hidden_size": 64,
    "intermediate_size": 128,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "head_dim": 16,
    "qk_norm": True,
}


def _make_args(tmp_path, **train_overrides):
    args = VeOmniArguments()
    args.model.config_overrides = dict(TOY)
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 128
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 8
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 100
    for k, v in train_overrides.items():
        setattr(args.train, k, v)
    return args


def test_e2e_training_fsdp_sp(tmp_path):
    from veomni_tpu.trainer import TextTrainer

    _write_dummy_data(tmp_path / "data.jsonl")
    args = _make_args(tmp_path, ulysses_parallel_size=2)
    trainer = TextTrainer(args)
    first_loss = None
    orig_step = trainer.train_step

    losses = []

    def wrapped(state, batch):
        out = orig_step(state, batch)
        losses.append(float(out[1]["loss"]))
        return out

    trainer.train_step = wrapped
    ctl = trainer.train()
    assert ctl.global_step == 8
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    trainer.checkpointer.close()


def test_e2e_resume(tmp_path):
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer import TextTrainer

    _write_dummy_data(tmp_path / "data.jsonl")
    args = _make_args(tmp_path, save_steps=4, train_steps=4)
    trainer = TextTrainer(args)
    trainer.train()
    step4_loss_params = trainer.train_state.params
    import jax

    p4 = jax.tree.map(lambda x: np.asarray(x), step4_loss_params)
    trainer.checkpointer.close()
    destroy_parallel_state()

    # new trainer, resume from step 4, run to 8
    args2 = _make_args(tmp_path, save_steps=4, train_steps=8)
    trainer2 = TextTrainer(args2)
    ctl = trainer2.train()
    assert ctl.global_step == 8
    trainer2.checkpointer.close()
