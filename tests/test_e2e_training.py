"""End-to-end training smoke: toy model, packed data, FSDP+SP mesh, resume.

Ports the reference's e2e strategy (``tests/e2e/test_e2e_training*.py`` +
``tests/checkpoints/test_trainer_saveload.py``): run real trainer steps on a
toy config and assert loss decreases and resume reproduces state.
"""

import json
import os

import numpy as np
import pytest

from veomni_tpu.arguments import VeOmniArguments


def _write_dummy_data(path, n=512, vocab=256, seed=0, channels=None):
    rng = np.random.default_rng(seed)
    # zipf-skewed tokens: unigram stats are learnable, so the smoke test's
    # "loss decreases" check measures optimization, not noise (uniform data
    # has optimal loss == ln(vocab) == the init loss)
    weights = 1.0 / (np.arange(vocab) + 5.0)
    weights /= weights.sum()
    rows = []
    for _ in range(n):
        ln = int(rng.integers(16, 100))
        row = {"input_ids": rng.choice(vocab, size=ln, p=weights).tolist()}
        if channels:
            row["channel"] = channels[int(rng.integers(0, len(channels)))]
        rows.append(row)
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


TOY = {
    "model_type": "qwen3",
    "vocab_size": 256,
    "hidden_size": 64,
    "intermediate_size": 128,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "head_dim": 16,
    "qk_norm": True,
}


def _make_args(tmp_path, **train_overrides):
    args = VeOmniArguments()
    args.model.config_overrides = dict(TOY)
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 128
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 8
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 100
    for k, v in train_overrides.items():
        setattr(args.train, k, v)
    return args


def test_e2e_training_fsdp_sp(tmp_path):
    from veomni_tpu.trainer import TextTrainer

    _write_dummy_data(tmp_path / "data.jsonl")
    args = _make_args(tmp_path, ulysses_parallel_size=2, train_steps=12, lr=5e-3)
    trainer = TextTrainer(args)
    orig_step = trainer.train_step

    losses = []

    def wrapped(state, batch):
        out = orig_step(state, batch)
        losses.append(float(out[1]["loss"]))
        return out

    trainer.train_step = wrapped
    ctl = trainer.train()
    assert ctl.global_step == 12
    head = np.mean(losses[:2])
    tail = np.mean(losses[-4:])
    assert tail < head, f"loss did not decrease: {losses}"
    trainer.checkpointer.close()


def _host_tree(tree):
    import jax

    return jax.tree.map(lambda x: np.asarray(x), tree)


def _assert_trees_identical(a, b, what):
    import jax

    leaves_a, treedef_a = jax.tree.flatten(a)
    leaves_b, treedef_b = jax.tree.flatten(b)
    assert treedef_a == treedef_b, f"{what}: tree structure differs"
    for i, (la, lb) in enumerate(zip(leaves_a, leaves_b)):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"{what}: leaf {i} ({treedef_a}) not bit-identical; "
            f"max abs diff {np.abs(np.asarray(la, np.float64) - np.asarray(lb, np.float64)).max()}"
        )


def _run_resume_case(tmp_path, *, data_kwargs=None, data_overrides=None,
                     **train_overrides):
    """8 straight steps vs (4 steps, save, restart, 4 steps) must produce
    bit-identical params, opt_state, and dataloader cursor (reference
    CheckpointerCallback exact-resume contract, checkpoint_callback.py:60-115)."""
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer import TextTrainer

    _write_dummy_data(tmp_path / "data.jsonl", **(data_kwargs or {}))

    def make(out_name, **over):
        args = _make_args(tmp_path, **{**train_overrides, **over})
        args.train.output_dir = str(tmp_path / out_name)
        for k, v in (data_overrides or {}).items():
            setattr(args.data, k, v)
        return args

    # ---- run A: 8 straight steps, one trainer
    trainer_a = TextTrainer(make("a", train_steps=8, save_steps=0))
    ctl_a = trainer_a.train()
    assert ctl_a.global_step == 8
    ref_state = _host_tree(
        {"params": trainer_a.train_state.params,
         "opt_state": trainer_a.train_state.opt_state}
    )
    def _consumed_cursor(trainer):
        # with background prefetch the raw loader runs ahead by a
        # timing-dependent amount; the consumed-batch cursor (what a
        # checkpoint would record) is the deterministic quantity
        src = getattr(trainer, "_prefetcher", None) or trainer.dataloader
        return src.state_dict() if hasattr(src, "state_dict") else None

    ref_loader = _consumed_cursor(trainer_a)
    trainer_a.checkpointer.close()
    destroy_parallel_state()

    # ---- run B: 4 steps, save, fresh process-equivalent restart, 4 more.
    # train_steps stays 8 (the lr-schedule horizon must match run A); a
    # callback stops the first leg after step 4, like a preempted job.
    from veomni_tpu.trainer.callbacks import Callback

    class StopAt(Callback):
        def __init__(self, at):
            self.at = at

        def on_step_end(self, trainer, state):
            if state.global_step >= self.at:
                state.should_stop = True

    trainer_b1 = TextTrainer(make("b", train_steps=8, save_steps=4))
    trainer_b1.callbacks.append(StopAt(4))
    trainer_b1.train()
    trainer_b1.checkpointer.close()
    destroy_parallel_state()

    trainer_b2 = TextTrainer(make("b", train_steps=8, save_steps=4))
    ctl_b = trainer_b2.train()
    assert ctl_b.global_step == 8

    got_state = _host_tree(
        {"params": trainer_b2.train_state.params,
         "opt_state": trainer_b2.train_state.opt_state}
    )
    _assert_trees_identical(ref_state, got_state, "resumed train_state")
    if ref_loader is not None:
        assert ref_loader == _consumed_cursor(trainer_b2), (
            "dataloader cursor state diverged after resume"
        )
    trainer_b2.checkpointer.close()
    destroy_parallel_state()


def test_e2e_resume_exact(tmp_path):
    _run_resume_case(tmp_path)


def test_e2e_resume_exact_dynbsz_channels(tmp_path):
    _run_resume_case(
        tmp_path,
        data_kwargs={"channels": ["code", "web"]},
        data_overrides={"dyn_bsz": True, "channel_list": ["code", "web"]},
    )


def test_e2e_eval_loop(tmp_path):
    """Periodic evaluation: eval_loss computed from data.eval_path every
    eval_steps and at train end (the reference's EvaluateCallback is an
    empty TODO — ours runs)."""
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer import TextTrainer

    _write_dummy_data(tmp_path / "data.jsonl")
    _write_dummy_data(tmp_path / "eval.jsonl")
    args = _make_args(tmp_path, train_steps=4)
    args.data.eval_path = str(tmp_path / "eval.jsonl")
    args.train.eval_steps = 2
    args.train.eval_batches = 2
    destroy_parallel_state()
    try:
        trainer = TextTrainer(args)
        seen = []
        orig = trainer.evaluate

        def spy():
            loss = orig()
            seen.append(loss)
            return loss

        trainer.evaluate = spy
        ctl = trainer.train()
        trainer.checkpointer.close()
        assert len(seen) == 2  # steps 2 and 4 (train-end skips: 4 % 2 == 0)
        assert all(np.isfinite(l) for l in seen)
        assert "eval_loss" in ctl.metrics
    finally:
        destroy_parallel_state()


def test_e2e_training_ctx_remat_policy(tmp_path):
    """bench.py's default remat policy ("ctx": save only the named attention
    context) must train end-to-end through the CLI argument plumbing
    (train.gradient_checkpointing_policy -> cfg.remat_policy) with losses
    matching the nothing-policy run exactly (same seeds, pure remat change)."""
    from veomni_tpu.trainer import TextTrainer

    _write_dummy_data(tmp_path / "data.jsonl")
    losses = {}
    for policy in ("ctx", "nothing"):
        args = _make_args(
            tmp_path, train_steps=4,
            gradient_checkpointing_policy=policy,
        )
        args.model.config_overrides = {**TOY, "remat": True}
        args.train.output_dir = str(tmp_path / f"out_{policy}")
        trainer = TextTrainer(args)
        orig_step = trainer.train_step
        seen = []

        def wrapped(state, batch, _s=seen, _o=orig_step):
            out = _o(state, batch)
            _s.append(float(out[1]["loss"]))
            return out

        trainer.train_step = wrapped
        trainer.train()
        losses[policy] = seen
    assert len(losses["ctx"]) == 4
    np.testing.assert_allclose(losses["ctx"], losses["nothing"], rtol=1e-6)
