"""Omni composite model: audio encoder, multi-modality merge, e2e training."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu.arguments import VeOmniArguments

TEXT = dict(model_type="qwen2", vocab_size=600, hidden_size=64,
            intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, attention_bias=True)
VISION = dict(image_size=28, patch_size=7, hidden_size=32, intermediate_size=64,
              num_hidden_layers=2, num_attention_heads=2, spatial_merge_size=2)
AUDIO = dict(n_mels=16, max_frames=32, subsample=4, hidden_size=32,
             intermediate_size=64, num_hidden_layers=2, num_attention_heads=2)


def test_audio_encoder_shapes():
    from veomni_tpu.models.omni import AudioEncoderConfig, audio_forward, init_audio_params

    cfg = AudioEncoderConfig(**AUDIO, out_hidden_size=64)
    params = init_audio_params(jax.random.PRNGKey(0), cfg)
    feats = audio_forward(params, cfg, jnp.ones((3, 32, 16)))
    assert feats.shape == (3, cfg.tokens_per_audio, 64)


MOVQ = dict(resolution=8, ch=8, ch_mult=(1, 2), num_res_blocks=1,
            attn_resolutions=(4,), z_channels=4, embed_dim=6, n_embed=32,
            num_groups=4)  # token_grid 4 -> 16 tokens/image


def _gen_cfg():
    from veomni_tpu.models.omni import OmniConfig

    return OmniConfig(
        text=dict(TEXT), image_gen={"movq": dict(MOVQ)}, image_gen_token_id=512,
        max_gen_images=1,
    )


def _gen_batch(cfg, with_gen: bool):
    from veomni_tpu.data.data_collator import IGNORE_INDEX

    rng = np.random.default_rng(1)
    s = 48
    t_gen = cfg.image_gen.tokens_per_image
    ids = rng.integers(1, 500, (2, s)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    labels[:, -1] = IGNORE_INDEX
    gen_mask = np.zeros((2, 1), bool)
    pixels = np.zeros((2, 1, 8, 8, 3), np.float32)
    if with_gen:
        # row 0 carries one generated image after 16 text tokens
        ids[0, 16:16 + t_gen] = cfg.image_gen_token_id
        labels[0, 15:15 + t_gen] = IGNORE_INDEX
        gen_mask[0, 0] = True
        pixels[0, 0] = rng.random((8, 8, 3), np.float32) * 2 - 1
    return {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(labels),
        "position_ids": jnp.broadcast_to(jnp.arange(s), (2, s)).astype(jnp.int32),
        "segment_ids": jnp.ones((2, s), jnp.int32),
        "gen_pixels": jnp.asarray(pixels),
        "gen_image_mask": jnp.asarray(gen_mask),
    }


def test_image_gen_loss_trains_and_text_invariant():
    from veomni_tpu.models.omni import OmniConfig, init_omni_params, omni_loss_fn

    cfg = _gen_cfg()
    params = init_omni_params(jax.random.PRNGKey(0), cfg)
    batch = _gen_batch(cfg, with_gen=True)

    @jax.jit
    def step(p):
        (total, metrics), grads = jax.value_and_grad(
            lambda q: omni_loss_fn(q, cfg, batch), has_aux=True
        )(p)
        # train only aligner + gen head (freeze_tokenizer semantics keep the
        # movq grads zero; LM drift would also move gen loss, so isolate)
        new_ig = {
            k: jax.tree.map(lambda a, g: a - 0.5 * g, p["image_gen"][k],
                            grads["image_gen"][k])
            for k in ("aligner", "gen_head")
        }
        new_p = dict(p)
        new_p["image_gen"] = dict(p["image_gen"], **new_ig)
        return new_p, metrics

    _, m0 = step(params)
    assert int(m0["gen_ntokens"]) == cfg.image_gen.tokens_per_image
    p1 = params
    for _ in range(6):
        p1, m = step(p1)
    gl0 = float(m0["gen_loss_sum"]) / float(m0["gen_ntokens"])
    gl1 = float(m["gen_loss_sum"]) / float(m["gen_ntokens"])
    assert gl1 < gl0 - 0.05, (gl0, gl1)

    # movq tokenizer stays frozen: its grads are exactly zero
    grads = jax.grad(lambda q: omni_loss_fn(q, cfg, batch)[0])(params)
    assert all(
        float(jnp.abs(g).max()) == 0.0
        for g in jax.tree.leaves(grads["image_gen"]["movq"])
    )

    # no gen tokens in the batch -> text loss identical to a plain text model
    nb = _gen_batch(cfg, with_gen=False)
    total_gen, m_gen = omni_loss_fn(params, cfg, nb)
    plain = OmniConfig(text=dict(TEXT))
    p_plain = dict(params)
    p_plain.pop("image_gen")
    total_plain, m_plain = omni_loss_fn(p_plain, plain, nb)
    assert float(m_gen["gen_loss_sum"]) == 0.0
    np.testing.assert_allclose(
        float(m_gen["loss_sum"]), float(m_plain["loss_sum"]), rtol=1e-6
    )


def test_image_gen_janus_vq_decoder():
    """The seed_omni decoder registry: the same composite machinery drives
    the llamagen/janus VQ decoder (reference decoder/janusvq16) via
    ImageGenConfig.decoder_type."""
    from veomni_tpu.models.omni import OmniConfig, init_omni_params, omni_loss_fn

    cfg = OmniConfig(
        text=dict(TEXT),
        image_gen={
            "decoder_type": "janus_vq",
            "movq": dict(codebook_size=32, codebook_embed_dim=6, ch=8,
                         encoder_ch_mult=(1, 2), decoder_ch_mult=(1, 2),
                         num_res_blocks=1, z_channels=4, image_size=8,
                         num_groups=4),
        },
        image_gen_token_id=512,
        max_gen_images=1,
    )
    assert cfg.image_gen.tokens_per_image == 16
    assert cfg.image_gen.image_size == 8
    params = init_omni_params(jax.random.PRNGKey(0), cfg)
    batch = _gen_batch(cfg, with_gen=True)
    total, metrics = omni_loss_fn(params, cfg, batch)
    assert np.isfinite(float(total))
    assert int(metrics["gen_ntokens"]) == 16
    # frozen VQ; aligner/head trainable
    grads = jax.grad(lambda p: omni_loss_fn(p, cfg, batch)[0])(params)
    assert all(float(jnp.abs(g).max()) == 0.0
               for g in jax.tree.leaves(grads["image_gen"]["movq"]))
    assert float(jnp.abs(grads["image_gen"]["gen_head"]["fc2"]).sum()) > 0.0


def test_generate_image():
    """lm_generate contract: autoregressive code sampling + VQ decode
    produce a correctly-shaped image; greedy determinism at temperature~0."""
    from veomni_tpu.models.omni import generate_image, init_omni_params

    cfg = _gen_cfg()
    params = init_omni_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray(np.random.default_rng(0).integers(1, 500, (1, 6)),
                         jnp.int32)
    pixels, codes = generate_image(params, cfg, prompt, jax.random.PRNGKey(1))
    r = cfg.image_gen.image_size
    assert pixels.shape == (1, r, r, 3)
    assert codes.shape == (1, cfg.image_gen.tokens_per_image)
    assert np.all(np.asarray(codes) >= 0)
    assert np.all(np.asarray(codes) < cfg.image_gen.movq.n_embed)
    # sampling is a pure function of the key (an untrained head has logit
    # ties, so near-greedy runs are NOT key-invariant — compare same-key)
    _, c1 = generate_image(params, cfg, prompt, jax.random.PRNGKey(2))
    _, c2 = generate_image(params, cfg, prompt, jax.random.PRNGKey(2))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


def test_movqgan_hf_roundtrip(tmp_path):
    from safetensors.numpy import save_file

    from veomni_tpu.models import movqgan

    cfg = movqgan.MoVQGANConfig(**MOVQ)
    params = movqgan.init_params(jax.random.PRNGKey(3), cfg)

    # emit the torch-layout (OIHW, reference module names) state dict by
    # walking the same structure hf_to_params expects
    sd = {}

    def put_conv(name, w, b):
        # ascontiguousarray: safetensors serializes the raw buffer, silently
        # ignoring the transpose's strides
        sd[name + ".weight"] = np.ascontiguousarray(np.transpose(np.asarray(w), (3, 2, 0, 1)))
        sd[name + ".bias"] = np.asarray(b)

    def put_norm(prefix, p, spatial):
        if spatial:
            sd[prefix + ".norm_layer.weight"] = np.asarray(p["gn_w"])
            sd[prefix + ".norm_layer.bias"] = np.asarray(p["gn_b"])
            put_conv(prefix + ".conv_y", p["conv_y_w"], p["conv_y_b"])
            put_conv(prefix + ".conv_b", p["conv_b_w"], p["conv_b_b"])
        else:
            sd[prefix + ".weight"] = np.asarray(p["gn_w"])
            sd[prefix + ".bias"] = np.asarray(p["gn_b"])

    def put_res(prefix, p, spatial):
        put_norm(prefix + ".norm1", p["norm1"], spatial)
        put_conv(prefix + ".conv1", p["conv1_w"], p["conv1_b"])
        put_norm(prefix + ".norm2", p["norm2"], spatial)
        put_conv(prefix + ".conv2", p["conv2_w"], p["conv2_b"])
        if "shortcut_w" in p:
            put_conv(prefix + ".nin_shortcut", p["shortcut_w"], p["shortcut_b"])

    def put_attn(prefix, p, spatial):
        put_norm(prefix + ".norm", p["norm"], spatial)
        for mine, theirs in (("q", "q"), ("k", "k"), ("v", "v"), ("proj", "proj_out")):
            put_conv(f"{prefix}.{theirs}", p[f"{mine}_w"], p[f"{mine}_b"])

    enc = params["encoder"]
    put_conv("encoder.conv_in", enc["conv_in_w"], enc["conv_in_b"])
    for i, level in enumerate(enc["down"]):
        for j, rp in enumerate(level["res"]):
            put_res(f"encoder.down.{i}.block.{j}", rp, False)
        for j, ap in enumerate(level["attn"]):
            put_attn(f"encoder.down.{i}.attn.{j}", ap, False)
        if "down_w" in level:
            put_conv(f"encoder.down.{i}.downsample.conv", level["down_w"], level["down_b"])
    put_res("encoder.mid.block_1", enc["mid_res1"], False)
    put_attn("encoder.mid.attn_1", enc["mid_attn"], False)
    put_res("encoder.mid.block_2", enc["mid_res2"], False)
    put_norm("encoder.norm_out", enc["norm_out"], False)
    put_conv("encoder.conv_out", enc["conv_out_w"], enc["conv_out_b"])

    dec = params["decoder"]
    levels = len(cfg.ch_mult)
    put_conv("decoder.conv_in", dec["conv_in_w"], dec["conv_in_b"])
    put_res("decoder.mid.block_1", dec["mid_res1"], True)
    put_attn("decoder.mid.attn_1", dec["mid_attn"], True)
    put_res("decoder.mid.block_2", dec["mid_res2"], True)
    for j, level in enumerate(dec["up"]):
        i = levels - 1 - j
        for k, rp in enumerate(level["res"]):
            put_res(f"decoder.up.{i}.block.{k}", rp, True)
        for k, ap in enumerate(level["attn"]):
            put_attn(f"decoder.up.{i}.attn.{k}", ap, True)
        if "up_w" in level:
            put_conv(f"decoder.up.{i}.upsample.conv", level["up_w"], level["up_b"])
    put_norm("decoder.norm_out", dec["norm_out"], True)
    put_conv("decoder.conv_out", dec["conv_out_w"], dec["conv_out_b"])

    sd["quantize.embedding.weight"] = np.asarray(params["codebook"])
    put_conv("quant_conv", params["quant_conv_w"], params["quant_conv_b"])
    put_conv("post_quant_conv", params["post_quant_conv_w"], params["post_quant_conv_b"])

    save_file(sd, str(tmp_path / "model.safetensors"))
    loaded = movqgan.hf_to_params(str(tmp_path), cfg)

    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = {jax.tree_util.keystr(p): v for p, v in
              jax.tree_util.tree_leaves_with_path(loaded)}
    assert len(flat_a) == len(flat_b)
    for path, v in flat_a:
        np.testing.assert_array_equal(np.asarray(v), np.asarray(flat_b[jax.tree_util.keystr(path)]), err_msg=jax.tree_util.keystr(path))

    # decode path with embed_dim != z_channels (regression: decoder conv_in
    # consumes post_quant_conv output, which has z_channels channels)
    pixels = jnp.asarray(np.random.default_rng(0).random((1, 8, 8, 3), np.float32))
    z_q, idx, _ = movqgan.encode(loaded, cfg, pixels)
    rec = movqgan.decode(loaded, cfg, z_q)
    assert rec.shape == (1, 8, 8, 3)
    assert idx.shape == (1, 4, 4)
    rec2 = movqgan.decode_code(loaded, cfg, idx.reshape(1, -1))
    np.testing.assert_allclose(np.asarray(rec), np.asarray(rec2), atol=1e-5)


def test_omni_trainer_e2e(tmp_path):
    from veomni_tpu.trainer.omni_trainer import OmniTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "omni.jsonl", "w") as f:
        for i in range(48):
            row = {"input_ids": rng.integers(0, 500, int(rng.integers(10, 30))).tolist()}
            if i % 2:
                row["images"] = [rng.random((28, 28, 3)).tolist()]
            if i % 3:
                row["audio"] = [rng.random((32, 16)).tolist()]
            if i % 5 == 0:
                row["gen_images"] = [rng.random((8, 8, 3)).tolist()]
            f.write(json.dumps(row) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "text": dict(TEXT), "vision": dict(VISION), "audio": dict(AUDIO),
        "image_gen": {"movq": dict(MOVQ)},
        "image_token_id": 510, "audio_token_id": 511, "image_gen_token_id": 512,
        "freeze_audio": False,
    }
    args.data.train_path = str(tmp_path / "omni.jsonl")
    args.data.max_seq_len = 96
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    trainer = OmniTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert np.isfinite(ctl.metrics["loss"])
    assert (tmp_path / "out" / "hf_ckpt" / "language_model" / "model.safetensors").exists()
    trainer.checkpointer.close()
