"""Omni composite model: audio encoder, multi-modality merge, e2e training."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu.arguments import VeOmniArguments

TEXT = dict(model_type="qwen2", vocab_size=600, hidden_size=64,
            intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, attention_bias=True)
VISION = dict(image_size=28, patch_size=7, hidden_size=32, intermediate_size=64,
              num_hidden_layers=2, num_attention_heads=2, spatial_merge_size=2)
AUDIO = dict(n_mels=16, max_frames=32, subsample=4, hidden_size=32,
             intermediate_size=64, num_hidden_layers=2, num_attention_heads=2)


def test_audio_encoder_shapes():
    from veomni_tpu.models.omni import AudioEncoderConfig, audio_forward, init_audio_params

    cfg = AudioEncoderConfig(**AUDIO, out_hidden_size=64)
    params = init_audio_params(jax.random.PRNGKey(0), cfg)
    feats = audio_forward(params, cfg, jnp.ones((3, 32, 16)))
    assert feats.shape == (3, cfg.tokens_per_audio, 64)


def test_omni_trainer_e2e(tmp_path):
    from veomni_tpu.trainer.omni_trainer import OmniTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "omni.jsonl", "w") as f:
        for i in range(48):
            row = {"input_ids": rng.integers(0, 500, int(rng.integers(10, 30))).tolist()}
            if i % 2:
                row["images"] = [rng.random((28, 28, 3)).tolist()]
            if i % 3:
                row["audio"] = [rng.random((32, 16)).tolist()]
            f.write(json.dumps(row) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "text": dict(TEXT), "vision": dict(VISION), "audio": dict(AUDIO),
        "image_token_id": 510, "audio_token_id": 511, "freeze_audio": False,
    }
    args.data.train_path = str(tmp_path / "omni.jsonl")
    args.data.max_seq_len = 96
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 1
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    trainer = OmniTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert np.isfinite(ctl.metrics["loss"])
    assert (tmp_path / "out" / "hf_ckpt" / "language_model" / "model.safetensors").exists()
    trainer.checkpointer.close()
