"""Qwen3-Omni-MoE thinker parity vs HF transformers (tiny config).

Oracle pattern as test_qwen3_vl.py: tiny
``Qwen3OmniMoeThinkerForConditionalGeneration``, HF-format export, import,
and identical audio-tower features / full loss on text + audio + image —
exercising the chunked conv downsampling, per-chunk sinusoid positions,
windowed audio attention, deepstack vision reuse, MoE text, and the omni
3-stream rope index.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

IMG_ID, VID_ID, AUD_ID = 9, 10, 11
VSTART_ID, ASTART_ID = 8, 7


def _tiny_hf_model(tmp_path):
    import torch
    from transformers.models.qwen3_omni_moe.configuration_qwen3_omni_moe import (
        Qwen3OmniMoeThinkerConfig,
    )
    from transformers.models.qwen3_omni_moe.modeling_qwen3_omni_moe import (
        Qwen3OmniMoeThinkerForConditionalGeneration,
    )

    cfg = Qwen3OmniMoeThinkerConfig(
        text_config=dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            moe_intermediate_size=32,
            num_experts=4,
            num_experts_per_tok=2,
            norm_topk_prob=True,
            router_aux_loss_coef=0.0,
            output_router_logits=False,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            head_dim=16,
            max_position_embeddings=512,
            rope_theta=10000.0,
            rope_scaling={"rope_type": "default", "mrope_section": [2, 3, 3],
                          "mrope_interleaved": True},
            tie_word_embeddings=False,
        ),
        vision_config=dict(
            depth=2,
            hidden_size=32,
            intermediate_size=64,
            num_heads=2,
            in_channels=3,
            patch_size=2,
            temporal_patch_size=2,
            spatial_merge_size=2,
            out_hidden_size=64,
            num_position_embeddings=16,
            deepstack_visual_indexes=[0],
        ),
        audio_config=dict(
            d_model=32,
            encoder_layers=2,
            encoder_attention_heads=2,
            encoder_ffn_dim=64,
            num_mel_bins=32,
            max_source_positions=200,
            n_window=50,          # chunks of 100 mel frames -> 13 conv frames
            n_window_infer=200,   # 2 chunks per attention window
            downsample_hidden_size=16,
            output_dim=64,
            conv_chunksize=500,
        ),
        image_token_id=IMG_ID,
        video_token_id=VID_ID,
        audio_token_id=AUD_ID,
        vision_start_token_id=VSTART_ID,
        audio_start_token_id=ASTART_ID,
        position_id_per_seconds=13,
    )
    torch.manual_seed(0)
    model = Qwen3OmniMoeThinkerForConditionalGeneration(cfg).eval()
    out = tmp_path / "hf_ckpt"
    model.save_pretrained(out, safe_serialization=True)
    return model, cfg, str(out)


@pytest.fixture(scope="module")
def hf_and_ours(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("q3omni")
    hf_model, hf_cfg, ckpt = _tiny_hf_model(tmp_path)

    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(ckpt, dtype="float32")
    params = model.load_hf(ckpt)
    return hf_model, hf_cfg, model, params


AUDIO_LENS = [130, 97]  # multi-chunk (100+30) + single-chunk audios


def test_audio_tower_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    rng = np.random.default_rng(0)
    mels = [rng.standard_normal((cfg.audio.num_mel_bins, L)).astype(np.float32)
            for L in AUDIO_LENS]

    with torch.no_grad():
        ref = hf_model.audio_tower(
            torch.from_numpy(np.concatenate(mels, axis=1)),
            feature_lens=torch.tensor(AUDIO_LENS),
        ).last_hidden_state.numpy()

    from veomni_tpu.models.qwen3_omni_moe import (
        audio_forward, audio_metadata, pack_audio_chunks,
    )

    n_chunk_pad, n_frame_pad = 4, 64
    meta = audio_metadata(AUDIO_LENS, cfg.audio, n_chunk_pad, n_frame_pad)
    chunks = pack_audio_chunks(mels, cfg.audio, n_chunk_pad)
    got = audio_forward(
        params["audio_tower"], cfg.audio, jnp.asarray(chunks),
        jnp.asarray(meta["frame_gather"]),
        jnp.asarray(meta["seg"]), dtype=jnp.float32,
    )
    got = np.asarray(got)[meta["frame_mask"]]
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_full_loss_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    rng = np.random.default_rng(1)

    from veomni_tpu.models.qwen3_omni_moe import (
        audio_metadata, audio_output_lengths, omni_position_ids,
        pack_audio_chunks,
    )
    from veomni_tpu.models.qwen3_vl import vision_metadata

    grids = [(1, 4, 4)]
    n_merged = [t * (h // 2) * (w // 2) for t, h, w in grids]
    n_img_patches = sum(t * h * w for t, h, w in grids)
    pixel_values = rng.standard_normal(
        (n_img_patches, cfg.vision.patch_dim)).astype(np.float32)
    mels = [rng.standard_normal((cfg.audio.num_mel_bins, L)).astype(np.float32)
            for L in AUDIO_LENS]
    aud_tokens = [audio_output_lengths(L) for L in AUDIO_LENS]

    ids = [ASTART_ID] + [AUD_ID] * aud_tokens[0]
    ids += list(rng.integers(12, 256, 5))
    ids += [VSTART_ID] + [IMG_ID] * n_merged[0]
    ids += list(rng.integers(12, 256, 4))
    ids += [ASTART_ID] + [AUD_ID] * aud_tokens[1]
    ids += list(rng.integers(12, 256, 6))
    input_ids = np.asarray([ids], np.int64)
    labels = input_ids.copy()

    max_mel = max(AUDIO_LENS)
    feat_padded = np.zeros((len(mels), cfg.audio.num_mel_bins, max_mel), np.float32)
    feat_mask = np.zeros((len(mels), max_mel), np.int64)
    for i, m in enumerate(mels):
        feat_padded[i, :, : m.shape[1]] = m
        feat_mask[i, : m.shape[1]] = 1
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.from_numpy(input_ids),
            labels=torch.from_numpy(labels),
            pixel_values=torch.from_numpy(pixel_values),
            image_grid_thw=torch.as_tensor(grids),
            input_features=torch.from_numpy(feat_padded),
            feature_attention_mask=torch.from_numpy(feat_mask),
        )
    ref_loss = float(ref.loss)

    n_chunk_pad, n_frame_pad = 4, 64
    ameta = audio_metadata(AUDIO_LENS, cfg.audio, n_chunk_pad, n_frame_pad)
    chunks = pack_audio_chunks(mels, cfg.audio, n_chunk_pad)
    vmeta = vision_metadata(grids, cfg.vision, n_pad_patches=n_img_patches)

    # reference position ids (our numpy port must match HF's)
    ref_pos, _ = hf_model.get_rope_index(
        torch.from_numpy(input_ids),
        image_grid_thw=torch.as_tensor(grids),
        audio_seqlens=torch.tensor(AUDIO_LENS),
        attention_mask=torch.ones_like(torch.from_numpy(input_ids)),
    )
    pos = omni_position_ids(
        input_ids, cfg, image_grid_thw=grids, audio_lens=AUDIO_LENS
    )
    np.testing.assert_array_equal(pos[0], ref_pos[:, 0].numpy())

    shifted = np.full_like(labels, -100)
    shifted[:, :-1] = labels[:, 1:]
    batch = {
        "input_ids": jnp.asarray(input_ids, jnp.int32),
        "labels": jnp.asarray(shifted, jnp.int32),
        "position_ids": jnp.asarray(pos, jnp.int32),
        "segment_ids": jnp.ones_like(jnp.asarray(input_ids, jnp.int32)),
        "pixel_values": jnp.asarray(pixel_values),
        "vis_pos_hw": jnp.asarray(vmeta["pos_hw"]),
        "vis_pos_interp_idx": jnp.asarray(vmeta["pos_interp_idx"]),
        "vis_pos_interp_w": jnp.asarray(vmeta["pos_interp_w"]),
        "vis_seg_full": jnp.asarray(vmeta["seg_full"]),
        "vis_merged_mask": jnp.asarray(vmeta["merged_mask"]),
        "audio_chunks": jnp.asarray(chunks),
        "aud_frame_gather": jnp.asarray(ameta["frame_gather"]),
        "aud_seg": jnp.asarray(ameta["seg"]),
        "aud_frame_mask": jnp.asarray(ameta["frame_mask"]),
    }
    loss_sum, metrics = model.loss_fn(params, batch)
    got_loss = float(loss_sum) / float(metrics["ntokens"])
    np.testing.assert_allclose(got_loss, ref_loss, rtol=3e-4)


def test_hf_export_roundtrip(hf_and_ours, tmp_path):
    import torch
    from transformers.models.qwen3_omni_moe.modeling_qwen3_omni_moe import (
        Qwen3OmniMoeThinkerForConditionalGeneration,
    )

    hf_model, hf_cfg, model, params = hf_and_ours
    out = tmp_path / "export"
    model.family.save_hf_checkpoint(params, model.config, str(out))

    reloaded = Qwen3OmniMoeThinkerForConditionalGeneration.from_pretrained(
        str(out), config=hf_cfg, torch_dtype=torch.float32
    ).eval()
    with torch.no_grad():
        for (n1, p1), (n2, p2) in zip(
            sorted(hf_model.named_parameters()),
            sorted(reloaded.named_parameters()),
        ):
            assert n1 == n2
            np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-6, atol=1e-6)


def test_qwen3_omni_trainer_e2e(tmp_path):
    """Full OmniTrainer drive: raw audio + images -> mel/patch plans ->
    omni rope -> deepstack MoE train steps; checkpoint + HF export."""
    import json

    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer import OmniTrainer

    rng = np.random.default_rng(0)
    rows = []
    for i in range(16):
        row = {
            "input_ids": rng.integers(12, 256, int(rng.integers(8, 20))).tolist(),
        }
        if i % 2 == 0:  # 8x8 pixels -> 4x4 patch grid (patch 2)
            row["images"] = [rng.random((8, 8, 3)).tolist()]
        if i % 3 == 0:  # precomputed mel [n_mels, T]
            row["audios"] = [rng.standard_normal((32, 60)).tolist()]
        rows.append(row)
    with open(tmp_path / "data.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen3_omni_moe",
        "vocab_size": 256,
        "hidden_size": 64,
        "intermediate_size": 128,
        "moe_intermediate_size": 32,
        "num_experts": 4,
        "num_experts_per_tok": 2,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "head_dim": 16,
        "rope_scaling": {"rope_type": "default", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "out_hidden_size": 64, "num_position_embeddings": 16,
            "deepstack_visual_indexes": [0],
        },
        "audio": {
            "d_model": 32, "encoder_layers": 2, "encoder_attention_heads": 2,
            "encoder_ffn_dim": 64, "num_mel_bins": 32,
            "max_source_positions": 64, "n_window": 50, "n_window_infer": 200,
            "downsample_hidden_size": 16, "output_dim": 64,
        },
        "image_token_id": 9, "video_token_id": 10, "audio_token_id": 11,
        "vision_start_token_id": 8, "audio_start_token_id": 7,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.data.max_patches = 256
    args.data.max_audio_chunks = 8
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    destroy_parallel_state()
    try:
        trainer = OmniTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 3
        assert np.isfinite(ctl.metrics["loss"])
        trainer.checkpointer.close()
        import os

        hf_dir = os.path.join(args.train.output_dir, "hf_ckpt")
        assert os.path.exists(os.path.join(hf_dir, "model.safetensors"))
        from veomni_tpu.models import build_foundation_model

        m2 = build_foundation_model(hf_dir, dtype="float32")
        m2.load_hf(hf_dir)
    finally:
        destroy_parallel_state()
