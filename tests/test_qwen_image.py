"""Qwen-Image MMDiT: structural self-tests (no diffusers oracle available;
same approach as test_wan.py — architecture contract, checkpoint
round-trip through the diffusers key layout, DiTTrainer drive)."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veomni_tpu.models.qwen_image import (
    QwenImageConfig, hf_to_params, init_params, loss_fn, params_to_hf,
    qwen_image_forward, rope_plan,
)

TINY = dict(
    patch_size=2,
    in_channels=16,    # latent C=4, p=2
    out_channels=4,
    num_layers=2,
    attention_head_dim=24,  # rope axes (8, 8, 8)
    num_attention_heads=2,
    joint_attention_dim=32,
    axes_dims_rope=(8, 8, 8),
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def model():
    cfg = QwenImageConfig(**TINY)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_forward_shape_and_conditioning(model):
    cfg, params = model
    rng = np.random.default_rng(0)
    lat = jnp.asarray(rng.standard_normal((2, 16, 16)), jnp.float32)  # 4x4 grid
    t = jnp.asarray([100.0, 700.0], jnp.float32)
    text = jnp.asarray(rng.standard_normal((2, 5, 32)), jnp.float32)
    mask = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.int32)
    out = qwen_image_forward(params, cfg, lat, t, text, mask)
    assert out.shape == (2, 16, cfg.proj_dim)
    # masked text tokens must not influence the prediction
    text2 = text.at[0, 3:].set(123.0)
    out2 = qwen_image_forward(params, cfg, lat, t, text2, mask)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out2[0]),
                               rtol=1e-5, atol=1e-6)
    # unmasked text changes it (joint attention live)
    text3 = text.at[0, 0].set(7.0)
    out3 = qwen_image_forward(params, cfg, lat, t, text3, mask)
    assert np.abs(np.asarray(out[0]) - np.asarray(out3[0])).max() > 1e-6
    # timestep conditioning live (dual-stream modulation)
    out4 = qwen_image_forward(params, cfg, lat, t * 0.1, text, mask)
    assert np.abs(np.asarray(out) - np.asarray(out4)).max() > 1e-6


def test_rope_joint_layout():
    """QwenEmbedRope scale_rope layout: centered image rows/cols, text
    range starting at max(h//2, w//2)."""
    cfg = QwenImageConfig(**TINY)
    cos, sin = rope_plan(cfg, (1, 4, 4), txt_len=3)
    assert cos.shape == (1, 19, 24)
    c = np.asarray(cos)[0]
    s = np.asarray(sin)[0]
    inv = 1.0 / (10000.0 ** (np.arange(0, 8, 2) / 8))
    # image grid rows span [-2, 2): token (0, row=-2, col=-2) is the first
    img0 = 3  # after the 3 text tokens
    np.testing.assert_allclose(
        s[img0, 8:16], np.sin(np.repeat(-2 * inv, 2)), rtol=1e-6, atol=1e-7
    )
    # the (row=0, col=0) token sits at grid index (2, 2)
    np.testing.assert_allclose(c[img0 + 2 * 4 + 2, 8:], 1.0)
    # text tokens start at max(h//2, w//2) = 2 on every axis
    np.testing.assert_allclose(
        c[0, :8], np.cos(np.repeat(2 * inv, 2)), rtol=1e-6
    )


def test_loss_and_grads_finite(model):
    cfg, params = model
    rng = np.random.default_rng(1)
    batch = {
        "latents": jnp.asarray(rng.standard_normal((2, 16, 16)), jnp.float32),
        "timestep": jnp.asarray([10.0, 500.0], jnp.float32),
        "text_states": jnp.asarray(rng.standard_normal((2, 5, 32)), jnp.float32),
        "target": jnp.asarray(rng.standard_normal((2, 16, 16)), jnp.float32),
    }

    loss, grads = jax.value_and_grad(lambda p: loss_fn(p, cfg, batch)[0])(params)
    assert np.isfinite(float(loss))
    flat = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    assert all(np.abs(np.asarray(g)).max() > 0 for g in flat)


def test_checkpoint_roundtrip(model, tmp_path):
    from safetensors.flax import save_file

    cfg, params = model
    tensors = params_to_hf(params, cfg)
    save_file({k: jnp.asarray(v) for k, v in tensors.items()},
              str(tmp_path / "model.safetensors"))
    reloaded = hf_to_params(str(tmp_path), cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, reloaded,
    )


def test_qwen_image_trainer_e2e(tmp_path):
    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer.dit_trainer import DiTTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for _ in range(16):
            f.write(json.dumps({
                "latents": rng.standard_normal((16, 16)).tolist(),
                "text_states": rng.standard_normal((5, 32)).tolist(),
            }) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen_image",
        **{k: v for k, v in TINY.items() if k != "dtype"},
        "latent_shape": (16, 16), "text_len": 5,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    destroy_parallel_state()
    try:
        trainer = DiTTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 3
        assert np.isfinite(ctl.metrics["loss"])
        trainer.checkpointer.close()
        import os

        hf_dir = os.path.join(args.train.output_dir, "hf_ckpt")
        from veomni_tpu.models import build_foundation_model

        m2 = build_foundation_model(hf_dir, dtype="float32")
        m2.load_hf(hf_dir)
    finally:
        destroy_parallel_state()
