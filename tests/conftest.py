"""Test harness: all tests run on a virtual 8-device CPU mesh.

Mirrors the reference's mp.spawn+gloo fallback strategy (SURVEY.md §4): the
collective/sharding logic runs on CPU with 8 virtual devices; numerics match
TPU because XLA semantics are backend-uniform. NOTE: the axon TPU plugin
force-registers itself via jax.config, so we must override *config*, not
just env vars, before first backend use.
"""

import os

os.environ.setdefault("VEOMNI_LOG_LEVEL", "WARNING")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    yield
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state

    destroy_parallel_state()
