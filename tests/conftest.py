"""Test harness: all tests run on a virtual 4-device CPU mesh.

Mirrors the reference's mp.spawn+gloo fallback strategy (SURVEY.md §4): the
collective/sharding logic runs on CPU with 4 virtual devices; numerics match
TPU because XLA semantics are backend-uniform. NOTE: the axon TPU plugin
force-registers itself via jax.config, so we must override *config*, not
just env vars, before first backend use.
"""

import os

os.environ.setdefault("VEOMNI_LOG_LEVEL", "WARNING")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from veomni_tpu.utils.jax_compat import (
    apply_cpu_collective_timeout_flags,
    set_virtual_cpu_devices,
)

# This box exposes 1 physical core for the virtual devices: XLA:CPU
# collective rendezvous can exceed its default 40s termination timeout under
# load and SIGABRT the process. Give the rendezvous generous timeouts
# (version-gated: old jaxlib XLA aborts on unknown flags).
apply_cpu_collective_timeout_flags(warn_s=120, terminate_s=600)
set_virtual_cpu_devices(4)
# With several virtual devices on a 1-core box, async dispatch lets several
# executions be in flight; their collective rendezvous can starve each other
# of pool threads and deadlock (observed SIGABRT in rendezvous.cc). Run CPU
# executions synchronously — one program in flight at a time.
jax.config.update("jax_cpu_enable_async_dispatch", False)
# NOTE: do NOT enable the persistent compilation cache here — reloading
# cached executables with in-process CPU collectives has been observed to
# deadlock the rendezvous on this box (cold runs pass, warm runs hang).

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    yield
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state

    destroy_parallel_state()
