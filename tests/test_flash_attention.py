"""Pallas flash attention numerics vs the XLA reference impl.

Reference test model: ``tests/ops/test_kernel_registry_numerical.py``
(per-(op,impl) alignment). Runs the kernel in interpret mode on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.ops.attention import _attention_xla
from veomni_tpu.ops.pallas.flash_attention import flash_attention


def _inputs(b=2, s=256, hq=4, hkv=2, d=64, seed=0, packed=True):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, hkv, d), jnp.float32)
    if packed:
        seg = np.ones((b, s), np.int32)
        seg[:, s // 3:] = 2
        seg[:, 2 * s // 3:] = 3
        seg[:, -7:] = 0  # trailing padding segment
        seg = jnp.asarray(seg)
    else:
        seg = None
    return q, k, v, seg


@pytest.mark.parametrize("packed", [False, True], ids=["dense", "packed"])
@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_flash_forward_matches_xla(packed, causal):
    q, k, v, seg = _inputs(packed=packed)
    ref = _attention_xla(q, k, v, segment_ids=seg, causal=causal)
    got = flash_attention(q, k, v, segment_ids=seg, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_backward_matches_xla():
    q, k, v, seg = _inputs(s=256)

    def loss_ref(q, k, v):
        return (_attention_xla(q, k, v, segment_ids=seg, causal=True) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, segment_ids=seg, causal=True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g_got, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-4, atol=5e-4,
            err_msg=f"grad d{name} mismatch",
        )


def test_flash_fallback_paths():
    # sliding window and non-divisible seq fall back to XLA silently
    q, k, v, seg = _inputs(s=100)
    out = flash_attention(q, k, v, segment_ids=seg, causal=True)
    ref = _attention_xla(q, k, v, segment_ids=seg, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


# ---------------------------------------------------------------------------
# Blockwise online-softmax XLA attention (the long-context path on platforms
# where Pallas is unavailable) vs the dense reference impl.
# ---------------------------------------------------------------------------
from veomni_tpu.ops.attention import _attention_dense, _attention_xla_chunked


@pytest.mark.parametrize("packed", [False, True], ids=["dense", "packed"])
@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_chunked_forward_matches_dense(packed, causal):
    q, k, v, seg = _inputs(s=512, packed=packed)
    ref = _attention_dense(q, k, v, segment_ids=seg, causal=causal)
    got = _attention_xla_chunked(
        q, k, v, segment_ids=seg, causal=causal, q_chunk=128, k_chunk=128
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_chunked_sliding_window_and_sinks():
    q, k, v, seg = _inputs(s=512, packed=True)
    sinks = jnp.linspace(-1.0, 1.0, q.shape[2])
    for window in (64, None):
        ref = _attention_dense(
            q, k, v, segment_ids=seg, causal=True,
            sliding_window=window, sinks=sinks,
        )
        got = _attention_xla_chunked(
            q, k, v, segment_ids=seg, causal=True,
            sliding_window=window, sinks=sinks, q_chunk=128, k_chunk=128,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_chunked_grads_match_dense():
    q, k, v, seg = _inputs(s=512, packed=True)

    def loss(fn, q, k, v):
        out = fn(q, k, v, segment_ids=seg, causal=True)
        return (out * jnp.arange(out.size).reshape(out.shape) / out.size).sum()

    ref_g = jax.grad(lambda *a: loss(_attention_dense, *a), argnums=(0, 1, 2))(q, k, v)
    got_g = jax.grad(
        lambda *a: loss(
            lambda *b, **kw: _attention_xla_chunked(*b, q_chunk=128, k_chunk=128, **kw),
            *a,
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for r, g in zip(ref_g, got_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4, atol=1e-4)


def test_chunked_threshold_dispatch(monkeypatch):
    """The default 'xla' impl must route long sequences through the chunked
    path (no [B,H,S,S] tensor) — probe via a tiny threshold."""
    monkeypatch.setenv("VEOMNI_ATTN_CHUNK_THRESHOLD", "128")
    q, k, v, seg = _inputs(s=512, packed=True)
    ref = _attention_dense(q, k, v, segment_ids=seg, causal=True)
    got = _attention_xla(q, k, v, segment_ids=seg, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


from veomni_tpu.ops.attention import _attention_xla_twopass


@pytest.mark.parametrize("packed", [False, True], ids=["dense", "packed"])
@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_twopass_forward_matches_dense(packed, causal):
    q, k, v, seg = _inputs(s=512, packed=packed)
    ref = _attention_dense(q, k, v, segment_ids=seg, causal=causal)
    got = _attention_xla_twopass(
        q, k, v, segment_ids=seg, causal=causal, q_chunk=128
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_twopass_no_segments_window_sinks():
    q, k, v, _ = _inputs(s=512, packed=False)
    sinks = jnp.linspace(-1.0, 1.0, q.shape[2])
    for window in (64, None):
        ref = _attention_dense(
            q, k, v, causal=True, sliding_window=window, sinks=sinks,
        )
        got = _attention_xla_twopass(
            q, k, v, causal=True, sliding_window=window, sinks=sinks,
            q_chunk=128,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_twopass_grads_match_dense():
    q, k, v, seg = _inputs(s=512, packed=True)

    def loss(fn, q, k, v):
        out = fn(q, k, v, segment_ids=seg, causal=True)
        return (out * jnp.arange(out.size).reshape(out.shape) / out.size).sum()

    ref_g = jax.grad(lambda *a: loss(_attention_dense, *a), argnums=(0, 1, 2))(q, k, v)
    got_g = jax.grad(
        lambda *a: loss(
            lambda *b, **kw: _attention_xla_twopass(*b, q_chunk=128, **kw), *a
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for r, g in zip(ref_g, got_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4, atol=1e-4)


def test_mask_mod_flex_attention():
    """FlexAttention analogue: a prefix-LM mask_mod (bidirectional inside a
    per-row prefix, causal after) must match a hand-masked dense softmax and
    agree across the dense and blockwise XLA impls."""
    from veomni_tpu.ops.attention import (
        _attention_dense,
        _attention_xla_chunked,
    )

    rng = np.random.default_rng(0)
    b, s, h, d = 2, 256, 2, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    prefix = jnp.asarray([64, 100])

    def mask_mod(qi, ki):
        # [B, Sq, Sk]: ki within the row's prefix OR causal
        return (ki[None, :, :] < prefix[:, None, None]) | (ki <= qi)[None]

    out = _attention_dense(q, k, v, causal=False, mask_mod=mask_mod)

    # manual oracle
    scores = np.einsum("bqhd,bkhd->bhqk", np.asarray(q), np.asarray(k)) / np.sqrt(d)
    qi = np.arange(s)[:, None]
    ki = np.arange(s)[None, :]
    allowed = (ki[None] < np.asarray(prefix)[:, None, None]) | (ki <= qi)[None]
    scores = np.where(allowed[:, None], scores, -1e30)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", probs, np.asarray(v))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    # blockwise path agrees (q_chunk/k_chunk force real blocking)
    out_blk = _attention_xla_chunked(
        q, k, v, causal=False, mask_mod=mask_mod, q_chunk=128, k_chunk=128
    )
    np.testing.assert_allclose(np.asarray(out_blk), np.asarray(out),
                               rtol=2e-5, atol=2e-5)
