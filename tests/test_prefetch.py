"""BackgroundPrefetcher: consumed-batch cursor semantics + error transport.

Reference capability: ``veomni/trainer/base.py:97-199`` (BackgroundPrefetcher
/ VeOmniIter). The checkpoint-critical property: a cursor saved mid-stream
describes the last batch the consumer SAW, not the last one the worker
pulled, so resume replays exactly the prefetched-but-unconsumed batches.
"""

import numpy as np
import pytest


class _StatefulLoader:
    """Deterministic loader with an explicit cursor (mimics the native
    dataloader's state_dict contract)."""

    def __init__(self, n=20, start=0):
        self.n = n
        self.cursor = start

    def __iter__(self):
        while self.cursor < self.n:
            item = {"x": np.full((2,), self.cursor)}
            self.cursor += 1
            yield item

    def state_dict(self):
        return {"cursor": self.cursor}


def test_prefetcher_consumed_state_resume():
    from veomni_tpu.data.prefetch import BackgroundPrefetcher

    loader = _StatefulLoader(n=20)
    pf = BackgroundPrefetcher(loader, depth=3)
    it = iter(pf)
    seen = [int(next(it)["x"][0]) for _ in range(7)]
    assert seen == list(range(7))
    state = pf.state_dict()
    pf.close()
    # the worker ran ahead (cursor > 7+1 possible); the SAVED state must not
    assert state["cursor"] == 7

    resumed = _StatefulLoader(n=20, start=state["cursor"])
    pf2 = BackgroundPrefetcher(resumed, depth=3)
    rest = [int(b["x"][0]) for b in pf2]
    assert rest == list(range(7, 20))
    pf2.close()


def test_prefetcher_exhaustion_and_error():
    from veomni_tpu.data.prefetch import BackgroundPrefetcher

    pf = BackgroundPrefetcher(_StatefulLoader(n=3), depth=2)
    assert len(list(pf)) == 3

    class _Boom:
        def __iter__(self):
            yield {"x": np.zeros(1)}
            raise RuntimeError("loader died")

    pf = BackgroundPrefetcher(_Boom(), depth=2)
    it = iter(pf)
    next(it)
    with pytest.raises(RuntimeError, match="loader died"):
        next(it)

    with pytest.raises(ValueError):
        BackgroundPrefetcher(_StatefulLoader(), depth=0)
