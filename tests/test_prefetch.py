"""BackgroundPrefetcher: consumed-batch cursor semantics + error transport.

Reference capability: ``veomni/trainer/base.py:97-199`` (BackgroundPrefetcher
/ VeOmniIter). The checkpoint-critical property: a cursor saved mid-stream
describes the last batch the consumer SAW, not the last one the worker
pulled, so resume replays exactly the prefetched-but-unconsumed batches.
"""

import numpy as np
import pytest


class _StatefulLoader:
    """Deterministic loader with an explicit cursor (mimics the native
    dataloader's state_dict contract)."""

    def __init__(self, n=20, start=0):
        self.n = n
        self.cursor = start

    def __iter__(self):
        while self.cursor < self.n:
            item = {"x": np.full((2,), self.cursor)}
            self.cursor += 1
            yield item

    def state_dict(self):
        return {"cursor": self.cursor}


def test_prefetcher_consumed_state_resume():
    from veomni_tpu.data.prefetch import BackgroundPrefetcher

    loader = _StatefulLoader(n=20)
    pf = BackgroundPrefetcher(loader, depth=3)
    it = iter(pf)
    seen = [int(next(it)["x"][0]) for _ in range(7)]
    assert seen == list(range(7))
    state = pf.state_dict()
    pf.close()
    # the worker ran ahead (cursor > 7+1 possible); the SAVED state must not
    assert state["cursor"] == 7

    resumed = _StatefulLoader(n=20, start=state["cursor"])
    pf2 = BackgroundPrefetcher(resumed, depth=3)
    rest = [int(b["x"][0]) for b in pf2]
    assert rest == list(range(7, 20))
    pf2.close()


def test_prefetcher_exhaustion_and_error():
    from veomni_tpu.data.prefetch import BackgroundPrefetcher

    pf = BackgroundPrefetcher(_StatefulLoader(n=3), depth=2)
    assert len(list(pf)) == 3

    class _Boom:
        def __iter__(self):
            yield {"x": np.zeros(1)}
            raise RuntimeError("loader died")

    pf = BackgroundPrefetcher(_Boom(), depth=2)
    it = iter(pf)
    next(it)
    with pytest.raises(RuntimeError, match="loader died"):
        next(it)

    with pytest.raises(ValueError):
        BackgroundPrefetcher(_StatefulLoader(), depth=0)


def test_prefetcher_worker_traceback_reaches_consumer():
    """The consumer must see the worker's ORIGINAL frames (where the data
    pipeline actually failed), not a bare sentinel/bare re-raise."""
    import traceback

    from veomni_tpu.data.prefetch import BackgroundPrefetcher

    def deep_failure():
        raise RuntimeError("shard corrupted")

    class _Boom:
        def __iter__(self):
            yield {"x": np.zeros(1)}
            deep_failure()

    pf = BackgroundPrefetcher(_Boom(), depth=2)
    it = iter(pf)
    next(it)
    with pytest.raises(RuntimeError, match="shard corrupted") as excinfo:
        next(it)
    frames = [f.name for f in traceback.extract_tb(excinfo.value.__traceback__)]
    assert "deep_failure" in frames and "_worker" in frames
    pf.close()


def test_prefetcher_close_idempotent_and_wakes_blocked_consumer():
    """close() is safe to call repeatedly (incl. from a signal handler) and
    wakes a consumer blocked on an empty queue promptly."""
    import threading
    import time

    from veomni_tpu.data.prefetch import BackgroundPrefetcher, PrefetcherClosed

    release = threading.Event()

    class _Stuck:
        def __iter__(self):
            yield {"x": np.zeros(1)}
            release.wait(30.0)  # bounded: never wedges the test on failure
            return
            yield  # pragma: no cover

    pf = BackgroundPrefetcher(_Stuck(), depth=1)
    it = iter(pf)
    next(it)
    threading.Timer(0.3, pf.close).start()
    t0 = time.monotonic()
    with pytest.raises(PrefetcherClosed):
        next(it)  # blocked on the empty queue when close() lands
    assert time.monotonic() - t0 < 5.0
    pf.close()  # idempotent
    pf.close()
    release.set()
