"""Chaos schedule generation: determinism, grammar validity, soak driver.

The chaos plan is the replay token for every soak failure — the whole
harness is worthless unless the same seed produces the identical schedule
on every machine, every run. These tests pin that, check the generated
specs actually parse under the ``faults.py`` grammar (a plan the fault
layer rejects at arm time would turn every chaos drill into a no-op), and
drive :func:`run_chaos_soak` once fault-free over a real (tiny) fleet so
the driver's storm/restore/invariant plumbing is covered without paying
for a full chaos drill here — ``scripts/chaos_smoke.py`` owns that as its
own tier-1 stage.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.resilience.chaos import (
    CHAOS_POINTS,
    ChaosPlan,
    KillEvent,
    build_chaos_plan,
    run_chaos_soak,
)
from veomni_tpu.resilience.faults import KNOWN_POINTS, _parse_specs
from veomni_tpu.serving import EngineConfig, Request, SamplingParams
from veomni_tpu.serving.router import Router, RouterConfig

QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)


@pytest.fixture(scope="module")
def qwen3():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


def test_chaos_plan_same_seed_identical():
    """Same seed -> field-for-field identical schedule; the to_doc() form
    is the canonical comparison (and what bench artifacts embed)."""
    kw = dict(duration_s=7.5, kills=2, hangs=2, delays=3, exceptions=2,
              hang_seconds=1.5, delay_ms=10.0, expected_ticks=200)
    a = build_chaos_plan(123, **kw)
    b = build_chaos_plan(123, **kw)
    assert a.to_doc() == b.to_doc()
    # and the doc is JSON-shaped: plain dicts/lists/numbers only
    import json

    json.dumps(a.to_doc())


def test_chaos_plan_different_seed_differs():
    kw = dict(duration_s=7.5, kills=1, hangs=1, delays=2, exceptions=1)
    docs = [build_chaos_plan(s, **kw).to_doc() for s in (1, 2, 3)]
    assert docs[0] != docs[1] or docs[1] != docs[2]


def test_chaos_plan_specs_parse_and_target_known_points():
    """Every generated fault spec must survive ``_parse_specs`` (the arm
    gate) and target a registered serving point; hangs must land only at
    pump-side points where the wedge detector can see them."""
    plan = build_chaos_plan(99, duration_s=10.0, kills=3, hangs=3,
                            delays=3, exceptions=3, hang_seconds=2.0)
    specs = _parse_specs(plan.fault_plan())
    assert len(specs) == 9
    for spec in specs:
        assert spec.point in CHAOS_POINTS
        assert spec.point in KNOWN_POINTS
        if spec.mode == "hang":
            # a hang at serve.admit would hang the ROUTER thread, not a
            # pump worker — a failure mode resurrection cannot fix
            assert spec.point in ("serve.prefill", "serve.decode_tick")
            assert spec.seconds == 2.0
    # kills: sorted ascending, inside the middle of the storm window
    kills = plan.kill_events()
    assert kills == sorted(kills, key=lambda k: k.at_s)
    for k in kills:
        assert 0.15 * 10.0 <= k.at_s <= 0.70 * 10.0
        assert k.pick >= 0


def test_chaos_plan_rejects_nonpositive_duration():
    with pytest.raises(ValueError):
        build_chaos_plan(1, duration_s=0.0)


def test_kill_event_resolution_is_modular():
    """The seeded pick resolves against the live set at fire time — any
    fleet size maps it onto a valid victim."""
    ev = KillEvent(at_s=1.0, pick=7)
    for n in (1, 2, 3, 5):
        assert 0 <= ev.pick % n < n


def test_chaos_plan_fault_plan_is_a_copy():
    plan = ChaosPlan(seed=1, duration_s=1.0,
                     faults=[{"point": "serve.admit", "mode": "delay",
                              "hit": 1, "ms": 5.0}])
    got = plan.fault_plan()
    got[0]["mode"] = "exception"
    assert plan.faults[0]["mode"] == "delay"


def test_run_chaos_soak_fault_free_reports_clean(qwen3):
    """The soak driver end to end with ``plan=None``: every id reaches a
    terminal output, no pool leaks, fleet stays at size, report flags
    read clean — the baseline every chaos verdict divides by."""
    params, cfg = qwen3
    rng = np.random.default_rng(5)
    prompts = [[int(t) for t in rng.integers(1, 128, 8)] for _ in range(6)]
    arrivals = [0.0, 0.01, 0.02, 0.05, 0.08, 0.1]

    def factory():
        r = Router(params, cfg,
                   EngineConfig(num_blocks=64, block_size=8, num_slots=2,
                                max_model_len=64),
                   RouterConfig(replicas=2))
        return r

    report = run_chaos_soak(
        router_factory=factory,
        requests=[Request(prompt_ids=list(p),
                          sampling=SamplingParams(max_new_tokens=4))
                  for p in prompts],
        arrivals=arrivals, plan=None, restore_timeout_s=10.0)
    assert report["seed"] is None
    assert report["submitted"] == 6 and report["completed"] == 6
    assert not report["lost_ids"] and not report["duplicated"]
    assert not report["leaked_blocks"]
    assert report["restored"] and not report["stalled"]
    assert report["wedged"] == 0 and report["respawns"] == 0
    assert report["goodput_tok"] > 0
    assert report["invariants_ok"]
