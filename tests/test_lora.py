"""LoRA: adapter init/merge/train/save-load (reference ``tests/lora/``)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu.lora import LoraConfig, init_lora_params, merge_lora_params
from veomni_tpu.lora.lora import load_adapter, save_adapter
from veomni_tpu.models import TransformerConfig, build_foundation_model


def _cfg(moe=False):
    kw = dict(
        model_type="qwen3_moe" if moe else "qwen3",
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, qk_norm=True, dtype=jnp.float32,
    )
    if moe:
        kw.update(num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32)
    return TransformerConfig(**kw)


def test_lora_init_zero_delta_and_gradients():
    model = build_foundation_model(config=_cfg())
    base = model.init(jax.random.PRNGKey(0))
    lcfg = LoraConfig(rank=4, alpha=8)
    lora = init_lora_params(jax.random.PRNGKey(1), base, lcfg)

    # B=0 init => merged == base exactly
    merged = merge_lora_params(base, lora)
    np.testing.assert_allclose(
        np.asarray(merged["layers"]["q_proj"]), np.asarray(base["layers"]["q_proj"])
    )

    batch = {
        "input_ids": jnp.ones((1, 16), jnp.int32),
        "labels": jnp.ones((1, 16), jnp.int32),
        "position_ids": jnp.broadcast_to(jnp.arange(16), (1, 16)),
        "segment_ids": jnp.ones((1, 16), jnp.int32),
    }

    def loss(lora_tree):
        return model.loss_fn(merge_lora_params(base, lora_tree), batch)[0]

    g = jax.grad(loss)(lora)
    ga = g["layers"]["q_proj"]["lora_a"]
    gb = g["layers"]["q_proj"]["lora_b"]
    # dB nonzero (dA is 0 at init because B=0 — standard LoRA property)
    assert float(jnp.abs(gb).sum()) > 0


def test_lora_moe_experts_adapted():
    model = build_foundation_model(config=_cfg(moe=True))
    base = model.init(jax.random.PRNGKey(0))
    lora = init_lora_params(jax.random.PRNGKey(1), base, LoraConfig(rank=2))
    exp = lora["layers"]["experts"]["gate_proj"]
    # batched adapters over [L, E, ...]
    assert exp["lora_a"].shape[:2] == base["layers"]["experts"]["gate_proj"].shape[:2]


# ---------------------------------------------------------- trainer matrix
# Reference composes LoRA with every trainer (``lora/model.py:101``,
# ``trainer/base.py:411-457``); these exercise the merged-forward wiring.

TOY_ARGS = {
    "model_type": "qwen2", "vocab_size": 256, "hidden_size": 64,
    "intermediate_size": 128, "num_hidden_layers": 2,
    "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
    "attention_bias": True,
}


def _base_args(tmp_path):
    from veomni_tpu.arguments import VeOmniArguments

    args = VeOmniArguments()
    args.model.config_overrides = dict(TOY_ARGS)
    args.model.lora = {"rank": 4, "alpha": 8}
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 100
    return args


def test_dpo_lora_e2e(tmp_path):
    from veomni_tpu.trainer.dpo_trainer import TextDPOTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "dpo.jsonl", "w") as f:
        for _ in range(32):
            f.write(json.dumps({
                "prompt": rng.integers(0, 256, int(rng.integers(4, 16))).tolist(),
                "chosen": rng.integers(0, 256, int(rng.integers(4, 24))).tolist(),
                "rejected": rng.integers(0, 256, int(rng.integers(4, 24))).tolist(),
            }) + "\n")
    args = _base_args(tmp_path)
    args.data.train_path = str(tmp_path / "dpo.jsonl")
    args.data.data_type = "dpo"
    args.data.max_seq_len = 64
    trainer = TextDPOTrainer(args)
    base_before = jax.tree.map(np.asarray, trainer.base_params)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert np.isfinite(ctl.metrics["loss"])
    # adapter-off reference policy IS the frozen base (no copy)
    assert trainer.ref_params is trainer.base_params
    # trainable surface is the adapter tree only; base stays bit-frozen
    np.testing.assert_array_equal(
        np.asarray(trainer.base_params["layers"]["q_proj"]),
        base_before["layers"]["q_proj"],
    )
    # the adapter actually moved (B leaves get nonzero grads)
    assert float(
        jnp.abs(trainer.train_state.params["layers"]["q_proj"]["lora_b"]).sum()
    ) > 0
    trainer.checkpointer.close()


def test_rl_lora_e2e(tmp_path):
    from veomni_tpu.trainer.rl_trainer import BaseRLTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "rl.jsonl", "w") as f:
        for _ in range(32):
            f.write(json.dumps({
                "prompt": rng.integers(0, 256, 8).tolist(),
                "response": rng.integers(0, 256, int(rng.integers(4, 16))).tolist(),
                "advantage": float(rng.normal()),
            }) + "\n")
    args = _base_args(tmp_path)
    args.data.train_path = str(tmp_path / "rl.jsonl")
    args.data.data_type = "rl"
    args.data.max_seq_len = 32
    trainer = BaseRLTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert np.isfinite(ctl.metrics["loss"])
    assert "ratio_mean" in ctl.metrics
    trainer.checkpointer.close()


def test_lora_channel_list_e2e(tmp_path):
    from veomni_tpu.trainer.text_trainer import TextTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for _ in range(64):
            f.write(json.dumps({
                "input_ids": rng.integers(0, 256, int(rng.integers(16, 80))).tolist(),
                "channel": ["code", "web"][int(rng.integers(0, 2))],
            }) + "\n")
    args = _base_args(tmp_path)
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.data.channel_list = ["code", "web"]
    trainer = TextTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert np.isfinite(ctl.metrics["loss"])
    trainer.checkpointer.close()


def test_lora_hf_export_roundtrip(tmp_path):
    """Trainer HF export under LoRA writes BOTH a merged full model and the
    adapter; reloading them reproduces merge(base, adapter) exactly."""
    from veomni_tpu.trainer.text_trainer import TextTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for _ in range(64):
            f.write(json.dumps({
                "input_ids": rng.integers(0, 256, int(rng.integers(16, 80))).tolist(),
            }) + "\n")
    args = _base_args(tmp_path)
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.train.save_hf_weights = True
    trainer = TextTrainer(args)
    trainer.train()
    out = str(tmp_path / "out")

    # adapter reload matches the live adapter tree
    restored = load_adapter(
        os.path.join(out, "lora_adapter"),
        jax.eval_shape(lambda: trainer.train_state.params),
    )
    np.testing.assert_allclose(
        np.asarray(restored["layers"]["q_proj"]["lora_b"]),
        np.asarray(trainer.train_state.params["layers"]["q_proj"]["lora_b"]),
    )

    # merged HF export loads back == merge(base, adapter)
    merged_live = merge_lora_params(trainer.base_params, trainer.train_state.params)
    reloaded = build_foundation_model(config_path=os.path.join(out, "hf_ckpt"))
    hf_params = reloaded.load_hf(os.path.join(out, "hf_ckpt"))
    np.testing.assert_allclose(
        np.asarray(hf_params["layers"]["q_proj"]),
        np.asarray(merged_live["layers"]["q_proj"]),
        atol=1e-6,
    )
    trainer.checkpointer.close()


def test_lora_adapter_roundtrip(tmp_path):
    model = build_foundation_model(config=_cfg())
    base = model.init(jax.random.PRNGKey(0))
    lcfg = LoraConfig(rank=4)
    lora = init_lora_params(jax.random.PRNGKey(1), base, lcfg)
    # perturb B so the roundtrip is nontrivial
    lora["layers"]["q_proj"]["lora_b"] = jnp.ones_like(lora["layers"]["q_proj"]["lora_b"])
    save_adapter(lora, lcfg, str(tmp_path / "adapter"))
    restored = load_adapter(str(tmp_path / "adapter"), jax.eval_shape(lambda: lora))
    np.testing.assert_allclose(
        np.asarray(restored["layers"]["q_proj"]["lora_b"]),
        np.asarray(lora["layers"]["q_proj"]["lora_b"]),
    )
