"""LoRA: adapter init/merge/train/save-load (reference ``tests/lora/``)."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from veomni_tpu.lora import LoraConfig, init_lora_params, merge_lora_params
from veomni_tpu.lora.lora import load_adapter, save_adapter
from veomni_tpu.models import TransformerConfig, build_foundation_model


def _cfg(moe=False):
    kw = dict(
        model_type="qwen3_moe" if moe else "qwen3",
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, qk_norm=True, dtype=jnp.float32,
    )
    if moe:
        kw.update(num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32)
    return TransformerConfig(**kw)


def test_lora_init_zero_delta_and_gradients():
    model = build_foundation_model(config=_cfg())
    base = model.init(jax.random.PRNGKey(0))
    lcfg = LoraConfig(rank=4, alpha=8)
    lora = init_lora_params(jax.random.PRNGKey(1), base, lcfg)

    # B=0 init => merged == base exactly
    merged = merge_lora_params(base, lora)
    np.testing.assert_allclose(
        np.asarray(merged["layers"]["q_proj"]), np.asarray(base["layers"]["q_proj"])
    )

    batch = {
        "input_ids": jnp.ones((1, 16), jnp.int32),
        "labels": jnp.ones((1, 16), jnp.int32),
        "position_ids": jnp.broadcast_to(jnp.arange(16), (1, 16)),
        "segment_ids": jnp.ones((1, 16), jnp.int32),
    }

    def loss(lora_tree):
        return model.loss_fn(merge_lora_params(base, lora_tree), batch)[0]

    g = jax.grad(loss)(lora)
    ga = g["layers"]["q_proj"]["lora_a"]
    gb = g["layers"]["q_proj"]["lora_b"]
    # dB nonzero (dA is 0 at init because B=0 — standard LoRA property)
    assert float(jnp.abs(gb).sum()) > 0


def test_lora_moe_experts_adapted():
    model = build_foundation_model(config=_cfg(moe=True))
    base = model.init(jax.random.PRNGKey(0))
    lora = init_lora_params(jax.random.PRNGKey(1), base, LoraConfig(rank=2))
    exp = lora["layers"]["experts"]["gate_proj"]
    # batched adapters over [L, E, ...]
    assert exp["lora_a"].shape[:2] == base["layers"]["experts"]["gate_proj"].shape[:2]


def test_lora_adapter_roundtrip(tmp_path):
    model = build_foundation_model(config=_cfg())
    base = model.init(jax.random.PRNGKey(0))
    lcfg = LoraConfig(rank=4)
    lora = init_lora_params(jax.random.PRNGKey(1), base, lcfg)
    # perturb B so the roundtrip is nontrivial
    lora["layers"]["q_proj"]["lora_b"] = jnp.ones_like(lora["layers"]["q_proj"]["lora_b"])
    save_adapter(lora, lcfg, str(tmp_path / "adapter"))
    restored = load_adapter(str(tmp_path / "adapter"), jax.eval_shape(lambda: lora))
    np.testing.assert_allclose(
        np.asarray(restored["layers"]["q_proj"]["lora_b"]),
        np.asarray(lora["layers"]["q_proj"]["lora_b"]),
    )
