"""Multimodal media utils + chat template (reference
multimodal_chat_template.py / {video,audio}_utils.py behaviors)."""

import numpy as np
import pytest


def test_smart_resize_budget():
    from veomni_tpu.data.media import smart_resize

    h, w = smart_resize(1000, 700, factor=28, max_pixels=28 * 28 * 100)
    assert h % 28 == 0 and w % 28 == 0
    assert h * w <= 28 * 28 * 100
    h2, w2 = smart_resize(10, 10, factor=28, min_pixels=56 * 56)
    assert h2 % 28 == 0 and w2 >= 28 and h2 * w2 >= 56 * 56


def test_smart_nframes_and_indices():
    from veomni_tpu.data.media import frame_indices, smart_nframes

    n = smart_nframes(300, 30.0, target_fps=2.0, frame_factor=2)
    assert n % 2 == 0 and 4 <= n <= 300  # 10s * 2fps = 20
    assert n == 20
    idx = frame_indices(300, n)
    assert idx[0] == 0 and idx[-1] == 299 and len(idx) == n


def test_load_video_from_frames():
    from veomni_tpu.data.media import load_video

    frames = (np.random.default_rng(0).random((12, 64, 48, 3)) * 255).astype(np.uint8)
    out, fps = load_video(frames, target_fps=2.0, min_frames=4, resize_factor=28)
    assert out.ndim == 4 and out.shape[3] == 3
    assert out.shape[1] % 28 == 0 and out.shape[2] % 28 == 0
    assert 0.0 <= out.min() and out.max() <= 1.0


def test_load_audio_resample_and_mel():
    from veomni_tpu.data.media import load_audio, log_mel_spectrogram

    t = np.linspace(0, 1.0, 16000, endpoint=False)
    tone = np.sin(2 * np.pi * 440 * t).astype(np.float32)
    wav = load_audio(tone, sample_rate=16000)  # passthrough (array = target)
    mel = log_mel_spectrogram(wav, n_mels=128)
    assert mel.shape[1] == 128 and mel.shape[0] == 101  # 1s @ hop 160: 16000/160+1
    assert np.isfinite(mel).all()
    # 440 Hz tone: energy concentrated in low mel bins
    assert mel[:, :32].mean() > mel[:, 64:].mean()


def test_load_audio_wav_file(tmp_path):
    from scipy.io import wavfile

    from veomni_tpu.data.media import load_audio

    sr = 22050
    t = np.linspace(0, 0.5, sr // 2, endpoint=False)
    wav = (np.sin(2 * np.pi * 220 * t) * 32767).astype(np.int16)
    p = str(tmp_path / "a.wav")
    wavfile.write(p, sr, wav)
    out = load_audio(p, sample_rate=16000)
    assert out.dtype == np.float32
    assert abs(len(out) - 8000) < 10
    assert np.abs(out).max() <= 1.001


class _StubTok:
    """Maps each character to an id (tiny deterministic tokenizer)."""

    def __call__(self, text, add_special_tokens=False):
        return {"input_ids": [ord(c) % 997 for c in text]}


def _vlm_cfg():
    from veomni_tpu.models.qwen2_5_vl import Qwen25VLConfig

    return Qwen25VLConfig(
        text=dict(model_type="qwen2", vocab_size=1024, hidden_size=32,
                  intermediate_size=64, num_hidden_layers=1,
                  num_attention_heads=2, num_key_value_heads=1, head_dim=16),
        vision=dict(depth=1, hidden_size=32, intermediate_size=64,
                    num_heads=2, patch_size=14, spatial_merge_size=2,
                    temporal_patch_size=2, window_size=28,
                    out_hidden_size=32),
    )


def test_chat_template_masks_and_media():
    from veomni_tpu.data.chat_template import IGNORE_INDEX, qwen_vl_chat_template

    cfg = _vlm_cfg()
    template = qwen_vl_chat_template(_StubTok(), cfg)
    img = np.random.default_rng(0).random((56, 56, 3)).astype(np.float32)
    enc = template.encode_messages([
        {"role": "user", "content": [
            {"type": "text", "text": "look:"},
            {"type": "image", "image": img},
        ]},
        {"role": "assistant", "content": "a cat"},
    ])
    ids = np.array(enc["input_ids"])
    labels = np.array(enc["labels"])
    assert len(ids) == len(labels)
    # image run present with the right merged count: 56/14=4 -> 4x4 patches
    # -> merge 2 -> 2*2 = 4 merged tokens
    n_img = int((ids == cfg.image_token_id).sum())
    assert n_img == 4
    assert (ids == cfg.vision_start_token_id).sum() == 1
    # all image placeholders unsupervised
    assert (labels[ids == cfg.image_token_id] == IGNORE_INDEX).all()
    # assistant text supervised, user text not
    assert (labels != IGNORE_INDEX).sum() > 0
    assert enc["vis_grids"] == [(1, 4, 4)]
    assert enc["vis_patches"][0].shape[0] == 16


def test_conversation_transform_contract():
    from veomni_tpu.data.data_transform import build_data_transform

    cfg = _vlm_cfg()
    tf = build_data_transform(
        "qwen2_5_vl_conversation", tokenizer=_StubTok(), vlm_config=cfg,
        max_seq_len=128,
    )
    img = np.random.default_rng(1).random((56, 84, 3)).astype(np.float32)
    out = tf({"messages": [
        {"role": "user", "content": [{"type": "image", "image": img},
                                     {"type": "text", "text": "hi"}]},
        {"role": "assistant", "content": "ok"},
    ]})
    assert set(out) >= {"input_ids", "labels", "vis_patches", "vis_grids"}
    assert out["vis_patches"].shape[0] == 4 * 6  # (56/14)x(84/14)
    assert out["vis_grids"] == [(1, 4, 6)]
    assert len(out["input_ids"]) == len(out["labels"]) <= 128
