"""Fleet & comm observatory (ISSUE 11 acceptance).

The fourth observability tier must be CPU-exercisable end to end: the live
collective census on a sharded train step agrees with the offline
``overlap_evidence`` analysis of the same compiled HLO (nonzero all-reduce
bytes on a 4-device mesh), the goodput window fracs still sum to 1.0 while
``comm_est_frac`` is reported, a ``delay``-fault straggler drill fires the
rank-0 warning + ``fleet.straggler`` flight event, heartbeat staleness is
detectable from outside the process, ``/debug/fleet`` is well-formed, and
``scripts/fleet.py`` merges rank artifacts onto one monotonic timeline.
Satellites ride along: the chunked-prefill recompile warning, native
Prometheus buckets for the serving latency SLOs, and deterministic tier-1
shard partitioning.
"""

import json
import logging
import os
import sys
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.observability.comm import (
    analyze_hlo_comm,
    get_comm_census,
)
from veomni_tpu.observability.cost import get_cost_census
from veomni_tpu.observability.fleet import (
    FleetMonitor,
    compute_skew,
    heartbeat_ages,
    read_heartbeats,
    write_heartbeat,
)
from veomni_tpu.observability.metrics import MetricsRegistry, get_registry
from veomni_tpu.utils.overlap_evidence import collective_bytes_census

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOY = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


# ---------------------------------------------------------- HLO byte census
def test_collective_bytes_census_parses_shapes_and_kinds():
    hlo = "\n".join([
        "ENTRY %main (p0: f32[128]) -> f32[128] {",
        "  %p0 = f32[128]{0} parameter(0)",
        "  %all-reduce.1 = f32[128]{0} all-reduce(%p0), replica_groups={}",
        "  %ag = f32[4,128]{1,0} all-gather(%all-reduce.1), dimensions={0}",
        "  %a2a = (bf16[64]{0}, bf16[64]{0}) all-to-all(%p0, %p0)",
        # async pairs count ONCE, at the -done, whose result is the pure
        # output payload (the -start tuple mixes input aliases + context
        # words whose layout differs per kind)
        "  %cp-start = u32[16]{0} collective-permute-start(%p0)",
        "  %cp-done = u32[16]{0} collective-permute-done(%cp-start)",
        "  %rs-start = (f32[128]{0}, f32[32]{0}, u32[2]{0}) "
        "reduce-scatter-start(%p0)",
        "  %rs-done = f32[32]{0} reduce-scatter-done(%rs-start)",
        "  ROOT %r = f32[128]{0} add(%all-reduce.1, %all-reduce.1)",
        "}",
    ])
    c = collective_bytes_census(hlo)
    assert c["all-reduce"] == {"count": 1, "bytes": 128 * 4}
    assert c["all-gather"] == {"count": 1, "bytes": 4 * 128 * 4}
    # sync tuple = genuine variadic payload: leaves sum
    assert c["all-to-all"] == {"count": 1, "bytes": 2 * 64 * 2}
    assert c["collective-permute"] == {"count": 1, "bytes": 16 * 4}
    # reduce-scatter's OUTPUT (f32[32], from the -done) — not the f32[128]
    # input the -start tuple happens to carry as its largest leaf
    assert c["reduce-scatter"] == {"count": 1, "bytes": 32 * 4}
    # the dependency census rides the same text
    fields = analyze_hlo_comm(hlo)
    assert fields["comm_bytes"] == sum(v["bytes"] for v in c.values())
    # 5 collectives: the -start halves count, the -done halves never do
    assert fields["collectives"] == 5
    assert fields["overlappable"] + fields["serialized"] == 5


def test_collective_bytes_census_concatenated_modules():
    """compiled.as_text() returns a LIST of module texts on some jax
    versions and the joiners concatenate them; each module has its own
    ENTRY and identically-named computations, so the census must count
    every module, not let the last shadow the rest."""
    one = "\n".join([
        "HloModule jit_f, entry_computation_layout={...}",
        "ENTRY %main (p0: f32[64]) -> f32[64] {",
        "  %p0 = f32[64]{0} parameter(0)",
        "  ROOT %ar = f32[64]{0} all-reduce(%p0)",
        "}",
    ])
    c = collective_bytes_census(one + "\n" + one)
    assert c["all-reduce"] == {"count": 2, "bytes": 2 * 64 * 4}
    # the computation iterator sees both modules' blocks too
    from veomni_tpu.utils.overlap_evidence import hlo_computations

    assert len(list(hlo_computations(one + "\n" + one))) == 2


def test_collective_bytes_census_variadic_async_and_trip_counts():
    """The TPU-critical shapes: XLA's all-reduce combiner emits variadic
    async pairs whose ``-done`` result is the ``(out...)`` tuple — counted
    once, at the output payload; a scan-lowered while body's collectives
    multiply by the loop's known_trip_count; conditional branches count
    only the heaviest (exactly one executes per visit)."""
    hlo = "\n".join([
        "%body (p: (s32[], f32[256])) -> (s32[], f32[256]) {",
        "  %p = (s32[], f32[256]{0}) parameter(0)",
        "  %gte = f32[256]{0} get-tuple-element(%p), index=1",
        # fused variadic async all-reduce: ((in,in),(out,out))
        "  %ar-start = ((f32[256]{0}, f32[256]{0}), (f32[256]{0}, "
        "f32[256]{0})) all-reduce-start(%gte, %gte)",
        "  %ar-done = (f32[256]{0}, f32[256]{0}) all-reduce-done(%ar-start)",
        "  ROOT %t = (s32[], f32[256]{0}) tuple(%gte, %gte)",
        "}",
        "%cond (p: (s32[], f32[256])) -> pred[] {",
        "  %p2 = (s32[], f32[256]{0}) parameter(0)",
        "  ROOT %lt = pred[] compare(%p2, %p2), direction=LT",
        "}",
        "%branch_a (q: f32[64]) -> f32[64] {",
        "  %q = f32[64]{0} parameter(0)",
        "  ROOT %ara = f32[64]{0} all-reduce(%q)",
        "}",
        "%branch_b (q2: f32[64]) -> f32[64] {",
        "  %q2 = f32[64]{0} parameter(0)",
        "  ROOT %arb = f32[64]{0} all-reduce(%q2)",
        "}",
        "ENTRY %main (x: f32[256]) -> f32[256] {",
        "  %x = f32[256]{0} parameter(0)",
        "  %t0 = (s32[], f32[256]{0}) tuple(%x, %x)",
        "  %w = (s32[], f32[256]{0}) while(%t0), condition=%cond, "
        'body=%body, backend_config={"known_trip_count":{"n":"7"}}',
        "  %y = f32[64]{0} slice(%x), slice={[0:64]}",
        "  %c = f32[64]{0} conditional(%y, %y, %y), "
        "branch_computations={%branch_a, %branch_b}",
        "  ROOT %r = f32[256]{0} get-tuple-element(%w), index=1",
        "}",
    ])
    c = collective_bytes_census(hlo)
    # body: one variadic start = 2 outputs x 256 x 4B = 2048B, x 7 trips;
    # conditional: ONE 64x4B branch (not two)
    assert c["all-reduce"]["count"] == 7 * 1 + 1
    assert c["all-reduce"]["bytes"] == pytest.approx(7 * 2048 + 256)


# --------------------------------------------- live census vs offline parity
def _build_sharded_step():
    """A genuinely data-parallel (ddp: grads all-reduce) train step on the
    4-device CPU mesh, mirroring the trainer's wiring."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.optim import build_lr_scheduler, build_optimizer
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.train import build_train_state, build_train_step
    from veomni_tpu.train.train_step import resolve_state_shardings

    ps = init_parallel_state(dp_replicate_size=4, dp_shard_size=1)
    cfg = TransformerConfig(dtype=jnp.float32, **TOY)
    with use_parallel_state(ps):
        model = build_foundation_model(config=cfg)
        plan = model.get_parallel_plan()
        opt = build_optimizer(
            model.abstract(), optimizer="adamw",
            lr=build_lr_scheduler(lr=1e-3, train_steps=10),
        )

        def make_state(rng):
            return build_train_state(model.family.init_params(rng, cfg), opt)

        abs_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        shardings = resolve_state_shardings(abs_state, plan, ps)
        state = jax.jit(make_state, out_shardings=shardings)(
            jax.random.PRNGKey(0)
        )
        keys = ("input_ids", "labels", "position_ids", "segment_ids")
        bsh = {k: NamedSharding(ps.mesh, P(None, ps.dp_axes, ps.sp_axes))
               for k in keys}
        step = build_train_step(
            model.loss_fn, opt, ps,
            state_shardings=shardings, batch_shardings=bsh,
        )
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, 4, 32))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(32), ids.shape).copy(), jnp.int32
            ),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        batch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    return ps, step, state, batch


def test_train_step_comm_census_matches_offline_and_window_fracs():
    """Acceptance: on a 4-device CPU mesh the live ``comm.train_step.*``
    gauges show nonzero all-reduce bytes agreeing with the offline
    ``overlap_evidence`` census on the same compiled HLO, and the goodput
    window fracs still sum to 1.0 with ``comm_est_frac`` reported."""
    from veomni_tpu.observability.cost import CostWindow
    from veomni_tpu.observability.goodput import GoodputTracker
    from veomni_tpu.parallel import use_parallel_state
    from veomni_tpu.utils.overlap_evidence import compiled_hlo_text

    ps, step, state, batch = _build_sharded_step()
    tracker = GoodputTracker()
    window = CostWindow(sites=("train_step",))
    tracker.begin_window()
    window.begin()
    with use_parallel_state(ps):
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
    bucket = "1x4x32"
    rec = get_comm_census().get("train_step", bucket)
    assert rec is not None, "train_step bucket missing from the comm census"
    assert rec.bytes_by_kind["all-reduce"] > 0, (
        "a ddp train step must all-reduce gradients"
    )
    assert rec.comm_bytes > 0 and rec.comm_time_est_s > 0
    assert rec.collectives == rec.overlappable + rec.serialized

    # offline parity: the SAME program via the PR 1 offline path (the
    # instrumented wrapper passes .lower through to the wrapped jit)
    with use_parallel_state(ps):
        offline = collective_bytes_census(compiled_hlo_text(step, state, batch))
    for kind, agg in offline.items():
        assert rec.bytes_by_kind[kind] == pytest.approx(agg["bytes"]), kind
        assert rec.counts_by_kind[kind] == agg["count"], kind

    # live gauges landed (global registry — the same one /metrics renders)
    reg = get_registry()
    prefix = f"comm.train_step.{bucket}"
    assert reg.gauge(f"{prefix}.bytes_all_reduce").value == \
        rec.bytes_by_kind["all-reduce"]
    assert reg.gauge(f"{prefix}.comm_bytes").value == rec.comm_bytes
    assert reg.gauge(f"{prefix}.serialized").value == rec.serialized

    # the cost census carries the comm_bytes too (roofline 'comm' input)
    cost_rec = get_cost_census().get("train_step", bucket)
    assert cost_rec is not None and cost_rec.comm_bytes == rec.comm_bytes
    assert cost_rec.bound() in ("compute", "bandwidth", "comm")

    # window accounting: goodput fracs sum to 1.0, comm_est_frac alongside
    gp = tracker.end_window()
    fracs = [v for k, v in gp.items() if k.endswith("_frac")]
    assert sum(fracs) == pytest.approx(1.0, abs=1e-6)
    cw = window.end()
    assert "comm_est_frac" in cw
    assert 0.0 <= cw["comm_est_frac"] <= 1.0


def test_comm_census_disabled_by_env(monkeypatch):
    """VEOMNI_COMM_CENSUS=0: the compile stays comm-census-free (no record,
    no comm_bytes folded into the cost census) and nothing raises."""
    from veomni_tpu.observability.comm import CommCensus, maybe_comm_census

    monkeypatch.setenv("VEOMNI_COMM_CENSUS", "0")
    f = jax.jit(lambda x: x + 1)
    compiled = f.lower(jnp.ones((4,))).compile()
    assert maybe_comm_census("off_site", "b", compiled, 1) == {}
    assert CommCensus().get("off_site", "b") is None


def test_roofline_comm_verdict():
    """A program whose estimated collective time dominates both device-local
    times is 'comm'-bound; without comm bytes the verdict is unchanged."""
    from veomni_tpu.observability.cost import ProgramCost
    from veomni_tpu.utils.device import (
        get_device_peak_bandwidth,
        get_device_peak_flops,
        get_device_peak_interconnect_bandwidth,
    )

    pc = ProgramCost(site="s", bucket="b", flops=1e6, bytes_accessed=1e3)
    assert pc.bound() in ("compute", "bandwidth")
    base = pc.bound()
    # comm bytes sized to dwarf compute AND memory time on any peak table
    t_dev = max(pc.flops / get_device_peak_flops(),
                pc.bytes_accessed / get_device_peak_bandwidth())
    pc.comm_bytes = 10.0 * t_dev * get_device_peak_interconnect_bandwidth()
    assert pc.bound() == "comm"
    pc.comm_bytes = 0.0
    assert pc.bound() == base


# ------------------------------------------------------------- skew + drills
def test_skew_math_units():
    table = np.array([
        [0.0, 0.010, 0.012, 7.0],
        [1.0, 0.011, 0.013, 7.0],
        [2.0, 0.050, 0.061, 7.0],   # the straggler
        [3.0, 0.009, 0.010, 7.0],
    ])
    skew = compute_skew(table)
    assert skew["slowest_rank"] == 2
    assert skew["step_time_max_s"] == pytest.approx(0.050)
    # the baseline median EXCLUDES the slowest rank (it must not inflate
    # its own detection threshold)
    assert skew["step_time_median_s"] == pytest.approx(0.010)
    assert skew["step_time_skew_s"] == pytest.approx(0.050 - 0.010)


def test_skew_two_rank_fleet_can_fire():
    """With the straggler included in the median, max > 2*median is
    unsatisfiable on a 2-rank fleet (median=(a+b)/2 ⇒ b > a+b): a 100x
    straggler on a two-host fleet would never be named. Excluding the
    suspect, the baseline is the healthy rank."""
    table = np.array([
        [0.0, 0.010, 0.010, 3.0],
        [1.0, 1.000, 1.000, 3.0],   # 100x slower
    ])
    skew = compute_skew(table)
    assert skew["slowest_rank"] == 1
    assert skew["step_time_median_s"] == pytest.approx(0.010)
    assert skew["step_time_max_s"] > 2.0 * skew["step_time_median_s"]


def test_fleet_monitor_off_below_two_ranks(tmp_path):
    reg = MetricsRegistry()
    mon = FleetMonitor(registry=reg, world_size=1, rank=0,
                       heartbeat_dir=str(tmp_path))
    assert not mon.exchange_enabled
    assert mon.observe_window(5, 0.01) is None
    # the heartbeat still flows: a single-rank wedge is diagnosable too
    assert read_heartbeats(str(tmp_path))[0]["global_step"] == 5


def test_delay_fault_straggler_drill(tmp_path):
    """Acceptance: a ``delay``-mode fault (same hit/times windowing as every
    other mode) slows this rank's loop deterministically; the skew exchange
    then produces the rank-0 STRAGGLER warning and the ``fleet.straggler``
    flight event naming the slow rank."""
    from veomni_tpu.observability.flight_recorder import (
        configure_flight_recorder,
        get_flight_recorder,
    )
    from veomni_tpu.resilience.faults import (
        configure_faults,
        disarm_faults,
        fault_point,
        fired_faults,
    )

    configure_flight_recorder(max_events=256, fresh=True)
    reg = MetricsRegistry()
    BASELINE = 0.001

    def fake_fleet(local):
        # three healthy ranks at the baseline; our (delayed) row passes
        # through — exactly what the all-gather returns on a real fleet
        rows = [np.array([r, BASELINE, BASELINE, local[3]])
                for r in range(4)]
        rows[int(local[0])] = local
        return np.stack(rows)

    mon = FleetMonitor(registry=reg, world_size=4, rank=3,
                       straggler_factor=2.0, heartbeat_dir=str(tmp_path),
                       exchange_fn=fake_fleet)
    # delay steps 2..4 by 30ms each — the deterministic straggler
    configure_faults([{"point": "step.delay", "mode": "delay", "ms": 30,
                       "hit": 2, "times": 3}])
    cap = _Capture()
    root = logging.getLogger("veomni_tpu")
    root.addHandler(cap)
    try:
        t0 = time.perf_counter()
        steps = 4
        for _ in range(steps):
            fault_point("step.delay")  # the trainer loop's drill site
        mean = (time.perf_counter() - t0) / steps
        skew = mon.observe_window(4, mean, steps=steps)
        fired = [a for a in fired_faults() if a.point == "step.delay"]
    finally:
        root.removeHandler(cap)
        disarm_faults()
    assert [a.hit for a in fired] == [2, 3, 4]  # hit/times window honored
    assert mean >= 3 * 0.030 / steps  # the delay actually slowed the loop
    assert skew is not None and skew["slowest_rank"] == 3
    assert reg.counter("fleet.stragglers").value == 1
    assert any("STRAGGLER" in r.getMessage() and "rank 3" in r.getMessage()
               for r in cap.records)
    evs = [e for e in get_flight_recorder().events()
           if e[1] == "fleet.straggler"]
    assert len(evs) == 1 and evs[0][2] == "3"  # cid names the slow rank


def test_fleet_exchange_failure_retries_then_disables(tmp_path):
    """A failed exchange never raises, and is RETRIED before the disable:
    a rank that stopped calling on the first transient would wedge its
    peers' next gather. Only a persistent failure earns the disable."""
    reg = MetricsRegistry()
    calls = [0]

    def broken(local):
        calls[0] += 1
        raise RuntimeError("collective transport down")

    mon = FleetMonitor(registry=reg, world_size=4, rank=0,
                       heartbeat_dir=str(tmp_path), exchange_fn=broken)
    budget = FleetMonitor.MAX_CONSECUTIVE_EXCHANGE_FAILURES
    for i in range(budget):
        assert mon.observe_window(i + 1, 0.01) is None
        # still retrying until the consecutive budget is spent
        assert mon.exchange_enabled == (i + 1 < budget)
    assert calls[0] == budget
    assert mon.observe_window(budget + 1, 0.01) is None
    assert calls[0] == budget  # disabled: no further transport attempts
    # heartbeats keep flowing fleet-blind
    assert read_heartbeats(str(tmp_path))[0]["global_step"] == budget + 1


def test_fleet_exchange_transient_failure_self_heals(tmp_path):
    reg = MetricsRegistry()
    fail_next = [True]

    def flaky(local):
        if fail_next[0]:
            fail_next[0] = False
            raise RuntimeError("one dropped round")
        rows = [np.array([r, 0.01, 0.01, local[3]]) for r in range(4)]
        rows[0] = local
        return np.stack(rows)

    mon = FleetMonitor(registry=reg, world_size=4, rank=0,
                       heartbeat_dir=str(tmp_path), exchange_fn=flaky)
    assert mon.observe_window(1, 0.01) is None
    assert mon.exchange_enabled
    skew = mon.observe_window(2, 0.01)
    assert skew is not None  # recovered; consecutive counter reset
    assert mon._exchange_failures == 0


# ------------------------------------------------------ heartbeat staleness
def test_heartbeat_staleness_detection(tmp_path):
    d = str(tmp_path)
    write_heartbeat(d, rank=0, global_step=40, phase="train")
    write_heartbeat(d, rank=1, global_step=12, phase="train")
    # age rank 1's beat by rewriting its wall stamp (a wedged rank stops
    # rewriting; from outside, that IS the signal)
    p = os.path.join(d, "heartbeat-1.json")
    doc = json.load(open(p))
    doc["wall_time_s"] -= 600.0
    json.dump(doc, open(p, "w"))
    rows = heartbeat_ages(d, stale_after_s=120.0)
    by_rank = {r["rank"]: r for r in rows}
    assert not by_rank[0]["stale"] and by_rank[0]["age_s"] < 60
    assert by_rank[1]["stale"] and by_rank[1]["age_s"] >= 600
    assert by_rank[1]["global_step"] == 12  # last progress step survives
    # torn/garbage heartbeat files are skipped, not fatal
    open(os.path.join(d, "heartbeat-2.json"), "w").write("{not json")
    assert {r["rank"] for r in heartbeat_ages(d)} == {0, 1}


# ------------------------------------------------------------- /debug/fleet
def test_debug_fleet_endpoint(tmp_path):
    from veomni_tpu.observability.exporter import MetricsExporter

    reg = get_registry()
    mon = FleetMonitor(registry=reg, world_size=4, rank=0,
                       straggler_factor=2.0, heartbeat_dir=str(tmp_path),
                       exchange_fn=lambda local: np.stack([
                           np.array([0.0, 0.001, 0.001, 9.0]),
                           np.array([1.0, 0.030, 0.030, 9.0]),
                           np.array([2.0, 0.001, 0.001, 9.0]),
                           np.array([3.0, 0.001, 0.001, 9.0]),
                       ]))
    mon.observe_window(9, 0.001)
    exp = MetricsExporter(port=0, registry=reg, fleet_fn=mon.debug_doc)
    port = exp.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/fleet", timeout=10
        ).read()
        doc = json.loads(body)
    finally:
        exp.stop()
    assert doc["enabled"] and doc["world_size"] == 4
    assert doc["last_window"]["slowest_rank"] == 1  # names the slow rank
    assert doc["last_window"]["straggling"] is True
    assert {row["rank"] for row in doc["last_window"]["table"]} == {0, 1, 2, 3}
    assert doc["heartbeats"] and doc["heartbeats"][0]["rank"] == 0
    assert "comm_census" in doc and "programs" in doc["comm_census"]


# --------------------------------------------------------- fleet CLI merge
def _load_script(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"_fleet_test_{name}", os.path.join(_REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_merge_monotonic(tmp_path):
    d = str(tmp_path)
    now = time.time()
    # two ranks' metrics JSONL (rank 1 stops progressing at step 10)
    with open(os.path.join(d, "metrics_rank0.jsonl"), "w") as f:
        for i, step in enumerate((10, 20)):
            f.write(json.dumps({
                "ts": now - 30 + 10 * i, "step": step, "rank": 0,
                "loss": 1.0, "fleet.slowest_rank": 1,
            }) + "\n")
    with open(os.path.join(d, "metrics_rank1.jsonl"), "w") as f:
        f.write(json.dumps({"ts": now - 30, "step": 10, "rank": 1,
                            "loss": 1.0}) + "\n")
    # heartbeats: rank 1 wedged 300s ago at step 10
    write_heartbeat(d, rank=0, global_step=20)
    write_heartbeat(d, rank=1, global_step=10)
    p = os.path.join(d, "heartbeat-1.json")
    hb = json.load(open(p))
    hb["wall_time_s"] = now - 300
    json.dump(hb, open(p, "w"))
    # one post-mortem with the PR 6 anchor pair
    perf = time.perf_counter_ns()
    json.dump({
        "rank": 1, "reason": "watchdog:train loop",
        "anchor": {"wall_time_s": now - 290, "perf_ns": perf},
        "events": [
            {"ts_ns": perf - 5_000_000_000, "kind": "step.dispatch",
             "cid": "10"},
            {"ts_ns": perf - 1_000_000_000, "kind": "watchdog.stall"},
        ],
    }, open(os.path.join(d, "postmortem-1.json"), "w"))

    doc = _load_script("fleet").merge_fleet(d, now=now)
    walls = [e["wall_s"] for e in doc["events"]]
    assert walls == sorted(walls)  # ONE monotonic cluster timeline
    kinds = {e["kind"] for e in doc["events"]}
    assert {"metrics", "heartbeat", "step.dispatch", "watchdog.stall"} <= kinds
    by_rank = {r["rank"]: r for r in doc["ranks"]}
    assert by_rank[1]["postmortem_reason"] == "watchdog:train loop"
    assert by_rank[1]["heartbeat_age_s"] == pytest.approx(300, abs=5)
    v = doc["verdict"]
    assert v["stalest_rank"] == 1 and v["lagging_rank"] == 1
    assert v["telemetry_slowest_rank"] == 1
    # and the human renderer doesn't crash
    text = _load_script("fleet").format_fleet(doc, tail=5)
    assert "VERDICT" in text and "rank 1" in text


# ----------------------------------------------------------- satellites
def test_recompile_detector_covers_paged_prefill():
    """Satellite: a chunked-prefill compile storm (new paged_prefill chunk/
    table buckets after the warmup grace) fires the loud RECOMPILE warning,
    not just decode-bucket storms."""
    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.models import decode as decode_mod
    from veomni_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        Request,
        SamplingParams,
    )

    cfg = TransformerConfig(dtype=jnp.float32, **TOY)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=16, max_model_len=256,
        prefill_chunk=16, recompile_warmup_ticks=1))
    # warmup: compiles the short prompt's paged-prefill buckets, arms at
    # tick 1
    eng.run([Request(prompt_ids=list(range(1, 9)),
                     sampling=SamplingParams(max_new_tokens=2))])
    base = get_registry().counter("recompiles").value
    prefill_traces0 = decode_mod.TRACE_COUNTS["paged_prefill"]

    cap = _Capture()
    root = logging.getLogger("veomni_tpu")
    root.addHandler(cap)
    try:
        # a much longer prompt forces NEW paged-prefill buckets mid-run
        eng.run([Request(prompt_ids=list(range(1, 100)),
                         sampling=SamplingParams(max_new_tokens=2))])
    finally:
        root.removeHandler(cap)
    assert decode_mod.TRACE_COUNTS["paged_prefill"] > prefill_traces0
    assert get_registry().counter("recompiles").value > base
    assert any("RECOMPILE" in r.getMessage() for r in cap.records)


def test_native_prometheus_buckets_for_serve_latency():
    """Satellite: serve.ttft_s/serve.tpot_s additionally render as native
    cumulative-bucket histograms so PromQL histogram_quantile (p99 SLO
    queries) works — not just the fixed p50/p95 summary quantiles."""
    from veomni_tpu.observability.exporter import render_prometheus

    reg = MetricsRegistry()
    h = reg.histogram("serve.ttft_s")
    for v in (0.002, 0.02, 0.02, 0.2, 2.0):
        h.observe(v)
    reg.histogram("span.other")  # non-SLO family: summary only
    text = render_prometheus(reg)
    assert "# TYPE veomni_serve_ttft_s summary" in text
    assert "# TYPE veomni_serve_ttft_s_hist histogram" in text
    # cumulative counts at the documented bounds
    lines = dict(
        line.rsplit(" ", 1)
        for line in text.splitlines() if "_hist_bucket" in line
    )
    assert lines['veomni_serve_ttft_s_hist_bucket{rank="0",le="0.005"}'] == "1"
    assert lines['veomni_serve_ttft_s_hist_bucket{rank="0",le="0.025"}'] == "3"
    assert lines['veomni_serve_ttft_s_hist_bucket{rank="0",le="0.25"}'] == "4"
    assert lines['veomni_serve_ttft_s_hist_bucket{rank="0",le="+Inf"}'] == "5"
    # cumulative counts are monotone non-decreasing in bound order
    counts = [int(lines[k]) for k in sorted(
        lines, key=lambda k: float(k.split('le="')[1].rstrip('"}'))
        if "+Inf" not in k else float("inf"))]
    assert counts == sorted(counts)
    assert 'veomni_serve_ttft_s_hist_count{rank="0"} 5' in text
    assert "veomni_span_other_hist" not in text


def test_cumulative_buckets_scale_past_reservoir():
    reg = MetricsRegistry()
    h = reg.histogram("serve.tpot_s", max_samples=64)
    for _ in range(1000):
        h.observe(0.01)
    for _ in range(1000):
        h.observe(1.0)
    # ad-hoc bounds (not the attached SLO set): reservoir-scaled estimate
    buckets = dict(h.cumulative_buckets((0.1, 10.0)))
    assert buckets["+Inf"] == 2000
    assert buckets[0.1] == pytest.approx(1000, rel=0.35)
    assert buckets[10.0] == 2000


def test_native_buckets_exact_and_monotone_past_reservoir():
    """The SLO families' bucket counts are EXACT counters maintained at
    observe() time — monotone non-decreasing across scrapes at any
    observation count, as PromQL rate() over _bucket series requires (a
    reservoir estimate can DECREASE between scrapes once samples churn,
    which rate() reads as a counter reset)."""
    from veomni_tpu.observability.exporter import NATIVE_HISTOGRAM_FAMILIES

    reg = MetricsRegistry()
    h = reg.histogram("serve.ttft_s", max_samples=64)  # tiny reservoir
    bounds = NATIVE_HISTOGRAM_FAMILIES["serve.ttft_s"]
    prev = None
    for round_ in range(4):
        for _ in range(500):
            h.observe(0.02)
        for _ in range(500):
            h.observe(2.0)
        cur = dict(h.cumulative_buckets(bounds))
        n = 1000 * (round_ + 1)
        assert cur["+Inf"] == n
        assert cur[0.025] == n // 2  # exact despite the 64-sample reservoir
        assert cur[2.5] == n
        if prev is not None:  # scrape-to-scrape monotone, every bound
            for le, count in cur.items():
                assert count >= prev[le], le
        prev = cur


def test_tier1_shard_partitions_deterministically():
    """Satellite: N shards partition the suite exactly (every test file in
    exactly one shard), and membership is stable under file additions."""
    shard_mod = _load_script("tier1_shard")
    files = shard_mod.discover()
    assert os.path.join(_REPO, "tests", "test_fleet_observatory.py") in files
    for n in (2, 3):
        shards = [shard_mod.shard_files(files, k, n)
                  for k in range(1, n + 1)]
        flat = [f for s in shards for f in s]
        assert sorted(flat) == sorted(files)      # exact partition
        assert len(set(flat)) == len(flat)        # disjoint
    # stability: adding a file never moves an existing one
    two = shard_mod.shard_files(files, 1, 2)
    grown = files + [os.path.join(_REPO, "tests", "test_zzz_new.py")]
    assert [f for f in shard_mod.shard_files(grown, 1, 2)
            if "zzz_new" not in f] == two
    with pytest.raises(ValueError):
        shard_mod.parse_shard("0/2")
    with pytest.raises(ValueError):
        shard_mod.parse_shard("3/2")
    assert shard_mod.parse_shard("2/3") == (2, 3)


def test_delay_mode_plan_grammar():
    """The delay mode parses from the JSON plan grammar with its ms knob
    and rejects nothing a drill needs."""
    from veomni_tpu.resilience.faults import (
        configure_faults,
        disarm_faults,
        fault_point,
        fired_faults,
    )

    configure_faults(json.dumps(
        [{"point": "step.delay", "mode": "delay", "ms": 5, "hit": 1}]
    ))
    try:
        t0 = time.perf_counter()
        action = fault_point("step.delay")
        dt = time.perf_counter() - t0
        assert action is not None and action.mode == "delay"
        assert dt >= 0.004
        assert fault_point("step.delay") is None  # times=1 window closed
        assert len(fired_faults()) == 1
    finally:
        disarm_faults()
