"""Qwen2-VL parity vs HF transformers (tiny config, random weights).

Same oracle strategy as test_qwen2_5_vl.py: build a tiny
``Qwen2VLForConditionalGeneration``, save HF safetensors, import into our
model, assert identical vision features / mrope walk / loss on text + two
differently-sized images (full per-frame attention, LayerNorm blocks,
quick-GELU MLP, merger)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

IMG_ID, VID_ID, VSTART_ID = 9, 10, 8


def _tiny_hf_model(tmp_path):
    import torch
    from transformers import Qwen2VLConfig, Qwen2VLForConditionalGeneration

    cfg = Qwen2VLConfig(
        text_config=dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            max_position_embeddings=512,
            rope_theta=10000.0,
            rope_scaling={"type": "mrope", "mrope_section": [2, 3, 3]},
            tie_word_embeddings=False,
        ),
        vision_config=dict(
            depth=3,
            embed_dim=32,
            hidden_size=64,   # LM width (merger out)
            mlp_ratio=2,
            num_heads=2,
            in_channels=3,
            patch_size=2,
            temporal_patch_size=2,
            spatial_merge_size=2,
        ),
        image_token_id=IMG_ID,
        video_token_id=VID_ID,
        vision_start_token_id=VSTART_ID,
    )
    torch.manual_seed(0)
    model = Qwen2VLForConditionalGeneration(cfg).eval()
    out = tmp_path / "hf_ckpt"
    model.save_pretrained(out, safe_serialization=True)
    return model, cfg, str(out)


def _vision_inputs(rng, grids, patch_dim):
    n = sum(t * h * w for t, h, w in grids)
    pixel_values = rng.standard_normal((n, patch_dim)).astype(np.float32)
    return pixel_values, np.asarray(grids, np.int64)


@pytest.fixture(scope="module")
def hf_and_ours(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("q2vl")
    hf_model, hf_cfg, ckpt = _tiny_hf_model(tmp_path)

    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(ckpt, dtype="float32")
    assert model.config.model_type == "qwen2_vl"
    params = model.load_hf(ckpt)
    return hf_model, hf_cfg, model, params


def test_vision_tower_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    # multi-frame grid exercises the per-frame attention segments
    grids = [(1, 4, 6), (2, 4, 4)]
    rng = np.random.default_rng(0)
    pixel_values, grid_thw = _vision_inputs(rng, grids, cfg.vision.patch_dim)

    with torch.no_grad():
        ref = hf_model.model.visual(
            torch.from_numpy(pixel_values), torch.from_numpy(grid_thw)
        ).numpy()

    from veomni_tpu.models.qwen2_vl import vision_forward, vision_metadata

    meta = vision_metadata(grids, cfg.vision, n_pad_patches=pixel_values.shape[0] + 8)
    px = np.zeros((pixel_values.shape[0] + 8, pixel_values.shape[1]), np.float32)
    px[: pixel_values.shape[0]] = pixel_values
    got = vision_forward(
        params["vision_tower"], cfg.vision,
        jnp.asarray(px), jnp.asarray(meta["pos_hw"]), jnp.asarray(meta["seg"]),
        dtype=jnp.float32,
    )
    got = np.asarray(got)[np.asarray(meta["merged_mask"])]
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_mrope_position_ids_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    grids = [(1, 4, 6), (2, 4, 4)]
    n_merged = [t * (h // 2) * (w // 2) for t, h, w in grids]
    rng = np.random.default_rng(1)

    ids = []
    for nm in n_merged:
        ids += [VSTART_ID] + [IMG_ID] * nm
    ids += list(rng.integers(11, 256, 7))
    input_ids = np.asarray([ids], np.int64)

    ref_pos, _ = hf_model.model.get_rope_index(
        torch.from_numpy(input_ids), torch.as_tensor(grids)
    )
    from veomni_tpu.models.qwen2_vl import mrope_position_ids

    got = mrope_position_ids(input_ids, grids, cfg)  # [B,3,S]
    np.testing.assert_array_equal(got[0], ref_pos[:, 0].numpy())


def test_full_loss_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    grids = [(1, 4, 6), (2, 4, 4)]
    n_merged = [t * (h // 2) * (w // 2) for t, h, w in grids]
    rng = np.random.default_rng(2)
    pixel_values, grid_thw = _vision_inputs(rng, grids, cfg.vision.patch_dim)

    ids = [VSTART_ID] + [IMG_ID] * n_merged[0] + list(rng.integers(11, 256, 5))
    ids += [VSTART_ID] + [IMG_ID] * n_merged[1] + list(rng.integers(11, 256, 6))
    input_ids = np.asarray([ids], np.int64)
    labels = input_ids.copy()
    labels[:, : n_merged[0] + 1] = -100  # mask the first image span

    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.from_numpy(input_ids),
            labels=torch.from_numpy(labels),
            pixel_values=torch.from_numpy(pixel_values),
            image_grid_thw=torch.from_numpy(grid_thw),
        )
    ref_loss = float(ref.loss)

    from veomni_tpu.models.qwen2_vl import mrope_position_ids, vision_metadata

    meta = vision_metadata(grids, cfg.vision, n_pad_patches=pixel_values.shape[0])
    pos = mrope_position_ids(input_ids, grids, cfg)
    shifted = np.full_like(labels, -100)
    shifted[:, :-1] = labels[:, 1:]
    batch = {
        "input_ids": jnp.asarray(input_ids, jnp.int32),
        "labels": jnp.asarray(shifted, jnp.int32),
        "position_ids": jnp.asarray(pos, jnp.int32),
        "segment_ids": jnp.ones_like(jnp.asarray(input_ids, jnp.int32)),
        "pixel_values": jnp.asarray(pixel_values),
        "vis_pos_hw": jnp.asarray(meta["pos_hw"]),
        "vis_seg": jnp.asarray(meta["seg"]),
        "vis_merged_mask": jnp.asarray(meta["merged_mask"]),
    }
    loss_sum, metrics = model.loss_fn(params, batch)
    got_loss = float(loss_sum) / float(metrics["ntokens"])
    np.testing.assert_allclose(got_loss, ref_loss, rtol=2e-4)


def test_hf_export_roundtrip(hf_and_ours, tmp_path):
    hf_model, hf_cfg, model, params = hf_and_ours
    out = tmp_path / "exported"
    model.family.save_hf_checkpoint(params, model.config, str(out))

    from veomni_tpu.models import build_foundation_model

    m2 = build_foundation_model(str(out), dtype="float32")
    p2 = m2.load_hf(str(out))
    flat_a = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(params)}
    flat_b = {jax.tree_util.keystr(p): v
              for p, v in jax.tree_util.tree_leaves_with_path(p2)}
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(
            np.asarray(flat_a[k]), np.asarray(flat_b[k]), err_msg=k
        )


def test_qwen2_vl_trainer_e2e(tmp_path):
    """Trainer drive: images -> patches/metadata -> mrope -> train steps."""
    import json

    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer import VLMTrainer

    rng = np.random.default_rng(0)
    rows = []
    for i in range(24):
        rows.append({
            "input_ids": rng.integers(11, 256, int(rng.integers(8, 24))).tolist(),
            "images": [rng.random((8 + 4 * (i % 2), 8, 3)).tolist()],
        })
    with open(tmp_path / "data.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen2_vl",
        "vocab_size": 256,
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "embed_dim": 32, "hidden_size": 64, "mlp_ratio": 2,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
        },
        "image_token_id": 9, "video_token_id": 10,
        "vision_start_token_id": 8,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.data.max_patches = 256
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    destroy_parallel_state()
    try:
        trainer = VLMTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 3
        assert np.isfinite(ctl.metrics["loss"])
        trainer.checkpointer.close()
        import os

        hf_dir = os.path.join(args.train.output_dir, "hf_ckpt")
        assert os.path.exists(os.path.join(hf_dir, "model.safetensors"))
        from veomni_tpu.models import build_foundation_model

        m2 = build_foundation_model(hf_dir, dtype="float32")
        m2.load_hf(hf_dir)
    finally:
        destroy_parallel_state()
