"""Cosmos FSQ tokenizer: wavelet exactness, FSQ invariants, encode/decode,
omni-composite integration (reference decoder/cosmos)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veomni_tpu.models.cosmos import (
    CosmosConfig,
    _dwt,
    _idwt,
    decode,
    decode_code,
    encode,
    fsq_indices_to_codes,
    fsq_quantize,
    init_params,
)

TINY = dict(channels=8, channels_mult=(1, 2), num_res_blocks=1,
            attn_resolutions=(4,), in_channels=3, out_channels=3,
            resolution=16, patch_size=2, spatial_compression=4,
            z_channels=8, embedding_dim=4, levels=(5, 5, 4, 4),
            num_groups=4)


def test_haar_roundtrip_exact():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), jnp.float32)
    y = _idwt(_dwt(x))
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-5)


def test_fsq_invariants():
    rng = np.random.default_rng(1)
    levels = (5, 5, 4, 4)
    z = jnp.asarray(rng.standard_normal((7, len(levels))) * 3, jnp.float32)
    zhat, idx = fsq_quantize(z, levels)
    assert np.all(np.asarray(idx) >= 0)
    assert np.all(np.asarray(idx) < int(np.prod(levels)))
    # the implicit codebook reproduces the quantized vector exactly
    codes = fsq_indices_to_codes(idx, levels)
    np.testing.assert_allclose(np.asarray(codes), np.asarray(zhat), atol=1e-6)
    # straight-through: gradient of sum(zhat) wrt z is the bound's gradient
    g = jax.grad(lambda q: fsq_quantize(q, levels)[0].sum())(z)
    assert np.all(np.isfinite(np.asarray(g)))
    assert float(jnp.abs(g).sum()) > 0.0


def test_encode_decode_shapes():
    cfg = CosmosConfig(**TINY)
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(2)
    px = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), jnp.float32)
    zhat, idx, qloss = encode(params, cfg, px)
    assert idx.shape == (2, 4, 4)          # 16 / spatial_compression 4
    assert zhat.shape == (2, 4, 4, len(cfg.levels))
    assert np.allclose(np.asarray(qloss), 0.0)  # FSQ: no commit loss
    rec = decode(params, cfg, zhat)
    assert rec.shape == (2, 16, 16, 3)
    rec2 = decode_code(params, cfg, idx.reshape(2, -1))
    np.testing.assert_allclose(np.asarray(rec), np.asarray(rec2), atol=1e-5)


def test_omni_composite_with_cosmos():
    from veomni_tpu.models.omni import OmniConfig, init_omni_params, omni_loss_fn

    TEXT = dict(model_type="qwen2", vocab_size=600, hidden_size=64,
                intermediate_size=128, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2, head_dim=16,
                attention_bias=True)
    cfg = OmniConfig(
        text=TEXT,
        image_gen={"decoder_type": "cosmos", "movq": dict(TINY)},
        image_gen_token_id=512, max_gen_images=1,
    )
    assert cfg.image_gen.tokens_per_image == 16
    assert cfg.image_gen.image_size == 16
    params = init_omni_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(3)
    s = 48
    t_gen = 16
    ids = rng.integers(1, 500, (1, s)).astype(np.int32)
    ids[0, 8:8 + t_gen] = 512
    labels = np.roll(ids, -1, 1).astype(np.int32)
    labels[:, -1] = -100
    batch = {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(labels),
        "position_ids": jnp.broadcast_to(jnp.arange(s), (1, s)).astype(jnp.int32),
        "segment_ids": jnp.ones((1, s), jnp.int32),
        "gen_pixels": jnp.asarray(rng.random((1, 1, 16, 16, 3), np.float32) * 2 - 1),
        "gen_image_mask": jnp.ones((1, 1), bool),
    }
    total, metrics = omni_loss_fn(params, cfg, batch)
    assert np.isfinite(float(total))
    assert int(metrics["gen_ntokens"]) == t_gen
    grads = jax.grad(lambda p: omni_loss_fn(p, cfg, batch)[0])(params)
    assert all(float(jnp.abs(g).max()) == 0.0
               for g in jax.tree.leaves(grads["image_gen"]["movq"]))
    assert float(jnp.abs(grads["image_gen"]["gen_head"]["fc2"]).sum()) > 0.0
