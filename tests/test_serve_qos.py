"""QoS serving layer: SLO classes, tenant fairness, load-shedding, deadlines.

The heavy-traffic hardening guarantees (docs/serving.md "QoS, fairness &
overload"):

* single-class / no-deadline config is **behavior-identical to the seed
  FIFO scheduler** (the whole of tests/test_serving.py runs on the default
  config and pins that);
* interactive requests cannot starve behind a batch backlog, and batch is
  preempted before interactive;
* one tenant cannot starve another inside a class (bounded share);
* past the queue bound, ``submit()`` load-sheds with a terminal
  ``rejected`` status instead of growing the queue — and a shed storm
  (including mid-chunked-prefill cancellations) leaks zero KV blocks;
* deadline-expired waiting/prefilling requests are cancelled; survivors
  stay token-exact vs an unloaded run;
* under open-loop overload at ~2x capacity the bounded-queue QoS engine
  rejects (never grows past the bound) and interactive p99 TTFT beats the
  FIFO baseline on the same workload.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.models.decode import greedy_generate
from veomni_tpu.observability.metrics import get_registry
from veomni_tpu.resilience.faults import (
    InjectedFault,
    configure_faults,
    disarm_faults,
    fired_faults,
)
from veomni_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    KVBlockManager,
    Request,
    SamplingParams,
    Scheduler,
    SequenceState,
    parse_classes,
)

QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)


@pytest.fixture(scope="module")
def qwen3():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


@pytest.fixture(autouse=True)
def _disarm():
    yield
    disarm_faults()


def _prompts(lengths, seed=0, vocab=128):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, vocab, n)] for n in lengths]


def _seq(rid, n_prompt, priority="interactive", tenant="", deadline_s=None):
    return SequenceState(request=Request(
        prompt_ids=list(range(1, n_prompt + 1)), request_id=rid,
        priority=priority, tenant=tenant, deadline_s=deadline_s,
    ))


def _pool_identity(eng):
    """The no-leak identity: every non-cached block on the free list, every
    cached block refcount-0, nothing still attributed to a sequence."""
    bm = eng.blocks
    assert bm.num_used == 0
    assert bm.num_free_uncached + bm.num_cached == bm.num_blocks - 1
    if eng.prefix_cache is not None:
        assert all(bm.refcount(b) == 0 for b in eng.prefix_cache._by_block)


# ------------------------------------------------------------- class parsing
def test_parse_classes():
    assert parse_classes("interactive:4,batch:1") == [
        ("interactive", 4), ("batch", 1)
    ]
    assert parse_classes(None) == [("interactive", 4), ("batch", 1)]
    assert parse_classes("rt:8, bulk:2 ,best_effort") == [
        ("rt", 8), ("bulk", 2), ("best_effort", 1)
    ]
    assert parse_classes([("a", 2)]) == [("a", 2)]
    with pytest.raises(ValueError, match="weight"):
        parse_classes("a:x")
    with pytest.raises(ValueError, match="weight"):
        parse_classes("a:0")
    with pytest.raises(ValueError, match="duplicate"):
        parse_classes("a:1,a:2")
    with pytest.raises(ValueError, match="no classes"):
        parse_classes(",")
    with pytest.raises(ValueError):  # malformed spec fails at construction
        EngineConfig(classes="a:-1")


def test_scheduler_unknown_priority():
    bm = KVBlockManager(num_blocks=8, block_size=4)
    multi = Scheduler(2, bm, classes=parse_classes(None))
    with pytest.raises(ValueError, match="unknown priority class 'vip'"):
        multi.add(_seq("a", 4, priority="vip"))
    # a single-class scheduler is the seed FIFO and accepts ANY label
    single = Scheduler(2, KVBlockManager(num_blocks=8, block_size=4),
                       classes=[("default", 1)])
    assert single.add(_seq("a", 4, priority="vip"))
    assert single.add(_seq("b", 4, priority="batch"))
    assert [s.seq_id for s in single.admit()] == ["a", "b"]  # plain FIFO


# -------------------------------------------------------- weighted admission
def test_scheduler_interactive_jumps_batch_backlog():
    """A batch backlog arrives first; interactive requests still get the
    weighted share of admissions (4:1 default) instead of queueing behind
    the entire backlog — and batch is NOT starved."""
    bm = KVBlockManager(num_blocks=64, block_size=4)
    sched = Scheduler(4, bm, classes=parse_classes(None))
    for i in range(4):
        sched.add(_seq(f"b{i}", 4, priority="batch"))
    for i in range(2):
        sched.add(_seq(f"i{i}", 4, priority="interactive"))
    # stride pick: interactive first (priority tie-break), then batch's
    # 1-in-5 turn, then interactive again
    assert [s.seq_id for s in sched.admit()] == ["i0", "b0", "i1", "b1"]


def test_scheduler_admission_order_weighted_share():
    """Drain a long mixed backlog through one slot: interactive ends up
    with ~4/5 of admissions while batch keeps progressing."""
    bm = KVBlockManager(num_blocks=64, block_size=4)
    sched = Scheduler(1, bm, classes=parse_classes(None))
    for i in range(10):
        sched.add(_seq(f"b{i}", 4, priority="batch"))
    for i in range(10):
        sched.add(_seq(f"i{i}", 4, priority="interactive"))
    order = []
    while sched.waiting and len(order) < 10:
        (adm,) = sched.admit()
        order.append(adm.seq_id)
        sched.finish(adm)
    n_inter = sum(1 for x in order if x.startswith("i"))
    assert n_inter == 8, order  # 4:1 stride over the first 10 picks
    assert any(x.startswith("b") for x in order)  # batch not starved


def test_scheduler_class_aware_preemption_order():
    """Pool pressure preempts BATCH before interactive even when the
    interactive sequence was admitted later (seed LIFO would evict it)."""
    bm = KVBlockManager(num_blocks=5, block_size=4)  # 4 usable
    sched = Scheduler(2, bm, classes=parse_classes(None))
    b = _seq("b", 4, priority="batch")
    sched.add(b)
    assert sched.admit() == [b]
    i = _seq("i", 4, priority="interactive")
    sched.add(i)
    assert sched.admit() == [i]
    assert b.admit_order < i.admit_order  # i is the newest admission
    b.prefilling = i.prefilling = False  # engine contract
    b.pos, i.pos = 4, 4
    sched.ensure_decode_capacity()  # both grow; pool dry
    i.pos = 8  # interactive needs another block
    preempted = sched.ensure_decode_capacity()
    # victim = newest admission of the LOWEST-priority class: batch
    assert preempted == [b] and b.slot == -1 and i.slot >= 0
    # within one class the choice stays LIFO (the seed test still passes
    # via test_serving.py; pin the class tie-break here too)
    assert sched._preempt_victim() is i  # only interactive left running


def test_scheduler_tenant_fairness_bounded_share():
    """A greedy tenant floods the queue; a trickle tenant arriving later
    still gets every other admission inside the class (unit-quantum DRR) —
    bounded share, no starvation."""
    bm = KVBlockManager(num_blocks=64, block_size=4)
    sched = Scheduler(1, bm, classes=parse_classes(None))
    for i in range(8):
        sched.add(_seq(f"greedy{i}", 4, tenant="greedy"))
    for i in range(3):
        sched.add(_seq(f"small{i}", 4, tenant="small"))
    order = []
    for _ in range(6):
        (adm,) = sched.admit()
        order.append(adm.seq_id)
        sched.finish(adm)
    # alternating shares while both are backlogged; FIFO within each tenant
    assert order == ["greedy0", "small0", "greedy1", "small1",
                     "greedy2", "small2"], order
    # a tenant joining late starts at the current credit level — it cannot
    # burst to "catch up" on rounds it never waited through
    sched.add(_seq("late0", 4, tenant="late"))
    sched.add(_seq("late1", 4, tenant="late"))
    (adm,) = sched.admit()
    assert adm.tenant == "late"  # fair share from now on...
    sched.finish(adm)
    (adm2,) = sched.admit()
    assert adm2.tenant == "greedy"  # ...but not two in a row


def test_scheduler_queue_bound_and_requeue_exempt():
    bm = KVBlockManager(num_blocks=8, block_size=4)
    sched = Scheduler(1, bm, classes=parse_classes(None), queue_bound=2)
    a = _seq("a", 4)
    sched.add(a)
    assert sched.admit() == [a]
    assert sched.add(_seq("w1", 4))
    assert sched.add(_seq("w2", 4))
    assert not sched.add(_seq("w3", 4))  # bound reached: shed
    assert len(sched.waiting) == 2
    # preemption requeue is EXEMPT: admitted work is never shed by its own
    # recompute — the queue may transiently exceed the bound
    a.prefilling = False
    a.pos = 40  # needs more blocks than the whole pool holds
    assert sched.ensure_decode_capacity() == [a]
    assert len(sched.waiting) == 3 and sched.waiting[0] is a


def test_scheduler_tenant_inflight_cap():
    bm = KVBlockManager(num_blocks=16, block_size=4)
    sched = Scheduler(2, bm, classes=parse_classes(None),
                      tenant_max_inflight=2)
    assert sched.add(_seq("a1", 4, tenant="a"))
    assert sched.add(_seq("a2", 4, tenant="a"))
    assert not sched.add(_seq("a3", 4, tenant="a"))  # cap: waiting counts
    assert sched.add(_seq("b1", 4, tenant="b"))  # other tenants unaffected
    sched.admit()  # a1, a2 admitted (b1 waits: 2 slots)
    assert not sched.add(_seq("a4", 4, tenant="a"))  # running counts too
    for _, s in sched.running():
        sched.finish(s)
    assert sched.add(_seq("a5", 4, tenant="a"))  # capacity released


# --------------------------------------------------------- engine: shedding
def test_engine_rejects_past_queue_bound(qwen3):
    params, cfg = qwen3
    reg = get_registry()
    rej0 = reg.counter("serve.rejected").value
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64, queue_bound=2,
    ))
    prompts = _prompts((5, 7, 9, 6, 8), seed=30)
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=4)))
           for p in prompts]
    # the queue never grew past the bound; the overflow is terminal NOW
    assert eng.scheduler.queue_depth == 2
    shed = [rid for rid in ids if eng._outputs[rid].finished]
    assert len(shed) == 3
    for rid in shed:
        o = eng._outputs[rid]
        assert o.finish_reason == "rejected" and o.token_ids == []
    outs = eng.run()
    # run() hands back terminal outputs (rejected included) — a driver
    # never hangs waiting for tokens a shed request will not produce
    assert set(outs) == set(ids)
    m = eng.metrics()
    assert m["rejected"] == 3
    assert m["shed_tokens"] == sum(
        len(eng._outputs.get(rid, outs[rid]).prompt_ids) + 4 for rid in shed
    )
    assert reg.counter("serve.rejected").value - rej0 == 3
    # survivors are token-exact: shedding changed WHO ran, never WHAT the
    # survivors computed
    for rid, p in zip(ids[:2], prompts[:2]):
        want = greedy_generate(params, cfg, p, max_new_tokens=4)[len(p):]
        assert outs[rid].token_ids == want
    # the tracer carries the rejections as terminal timelines
    snap = eng.tracer.snapshot()
    rej_rows = [r for r in snap["finished"]
                if r.get("finish_reason") == "rejected"]
    assert len(rej_rows) == 3
    _pool_identity(eng)


def test_engine_tenant_inflight_cap(qwen3):
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64, tenant_max_inflight=1,
    ))
    p1, p2, p3 = _prompts((5, 7, 6), seed=31)
    r1 = eng.submit(Request(prompt_ids=p1, tenant="t0",
                            sampling=SamplingParams(max_new_tokens=4)))
    r2 = eng.submit(Request(prompt_ids=p2, tenant="t0",
                            sampling=SamplingParams(max_new_tokens=4)))
    r3 = eng.submit(Request(prompt_ids=p3, tenant="t1",
                            sampling=SamplingParams(max_new_tokens=4)))
    assert eng._outputs[r2].finish_reason == "rejected"  # t0 at cap
    outs = eng.run()
    assert outs[r1].finish_reason == "length"
    assert outs[r3].finish_reason == "length"  # other tenant unaffected
    _pool_identity(eng)


# --------------------------------------------------------- engine: deadlines
def test_engine_deadline_expiry_cancellation_and_parity(qwen3):
    """Expired-while-waiting requests are cancelled (blocks released,
    terminal 'deadline' status) and the survivors stay token-exact vs an
    unloaded run."""
    params, cfg = qwen3
    reg = get_registry()
    miss0 = reg.counter("serve.deadline_misses").value
    prompts = _prompts((9, 11, 7, 8), seed=32)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=1, block_size=8, max_model_len=64,
    ))
    # slot width 1: the later arrivals genuinely WAIT; the deadline=0 ones
    # expire in the queue before a slot ever frees for them
    ids, deadlines = [], [None, 0.0, None, 0.0]
    for p, dl in zip(prompts, deadlines):
        ids.append(eng.submit(Request(
            prompt_ids=p, deadline_s=dl,
            sampling=SamplingParams(max_new_tokens=6),
        )))
    outs = eng.run()
    for rid, p, dl in zip(ids, prompts, deadlines):
        if dl is None:
            want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
            assert outs[rid].token_ids == want  # survivor parity
            assert not outs[rid].deadline_missed
        else:
            assert outs[rid].finish_reason == "deadline"
            assert outs[rid].deadline_missed and outs[rid].token_ids == []
    assert reg.counter("serve.deadline_misses").value - miss0 == 2
    assert eng.metrics()["deadline_misses"] == 2
    _pool_identity(eng)


def test_engine_late_finish_counts_deadline_miss_not_goodput(qwen3):
    """A request that is already DECODING when its deadline passes runs to
    completion (the tokens exist; cancelling wastes them) but is marked
    deadline_missed and contributes nothing to goodput."""
    params, cfg = qwen3
    p1, p2 = _prompts((9, 7), seed=33)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    r1 = eng.submit(Request(prompt_ids=p1, deadline_s=30.0,
                            sampling=SamplingParams(max_new_tokens=5)))
    r2 = eng.submit(Request(prompt_ids=p2,
                            sampling=SamplingParams(max_new_tokens=5)))
    eng.metrics()  # reset the window
    eng.step()  # r1 admitted + first token: now decoding
    # make the deadline ALREADY passed without wall-clock sleeps: shift the
    # submit time back (deterministic — no timing races in tier-1)
    seq = eng._find_seq(r1)
    assert seq is not None and not seq.prefilling
    seq.submit_time -= 60.0
    outs = eng.run()
    assert outs[r1].finish_reason == "length"  # ran to completion
    assert outs[r1].deadline_missed
    want = greedy_generate(params, cfg, p1, max_new_tokens=5)[len(p1):]
    assert outs[r1].token_ids == want  # tokens kept, and still exact
    m = eng.metrics()
    assert m["deadline_misses"] == 1
    # goodput counted ONLY the in-deadline request's tokens
    assert m["goodput_tokens"] == 5
    m2 = eng.metrics()  # window reset: rate returns to 0
    assert m2["goodput_tokens_per_sec"] == 0.0
    assert m2["goodput_tokens"] == 5  # lifetime total survives


def test_preempted_streaming_request_not_cancelled_by_deadline(qwen3):
    """Review-pinned: deadline expiry only cancels requests that produced
    NOTHING. A request that already streamed tokens and then got preempted
    (requeued, waiting past its deadline) is re-admitted and runs to
    completion — cancelling it mid-stream would waste delivered tokens and
    make the client-visible outcome depend on pool pressure. It finishes
    late: deadline_missed, excluded from goodput, tokens exact."""
    params, cfg = qwen3
    prompts = _prompts((9, 11, 7), seed=44)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, num_blocks=8,
    ))
    ids = [eng.submit(Request(prompt_ids=p, deadline_s=30.0,
                              sampling=SamplingParams(max_new_tokens=10)))
           for p in prompts]
    victim = None
    while eng.has_work:
        eng.step()
        if victim is None:
            streaming_waiters = [s for s in eng.scheduler.waiting
                                 if s.generated]
            if streaming_waiters:
                victim = streaming_waiters[0]
                victim.submit_time -= 60.0  # deadline now LONG past
    assert victim is not None  # preemption really hit a streaming request
    outs = eng.run()
    out = outs[victim.seq_id]
    assert out.finish_reason == "length"  # finished, not "deadline"
    assert out.deadline_missed
    idx = ids.index(victim.seq_id)
    want = greedy_generate(params, cfg, prompts[idx],
                           max_new_tokens=10)[len(prompts[idx]):]
    assert out.token_ids == want
    _pool_identity(eng)


def test_engine_cancel_mid_prefill_releases_blocks(qwen3):
    """The satellite bugfix pin: cancelling a request mid-chunked-prefill
    releases its partially-claimed blocks (and any cow pin) — the pool
    identity holds immediately, not just after a drain."""
    params, cfg = qwen3
    long_prompt = _prompts((60,), seed=34)[0]
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=128,
        prefix_cache=True, prefill_chunk=8,
    ))
    rid = eng.submit(Request(prompt_ids=long_prompt,
                             sampling=SamplingParams(max_new_tokens=4)))
    eng.step()  # admitted + first chunk
    seq = eng._find_seq(rid)
    assert seq is not None and seq.prefilling  # genuinely mid-prefill
    assert eng.blocks.num_used > 0
    assert eng.cancel(rid)
    out = eng._outputs[rid]
    assert out.finished and out.finish_reason == "cancelled"
    _pool_identity(eng)
    assert not eng.cancel(rid)  # idempotent: already terminal
    assert not eng.has_work


def test_engine_shed_storm_no_block_leaks(qwen3):
    """Shed-under-pressure storm over a TIGHT pool with chunked prefill:
    rejections, deadline expirations (waiting AND mid-prefill), explicit
    cancels, preemptions and completions all interleave — afterwards the
    block accounting identity holds exactly (free_uncached + cached ==
    pool) and survivors are token-exact."""
    params, cfg = qwen3
    rng = np.random.default_rng(35)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=48, num_blocks=10,
        prefix_cache=True, prefill_chunk=8, queue_bound=4,
    ))
    prompts = _prompts((20, 30, 9, 25, 11, 28, 7, 18, 26, 13), seed=35)
    ids, survivors = [], {}
    for i, p in enumerate(prompts):
        dl = 0.0 if i % 3 == 1 else None  # a third expire in the queue
        ids.append(eng.submit(Request(
            prompt_ids=p, deadline_s=dl,
            sampling=SamplingParams(max_new_tokens=6),
        )))
        # churn: a couple of ticks between arrivals, with a mid-prefill
        # cancel thrown in whenever something is prefilling
        for _ in range(int(rng.integers(0, 3))):
            if eng.has_work:
                eng.step()
        if i == 4:
            prefilling = [s for _, s in eng.scheduler.running()
                          if s.prefilling]
            if prefilling:
                assert eng.cancel(prefilling[0].seq_id)
    outs = eng.run()
    statuses = {rid: eng._outputs.get(rid, outs.get(rid)).finish_reason
                for rid in ids}
    assert any(v == "deadline" for v in statuses.values())
    for rid, p in zip(ids, prompts):
        o = outs.get(rid) or eng._outputs.get(rid)
        if o.finish_reason in ("eos", "length"):
            survivors[rid] = (p, o)
    assert survivors  # the storm didn't shed literally everything
    for rid, (p, o) in survivors.items():
        want = greedy_generate(params, cfg, p, max_new_tokens=6)[len(p):]
        assert o.token_ids == want, (rid, o.token_ids, want)
    _pool_identity(eng)


# ------------------------------------------------------- engine: fault drills
def test_serve_admit_fault_point(qwen3):
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    configure_faults([{"point": "serve.admit", "mode": "exception",
                       "hit": 2}])
    p1, p2 = _prompts((5, 7), seed=36)
    eng.submit(Request(prompt_ids=p1,
                       sampling=SamplingParams(max_new_tokens=3)))
    with pytest.raises(InjectedFault):
        eng.submit(Request(prompt_ids=p2,
                           sampling=SamplingParams(max_new_tokens=3)))
    disarm_faults()
    outs = eng.run()  # the accepted request is unaffected by the drill
    assert len(outs) == 1
    _pool_identity(eng)


def test_serve_prefill_delay_fault(qwen3):
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    configure_faults([{"point": "serve.prefill", "mode": "delay", "ms": 1,
                       "times": 2}])
    eng.run([Request(prompt_ids=_prompts((9,), seed=37)[0],
                     sampling=SamplingParams(max_new_tokens=3))])
    fired = [a for a in fired_faults() if a.point == "serve.prefill"]
    assert fired and all(a.mode == "delay" for a in fired)


def test_serve_decode_tick_delay_drill_postmortem_names_tick(qwen3, tmp_path):
    """The serving stall drill: a delay fault on serve.decode_tick outlives
    the watchdog deadline; the dog's flight-recorder post-mortem carries
    the injected-fault event naming the stalled tick (and thread stacks) —
    exactly the artifact an operator gets from a real decode stall."""
    from veomni_tpu.observability.flight_recorder import (
        configure_flight_recorder,
    )
    from veomni_tpu.utils.helper import Watchdog

    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    # warm the jit caches first: a compile wall would also trip a 0.3s dog
    eng.run([Request(prompt_ids=_prompts((5,), seed=38)[0],
                     sampling=SamplingParams(max_new_tokens=2))])
    configure_flight_recorder(dump_dir=str(tmp_path), fresh=True)
    configure_faults([{"point": "serve.decode_tick", "mode": "delay",
                       "hit": 2, "ms": 900}])
    wd = Watchdog(0.25, exit_code=None, description="serve drill").start()
    try:
        outs = eng.run([Request(prompt_ids=_prompts((7,), seed=39)[0],
                                sampling=SamplingParams(max_new_tokens=4))])
    finally:
        wd.stop()
        disarm_faults()
    assert wd.stall_count >= 1  # the dog fired DURING the stalled tick
    assert wd.last_postmortem_path
    with open(wd.last_postmortem_path) as f:
        pm = json.load(f)
    faults = [e for e in pm["events"]
              if e["kind"] == "fault.injected"
              and e["cid"] == "serve.decode_tick"]
    assert faults, [e["kind"] for e in pm["events"]]
    assert faults[0]["payload"]["mode"] == "delay"
    assert pm["thread_stacks"]  # where every thread was, mid-stall
    # the run itself survived the drill (delay, not a wedge): tokens exact
    (out,) = outs.values()
    assert out.finish_reason == "length"


# ------------------------------------------------------------ overload drill
def _drive_overload(params, cfg, classes, batch_prompts, inter_prompts,
                    queue_bound=0):
    """Staged overload: a batch backlog saturates the engine, interactive
    requests arrive after the first wave is already running. Returns
    (outputs-by-id, interactive ids, batch ids, max observed queue depth,
    engine)."""
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
        classes=classes, queue_bound=queue_bound,
    ))
    # warm EVERY bucket the timed run can hit (one length class at a time,
    # full allocation trajectory — the run_serve_bench warmup discipline):
    # a cold compile landing on an interactive request in one engine but a
    # batch request in the other would swamp the scheduling signal the
    # TTFT comparison measures
    for p in _prompts((6, 9, 12), seed=99):
        eng.run([Request(prompt_ids=p,
                         sampling=SamplingParams(max_new_tokens=8))])
    ids_b = [eng.submit(Request(prompt_ids=p, priority="batch",
                                sampling=SamplingParams(max_new_tokens=8)))
             for p in batch_prompts]
    max_q = eng.scheduler.queue_depth
    for _ in range(2):  # first batch wave starts decoding
        eng.step()
        max_q = max(max_q, eng.scheduler.queue_depth)
    ids_i = [eng.submit(Request(prompt_ids=p, priority="interactive",
                                sampling=SamplingParams(max_new_tokens=8)))
             for p in inter_prompts]
    max_q = max(max_q, eng.scheduler.queue_depth)
    outs = {}
    while eng.has_work:
        eng.step()
        max_q = max(max_q, eng.scheduler.queue_depth)
    outs.update(eng.run())
    for rid in ids_b + ids_i:  # rejected outputs stay in _outputs until run
        if rid not in outs:
            outs[rid] = eng._outputs[rid]
    return outs, ids_i, ids_b, max_q, eng


def test_overload_interactive_p99_beats_fifo_and_parity(qwen3):
    """The acceptance drill: same overload workload through (1) a
    single-class FIFO engine and (2) the QoS engine with a bounded queue.
    The QoS side must (a) shed — nonzero rejected, queue never past the
    bound, (b) give interactive strictly better p99 TTFT than FIFO, (c)
    keep every non-shed output token-exact, (d) leak zero blocks."""
    params, cfg = qwen3
    batch_prompts = _prompts((9, 11, 7, 10, 8, 12), seed=40)
    inter_prompts = _prompts((6, 9, 7, 8), seed=41)

    fifo_outs, fifo_i, _, _, fifo_eng = _drive_overload(
        params, cfg, "default:1", batch_prompts, inter_prompts,
        queue_bound=0,
    )
    qos_outs, qos_i, qos_b, max_q, qos_eng = _drive_overload(
        params, cfg, "interactive:4,batch:1", batch_prompts, inter_prompts,
        queue_bound=5,
    )
    # (a) load was actually shed, and the queue respected its bound
    n_rej = sum(1 for rid, o in qos_outs.items()
                if o.finish_reason == "rejected")
    assert n_rej > 0
    assert max_q <= 5
    assert qos_eng.metrics()["rejected"] == n_rej

    # (b) interactive p99 TTFT strictly better than the FIFO baseline
    def p99(outs, ids):
        vals = [outs[r].ttft_s for r in ids
                if outs[r].ttft_s is not None]
        assert vals
        return float(np.percentile(np.asarray(vals), 99))

    assert p99(qos_outs, qos_i) < p99(fifo_outs, fifo_i), (
        p99(qos_outs, qos_i), p99(fifo_outs, fifo_i)
    )
    # (c) token parity for every non-shed request, both engines
    for outs, prompts_by_id in (
        (fifo_outs, dict(zip(fifo_i, inter_prompts))),
        (qos_outs, dict(zip(qos_i, inter_prompts))),
        (qos_outs, dict(zip(qos_b, batch_prompts))),
    ):
        for rid, p in prompts_by_id.items():
            o = outs[rid]
            if o.finish_reason == "rejected":
                continue
            want = greedy_generate(params, cfg, p,
                                   max_new_tokens=8)[len(p):]
            assert o.token_ids == want, (rid, o.token_ids, want)
    # (d) zero leaked blocks on both engines
    _pool_identity(fifo_eng)
    _pool_identity(qos_eng)


def test_open_loop_bench_smoke(qwen3):
    """BENCH_SERVE_OPEN_LOOP machinery end to end on CPU: Poisson arrivals
    at 3x measured capacity against a bounded queue produce a well-formed
    sweep entry with nonzero rejects, a respected bound, and the JSON
    fields the bench line promises (reject_rate / p99 TTFT / goodput)."""
    import bench

    params, cfg = qwen3
    r = bench.run_serve_open_loop_bench(
        num_slots=2, block_size=8, n_requests=16, prompt_lens=(12, 20),
        max_new_tokens=6, arrival_rate_mults=(3.0,), queue_bound=3,
        deadline_s=2.0, interactive_frac=0.5, seed=42,
        _model=(params, cfg),
    )
    assert r["capacity_rps"] > 0
    (entry,) = r["sweep"]
    assert entry["rate_vs_capacity"] == pytest.approx(3.0)
    for key in ("reject_rate", "deadline_miss_rate", "ttft_p50_s",
                "ttft_p99_s", "ttft_p99_interactive_s", "tpot_p99_s",
                "goodput_tok_s", "decode_tok_s", "max_queue_depth",
                "shed_tokens", "completed"):
        assert key in entry, key
    assert entry["reject_rate"] > 0  # 3x capacity vs a 3-deep queue
    assert entry["max_queue_depth"] <= 3
    assert entry["completed"] > 0 and entry["goodput_tok_s"] >= 0
    json.dumps(r)  # the whole result is JSON-serializable (bench line)
