"""qwen3_next (hybrid GatedDeltaNet) framework integration: sharded train
step + HF export round-trip. (HF numerical parity lives in
test_hf_parity.py; reference capability: models/transformers/qwen3_5/.)
"""

import jax
import jax.numpy as jnp
import numpy as np


def _cfg(moe=True):
    from veomni_tpu.models.config import TransformerConfig

    return TransformerConfig(
        model_type="qwen3_next",
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, partial_rotary_factor=0.25, norm_zero_centered=True,
        attn_output_gate=True,
        linear_num_value_heads=4, linear_num_key_heads=2,
        linear_key_head_dim=16, linear_value_head_dim=16,
        full_attention_interval=4,
        **(dict(num_experts=4, num_experts_per_tok=2, moe_intermediate_size=48,
                shared_expert_intermediate_size=32, shared_expert_gated=True,
                router_aux_loss_coef=0.0) if moe else {}),
        dtype=jnp.float32,
    )


def _batch(bsz=4, seq=32, vocab=256):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (1, bsz, seq))
    return {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "labels": jnp.asarray(ids, jnp.int32),
        "position_ids": jnp.asarray(
            np.broadcast_to(np.arange(seq), ids.shape).copy(), jnp.int32),
        "segment_ids": jnp.ones(ids.shape, jnp.int32),
    }


def test_sharded_train_step_fsdp_ep():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.optim import build_lr_scheduler, build_optimizer
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.train import build_train_state, build_train_step
    from veomni_tpu.train.train_step import resolve_state_shardings

    destroy_parallel_state()
    ps = init_parallel_state(ep_size=2, dp_shard_size=4)
    with use_parallel_state(ps):
        model = build_foundation_model(config=_cfg())
        plan = model.get_parallel_plan()
        opt = build_optimizer(
            model.abstract(), lr=build_lr_scheduler(lr=1e-3, train_steps=4))

        def make_state(rng):
            return build_train_state(model.family.init_params(rng, model.config), opt)

        abs_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        shardings = resolve_state_shardings(abs_state, plan, ps)
        # EP rule applies to the double-stacked expert tensors: dim 2 = E
        exp_sh = shardings.params["linear_layers"]["experts"]["gate_proj"]
        assert exp_sh.spec[:3] == (None, None, "ep"), exp_sh.spec
        state = jax.jit(make_state, out_shardings=shardings)(jax.random.PRNGKey(0))
        batch = _batch()
        bsh = {k: NamedSharding(ps.mesh, P(None, ps.dp_axes, ps.sp_axes))
               for k in batch}
        step = build_train_step(model.loss_fn, opt, ps,
                                state_shardings=shardings, batch_shardings=bsh)
        batch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
        losses = []
        for _ in range(3):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]  # trains
    destroy_parallel_state()


def test_hf_export_roundtrip(tmp_path):
    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(config=_cfg(moe=True))
    params = model.init(jax.random.PRNGKey(0))
    out = str(tmp_path / "hf")
    model.save_hf(out)

    model2 = build_foundation_model(out, dtype=jnp.float32)
    params2 = model2.load_hf(out)
    batch = _batch(bsz=2, seq=16)
    batch = {k: v[0] for k, v in batch.items()}
    l1, m1 = jax.jit(model.loss_fn)(params, batch)
    l2, m2 = jax.jit(model2.loss_fn)(params2, batch)
    np.testing.assert_allclose(
        float(l1 / m1["ntokens"]), float(l2 / m2["ntokens"]), rtol=1e-6)

    # streamed shard-aligned load (EP-sliced expert reads) == plain load
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state

    destroy_parallel_state()
    try:
        ps = init_parallel_state(ep_size=2, dp_shard_size=4)
        with use_parallel_state(ps):
            shardings = model2.get_parallel_plan().resolve(
                jax.eval_shape(lambda: params2), ps
            )
            sharded = model2.family.hf_to_params(out, model2.config, shardings)
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(params2),
            jax.tree_util.tree_leaves_with_path(sharded),
        ):
            assert pa == pb
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=str(pa))
    finally:
        destroy_parallel_state()


def test_gated_delta_rule_segment_reset():
    """Packed 2-document row == per-document runs (reference varlen
    cu_seqlens semantics: no state leaks across documents). Documents are
    sized so one boundary falls mid-chunk and one document crosses a chunk
    boundary (exercising the in-chunk pair masks AND the carried-state
    continuation/keep masks)."""
    from veomni_tpu.models.qwen3_next import chunk_gated_delta_rule

    rng = np.random.default_rng(7)
    b, h, dk, dv = 2, 3, 8, 8
    la, lb = 40, 56  # chunk=64: boundary at 40; doc B spans chunks 0->1
    s = la + lb

    def mk(*shape):
        return jnp.asarray(rng.standard_normal(shape), jnp.float32)

    from veomni_tpu.models.qwen3_next import _l2norm

    # q/k l2-normalized as in the model: the delta-rule in-chunk inversion
    # is only well-conditioned for unit keys (real usage always normalizes)
    q, k = _l2norm(mk(b, s, h, dk)), _l2norm(mk(b, s, h, dk))
    v = mk(b, s, h, dv)
    g = -jnp.abs(mk(b, s, h)) * 0.1
    beta = jax.nn.sigmoid(mk(b, s, h))
    seg = jnp.asarray([[1] * la + [2] * lb] * b, jnp.int32)

    packed = chunk_gated_delta_rule(q, k, v, g, beta, segment_ids=seg)
    out_a = chunk_gated_delta_rule(
        q[:, :la], k[:, :la], v[:, :la], g[:, :la], beta[:, :la])
    out_b = chunk_gated_delta_rule(
        q[:, la:], k[:, la:], v[:, la:], g[:, la:], beta[:, la:])
    np.testing.assert_allclose(packed[:, :la], out_a, atol=2e-4)
    np.testing.assert_allclose(packed[:, la:], out_b, atol=2e-4)

    # segment_ids=None (single doc) still matches an all-ones mask run
    ref = chunk_gated_delta_rule(q, k, v, g, beta)
    one = chunk_gated_delta_rule(
        q, k, v, g, beta, segment_ids=jnp.ones((b, s), jnp.int32))
    np.testing.assert_allclose(ref, one, atol=1e-6)


def test_forward_packed_vs_separate_documents():
    """Full hybrid forward: each document of a packed row equals its
    standalone forward (conv taps, delta-rule state, and full attention all
    boundary-isolated)."""
    from veomni_tpu.models.qwen3_next import abstract_params  # noqa: F401
    from veomni_tpu.models.qwen3_next import forward_hidden, init_params

    cfg = _cfg(moe=False)
    params = init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(3)
    la, lb = 20, 12
    ids_a = rng.integers(0, cfg.vocab_size, (1, la))
    ids_b = rng.integers(0, cfg.vocab_size, (1, lb))

    packed = {
        "input_ids": jnp.asarray(np.concatenate([ids_a, ids_b], 1), jnp.int32),
        "position_ids": jnp.asarray(
            np.concatenate([np.arange(la)[None], np.arange(lb)[None]], 1),
            jnp.int32),
        "segment_ids": jnp.asarray([[1] * la + [2] * lb], jnp.int32),
    }
    hp, _, _ = forward_hidden(params, cfg, packed["input_ids"],
                              packed["position_ids"], packed["segment_ids"])
    for ids, lo, hi in ((ids_a, 0, la), (ids_b, la, la + lb)):
        n = hi - lo
        hs, _, _ = forward_hidden(
            params, cfg, jnp.asarray(ids, jnp.int32),
            jnp.asarray(np.arange(n)[None], jnp.int32),
            jnp.ones((1, n), jnp.int32))
        np.testing.assert_allclose(
            np.asarray(hp[:, lo:hi]), np.asarray(hs), atol=2e-4,
            err_msg=f"doc [{lo}:{hi}] leaked cross-document state")
