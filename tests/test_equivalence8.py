"""8-device mesh-layout equivalence (subprocess driver: needs 8 virtual
devices while the in-process suite runs on 4). Covers HSDP, DDP inference,
ep=4, sp=4, combined replicate x ep x sp, and capacity-mode EP."""

import json
import os
import subprocess
import sys

import numpy as np

DRIVER = os.path.join(os.path.dirname(__file__), "tools", "equivalence8.py")


def test_eight_device_layouts():
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    proc = subprocess.run(
        [sys.executable, DRIVER], env=env, capture_output=True, text=True,
        timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])

    for fam in ("dense", "moe"):
        base_loss, base_gnorm, _ = out[f"{fam}/base"]
        for key, (loss, gnorm, dropped) in out.items():
            if not key.startswith(f"{fam}/") or key.endswith(("base", "capacity")):
                continue
            np.testing.assert_allclose(loss, base_loss, rtol=2e-5, err_msg=key)
            np.testing.assert_allclose(gnorm, base_gnorm, rtol=2e-4, err_msg=key)

    # capacity mode: drops visible, loss within a bounded delta of dropless
    cap_loss, _, cap_dropped = out["moe/ep4_capacity"]
    base_loss = out["moe/base"][0]
    assert 0.0 <= cap_dropped < 0.5, f"implausible drop fraction {cap_dropped}"
    assert abs(cap_loss - base_loss) < 0.05, (cap_loss, base_loss)
