"""Per-row patch-budget VLM data path (multihost variant).

Packed mode (one global patch buffer, replicated) and per-row mode (budget
per row, batch-sharded) must produce identical losses — the per-row layout is
what multihost assembly ships (reference per-rank multimodal slicing,
``data/data_collator.py:317-431``).
"""

import jax
import numpy as np
import pytest

from veomni_tpu.data.data_transform import build_data_transform
from veomni_tpu.models.auto import build_config

_TEXT = {
    "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
    "num_hidden_layers": 2, "num_attention_heads": 4, "num_key_value_heads": 2,
    "image_token_id": 9, "video_token_id": 10, "vision_start_token_id": 8,
}
OVERRIDES = {
    "qwen2_5_vl": {
        **_TEXT,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "window_size": 8, "fullatt_block_indexes": [1],
            "out_hidden_size": 64,
        },
    },
    "qwen2_vl": {
        **_TEXT,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "embed_dim": 32, "hidden_size": 64, "mlp_ratio": 2,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
        },
    },
    "qwen3_vl": {
        **_TEXT,
        "head_dim": 16,
        "rope_scaling": {"rope_type": "default", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "out_hidden_size": 64, "num_position_embeddings": 16,
            "deepstack_visual_indexes": [0],
        },
    },
}


def _samples(cfg, key, n=4, seed=0):
    rng = np.random.default_rng(seed)
    transform = build_data_transform(
        key, tokenizer=None, vlm_config=cfg, max_seq_len=64,
        max_patches_per_sample=32, text_keys="text",
    )
    rows = []
    for i in range(n):
        rows.append(transform({
            "input_ids": rng.integers(11, 256, int(rng.integers(8, 24))).tolist(),
            "images": [rng.random((8 + 4 * (i % 2), 8, 3))],
        }))
    return rows


def _losses(model_type, collator_cls, loss_fn):
    cfg = build_config(model_type, **OVERRIDES[model_type])
    key = "qwen3_vl" if model_type.startswith("qwen3") else model_type
    samples = _samples(cfg, key)
    model_params = None

    out = []
    for per_row in (False, True):
        col = collator_cls(
            seq_len=64, micro_batch_size=4, vlm_config=cfg,
            max_patches=128, per_row=per_row,
        )
        batch = {k: jax.numpy.asarray(v) for k, v in col(samples).items()}
        if model_params is None:
            from veomni_tpu.models import build_foundation_model

            model = build_foundation_model(config=cfg)
            model_params = model.init(jax.random.PRNGKey(0))
        loss, metrics = loss_fn(model_params, cfg, batch)
        out.append((float(loss), float(metrics["ntokens"])))
    return out


def test_qwen25_vl_per_row_matches_packed():
    from veomni_tpu.data.multimodal import Qwen25VLCollator
    from veomni_tpu.models.qwen2_5_vl import loss_fn

    (lp, np_), (lr, nr) = _losses("qwen2_5_vl", Qwen25VLCollator, loss_fn)
    assert np_ == nr
    assert lp == pytest.approx(lr, rel=1e-5)


def test_qwen2_vl_per_row_matches_packed():
    from veomni_tpu.data.multimodal import Qwen2VLCollator
    from veomni_tpu.models.qwen2_vl import loss_fn

    (lp, np_), (lr, nr) = _losses("qwen2_vl", Qwen2VLCollator, loss_fn)
    assert np_ == nr
    assert lp == pytest.approx(lr, rel=1e-5)


def test_qwen3_vl_per_row_matches_packed():
    from veomni_tpu.data.multimodal import Qwen3VLCollator
    from veomni_tpu.models.qwen3_vl import loss_fn

    (lp, np_), (lr, nr) = _losses("qwen3_vl", Qwen3VLCollator, loss_fn)
    assert np_ == nr
    assert lp == pytest.approx(lr, rel=1e-5)
