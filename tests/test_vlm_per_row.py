"""Per-row patch-budget VLM data path (multihost variant).

Packed mode (one global patch buffer, replicated) and per-row mode (budget
per row, batch-sharded) must produce identical losses — the per-row layout is
what multihost assembly ships (reference per-rank multimodal slicing,
``data/data_collator.py:317-431``).
"""

import jax
import numpy as np
import pytest

from veomni_tpu.data.data_transform import build_data_transform
from veomni_tpu.models.auto import build_config

_TEXT = {
    "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
    "num_hidden_layers": 2, "num_attention_heads": 4, "num_key_value_heads": 2,
    "image_token_id": 9, "video_token_id": 10, "vision_start_token_id": 8,
}
OVERRIDES = {
    "qwen2_5_vl": {
        **_TEXT,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "window_size": 8, "fullatt_block_indexes": [1],
            "out_hidden_size": 64,
        },
    },
    "qwen2_vl": {
        **_TEXT,
        "rope_scaling": {"type": "mrope", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "embed_dim": 32, "hidden_size": 64, "mlp_ratio": 2,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
        },
    },
    "qwen3_vl": {
        **_TEXT,
        "head_dim": 16,
        "rope_scaling": {"rope_type": "default", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "out_hidden_size": 64, "num_position_embeddings": 16,
            "deepstack_visual_indexes": [0],
        },
    },
}


def _samples(cfg, key, n=4, seed=0):
    rng = np.random.default_rng(seed)
    transform = build_data_transform(
        key, tokenizer=None, vlm_config=cfg, max_seq_len=64,
        max_patches_per_sample=32, text_keys="text",
    )
    rows = []
    for i in range(n):
        rows.append(transform({
            "input_ids": rng.integers(11, 256, int(rng.integers(8, 24))).tolist(),
            "images": [rng.random((8 + 4 * (i % 2), 8, 3))],
        }))
    return rows


def _losses(model_type, collator_cls, loss_fn):
    cfg = build_config(model_type, **OVERRIDES[model_type])
    key = "qwen3_vl" if model_type.startswith("qwen3") else model_type
    samples = _samples(cfg, key)
    model_params = None

    out = []
    for per_row in (False, True):
        col = collator_cls(
            seq_len=64, micro_batch_size=4, vlm_config=cfg,
            max_patches=128, per_row=per_row,
        )
        batch = {k: jax.numpy.asarray(v) for k, v in col(samples).items()}
        if model_params is None:
            from veomni_tpu.models import build_foundation_model

            model = build_foundation_model(config=cfg)
            model_params = model.init(jax.random.PRNGKey(0))
        loss, metrics = loss_fn(model_params, cfg, batch)
        out.append((float(loss), float(metrics["ntokens"])))
    return out


def test_qwen25_vl_per_row_matches_packed():
    from veomni_tpu.data.multimodal import Qwen25VLCollator
    from veomni_tpu.models.qwen2_5_vl import loss_fn

    (lp, np_), (lr, nr) = _losses("qwen2_5_vl", Qwen25VLCollator, loss_fn)
    assert np_ == nr
    assert lp == pytest.approx(lr, rel=1e-5)


def test_qwen2_vl_per_row_matches_packed():
    from veomni_tpu.data.multimodal import Qwen2VLCollator
    from veomni_tpu.models.qwen2_vl import loss_fn

    (lp, np_), (lr, nr) = _losses("qwen2_vl", Qwen2VLCollator, loss_fn)
    assert np_ == nr
    assert lp == pytest.approx(lr, rel=1e-5)


def test_vlm_channel_loss_e2e(tmp_path):
    """Per-source loss accounting on a VLM trainer (VERDICT r4 weak #6:
    channel loss was text-only)."""
    import json

    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.train.channel_loss import ChannelLossCallback
    from veomni_tpu.trainer import VLMTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for i in range(24):
            f.write(json.dumps({
                "input_ids": rng.integers(11, 256, int(rng.integers(8, 24))).tolist(),
                "images": [rng.random((8, 8, 3)).tolist()],
                "channel": ["chart", "photo"][i % 2],
            }) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {"model_type": "qwen2_5_vl",
                                   **OVERRIDES["qwen2_5_vl"]}
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.data.max_patches = 256
    args.data.channel_list = ["chart", "photo"]
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 1
    destroy_parallel_state()
    try:
        trainer = VLMTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 3
        assert np.isfinite(ctl.metrics["loss"])
        cb = next(c for c in trainer.callbacks
                  if isinstance(c, ChannelLossCallback))
        cb._fold()
        # both sources saw tokens and accumulated loss
        assert all(c > 0 for c in cb._counts), cb._counts
        assert all(s > 0 for s in cb._sums), cb._sums
        trainer.checkpointer.close()
    finally:
        destroy_parallel_state()


def test_qwen3_vl_per_row_matches_packed():
    from veomni_tpu.data.multimodal import Qwen3VLCollator
    from veomni_tpu.models.qwen3_vl import loss_fn

    (lp, np_), (lr, nr) = _losses("qwen3_vl", Qwen3VLCollator, loss_fn)
    assert np_ == nr
    assert lp == pytest.approx(lr, rel=1e-5)
