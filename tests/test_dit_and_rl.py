"""DiT diffusion trainer + RL trainer e2e on the CPU mesh."""

import json

import numpy as np

from veomni_tpu.arguments import VeOmniArguments


def test_dit_trainer_e2e(tmp_path):
    from veomni_tpu.trainer.dit_trainer import DiTTrainer

    rng = np.random.default_rng(0)
    rows = [{
        "latents": rng.standard_normal((8, 8, 4)).tolist(),
        "cond": rng.standard_normal(32).tolist(),
    } for _ in range(64)]
    with open(tmp_path / "latents.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "latent_size": 8, "latent_channels": 4, "patch_size": 2,
        "hidden_size": 64, "num_hidden_layers": 2, "num_attention_heads": 4,
        "cond_dim": 32,
    }
    args.data.train_path = str(tmp_path / "latents.jsonl")
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 4
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    trainer = DiTTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 4
    assert np.isfinite(ctl.metrics["loss"])
    assert (tmp_path / "out" / "hf_ckpt" / "model.safetensors").exists()
    trainer.checkpointer.close()


def test_flow_match_scheduler():
    from veomni_tpu.schedulers import FlowMatchScheduler

    s = FlowMatchScheduler(shift=3.0)
    rng = np.random.default_rng(0)
    t = s.sample_timesteps(rng, 1000)
    assert (t >= 0).all() and (t <= 1).all()
    x0 = np.ones((4, 2, 2, 1))
    noise = np.zeros_like(x0)
    xt = s.add_noise(x0, noise, np.array([0.25] * 4, np.float32))
    np.testing.assert_allclose(xt, 0.75)


def test_rl_trainer_e2e(tmp_path):
    from veomni_tpu.trainer.rl_trainer import BaseRLTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "rl.jsonl", "w") as f:
        for _ in range(64):
            rlen = int(rng.integers(4, 16))
            f.write(json.dumps({
                "prompt": rng.integers(0, 256, 8).tolist(),
                "response": rng.integers(0, 256, rlen).tolist(),
                "advantage": float(rng.normal()),
            }) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen2", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
        "attention_bias": True,
    }
    args.data.train_path = str(tmp_path / "rl.jsonl")
    args.data.data_type = "rl"
    args.data.max_seq_len = 32
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 100
    trainer = BaseRLTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert np.isfinite(ctl.metrics["loss"])
    assert "ratio_mean" in ctl.metrics
    trainer.checkpointer.close()
