"""Offline DiT condition-cache pipeline -> trainer data path (VERDICT r4
weak #7): scripts/cache_dit_conditions.py must produce rows the DiT
trainer's collators consume unchanged.

Reference parity target: ``veomni/trainer/dit_trainer.py:168-595`` runs VAE
+ text encoders inline; this build produces the same tensors offline (script)
and keeps the train step pure DiT."""

import json
import sys

import numpy as np
import pytest


def _run_cache(argv, monkeypatch):
    import scripts.cache_dit_conditions as mod

    monkeypatch.setattr(sys, "argv", ["cache_dit_conditions.py"] + argv)
    mod.main()


def _write_rows(path, n=3, hw=24):
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for _ in range(n):
            img = (rng.random((hw, hw, 3)) * 255).astype(np.float64)
            f.write(json.dumps({"image": img.tolist(), "caption": "a cat"}) + "\n")


def test_cache_slot_dit_rows_feed_collator(tmp_path, monkeypatch):
    src, out = tmp_path / "in.jsonl", tmp_path / "out.jsonl"
    _write_rows(src)
    _run_cache(
        ["--in", str(src), "--out", str(out), "--latent_shape", "4,8,8",
         "--pixel_latents", "--cond_dim", "16"],
        monkeypatch,
    )
    rows = [json.loads(l) for l in open(out)]
    assert len(rows) == 3
    lat = np.asarray(rows[0]["latents"], np.float32)
    assert lat.shape == (4, 8, 8)
    assert lat.min() >= -1.0 and lat.max() <= 1.0
    assert np.asarray(rows[0]["cond"], np.float32).shape == (16,)

    from veomni_tpu.models.dit import DiTConfig
    from veomni_tpu.schedulers import FlowMatchScheduler
    from veomni_tpu.trainer.dit_trainer import DiTCollator

    cfg = DiTConfig(latent_size=8, latent_channels=4, cond_dim=16)
    # slot-dit collator expects [G,G,C] row layout
    samples = [{"latents": np.moveaxis(np.asarray(r["latents"], np.float32), 0, -1),
                "cond": r["cond"]} for r in rows]
    batch = DiTCollator(cfg, micro_batch_size=3, scheduler=FlowMatchScheduler())(samples)
    assert batch["latents"].shape == (3, 8, 8, 4)
    assert batch["cond"].shape == (3, 16)
    assert batch["noise"].shape == (3, 8, 8, 4) and batch["t"].shape == (3,)


def test_cache_video_latent_rows(tmp_path, monkeypatch):
    src, out = tmp_path / "in.jsonl", tmp_path / "out.jsonl"
    _write_rows(src, n=2)
    _run_cache(
        ["--in", str(src), "--out", str(out), "--latent_shape", "8,4,6,6",
         "--pixel_latents"],
        monkeypatch,
    )
    rows = [json.loads(l) for l in open(out)]
    lat = np.asarray(rows[0]["latents"], np.float32)
    assert lat.shape == (8, 4, 6, 6)
    # every frame identical (single-image broadcast semantics)
    assert np.allclose(lat[:, 0], lat[:, 1])


def test_cache_requires_explicit_vae_fallback(tmp_path, monkeypatch):
    src, out = tmp_path / "in.jsonl", tmp_path / "out.jsonl"
    _write_rows(src, n=1)
    with pytest.raises(SystemExit):
        _run_cache(["--in", str(src), "--out", str(out),
                    "--latent_shape", "4,8,8"], monkeypatch)
