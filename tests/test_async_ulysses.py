"""Async (chunked-pipeline) Ulysses: exact parity + HLO overlap evidence.

Two contracts anchor the tentpole (ISSUE 1):

1. the chunked a2a/compute pipeline (``parallel/async_ulysses.py``) is
   numerically EXACT vs the monolithic Ulysses wrap — per-chunk attention is
   the same program restricted to a head slice, so forward and grads match
   bitwise on CPU (GQA head-repeat + attention-sink slicing included);

2. the overlap claim is regression-gated in the emitted HLO: the dependency
   census (``utils/overlap_evidence.py``) must report at least as many
   independent collective/compute pairs for the chunked train step as the
   monolithic one — the precondition the latency-hiding scheduler needs to
   actually hide a2a latency behind dot-generals on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.ops.attention import _attention_xla
from veomni_tpu.parallel import init_parallel_state, use_parallel_state
from veomni_tpu.parallel.async_ulysses import async_ulysses_attention
from veomni_tpu.parallel.sequence_parallel import (
    UlyssesLayout,
    sp_attention,
    ulysses_monolithic,
)


def _qkv(b=2, s=32, hq=8, hkv=4, d=16, seed=0):
    rng = jax.random.PRNGKey(seed)
    qk, kk, vk, sk = jax.random.split(rng, 4)
    q = jax.random.normal(qk, (b, s, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(vk, (b, s, hkv, d), jnp.float32)
    sinks = jax.random.normal(sk, (hq,), jnp.float32)
    seg = jnp.concatenate(
        [jnp.ones((b, s // 2), jnp.int32), jnp.full((b, s // 2), 2, jnp.int32)],
        axis=1,
    )
    return q, k, v, sinks, seg


def test_layout_chunk_clamp():
    """Chunk boundaries must respect both a2a divisibility and GQA groups."""
    lay = UlyssesLayout(u=2, hq=8, hkv=4)  # kv_rep 1, hkv_rep 4
    assert (lay.kv_rep, lay.hkv_rep, lay.max_chunks) == (1, 4, 2)
    assert lay.clamp_chunks(8) == 2 and lay.clamp_chunks(1) == 1
    lay = UlyssesLayout(u=2, hq=8, hkv=2)  # kv_rep 1, max_chunks gcd(4,1)=1
    assert lay.max_chunks == 1  # chunking infeasible -> monolithic fallback
    lay = UlyssesLayout(u=4, hq=16, hkv=2)  # kv_rep 2, hkv_rep 4
    assert (lay.kv_rep, lay.max_chunks) == (2, 1)
    with pytest.raises(ValueError):
        UlyssesLayout(u=4, hq=6, hkv=2)


def test_async_exact_parity_gqa_sinks():
    """Chunked == monolithic, bitwise, forward AND grads, under GQA + sinks
    + packing segments."""
    q, k, v, sinks, seg = _qkv()
    ps = init_parallel_state(ulysses_size=2, dp_shard_size=2)
    with use_parallel_state(ps):
        ref = jax.jit(
            lambda *a: ulysses_monolithic(
                _attention_xla, *a, pstate=ps, causal=True, sinks=sinks
            )
        )(q, k, v, seg)
        got = jax.jit(
            lambda *a: async_ulysses_attention(
                _attention_xla, *a, pstate=ps, chunks=2, causal=True,
                sinks=sinks,
            )
        )(q, k, v, seg)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
        # single-device reference: the whole SP stack must also match local
        local = _attention_xla(q, k, v, segment_ids=seg, causal=True,
                               sinks=sinks)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(local), rtol=2e-5, atol=2e-5
        )

        def loss(fn):
            def f(q, k, v):
                return fn(
                    _attention_xla, q, k, v, seg, ps, causal=True, sinks=sinks
                ).sum()

            return jax.jit(jax.grad(f, argnums=(0, 1, 2)))

        g_ref = loss(ulysses_monolithic)(q, k, v)
        g_got = loss(
            lambda inner, *a, **kw: async_ulysses_attention(
                inner, *a, chunks=2, **kw
            )
        )(q, k, v)
        for a, b in zip(g_ref, g_got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dispatcher_knobs(monkeypatch):
    """sp_attention routes by async_chunks arg / env / registry pin, and
    falls back to monolithic when the head layout admits no chunking."""
    from veomni_tpu.ops.kernel_registry import KERNEL_REGISTRY

    q, k, v, _, seg = _qkv()
    ps = init_parallel_state(ulysses_size=2, dp_shard_size=2)
    with use_parallel_state(ps):
        base = jax.jit(
            lambda *a: sp_attention(_attention_xla, *a, pstate=ps, causal=True)
        )(q, k, v, seg)
        # explicit chunk count
        got = jax.jit(
            lambda *a: sp_attention(
                _attention_xla, *a, pstate=ps, async_chunks=2, causal=True
            )
        )(q, k, v, seg)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(got))
        # env knob
        monkeypatch.setenv("VEOMNI_ULYSSES_ASYNC", "1")
        monkeypatch.setenv("VEOMNI_ULYSSES_ASYNC_CHUNKS", "2")
        got = jax.jit(
            lambda *a: sp_attention(_attention_xla, *a, pstate=ps, causal=True)
        )(q, k, v, seg)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(got))
        monkeypatch.delenv("VEOMNI_ULYSSES_ASYNC")
        # registry pin (the ops_implementation config surface)
        KERNEL_REGISTRY.pin("ulysses", "ulysses_async")
        try:
            got = jax.jit(
                lambda *a: sp_attention(
                    _attention_xla, *a, pstate=ps, causal=True
                )
            )(q, k, v, seg)
            np.testing.assert_array_equal(np.asarray(base), np.asarray(got))
        finally:
            KERNEL_REGISTRY.clear_pins()
        # infeasible layout (hkv=2 -> max_chunks 1) silently stays monolithic
        q2, k2, v2, _, seg2 = _qkv(hkv=2)
        ref2 = jax.jit(
            lambda *a: sp_attention(_attention_xla, *a, pstate=ps, causal=True)
        )(q2, k2, v2, seg2)
        got2 = jax.jit(
            lambda *a: sp_attention(
                _attention_xla, *a, pstate=ps, async_chunks=4, causal=True
            )
        )(q2, k2, v2, seg2)
        np.testing.assert_array_equal(np.asarray(ref2), np.asarray(got2))


def _train_step_hlo(ulysses_async_chunks: int) -> str:
    """Optimized HLO text of the full jitted train step (fwd+bwd+adamw) on a
    ulysses=2 x fsdp=2 CPU mesh, monolithic (chunks=1) or chunked (>=2)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.optim import build_lr_scheduler, build_optimizer
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.train import build_train_state, build_train_step
    from veomni_tpu.train.train_step import resolve_state_shardings
    from veomni_tpu.utils.overlap_evidence import compiled_hlo_text

    destroy_parallel_state()
    ps = init_parallel_state(ulysses_size=2, dp_shard_size=2)
    with use_parallel_state(ps):
        cfg = TransformerConfig(
            model_type="qwen3", vocab_size=256, hidden_size=64,
            intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=8, num_key_value_heads=4, head_dim=8,
            qk_norm=True, dtype=jnp.float32,
            ulysses_async_chunks=ulysses_async_chunks,
        )
        model = build_foundation_model(config=cfg)
        plan = model.get_parallel_plan()
        opt = build_optimizer(
            model.abstract(), lr=build_lr_scheduler(lr=1e-3, train_steps=10)
        )

        def make_state(rng):
            return build_train_state(model.family.init_params(rng, cfg), opt)

        abs_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        shardings = resolve_state_shardings(abs_state, plan, ps)
        state = jax.jit(make_state, out_shardings=shardings)(
            jax.random.PRNGKey(0)
        )
        keys = ("input_ids", "labels", "position_ids", "segment_ids")
        bsh = {k: NamedSharding(ps.mesh, P(None, ps.dp_axes, ps.sp_axes))
               for k in keys}
        step = build_train_step(model.loss_fn, opt, ps,
                                state_shardings=shardings, batch_shardings=bsh)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, 4, 64))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(64), ids.shape).copy(), jnp.int32
            ),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        batch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
        return compiled_hlo_text(step, state, batch)


def test_hlo_overlap_evidence_gate():
    """THE regression gate: the chunked train step must expose >= as many
    overlappable collective/compute pairs as the monolithic one in its
    compiled HLO (and at least one at all) — if a refactor serializes the
    pipeline back into a dependency chain, this fails."""
    from veomni_tpu.utils.overlap_evidence import overlap_report

    mono = overlap_report(_train_step_hlo(1))
    chunked = overlap_report(_train_step_hlo(2))
    # both paths emit Ulysses a2a collectives at all
    assert mono.collectives > 0 and chunked.collectives > 0
    # the pipeline must create overlap opportunity, never destroy it
    assert chunked.overlappable >= mono.overlappable, (
        chunked.describe(), mono.describe()
    )
    assert chunked.pairs >= mono.pairs, (chunked.describe(), mono.describe())
    assert chunked.overlappable >= 1


def test_overlap_report_parser():
    """Unit anchor for the HLO dependency census (no jax involved)."""
    from veomni_tpu.utils.overlap_evidence import overlap_report

    hlo = """
HloModule toy

ENTRY %main (p0: f32[4], p1: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  %p1 = f32[4]{0} parameter(1)
  %a2a.1 = f32[4]{0} all-to-all(f32[4]{0} %p0), replica_groups={{0,1}}
  %dot.1 = f32[4]{0} dot(f32[4]{0} %p1, f32[4]{0} %p1), metadata={}
  ROOT %add.1 = f32[4]{0} add(f32[4]{0} %a2a.1, f32[4]{0} %dot.1)
}
"""
    rep = overlap_report(hlo)
    # dot.1 neither feeds nor consumes a2a.1 -> one overlappable pair
    assert (rep.collectives, rep.overlappable, rep.pairs) == (1, 1, 1)

    serial = hlo.replace(
        "dot(f32[4]{0} %p1, f32[4]{0} %p1)", "dot(f32[4]{0} %a2a.1, f32[4]{0} %p1)"
    )
    rep = overlap_report(serial)
    assert (rep.collectives, rep.overlappable, rep.pairs) == (1, 0, 0)
