"""Observability subsystem: registry, spans, goodput, recompile detection,
Prometheus exporter.

Acceptance contract (ISSUE 4): registry thread-safety + percentiles; spans
disabled cost ≈ nothing and produce chrome-trace JSON that
``scripts/merge_chrome_trace.py`` accepts; goodput fractions for a synthetic
step sum to ~1.0; a forced re-trace trips the recompile warning; ``/metrics``
serves parseable Prometheus text with trainer *and* serving metrics on CPU.
"""

import gzip
import importlib.util
import json
import logging
import os
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from veomni_tpu.observability import (
    GoodputTracker,
    MetricsExporter,
    MetricsRegistry,
    RecompileDetector,
    render_prometheus,
)
from veomni_tpu.observability import spans as spans_mod
from veomni_tpu.observability.metrics import get_registry
from veomni_tpu.observability.spans import (
    disable_spans,
    dump_chrome_trace,
    enable_spans,
    span,
)


def _load_merge_script():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "merge_chrome_trace.py")
    spec = importlib.util.spec_from_file_location("merge_chrome_trace", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def spans_off():
    """Leave the process-global span switch the way we found it."""
    was = spans_mod.spans_enabled()
    disable_spans()
    yield
    if was:
        enable_spans()


@pytest.fixture
def spans_on():
    was = spans_mod.spans_enabled()
    enable_spans()
    yield
    if not was:
        disable_spans()


# ----------------------------------------------------------------- registry
def test_registry_thread_safety():
    reg = MetricsRegistry()
    threads = 8
    per_thread = 1000

    def work():
        c = reg.counter("t.count")
        h = reg.histogram("t.hist")
        g = reg.gauge("t.gauge")
        for i in range(per_thread):
            c.inc()
            h.observe(float(i))
            g.set(i)

    ts = [threading.Thread(target=work) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert reg.counter("t.count").value == threads * per_thread
    assert reg.histogram("t.hist").count == threads * per_thread


def test_histogram_percentiles_and_bounds():
    reg = MetricsRegistry()
    h = reg.histogram("lat", max_samples=512)
    for v in range(1, 101):
        h.observe(float(v))
    snap = h.snapshot()
    assert snap["count"] == 100 and snap["sum"] == pytest.approx(5050.0)
    assert snap["min"] == 1.0 and snap["max"] == 100.0
    assert snap["p50"] == pytest.approx(50.0, abs=2.0)
    assert snap["p95"] == pytest.approx(95.0, abs=2.0)
    # reservoir stays bounded while count/sum stay exact
    small = reg.histogram("small", max_samples=16)
    for v in range(10_000):
        small.observe(float(v))
    assert small.count == 10_000
    assert len(small._samples) == 16
    assert small.snapshot()["max"] == 9999.0


def test_registry_kind_conflict_and_get_or_create():
    reg = MetricsRegistry()
    c1 = reg.counter("x")
    assert reg.counter("x") is c1  # shared instrument
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_registry_jsonl_sink_and_export_hook(tmp_path):
    reg = MetricsRegistry()
    path = str(tmp_path / "metrics.jsonl")
    reg.attach_jsonl(path)
    seen = []
    reg.add_export_hook(lambda step, payload: seen.append((step, payload)))
    reg.counter("c").inc(3)
    merged = reg.export(7, {"loss": 1.5, "future": object()})
    assert merged["c"] == 3.0 and merged["loss"] == 1.5
    assert "future" not in merged  # non-numeric payload values dropped
    assert seen and seen[0][0] == 7 and seen[0][1]["loss"] == 1.5
    assert reg.last_export(step=7)["loss"] == 1.5
    assert reg.last_export(step=8) is None
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["step"] == 7 and lines[0]["c"] == 3.0
    assert "rank" in lines[0]


# -------------------------------------------------------------------- spans
def test_span_disabled_is_allocation_free(spans_off):
    # the disabled path hands back ONE shared no-op context manager: no
    # per-call object, no clock read, no histogram feed
    assert span("a") is span("b")
    before = len(get_registry().items_snapshot())
    with span("disabled.phase"):
        pass
    # no histogram was created/fed: the disabled path never touches the
    # registry (or the clock, or an allocator)
    assert len(get_registry().items_snapshot()) == before
    assert get_registry().get("span.disabled.phase") is None


def test_span_feeds_histograms_and_chrome_trace(tmp_path, spans_on):
    spans_mod.clear_events()
    reg = get_registry()
    base = reg.histogram_sum("span.unit.phase")
    with span("unit.phase"):
        time.sleep(0.002)
    with span("unit.phase"):
        time.sleep(0.002)
    assert reg.histogram_sum("span.unit.phase") - base >= 0.004

    plain = str(tmp_path / "trace.json")
    gz = str(tmp_path / "trace.json.gz")
    assert dump_chrome_trace(plain) >= 2
    assert dump_chrome_trace(gz) >= 2
    doc = json.load(open(plain))
    events = doc["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs, "no complete events"
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] > 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    assert any(e.get("name") == "process_name" for e in events)
    with gzip.open(gz, "rt") as f:
        assert json.load(f)["traceEvents"]

    # ... and merge_chrome_trace accepts both (gzip + plain roundtrip)
    merge = _load_merge_script()
    merged = merge.merge_traces([plain, gz])
    assert len(merged) == 2 * len(events)


def test_merge_chrome_trace_monotonic_pid_remap(tmp_path):
    merge = _load_merge_script()
    host0 = [
        {"name": "process_name", "ph": "M", "pid": 3, "args": {"name": "p"}},
        {"name": "a", "ph": "X", "pid": 0, "tid": 1, "ts": 0, "dur": 5},
        {"name": "b", "ph": "X", "pid": 3, "tid": 1, "ts": 1, "dur": 5},
    ]
    host1 = [
        {"name": "a", "ph": "X", "pid": 0, "tid": 2, "ts": 0, "dur": 5},
        {"name": "b", "ph": "X", "pid": 1, "tid": 2, "ts": 2, "dur": 5},
    ]
    p0 = str(tmp_path / "h0.json")
    p1 = str(tmp_path / "h1.json.gz")
    json.dump({"traceEvents": host0}, open(p0, "w"))
    with gzip.open(p1, "wt") as f:
        json.dump(host1, f)  # bare event-list form must load too
    merged = merge.merge_traces([p0, p1])
    assert len(merged) == 5
    pids0 = {e["pid"] for e in merged[:3]}
    pids1 = {e["pid"] for e in merged[3:]}
    assert pids0 == {0, 3}  # first host unshifted
    assert pids1 == {4, 5}  # offset past host0's max pid (3) + 1
    assert max(pids0) < min(pids1)  # monotonic: later hosts sort after
    # host tag folded into process names
    pnames = [e for e in merged if e.get("name") == "process_name"]
    assert pnames and pnames[0]["args"]["name"].startswith("host0/")
    # roundtrip through main()'s output shape
    out = str(tmp_path / "merged.json")
    json.dump({"traceEvents": merged}, open(out, "w"))
    again = merge.load(out)
    assert len(again) == 5


# ------------------------------------------------------------------ goodput
def test_goodput_fractions_sum_to_one(spans_on):
    reg = MetricsRegistry()
    tracker = GoodputTracker(reg)
    # synthetic step built from the exact spans the trainer emits — but fed
    # through a private registry so other tests' spans can't skew it
    prev = spans_mod.get_registry
    spans_mod.get_registry = lambda: reg
    try:
        tracker.begin_window()
        with span("data.wait"):
            time.sleep(0.03)
        with span("data.ship"):
            time.sleep(0.005)
        with span("step.dispatch"):
            time.sleep(0.01)
        with span("host.callbacks"):
            with span("ckpt.save"):
                time.sleep(0.01)
            time.sleep(0.005)
        time.sleep(0.02)  # unattributed (the sync fetch / device wait)
        w = tracker.end_window()
    finally:
        spans_mod.get_registry = prev
    fracs = {k: v for k, v in w.items() if k.endswith("_frac")}
    assert set(fracs) == {"data_wait_frac", "host_frac", "dispatch_frac",
                          "checkpoint_frac", "other_frac"}
    assert sum(fracs.values()) == pytest.approx(1.0, abs=1e-6)
    assert w["data_wait_frac"] > 0.15  # the dominant injected stall
    assert w["checkpoint_frac"] > 0.05
    # ckpt time nested in the callback hook must not be double counted
    assert w["host_frac"] < w["checkpoint_frac"] + 0.15
    assert w["goodput_pct"] == pytest.approx(
        100.0 * (w["dispatch_frac"] + w["other_frac"]), abs=1e-6)
    # next window starts clean
    w2 = tracker.end_window()
    assert w2["data_wait_frac"] == pytest.approx(0.0, abs=1e-3)


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


def test_forced_retrace_trips_recompile_warning():
    import jax
    import jax.numpy as jnp

    from veomni_tpu.train import train_step as train_step_mod

    reg = MetricsRegistry()
    det = RecompileDetector(
        [("train_step", train_step_mod.TRACE_COUNTS, ("train_step",))],
        shape_source=train_step_mod.LAST_TRACE_SHAPES,
        registry=reg,
    )

    def impl(batch):
        # the same trace-time counting discipline the real step_fn uses
        train_step_mod.TRACE_COUNTS["train_step"] += 1
        train_step_mod.LAST_TRACE_SHAPES["train_step"] = {
            k: tuple(v.shape) for k, v in batch.items()
        }
        return batch["input_ids"] * 2

    f = jax.jit(impl)
    f({"input_ids": jnp.ones((1, 8), jnp.int32)})  # warmup compile
    det.arm()
    assert det.check() == 0  # steady state: same shape, no retrace
    f({"input_ids": jnp.ones((1, 8), jnp.int32)})
    assert det.check() == 0

    cap = _Capture()
    root = logging.getLogger("veomni_tpu")
    root.addHandler(cap)
    try:
        f({"input_ids": jnp.ones((1, 16), jnp.int32)})  # forced re-trace
        assert det.check() == 1
    finally:
        root.removeHandler(cap)
    msgs = [r.getMessage() for r in cap.records]
    assert any("RECOMPILE" in m for m in msgs), msgs
    assert any("(1, 16)" in m for m in msgs), "offending shapes not logged"
    assert reg.counter("recompiles").value == 1
    assert det.total_recompiles == 1


# ----------------------------------------------------------------- exporter
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [-+]?[0-9.eE+-]+$"
)


def _parse_prometheus(body: str):
    names = set()
    for line in body.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith("# TYPE "), line
            continue
        assert _PROM_LINE.match(line), f"unparseable exposition line: {line!r}"
        names.add(line.split("{")[0].split(" ")[0])
    return names


def test_metrics_endpoint_serves_trainer_and_serving_metrics(tmp_path):
    """The acceptance check: one /metrics endpoint, trainer + serving
    families, parseable Prometheus text, all under JAX_PLATFORMS=cpu."""
    import jax
    import jax.numpy as jnp

    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.serving import EngineConfig, InferenceEngine, Request, SamplingParams
    from veomni_tpu.trainer import TextTrainer

    from tests.test_e2e_training import TOY, _make_args, _write_dummy_data

    destroy_parallel_state()
    _write_dummy_data(tmp_path / "data.jsonl")
    args = _make_args(tmp_path, train_steps=4, log_steps=2)
    trainer = TextTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 4
    trainer.checkpointer.close()
    destroy_parallel_state()

    # the trainer's sync-step export also wrote the rank-local JSONL sink
    jsonl = os.path.join(args.train.output_dir, "metrics_rank0.jsonl")
    rows = [json.loads(l) for l in open(jsonl)]
    assert rows and rows[-1]["step"] == 4
    assert "loss" in rows[-1] and "goodput_pct" in rows[-1]
    frac_keys = ("data_wait_frac", "host_frac", "dispatch_frac",
                 "checkpoint_frac", "other_frac")
    assert sum(rows[-1][k] for k in frac_keys) == pytest.approx(1.0, abs=1e-3)

    # serving metrics land in the same registry
    cfg = TransformerConfig(dtype=jnp.float32, **{
        **TOY, "vocab_size": 128, "num_hidden_layers": 2})
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=16, max_model_len=128))
    eng.run([Request(prompt_ids=[1, 2, 3, 4],
                     sampling=SamplingParams(max_new_tokens=4))])
    eng.metrics()

    sup_health = {"healthy": True, "anomalies": 0}
    exp = MetricsExporter(port=0, health_fn=lambda: dict(sup_health))
    port = exp.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        names = _parse_prometheus(body)
        # trainer family
        assert "veomni_train_loss" in names
        assert "veomni_train_goodput_pct" in names
        assert any(n.startswith("veomni_span_") for n in names)
        # serving family
        assert "veomni_serve_generated_tokens" in names
        assert "veomni_serve_ttft_s_sum" in names
        assert "veomni_serve_kv_utilization" in names
        # healthz: healthy -> 200, unhealthy -> 503 (no body parsing needed)
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert doc["healthy"] is True
        sup_health["healthy"] = False
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                   timeout=10)
        assert ei.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        exp.stop()


def test_moe_router_stats_published():
    from veomni_tpu.utils.moe_monitor import publish_router_stats

    reg = MetricsRegistry()
    load = np.array([
        [0.5, 0.5, 0.0, 0.0],      # collapsed onto two experts
        [0.25, 0.25, 0.25, 0.25],  # perfectly balanced
    ])
    publish_router_stats(load, registry=reg)
    assert reg.gauge("moe.layer0.max_load").value == 0.5
    assert reg.gauge("moe.layer0.entropy").value == pytest.approx(np.log(2))
    # mass above the 1/E fair share = what a capacity-1.0 router would drop
    assert reg.gauge("moe.layer0.drop_frac").value == pytest.approx(0.5)
    assert reg.gauge("moe.layer1.entropy").value == pytest.approx(np.log(4))
    assert reg.gauge("moe.layer1.drop_frac").value == pytest.approx(0.0)


def test_supervisor_health_document():
    from veomni_tpu.resilience import SupervisorPolicy, TrainSupervisor

    sup = TrainSupervisor(SupervisorPolicy(
        anomaly_budget=1, rollback_after=5, inflight_depth=0))
    assert sup.health()["healthy"] is True
    sup.observe(1, {"loss": float("nan"), "step_ok": np.False_})
    sup.drain()
    h = sup.health()
    assert h["healthy"] is True and h["last_verdict"] == "skip"
    sup.observe(2, {"loss": float("nan"), "step_ok": np.False_})
    sup.drain()  # budget (1) blown -> abort, sticky
    assert sup.health()["healthy"] is False
    assert sup.health()["last_verdict"] == "abort"


# ---------------------------------------------------- ProfileCallback fix
def test_profile_callback_exception_safe_and_env_overrides(tmp_path, monkeypatch):
    import veomni_tpu.trainer.callbacks as cb_mod

    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(
        cb_mod.jax.profiler, "start_trace",
        lambda d: calls.__setitem__("start", calls["start"] + 1))

    def fake_stop():
        calls["stop"] += 1
        if calls["stop"] > calls["start"]:
            raise RuntimeError("No profile data")  # double-stop would raise

    monkeypatch.setattr(cb_mod.jax.profiler, "stop_trace", fake_stop)
    monkeypatch.setenv("VEOMNI_PROFILE_START", "2")
    monkeypatch.setenv("VEOMNI_PROFILE_END", "9")

    cb = cb_mod.ProfileCallback(str(tmp_path), start_step=3, end_step=5)
    assert cb.start == 2 and cb.end == 9  # env overrides win
    state = cb_mod.TrainerControlState()
    state.global_step = 2
    cb.on_step_begin(None, state)
    assert calls["start"] == 1 and cb._active
    # crash inside the traced window: close() (the trainer's finally path)
    # must stop the trace exactly once; every later stop is a guarded no-op
    cb.close()
    assert calls["stop"] == 1 and not cb._active
    cb.close()
    cb.on_train_end(None, state)
    state.global_step = 9
    cb.on_step_end(None, state)
    assert calls["stop"] == 1  # double-stop guard held everywhere
