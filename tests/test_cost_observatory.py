"""Device cost & capacity observatory (ISSUE 10 acceptance).

The census must be CPU-exercisable end to end: non-zero XLA FLOPs/bytes for
the train-step and paged-decode jit sites, a window MFU gauge that agrees
with the offline bench-style computation, a live buffer census aggregated
by dtype, well-formed ``/debug/memory`` + ``/debug/cost`` documents, a
serving-side recompile warning after the warmup grace, and a subprocess
drill proving a simulated ``RESOURCE_EXHAUSTED`` produces a post-mortem
carrying the buffer census.
"""

import json
import logging
import os
import subprocess
import sys
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.observability.cost import (
    CostCensus,
    CostWindow,
    get_cost_census,
    instrument_jit,
)
from veomni_tpu.observability.devmem import (
    buffer_census,
    is_resource_exhausted,
    kv_capacity_stats,
    oom_report,
    publish_memory_gauges,
)
from veomni_tpu.observability.metrics import MetricsRegistry, get_registry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOY = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)


# ------------------------------------------------------------- jit census
def test_instrument_jit_records_cost_and_calls():
    reg = MetricsRegistry()
    census = CostCensus(registry=reg)
    f = jax.jit(lambda x, n: (x @ x) * n, static_argnums=(1,))
    wf = instrument_jit(
        "unit", f, static_argnums=(1,), census=census,
        bucket_fn=lambda a: f"m{a[0].shape[0]}_n{a[1]}",
    )
    x = jnp.ones((32, 32))
    r1 = np.asarray(wf(x, 3))
    r2 = np.asarray(wf(x, 3))          # cached executable, same program
    r3 = np.asarray(wf(jnp.ones((16, 16)), 2))  # new bucket
    assert np.array_equal(r1, r2)
    assert np.array_equal(r1, np.asarray(f(x, 3)))  # parity with plain jit
    assert r3.shape == (16, 16)

    recs = {p.bucket: p for p in census.programs("unit")}
    assert set(recs) == {"m32_n3", "m16_n2"}
    big = recs["m32_n3"]
    assert big.flops > 0 and big.bytes_accessed > 0
    assert big.argument_bytes > 0 and big.output_bytes > 0
    assert big.compile_time_s > 0
    assert big.calls == 2 and recs["m16_n2"].calls == 1
    assert big.bound() in ("compute", "bandwidth")
    # the registry families landed
    assert reg.gauge("cost.unit.m32_n3.flops").value == big.flops
    assert reg.counter("cost.unit.m32_n3.calls").value == 2
    assert reg.counter("cost.programs").value == 2
    assert reg.histogram("cost.compile_s").count == 2
    # the wrapper stays a jit function for AOT tooling
    assert hasattr(wf, "lower") and wf.lower(x, 3) is not None


def test_scan_trip_count_correction():
    """XLA's HloCostAnalysis counts a scan body once; the census must
    multiply by the static trip count (incl. nested scans) or a layer-
    stacked model under-reports ~L-fold."""
    W = jnp.ones((64, 64))

    def nested(x):
        def outer(c, _):
            def inner(c2, _):
                return c2 @ W, None

            c2, _ = jax.lax.scan(inner, c, None, length=3)
            return c2, None

        c, _ = jax.lax.scan(outer, x, None, length=4)
        return c

    census = CostCensus(registry=MetricsRegistry())
    wf = instrument_jit("scan_unit", jax.jit(nested), census=census)
    wf(jnp.ones((32, 64))).block_until_ready()
    rec = census.latest("scan_unit")
    matmul = 2.0 * 32 * 64 * 64
    # 4 x 3 = 12 matmuls; the raw XLA reading saw ~1
    assert rec.flops == pytest.approx(12 * matmul, rel=0.05)
    assert rec.xla_flops_raw == pytest.approx(matmul, rel=0.05)
    assert rec.bytes_accessed > rec.xla_bytes_raw


def test_instrument_jit_disabled_by_env(monkeypatch):
    monkeypatch.setenv("VEOMNI_COST_CENSUS", "0")
    f = jax.jit(lambda x: x + 1)
    assert instrument_jit("off", f) is f


def test_train_step_census_nonzero_on_cpu():
    """Acceptance: the train-step jit site lands in the census with real
    XLA FLOPs/bytes under JAX_PLATFORMS=cpu (no chip required)."""
    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.optim import build_lr_scheduler, build_optimizer
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.train import build_train_state, build_train_step

    cfg = TransformerConfig(dtype=jnp.float32, **TOY)
    model = build_foundation_model(config=cfg)
    ps = init_parallel_state()
    with use_parallel_state(ps):
        opt = build_optimizer(
            model.abstract(), optimizer="adamw",
            lr=build_lr_scheduler(lr=1e-3, train_steps=10),
        )
        params = model.family.init_params(jax.random.PRNGKey(0), cfg)
        state = build_train_state(params, opt)
        step = build_train_step(model.loss_fn, opt, ps)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, 2, 32))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(32), ids.shape).copy(), jnp.int32
            ),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    rec = get_cost_census().get("train_step", "1x2x32")
    assert rec is not None, "train_step bucket missing from the census"
    assert rec.flops > 0 and rec.bytes_accessed > 0
    assert rec.compile_time_s > 0 and rec.calls >= 1
    assert rec.argument_bytes > 0  # memory_analysis ran on CPU too


def test_window_mfu_agrees_with_offline_computation():
    """Acceptance: the window MFU gauge agrees with the offline
    bench.py-style computation (census FLOPs x steps / dt / peak) within
    5% over the same step loop."""
    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.optim import build_lr_scheduler, build_optimizer
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.train import build_train_state, build_train_step
    from veomni_tpu.utils.device import get_device_peak_flops

    cfg = TransformerConfig(dtype=jnp.float32, **TOY)
    model = build_foundation_model(config=cfg)
    ps = init_parallel_state()
    with use_parallel_state(ps):
        opt = build_optimizer(
            model.abstract(), optimizer="adamw",
            lr=build_lr_scheduler(lr=1e-3, train_steps=100),
        )
        params = model.family.init_params(jax.random.PRNGKey(0), cfg)
        state = build_train_state(params, opt)
        step = build_train_step(model.loss_fn, opt, ps)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, 2, 64))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(64), ids.shape).copy(), jnp.int32
            ),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        state, metrics = step(state, batch)  # warmup: compile + record
        _ = float(metrics["loss"])

        steps = 6
        window = CostWindow(sites=("train_step",))
        window.begin()
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        _ = float(metrics["loss"])  # host fetch: the loop really finished
        dt = time.perf_counter() - t0
        out = window.end()

    rec = get_cost_census().get("train_step", "1x2x64")
    assert rec is not None and rec.flops > 0
    offline_mfu = 100.0 * rec.flops * steps / dt / get_device_peak_flops()
    assert out["mfu_pct"] > 0
    assert out["mfu_pct"] == pytest.approx(offline_mfu, rel=0.05)
    assert out["bandwidth_util_pct"] > 0
    assert out["census_tflops_s"] == pytest.approx(
        rec.flops * steps / dt / 1e12, rel=0.05)


def test_census_latest_tracks_recency_and_programs_stay_distinct():
    """latest() must follow record() recency, not dict insertion order —
    a sweep that revisits an earlier bucket re-records it in place; and
    cost.programs counts DISTINCT programs, not record() calls."""
    reg = MetricsRegistry()
    census = CostCensus(registry=reg)
    census.record("sweep", "a", flops=1.0)
    census.record("sweep", "b", flops=2.0)
    assert census.latest("sweep").bucket == "b"
    census.record("sweep", "a", flops=3.0)  # revisit: in-place re-record
    assert census.latest("sweep").bucket == "a"
    assert census.latest("sweep").flops == 3.0
    assert reg.counter("cost.programs").value == 2  # a, b — not 3 records


def test_window_mfu_from_fake_census():
    """The window math itself, decoupled from XLA: a hand-built census
    record + N invocations must yield exactly calls x flops / wall / peak."""
    from veomni_tpu.utils.device import (
        get_device_peak_bandwidth,
        get_device_peak_flops,
    )

    census = CostCensus(registry=MetricsRegistry())
    census.record("fake", "b0", compile_time_s=0.5, flops=1e9,
                  bytes_accessed=2e9)
    window = CostWindow(census=census)
    window.begin()
    for _ in range(5):
        census.note_call("fake", "b0")
    time.sleep(0.01)
    out = window.end()
    wall = out["census_window_s"]
    assert out["mfu_pct"] == pytest.approx(
        100.0 * 5e9 / wall / get_device_peak_flops(), rel=1e-6)
    assert out["bandwidth_util_pct"] == pytest.approx(
        100.0 * 1e10 / wall / get_device_peak_bandwidth(), rel=1e-6)
    # an idle window makes no utilization statement (the degenerate
    # train-end window must not zero the last real sync window's gauges)
    assert window.end() == {}


def test_paged_decode_census_and_kv_gauges():
    """Acceptance: the serving engine's paged decode bucket lands in the
    census, and the pool capacity gauges answer 'how many users fit'."""
    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        Request,
        SamplingParams,
    )

    cfg = TransformerConfig(dtype=jnp.float32, **TOY)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=16, max_model_len=128))
    outs = eng.run([Request(prompt_ids=[1, 2, 3, 4],
                            sampling=SamplingParams(max_new_tokens=4))])
    assert len(next(iter(outs.values())).token_ids) == 4

    rec = get_cost_census().latest("paged_decode")
    assert rec is not None
    assert rec.flops > 0 and rec.bytes_accessed > 0
    assert rec.compile_time_s > 0 and rec.calls >= 1

    cap = eng.kv_capacity()
    pool_bytes = eng.k_pool.nbytes + eng.v_pool.nbytes
    assert cap["pool_bytes"] == pool_bytes
    # 17 blocks (1 null + 2 slots x 8), 8 blocks per max-length sequence
    assert cap["max_concurrent_seqs"] == 2.0
    assert cap["free_concurrent_seqs"] == 2.0  # request finished, all free
    reg = get_registry()
    assert reg.gauge("serve.kv_pool_bytes").value == pool_bytes
    assert reg.gauge("serve.kv_max_concurrent_seqs").value == 2.0


def test_kv_capacity_stats_units():
    from veomni_tpu.serving import KVBlockManager

    bm = KVBlockManager(num_blocks=9, block_size=4)
    bm.allocate("a", 2)
    cap = kv_capacity_stats(bm, max_model_len=16)  # 4 blocks per seq
    assert cap["blocks_per_max_len_seq"] == 4.0
    assert cap["max_concurrent_seqs"] == 2.0  # 8 usable // 4
    assert cap["free_concurrent_seqs"] == 1.0  # 6 free // 4
    assert cap["blocks_free"] == 6.0


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


def test_serving_recompile_detector_fires_after_grace():
    """A decode-bucket compile past the warmup grace window gets the same
    loud RECOMPILE treatment the train step has had since PR 4."""
    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        Request,
        SamplingParams,
    )

    cfg = TransformerConfig(dtype=jnp.float32, **TOY)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=16, max_model_len=128,
        recompile_warmup_ticks=1))
    # warmup request: compiles prefill + decode buckets, arms at tick 1
    eng.run([Request(prompt_ids=[1, 2, 3],
                     sampling=SamplingParams(max_new_tokens=3))])
    base = get_registry().counter("recompiles").value

    cap = _Capture()
    root = logging.getLogger("veomni_tpu")
    root.addHandler(cap)
    try:
        # a longer prompt forces a NEW prefill bucket mid-run — exactly the
        # "serving compile storm" signature the detector now watches
        eng.run([Request(prompt_ids=list(range(1, 41)),
                         sampling=SamplingParams(max_new_tokens=3))])
    finally:
        root.removeHandler(cap)
    assert get_registry().counter("recompiles").value > base
    assert any("RECOMPILE" in r.getMessage() for r in cap.records)


# --------------------------------------------------------------- devmem
def test_buffer_census_aggregates_by_dtype():
    big = jnp.ones((128, 128), jnp.float32)   # 64 KiB
    small = jnp.ones((8,), jnp.int32)
    census = buffer_census(top_k=5)
    assert census["num_arrays"] >= 2
    assert census["total_bytes"] >= big.nbytes + small.nbytes
    assert "float32" in census["by_dtype"] and "int32" in census["by_dtype"]
    assert census["by_dtype"]["float32"]["bytes"] >= big.nbytes
    tops = census["top"]
    assert len(tops) <= 5
    # sorted by aggregate bytes descending
    assert all(tops[i]["bytes"] >= tops[i + 1]["bytes"]
               for i in range(len(tops) - 1))
    assert any(tuple(t["shape"]) == (128, 128) and t["dtype"] == "float32"
               for t in tops)
    del big, small


def test_memory_gauges_live_on_cpu():
    """The mem.* family must be live under JAX_PLATFORMS=cpu (the satellite
    fix: tier-1 used to never exercise the gauge path)."""
    from veomni_tpu.utils.helper import live_memory_stats

    stats = live_memory_stats()
    assert stats.get("host_rss_bytes", 0) > 0  # the RSS fallback, always on

    reg = MetricsRegistry()
    anchor = jnp.ones((64, 64))  # keep a live buffer during the publish
    published = publish_memory_gauges(reg)
    assert reg.gauge("mem.host_rss_bytes").value > 0
    assert reg.gauge("mem.live_buffer_bytes").value >= anchor.nbytes
    # the watermark is monotone and at least the current live total
    assert (reg.gauge("mem.high_watermark_bytes").value
            >= published["live_buffer_bytes"])
    wm1 = reg.gauge("mem.high_watermark_bytes").value
    del anchor
    publish_memory_gauges(reg)
    assert reg.gauge("mem.high_watermark_bytes").value >= wm1 - 1e-6


def test_is_resource_exhausted_matches_oom_shapes():
    assert is_resource_exhausted(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate"))
    assert is_resource_exhausted(RuntimeError(
        "Allocator ran out of memory trying to allocate 2.0GiB"))
    assert not is_resource_exhausted(ValueError("shape mismatch"))


def test_oom_report_carries_both_censuses():
    anchor = jnp.ones((32, 32))
    rep = oom_report(top_k=4)
    assert rep["buffer_census"]["num_arrays"] >= 1
    assert "programs" in rep["cost_census"]
    assert rep["host_rss_bytes"] > 0
    del anchor


# -------------------------------------------------------------- exporter
def test_debug_memory_and_cost_endpoints():
    from veomni_tpu.observability import MetricsExporter

    get_cost_census().record("endpoint_unit", "b0", compile_time_s=0.1,
                             flops=123.0, bytes_accessed=456.0)
    anchor = jnp.ones((64, 64))
    exp = MetricsExporter(port=0, memory_fn=lambda: {"pool_bytes": 99.0})
    port = exp.start()
    try:
        mem = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/memory?k=3", timeout=10).read())
        assert mem["buffer_census"]["total_bytes"] >= anchor.nbytes
        assert len(mem["buffer_census"]["top"]) <= 3
        assert mem["host_rss_bytes"] > 0
        assert mem["pool"] == {"pool_bytes": 99.0}

        cost = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/cost", timeout=10).read())
        sites = {p["site"] for p in cost["programs"]}
        assert "endpoint_unit" in sites
        rec = next(p for p in cost["programs"]
                   if p["site"] == "endpoint_unit")
        assert rec["flops"] == 123.0 and rec["bytes_accessed"] == 456.0
        assert cost["totals"]["programs"] >= 1
        assert "live" in cost  # scrape-to-scrape MFU window armed
    finally:
        exp.stop()
    del anchor


# ----------------------------------------------------------------- bench
def test_bench_census_fields_and_drift_warning(capsys):
    import bench

    census = CostCensus(registry=MetricsRegistry())
    census.record("train_step", "drift_unit", compile_time_s=2.0,
                  num_devices=4, flops=250.0)
    out = bench.census_bench_fields(1000.0, census=census)
    assert out["xla_flops_per_step"] == 1000.0  # 250 per device x 4
    assert out["analytic_vs_xla_flops_ratio"] == 1.0
    assert out["compile_time_s"]["drift_unit"] == 2.0
    assert "WARNING" not in capsys.readouterr().err

    # the same bucket again: compile-time DELTA only (sweep discipline)
    census.record("train_step", "drift_unit", compile_time_s=0.5,
                  num_devices=4, flops=250.0)
    out = bench.census_bench_fields(2000.0, census=census)
    assert out["compile_time_s"]["drift_unit"] == pytest.approx(0.5)
    assert out["analytic_vs_xla_flops_ratio"] == 2.0
    assert "WARNING" in capsys.readouterr().err  # outside FLOPS_RATIO_BAND


# ------------------------------------------------------ subprocess drill
_OOM_DRIVER = """\
import json, os, sys

cfg = json.load(open(sys.argv[1]))
sys.path.insert(0, cfg["repo"])

from veomni_tpu.arguments import VeOmniArguments
from veomni_tpu.trainer import TextTrainer

args = VeOmniArguments()
args.model.config_overrides = cfg["toy"]
args.data.train_path = cfg["data"]
args.data.data_type = "pretokenized"
args.data.max_seq_len = 64
t = args.train
t.output_dir = cfg["out"]
t.micro_batch_size = 2
t.train_steps = 6
t.async_save = False
t.lr = 1e-3
t.bf16 = False
t.save_hf_weights = False
t.log_steps = 1

trainer = TextTrainer(args)
res = {"error": ""}
try:
    trainer.train()
except Exception as e:
    res["error"] = type(e).__name__
    res["message"] = str(e)
finally:
    trainer.checkpointer.close()
with open(cfg["result"], "w") as f:
    json.dump(res, f)
"""


def test_oom_drill_postmortem_contains_buffer_census(tmp_path):
    """Acceptance drill: a simulated RESOURCE_EXHAUSTED escaping the train
    loop auto-dumps a post-mortem whose extra payload carries the top-K
    buffer census and the compiled-program cost census."""
    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for _ in range(64):
            f.write(json.dumps({
                "input_ids": rng.integers(
                    0, 128, int(rng.integers(16, 60))).tolist(),
            }) + "\n")
    driver = tmp_path / "driver.py"
    driver.write_text(_OOM_DRIVER)
    cfg = {
        "repo": _REPO, "toy": TOY,
        "data": str(tmp_path / "data.jsonl"),
        "out": str(tmp_path / "out"),
        "result": str(tmp_path / "result.json"),
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))
    fault_plan = [{
        "point": "step.loss", "mode": "exception", "hit": 3,
        "message": ("RESOURCE_EXHAUSTED: Out of memory while trying to "
                    "allocate 9437184 bytes (simulated OOM drill)"),
    }]
    env = dict(os.environ, JAX_PLATFORMS="cpu", VEOMNI_LOG_LEVEL="WARNING",
               VEOMNI_FAULT_PLAN=json.dumps(fault_plan))
    p = subprocess.run(
        [sys.executable, str(driver), str(cfg_path)],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=240,
    )
    assert os.path.exists(cfg["result"]), (
        f"driver died rc={p.returncode}:\n{p.stderr[-3000:]}"
    )
    res = json.load(open(cfg["result"]))
    assert res["error"] == "InjectedFault"
    assert "RESOURCE_EXHAUSTED" in res["message"]

    pm_path = os.path.join(cfg["out"], "postmortem-0.json")
    assert os.path.exists(pm_path), "OOM must auto-dump a post-mortem"
    doc = json.load(open(pm_path))
    assert doc["reason"] == "exception:InjectedFault"
    assert "RESOURCE_EXHAUSTED" in doc["error"]
    # the OOM forensics: what held the memory...
    census = doc["buffer_census"]
    assert census["num_arrays"] > 0 and census["total_bytes"] > 0
    assert census["top"], "top-K buffer table missing"
    top = census["top"][0]
    assert top["bytes"] > 0 and top["dtype"]
    # ... and what each compiled program needs on top of it
    sites = {prog["site"] for prog in doc["cost_census"]["programs"]}
    assert "train_step" in sites
    tstep = next(prog for prog in doc["cost_census"]["programs"]
                 if prog["site"] == "train_step")
    assert tstep["flops"] > 0
