"""Streaming shard dataset: formats, determinism, exact resume, dp split,
weighted-multisource integration (reference energon capability,
``veomni/data/dataset.py:1397-1533``)."""

import io
import json
import os
import tarfile

import numpy as np
import pytest


def _make_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def _make_tar(path, rows):
    with tarfile.open(path, "w") as tf:
        for i, r in enumerate(rows):
            raw = json.dumps(r).encode()
            info = tarfile.TarInfo(name=f"{i:05d}.json")
            info.size = len(raw)
            tf.addfile(info, io.BytesIO(raw))


def _make_parquet(path, rows):
    import pyarrow as pa
    import pyarrow.parquet as pq

    table = pa.Table.from_pylist(rows)
    pq.write_table(table, path, row_group_size=3)


def _corpus(tmp_path, n_shards=4, per_shard=7):
    d = tmp_path / "shards"
    d.mkdir()
    expect = []
    for s in range(n_shards):
        rows = [{"uid": s * 1000 + i} for i in range(per_shard)]
        expect += rows
        maker = [_make_jsonl, _make_tar, _make_parquet][s % 3]
        ext = [".jsonl", ".tar", ".parquet"][s % 3]
        maker(str(d / f"shard-{s:03d}{ext}"), rows)
    return str(d), expect


def test_formats_and_full_epoch(tmp_path):
    from veomni_tpu.data.dataset import build_dataset

    path, expect = _corpus(tmp_path)
    ds = build_dataset("streaming", path=path, shuffle=False)
    got = list(ds)
    assert sorted(r["uid"] for r in got) == sorted(r["uid"] for r in expect)
    # random access covers the same corpus
    assert len(ds) == len(expect)
    assert sorted(ds[i]["uid"] for i in range(len(ds))) == sorted(
        r["uid"] for r in expect
    )


def test_shuffle_deterministic_and_epoch_varying(tmp_path):
    from veomni_tpu.data.dataset import build_dataset

    path, expect = _corpus(tmp_path)
    a = build_dataset("streaming", path=path, seed=7)
    b = build_dataset("streaming", path=path, seed=7)
    ep0_a = [r["uid"] for r in a]
    ep0_b = [r["uid"] for r in b]
    assert ep0_a == ep0_b
    ep1_a = [r["uid"] for r in a]
    assert sorted(ep1_a) == sorted(ep0_a)
    assert ep1_a != ep0_a  # new epoch, new permutation


def test_exact_resume_mid_shard(tmp_path):
    from veomni_tpu.data.dataset import build_dataset

    path, _ = _corpus(tmp_path)
    ref = build_dataset("streaming", path=path, seed=3)
    full = [r["uid"] for r in ref] + [r["uid"] for r in ref]  # two epochs

    ds = build_dataset("streaming", path=path, seed=3)
    got = []
    it = iter(ds)
    for _ in range(11):  # stop mid-shard, mid-epoch
        got.append(next(it)["uid"])
    state = ds.state_dict()

    res = build_dataset("streaming", path=path, seed=3)
    res.load_state_dict(state)
    for r in res:
        got.append(r["uid"])
    for r in res:
        got.append(r["uid"])
    assert got == full


def test_dp_shard_split(tmp_path):
    from veomni_tpu.data.dataset import build_dataset

    path, expect = _corpus(tmp_path, n_shards=4)
    parts = []
    for rank in range(2):
        ds = build_dataset("streaming", path=path, seed=1, dp_rank=rank, dp_size=2)
        parts.append([r["uid"] for r in ds])
    assert set(parts[0]).isdisjoint(parts[1])
    assert sorted(parts[0] + parts[1]) == sorted(r["uid"] for r in expect)


def test_dp_record_stride_when_few_shards(tmp_path):
    from veomni_tpu.data.dataset import build_dataset

    d = tmp_path / "one"
    d.mkdir()
    rows = [{"uid": i} for i in range(10)]
    _make_jsonl(str(d / "only.jsonl"), rows)
    parts = []
    for rank in range(4):
        ds = build_dataset("streaming", path=str(d), seed=1, dp_rank=rank, dp_size=4)
        parts.append([r["uid"] for r in ds])
    allv = sum(parts, [])
    assert sorted(allv) == list(range(10))
    assert all(set(a).isdisjoint(b) for i, a in enumerate(parts)
               for b in parts[i + 1:])


def test_streaming_under_weighted_mix(tmp_path):
    from veomni_tpu.data.dataset import build_dataset

    path, _ = _corpus(tmp_path)
    d2 = tmp_path / "other"
    d2.mkdir()
    _make_jsonl(str(d2 / "s.jsonl"), [{"uid": 9000 + i} for i in range(5)])
    s1 = build_dataset("streaming", path=path, shuffle=False)
    s2 = build_dataset("streaming", path=str(d2), shuffle=False)
    mix = build_dataset("weighted", datasets=[s1, s2], weights=[0.5, 0.5], seed=0)
    it = iter(mix)
    first = [next(it)["uid"] for _ in range(20)]
    state = mix.state_dict()
    mix2 = build_dataset("weighted", datasets=[
        build_dataset("streaming", path=path, shuffle=False),
        build_dataset("streaming", path=str(d2), shuffle=False),
    ], weights=[0.5, 0.5], seed=0)
    mix2.load_state_dict(state)
    it1, it2 = iter(mix), iter(mix2)
    for _ in range(20):
        assert next(it1)["uid"] == next(it2)["uid"]
    assert {u for u in first if u >= 9000}  # both sources drawn
    assert {u for u in first if u < 9000}


def test_transform_applied(tmp_path):
    from veomni_tpu.data.dataset import build_dataset

    path, _ = _corpus(tmp_path, n_shards=1)
    ds = build_dataset(
        "streaming", path=path, shuffle=False,
        transform=lambda r: {"uid2": r["uid"] * 2},
    )
    assert next(iter(ds))["uid2"] % 2 == 0
    assert ds[0]["uid2"] % 2 == 0
