"""Long-context memory levers must not change math.

ChunkMBS analogue (sequence-chunked MLP, reference distributed/chunk_mbs.py)
and remat policies are pure memory/scheduling levers: loss and grads must be
bit-comparable with the unchunked path.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


def _run(cfg, batch):
    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(config=cfg)
    params = model.init(jax.random.PRNGKey(0))

    def norm_loss(p, b):
        loss_sum, metrics = model.loss_fn(p, b)
        return loss_sum / jnp.maximum(metrics["ntokens"], 1)

    loss, grads = jax.jit(jax.value_and_grad(norm_loss))(params, batch)
    import optax

    return float(loss), float(jax.jit(optax.global_norm)(grads))


def test_chunk_mbs_equivalence():
    from tests.test_parallel_equivalence import _batch, _toy_cfg

    cfg = _toy_cfg()
    batch = _batch(bsz=2, seq=64)
    base = _run(cfg, batch)
    chunked = _run(dataclasses.replace(cfg, chunk_mbs=16), batch)
    np.testing.assert_allclose(chunked[0], base[0], rtol=1e-6)
    np.testing.assert_allclose(chunked[1], base[1], rtol=1e-5)


def test_ctx_remat_under_sequence_parallel():
    """The ctx policy's checkpoint_name sits outside the Ulysses shard_map
    body — saving the attention context must not change loss/grad-norm
    under an sp layout (the bench default composes exactly this way)."""
    from tests.test_parallel_equivalence import _batch, _loss_and_gnorm, _toy_cfg

    cfg = _toy_cfg()
    batch = _batch(bsz=2, seq=64)
    layout = dict(ulysses_size=2, cp_size=2, dp_shard_size=1)
    base = _loss_and_gnorm(
        dataclasses.replace(cfg, remat=True, remat_policy="nothing"),
        layout, batch,
    )
    ctx = _loss_and_gnorm(
        dataclasses.replace(cfg, remat=True, remat_policy="ctx"),
        layout, batch,
    )
    np.testing.assert_allclose(ctx[0], base[0], rtol=1e-6)
    np.testing.assert_allclose(ctx[1], base[1], rtol=1e-5)
