"""Resilience subsystem tests.

Adversarial training behavior (capacity overflow, topology-change resume)
plus the ``veomni_tpu/resilience`` recovery paths, each driven by the
deterministic fault-injection plan (``VEOMNI_FAULT_PLAN`` /
``configure_faults``) under ``JAX_PLATFORMS=cpu``:

* fault-plan grammar + hit-window arming;
* device-side non-finite skip inside the jitted train step;
* NaN-skip accounting, checkpoint rollback + bit-exact replay, abort budget;
* checkpoint save/restore I/O faults survived within the retry budget, and
  retry-exhaustion aborting the run;
* async-save error surfacing/eviction at step boundaries;
* streaming data-fetch faults absorbed by the retry layer;
* hang watchdog firing on a stalled loop (bounded — no unbounded hang);
* SIGTERM graceful final checkpoint + exit 0 + exact resume (subprocess);
* SIGKILL mid-async-save crash consistency: resumed loss trajectory is
  bit-exact vs an uninterrupted run (subprocess).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from veomni_tpu.arguments import VeOmniArguments


@pytest.fixture(autouse=True)
def _disarm_fault_plan():
    yield
    from veomni_tpu.resilience.faults import disarm_faults

    disarm_faults()
    os.environ.pop("VEOMNI_FAULT_PLAN", None)


def _write_data(path, n=96, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            f.write(json.dumps({
                "input_ids": rng.integers(0, vocab, int(rng.integers(16, 80))).tolist(),
            }) + "\n")


def _args(tmp_path, **overrides):
    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen3_moe", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
        "qk_norm": True, "num_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 32, **overrides,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 4
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 1
    return args


def test_capacity_overflow_training_stays_finite(tmp_path):
    """A drastically undersized expert capacity (most tokens dropped) must
    degrade throughput, not stability: finite loss/grad at every step."""
    from veomni_tpu.trainer import TextTrainer

    _write_data(tmp_path / "data.jsonl")
    args = _args(tmp_path, moe_capacity_factor=0.25)
    trainer = TextTrainer(args)
    losses = []

    from veomni_tpu.trainer.callbacks import Callback

    class Rec(Callback):
        def on_step_end(self, t, state):
            if state.synced:
                losses.append(float(state.metrics["loss"]))
                assert np.isfinite(state.metrics["grad_norm"])

    trainer.callbacks.append(Rec())
    ctl = trainer.train()
    assert ctl.global_step == 4
    assert all(np.isfinite(l) for l in losses) and len(losses) == 4
    trainer.checkpointer.close()


def test_resume_after_topology_change_warns_and_continues(tmp_path):
    """A checkpoint whose per-rank extra state doesn't cover this rank
    (process count changed between save and resume) must warn about the
    dataloader cursor and still restore the train state + continue."""
    from veomni_tpu.trainer import TextTrainer

    _write_data(tmp_path / "data.jsonl")
    args = _args(tmp_path)
    args.train.save_steps = 2
    trainer = TextTrainer(args)
    trainer.train()
    trainer.checkpointer.close()

    # simulate "saved by a different topology": this rank's extra-state file
    # is absent, another rank's is present
    step_dir = os.path.join(args.train.output_dir, "checkpoints", "global_step_4")
    os.rename(
        os.path.join(step_dir, "extra_state_rank0.json"),
        os.path.join(step_dir, "extra_state_rank7.json"),
    )

    args2 = _args(tmp_path)
    args2.train.train_steps = 6
    trainer2 = TextTrainer(args2)
    import logging

    records = []
    handler = logging.Handler()
    handler.emit = records.append
    target = logging.getLogger("veomni_tpu.checkpoint.checkpointer")
    target.addHandler(handler)
    try:
        restored, extra = trainer2.try_resume()
    finally:
        target.removeHandler(handler)
    assert restored
    assert any("topology" in r.getMessage() for r in records)
    # training continues from the restored params
    ctl = trainer2.train()
    assert ctl.global_step == 6
    assert np.isfinite(ctl.metrics["loss"])
    trainer2.checkpointer.close()


# ---------------------------------------------------------------------------
# shared helpers for the resilience-path tests (tiny DENSE model: these tests
# run several full trains; the MoE toy above stays with its capacity test)
# ---------------------------------------------------------------------------

DENSE_TOY = {
    "model_type": "qwen3", "vocab_size": 256, "hidden_size": 32,
    "intermediate_size": 64, "num_hidden_layers": 2,
    "num_attention_heads": 2, "num_key_value_heads": 2, "head_dim": 16,
    "qk_norm": True,
}


def _dense_args(tmp_path, out_name="out", **train_overrides):
    args = VeOmniArguments()
    args.model.config_overrides = dict(DENSE_TOY)
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.train.output_dir = str(tmp_path / out_name)
    args.train.micro_batch_size = 2
    args.train.train_steps = 4
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 1
    args.train.resilience_retry_base_s = 0.001
    for k, v in train_overrides.items():
        setattr(args.train, k, v)
    return args


def _train_with_loss_log(args, data_path_writer=None):
    """Run a TextTrainer recording the bit pattern of every synced loss;
    returns (ctl, {step: loss_hex}, trainer)."""
    from veomni_tpu.trainer import TextTrainer
    from veomni_tpu.trainer.callbacks import Callback

    trainer = TextTrainer(args)
    losses = {}

    class Rec(Callback):
        def on_step_end(self, t, state):
            if state.synced:
                # replayed (post-rollback) steps overwrite: last wins
                losses[state.global_step] = float(state.metrics["loss"]).hex()

    trainer.callbacks.append(Rec())
    ctl = trainer.train()
    return ctl, losses, trainer


def _tree_bits_equal(a, b):
    import jax

    la, ta = jax.tree.flatten(a)
    lb, tb = jax.tree.flatten(b)
    assert ta == tb
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# fault plan grammar + retry unit behavior
# ---------------------------------------------------------------------------

def test_fault_plan_grammar_env_and_file(tmp_path):
    from veomni_tpu.resilience import faults

    os.environ["VEOMNI_FAULT_PLAN"] = json.dumps(
        [{"point": "ckpt.save", "mode": "exception", "hit": 2, "times": 2,
          "message": "boom"}]
    )
    assert faults.arm_from_env()
    assert faults.fault_point("ckpt.save") is None          # hit 1: unarmed
    for _ in range(2):                                       # hits 2-3 fire
        with pytest.raises(faults.InjectedFault, match="boom"):
            faults.fault_point("ckpt.save")
    assert faults.fault_point("ckpt.save") is None           # hit 4: window past
    assert [a.hit for a in faults.fired_faults()] == [2, 3]
    # injected faults are OSErrors: the retry layer's default classification
    assert issubclass(faults.InjectedFault, OSError)

    # @file indirection + nan mode returns an action instead of raising
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps([{"point": "step.loss", "mode": "nan"}]))
    os.environ["VEOMNI_FAULT_PLAN"] = "@" + str(plan_file)
    assert faults.arm_from_env()
    act = faults.fault_point("step.loss")
    assert act is not None and act.mode == "nan" and act.hit == 1
    assert faults.fault_point("step.loss") is None

    with pytest.raises(ValueError, match="unknown fault mode"):
        faults.configure_faults([{"point": "x", "mode": "explode"}])
    with pytest.raises(ValueError, match="missing 'point'"):
        faults.configure_faults([{"mode": "nan"}])
    faults.disarm_faults()
    assert faults.fault_point("ckpt.save") is None
    assert faults.fired_faults() == []


def test_retry_deterministic_backoff_and_exhaustion():
    from veomni_tpu.resilience.retry import RetryPolicy, retry_call

    delays, calls = [], []

    def flaky(fail_times):
        calls.append(1)
        if len(calls) <= fail_times:
            raise OSError(f"transient {len(calls)}")
        return "ok"

    policy = RetryPolicy(retries=3, base_delay_s=0.5, max_delay_s=1.5)
    assert retry_call(flaky, 2, policy=policy, sleep=delays.append) == "ok"
    assert delays == [0.5, 1.0]  # deterministic: base * 2**attempt, no jitter
    assert policy.delay(5) == 1.5  # capped

    calls.clear()
    with pytest.raises(OSError, match="transient 4"):  # original, not laundered
        retry_call(flaky, 99, policy=policy, sleep=lambda _: None)
    assert len(calls) == 4  # 1 + 3 retries

    # non-I/O errors are NOT retried
    def bug():
        calls.append(1)
        raise ValueError("schema mismatch")

    calls.clear()
    with pytest.raises(ValueError):
        retry_call(bug, policy=policy, sleep=lambda _: None)
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# device-side non-finite skip in the jitted train step
# ---------------------------------------------------------------------------

def test_train_step_device_side_skip(monkeypatch):
    import jax.numpy as jnp
    import optax

    from veomni_tpu.train import build_train_state, build_train_step

    # the test re-steps from the SAME state object; donation would delete it
    monkeypatch.setenv("VEOMNI_DONATE_STATE", "0")

    def loss_fn(params, micro):
        loss = (params["w"] * micro["x"]).sum() * micro["scale"][0]
        return loss, {"ntokens": jnp.int32(micro["x"].size)}

    opt = optax.adam(0.1)
    state0 = build_train_state({"w": jnp.ones((4,), jnp.float32)}, opt)
    step = build_train_step(loss_fn, opt, None, skip_nonfinite=True)

    def batch(scale):
        return {"x": jnp.ones((1, 4), jnp.float32),
                "scale": jnp.full((1, 1), scale, jnp.float32)}

    bad_state, bad_metrics = step(state0, batch(float("nan")))
    assert not bool(bad_metrics["step_ok"])
    assert not np.isfinite(float(bad_metrics["loss"]))
    # params AND optimizer state untouched by the non-finite update
    assert _tree_bits_equal(bad_state.params, state0.params)
    assert _tree_bits_equal(bad_state.opt_state, state0.opt_state)

    good_state, good_metrics = step(state0, batch(1.0))
    assert bool(good_metrics["step_ok"])
    assert not _tree_bits_equal(good_state.params, state0.params)

    # ungated build: the same bad batch poisons params (documents the knob)
    step_raw = build_train_step(loss_fn, opt, None, skip_nonfinite=False)
    raw_state, raw_metrics = step_raw(state0, batch(float("nan")))
    assert not bool(raw_metrics["step_ok"])  # flag still reported
    assert not np.isfinite(np.asarray(raw_state.params["w"])).all()


# ---------------------------------------------------------------------------
# supervisor escalation: NaN-skip, rollback + bit-exact replay, abort
# ---------------------------------------------------------------------------

def test_nan_skip_counts_anomaly_and_completes(tmp_path):
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, resilience_rollback_after=10)
    configure_faults([{"point": "step.loss", "mode": "nan", "hit": 2}])
    ctl, losses, trainer = _train_with_loss_log(args)
    trainer.checkpointer.close()
    assert ctl.global_step == 4
    assert ctl.resilience["anomalies"] == 1
    assert ctl.resilience["anomaly_steps"] == [2]
    assert ctl.resilience["rollbacks"] == 0
    assert all(np.isfinite(float.fromhex(h)) for h in losses.values())


def test_rollback_replays_bit_exact(tmp_path):
    """Two consecutive anomalies at steps 4-5 -> rollback to the step-4
    checkpoint, cursor-exact iterator replay; the final params and the
    replayed per-step losses must be BIT-identical to an uninterrupted run."""
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")

    ctl_a, losses_a, trainer_a = _train_with_loss_log(
        _dense_args(tmp_path, "clean", train_steps=6, save_steps=2)
    )
    import jax

    ref_params = jax.tree.map(np.asarray, trainer_a.train_state.params)
    trainer_a.checkpointer.close()
    destroy_parallel_state()

    configure_faults([{"point": "step.loss", "mode": "nan", "hit": 4, "times": 2}])
    ctl_b, losses_b, trainer_b = _train_with_loss_log(
        _dense_args(tmp_path, "faulty", train_steps=6, save_steps=2,
                    resilience_rollback_after=2)
    )
    assert ctl_b.global_step == 6
    assert ctl_b.resilience["rollbacks"] == 1
    assert ctl_b.resilience["anomalies"] == 2
    assert ctl_b.resilience["anomaly_steps"] == [4, 5]
    assert _tree_bits_equal(
        ref_params, jax.tree.map(np.asarray, trainer_b.train_state.params)
    )
    assert losses_a == losses_b  # incl. replayed steps 5-6 (last-wins)
    trainer_b.checkpointer.close()


def test_rollback_without_checkpoint_is_impossible(tmp_path):
    from veomni_tpu.resilience import RollbackImpossible
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, save_steps=0, resilience_rollback_after=2)
    configure_faults([{"point": "step.loss", "mode": "nan", "hit": 2, "times": 2}])
    with pytest.raises(RollbackImpossible):
        _train_with_loss_log(args)


def test_anomaly_budget_aborts(tmp_path):
    from veomni_tpu.resilience import AnomalyBudgetExceeded
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, train_steps=8, resilience_anomaly_budget=2,
                       resilience_rollback_after=10)
    configure_faults([{"point": "step.loss", "mode": "nan", "hit": 2, "times": 6}])
    with pytest.raises(AnomalyBudgetExceeded):
        _train_with_loss_log(args)


# ---------------------------------------------------------------------------
# checkpoint I/O faults: retried saves/restores, exhaustion, async eviction
# ---------------------------------------------------------------------------

def test_ckpt_save_fault_survived_within_retry_budget(tmp_path):
    from veomni_tpu.resilience.faults import configure_faults, fired_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, save_steps=2, resilience_io_retries=3)
    configure_faults([{"point": "ckpt.save", "mode": "exception", "hit": 1,
                       "times": 2}])
    ctl, losses, trainer = _train_with_loss_log(args)
    trainer.checkpointer.close()
    assert ctl.global_step == 4
    assert len(fired_faults()) == 2  # two failed attempts, third succeeded
    ckpts = trainer.checkpointer.list_steps()
    assert ckpts == [2, 4]


def test_ckpt_save_retry_exhaustion_aborts_run(tmp_path):
    from veomni_tpu.resilience.faults import InjectedFault, configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, save_steps=2, resilience_io_retries=1)
    configure_faults([{"point": "ckpt.save", "mode": "exception", "times": 20}])
    with pytest.raises(InjectedFault):
        _train_with_loss_log(args)


def test_ckpt_restore_fault_survived_within_retry_budget(tmp_path):
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.resilience.faults import configure_faults, fired_faults
    from veomni_tpu.trainer import TextTrainer

    _write_data(tmp_path / "data.jsonl")
    ctl, _, trainer = _train_with_loss_log(_dense_args(tmp_path, save_steps=2))
    trainer.checkpointer.close()
    destroy_parallel_state()

    configure_faults([{"point": "ckpt.restore", "mode": "exception", "hit": 1}])
    trainer2 = TextTrainer(_dense_args(tmp_path))
    restored, extra = trainer2.try_resume()
    assert restored and int(extra["global_step"]) == 4
    assert len(fired_faults()) == 1
    trainer2.checkpointer.close()


def test_async_save_error_surfaced_and_evicted(tmp_path):
    """check_for_errors-style probe at the step boundary: a failed async
    commit raises at wait(), and the step leaves the dedupe set so a later
    save() re-dispatches instead of silently skipping."""
    import jax.numpy as jnp

    from veomni_tpu.checkpoint import build_checkpointer

    ck = build_checkpointer(str(tmp_path / "ck"), async_save=True)
    state = {"w": jnp.arange(4, dtype=jnp.float32)}
    ck.save(1, state, extra_state={"global_step": 1})
    ck.wait()
    assert ck.list_steps() == [1]

    # simulate an async commit failure of a dispatched step 2
    ck._saved_steps.add(2)
    ck._inflight_step = 2
    ck._ckptr.check_for_errors = lambda: (_ for _ in ()).throw(IOError("commit failed"))
    with pytest.raises(IOError, match="commit failed"):
        ck.wait()
    assert 2 not in ck._saved_steps  # evicted: not silently lost

    del ck._ckptr.check_for_errors  # commit thread healthy again
    ck.save(2, state, extra_state={"global_step": 2})  # NOT dedupe-skipped
    ck.wait()
    assert ck.list_steps() == [1, 2]
    ck.close()


def test_extra_state_precedes_payload_commit(tmp_path):
    """The train_state dir rename is the commit marker; the JSON sidecars a
    committed checkpoint needs must already be on disk when it appears —
    a crash can never yield a committed step missing its cursor metadata."""
    import jax.numpy as jnp

    from veomni_tpu.checkpoint import Checkpointer
    from veomni_tpu.resilience.faults import InjectedFault, configure_faults

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False, io_retries=0)
    configure_faults([{"point": "ckpt.save", "mode": "exception"}])
    with pytest.raises(InjectedFault):
        ck.save(3, {"w": jnp.zeros(2)}, extra_state={"global_step": 3},
                rank_state={"dataloader": {"cursor": 7}})
    step_dir = tmp_path / "ck" / "global_step_3"
    assert (step_dir / "extra_state.json").exists()
    assert (step_dir / "extra_state_rank0.json").exists()
    assert not (step_dir / "train_state").exists()
    assert ck.list_steps() == []  # uncommitted: invisible to resume
    ck.close()


# ---------------------------------------------------------------------------
# data-fetch faults: streaming retry + watchdog on a stalled loop
# ---------------------------------------------------------------------------

def test_streaming_fetch_fault_survived_by_retry(tmp_path):
    from veomni_tpu.data.streaming import StreamingShardDataset
    from veomni_tpu.resilience.faults import configure_faults, fired_faults

    shard_dir = tmp_path / "shards"
    shard_dir.mkdir()
    rows = [{"i": i} for i in range(10)]
    with open(shard_dir / "00.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    configure_faults([{"point": "data.fetch", "mode": "exception", "hit": 3,
                       "times": 2}])
    ds = StreamingShardDataset(str(shard_dir), shuffle=False, retry_base_s=0.001)
    got = [r["i"] for r in ds]
    assert got == list(range(10))  # nothing dropped, order preserved
    assert len(fired_faults()) == 2


def test_watchdog_fires_on_stalled_loop_and_run_completes(tmp_path):
    """A bounded hang at data.fetch stalls the loop past the watchdog
    deadline: stacks are dumped (stall counted) but the run still finishes —
    no unbounded hang, no spurious kill."""
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, resilience_watchdog_s=0.25, prefetch_depth=1)
    # the LAST fetch of the run: nothing queued behind it hides the stall
    configure_faults([{"point": "data.fetch", "mode": "hang", "hit": 4,
                       "seconds": 1.5}])
    ctl, losses, trainer = _train_with_loss_log(args)
    trainer.checkpointer.close()
    assert ctl.global_step == 4
    assert ctl.resilience["watchdog_stalls"] >= 1


def test_watchdog_unit_dump_names_threads():
    from veomni_tpu.utils.helper import Watchdog

    dumps = []
    wd = Watchdog(0.1, on_stall=dumps.append, description="unit").start()
    try:
        time.sleep(0.35)
    finally:
        wd.stop()
    assert wd.stall_count >= 1 and dumps
    assert "MainThread" in dumps[0] and "test_watchdog_unit" in dumps[0]
    # petting resets the deadline
    wd2 = Watchdog(0.25, on_stall=dumps.append).start()
    try:
        for _ in range(4):
            time.sleep(0.1)
            wd2.pet()
        assert wd2.stall_count == 0
    finally:
        wd2.stop()


# ---------------------------------------------------------------------------
# real-process preemption/crash tests (subprocess: signals need a process)
# ---------------------------------------------------------------------------

_DRIVER = """\
import json, os, sys, time

cfg = json.load(open(sys.argv[1]))
sys.path.insert(0, cfg["repo"])

from veomni_tpu.arguments import VeOmniArguments
from veomni_tpu.trainer import TextTrainer
from veomni_tpu.trainer.callbacks import Callback

args = VeOmniArguments()
args.model.config_overrides = cfg["toy"]
args.data.train_path = cfg["data"]
args.data.data_type = "pretokenized"
args.data.max_seq_len = 64
t = args.train
t.output_dir = cfg["out"]
t.micro_batch_size = 2
t.train_steps = cfg["train_steps"]
t.save_steps = cfg.get("save_steps", 0)
t.async_save = cfg.get("async_save", False)
t.lr = 1e-3
t.bf16 = False
t.save_hf_weights = False
t.log_steps = 1

trainer = TextTrainer(args)


class Rec(Callback):
    def on_step_end(self, tr, state):
        if state.synced:
            with open(cfg["loss_log"], "a") as f:
                f.write(json.dumps({
                    "step": state.global_step,
                    "loss_hex": float(state.metrics["loss"]).hex(),
                }) + "\\n")
        # AFTER CheckpointCallback in the list: by marker time the step's
        # save has been dispatched
        if cfg.get("marker_at") and state.global_step == cfg["marker_at"]:
            with open(cfg["marker"], "w") as f:
                f.write(str(state.global_step))
        if cfg.get("step_sleep"):
            time.sleep(cfg["step_sleep"])


trainer.callbacks.append(Rec())
ctl = trainer.train()
trainer.checkpointer.close()
with open(cfg["result"], "w") as f:
    json.dump({"global_step": ctl.global_step, "preempted": ctl.preempted,
               "resilience": ctl.resilience}, f)
"""

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_driver(tmp_path, cfg):
    driver = tmp_path / "driver.py"
    driver.write_text(_DRIVER)
    cfg_path = tmp_path / f"cfg_{os.path.basename(cfg['loss_log'])}.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ, JAX_PLATFORMS="cpu", VEOMNI_LOG_LEVEL="WARNING")
    env.pop("VEOMNI_FAULT_PLAN", None)
    return subprocess.Popen(
        [sys.executable, str(driver), str(cfg_path)],
        env=env, cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _base_cfg(tmp_path, out_name, loss_log, **over):
    cfg = {
        "repo": _REPO,
        "toy": DENSE_TOY,
        "data": str(tmp_path / "data.jsonl"),
        "out": str(tmp_path / out_name),
        "loss_log": str(tmp_path / loss_log),
        "result": str(tmp_path / (loss_log + ".result.json")),
        "marker": str(tmp_path / (loss_log + ".marker")),
        "train_steps": 8,
    }
    cfg.update(over)
    return cfg


def _wait_for(path, proc, timeout=180.0):
    t0 = time.monotonic()
    while not os.path.exists(path):
        if proc.poll() is not None:
            out, err = proc.communicate()
            raise AssertionError(
                f"driver exited rc={proc.returncode} before {path}:\n{err[-2000:]}"
            )
        if time.monotonic() - t0 > timeout:
            proc.kill()
            raise AssertionError(f"timed out waiting for {path}")
        time.sleep(0.05)


def _read_losses(path):
    out = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            out[rec["step"]] = rec["loss_hex"]  # replayed steps: last wins
    return out


def test_sigterm_graceful_checkpoint_exit0_and_resume(tmp_path):
    """SIGTERM mid-run: the loop finishes the in-flight step, takes one
    final synchronous checkpoint, and exits 0; a restart resumes from
    exactly that step."""
    _write_data(tmp_path / "data.jsonl")
    cfg = _base_cfg(tmp_path, "out", "leg1.jsonl",
                    train_steps=60, step_sleep=0.15, marker_at=2)
    proc = _spawn_driver(tmp_path, cfg)
    _wait_for(cfg["marker"], proc)
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=180)
    assert proc.returncode == 0, f"expected clean exit, rc={proc.returncode}:\n{err[-2000:]}"

    result = json.load(open(cfg["result"]))
    stopped_at = result["global_step"]
    assert result["preempted"] and 2 <= stopped_at < 60
    step_dir = os.path.join(cfg["out"], "checkpoints", f"global_step_{stopped_at}")
    assert os.path.isdir(os.path.join(step_dir, "train_state"))  # committed
    assert os.path.exists(os.path.join(step_dir, "extra_state.json"))

    # restart: auto-resume picks up at stopped_at and continues
    cfg2 = _base_cfg(tmp_path, "out", "leg2.jsonl", train_steps=stopped_at + 2)
    proc2 = _spawn_driver(tmp_path, cfg2)
    out, err = proc2.communicate(timeout=300)
    assert proc2.returncode == 0, err[-2000:]
    result2 = json.load(open(cfg2["result"]))
    assert not result2["preempted"] and result2["global_step"] == stopped_at + 2
    leg2 = _read_losses(cfg2["loss_log"])
    assert min(leg2) == stopped_at + 1  # no step re-run, none skipped


def test_sigkill_mid_async_save_resume_bit_exact(tmp_path):
    """Crash consistency: SIGKILL the trainer right as the step-4 async save
    is in flight, restart, and the resumed loss trajectory must be BIT-exact
    vs an uninterrupted run — whether the kill landed before or after the
    async commit (uncommitted debris is cleaned, committed state resumes)."""
    _write_data(tmp_path / "data.jsonl")

    ref_cfg = _base_cfg(tmp_path, "ref_out", "ref.jsonl",
                        save_steps=4, async_save=True)
    proc = _spawn_driver(tmp_path, ref_cfg)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]
    ref = _read_losses(ref_cfg["loss_log"])
    assert sorted(ref) == list(range(1, 9))

    kill_cfg = _base_cfg(tmp_path, "kill_out", "kill1.jsonl",
                         save_steps=4, async_save=True, marker_at=4)
    proc = _spawn_driver(tmp_path, kill_cfg)
    _wait_for(kill_cfg["marker"], proc)
    proc.kill()  # SIGKILL: no handlers, no cleanup — a real crash
    proc.communicate(timeout=60)
    assert proc.returncode == -signal.SIGKILL
    assert not os.path.exists(kill_cfg["result"])

    resume_cfg = _base_cfg(tmp_path, "kill_out", "kill2.jsonl",
                           save_steps=4, async_save=True)
    proc = _spawn_driver(tmp_path, resume_cfg)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]
    result = json.load(open(resume_cfg["result"]))
    assert result["global_step"] == 8
    leg2 = _read_losses(resume_cfg["loss_log"])
    assert max(leg2) == 8
    for step, hexloss in leg2.items():
        assert ref[step] == hexloss, (
            f"step {step}: resumed loss {hexloss} != uninterrupted {ref[step]}"
        )
