"""Resilience subsystem tests.

Adversarial training behavior (capacity overflow, topology-change resume)
plus the ``veomni_tpu/resilience`` recovery paths, each driven by the
deterministic fault-injection plan (``VEOMNI_FAULT_PLAN`` /
``configure_faults``) under ``JAX_PLATFORMS=cpu``:

* fault-plan grammar + hit-window arming;
* device-side non-finite skip inside the jitted train step;
* NaN-skip accounting, checkpoint rollback + bit-exact replay, abort budget;
* checkpoint save/restore I/O faults survived within the retry budget, and
  retry-exhaustion aborting the run;
* async-save error surfacing/eviction at step boundaries;
* streaming data-fetch faults absorbed by the retry layer;
* hang watchdog firing on a stalled loop (bounded — no unbounded hang);
* SIGTERM graceful final checkpoint + exit 0 + exact resume (subprocess);
* SIGKILL mid-async-save crash consistency: resumed loss trajectory is
  bit-exact vs an uninterrupted run (subprocess).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from veomni_tpu.arguments import VeOmniArguments


@pytest.fixture(autouse=True)
def _disarm_fault_plan():
    yield
    from veomni_tpu.resilience.faults import disarm_faults

    disarm_faults()
    os.environ.pop("VEOMNI_FAULT_PLAN", None)


def _write_data(path, n=96, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            f.write(json.dumps({
                "input_ids": rng.integers(0, vocab, int(rng.integers(16, 80))).tolist(),
            }) + "\n")


def _args(tmp_path, **overrides):
    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen3_moe", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
        "qk_norm": True, "num_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 32, **overrides,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 4
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 1
    return args


def test_capacity_overflow_training_stays_finite(tmp_path):
    """A drastically undersized expert capacity (most tokens dropped) must
    degrade throughput, not stability: finite loss/grad at every step."""
    from veomni_tpu.trainer import TextTrainer

    _write_data(tmp_path / "data.jsonl")
    args = _args(tmp_path, moe_capacity_factor=0.25)
    trainer = TextTrainer(args)
    losses = []

    from veomni_tpu.trainer.callbacks import Callback

    class Rec(Callback):
        def on_step_end(self, t, state):
            if state.synced:
                losses.append(float(state.metrics["loss"]))
                assert np.isfinite(state.metrics["grad_norm"])

    trainer.callbacks.append(Rec())
    ctl = trainer.train()
    assert ctl.global_step == 4
    assert all(np.isfinite(l) for l in losses) and len(losses) == 4
    trainer.checkpointer.close()


def test_resume_after_torn_sidecar_set_falls_back_and_continues(tmp_path):
    """A generation whose per-rank extra state doesn't cover this rank
    (here: rank 0's sidecar renamed to rank 7 — a torn set no world size
    explains) must NOT silently restore with an empty dataloader cursor
    (the pre-elastic behavior, which repeats/skips that rank's samples).
    With a digest manifest the integrity gate already quarantines the
    missing-file generation; this test removes the manifest (an off-mode /
    pre-integrity checkpoint) so the TOPOLOGY gate is the layer that
    refuses: a pinned-step load raises `ElasticRestoreError`, and the
    restore walk falls back to the previous intact generation."""
    from veomni_tpu.resilience import ElasticRestoreError
    from veomni_tpu.trainer import TextTrainer

    _write_data(tmp_path / "data.jsonl")
    args = _args(tmp_path)
    args.train.save_steps = 2
    trainer = TextTrainer(args)
    trainer.train()
    trainer.checkpointer.close()

    # simulate "saved by a different topology": this rank's extra-state file
    # is absent, another rank's is present — and no digest manifest exists
    # to catch the missing file first
    step_dir = os.path.join(args.train.output_dir, "checkpoints", "global_step_4")
    os.rename(
        os.path.join(step_dir, "extra_state_rank0.json"),
        os.path.join(step_dir, "extra_state_rank7.json"),
    )
    os.remove(os.path.join(step_dir, "manifest.json"))

    args2 = _args(tmp_path)
    args2.train.train_steps = 6
    trainer2 = TextTrainer(args2)
    # a pinned-step load of the torn generation surfaces the error directly
    import jax

    abstract = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        trainer2.abstract_state, trainer2.state_shardings,
    )
    # without topology metadata a lone rank-7 sidecar reads as a world-8
    # save missing ranks 0-6 — either way, unmergeable and refused
    with pytest.raises(ElasticRestoreError, match="sidecar"):
        trainer2.checkpointer.load(abstract, step=4)

    import logging

    records = []
    handler = logging.Handler()
    handler.emit = records.append
    target = logging.getLogger("veomni_tpu.checkpoint.checkpointer")
    target.addHandler(handler)
    try:
        restored, extra = trainer2.try_resume()
    finally:
        target.removeHandler(handler)
    assert restored
    assert any("onto this topology" in r.getMessage() for r in records)
    # the torn step-4 generation was refused; the walk landed on step 2
    assert int(extra["global_step"]) == 2
    # training continues from the restored params
    ctl = trainer2.train()
    assert ctl.global_step == 6
    assert np.isfinite(ctl.metrics["loss"])
    trainer2.checkpointer.close()


# ---------------------------------------------------------------------------
# shared helpers for the resilience-path tests (tiny DENSE model: these tests
# run several full trains; the MoE toy above stays with its capacity test)
# ---------------------------------------------------------------------------

DENSE_TOY = {
    "model_type": "qwen3", "vocab_size": 256, "hidden_size": 32,
    "intermediate_size": 64, "num_hidden_layers": 2,
    "num_attention_heads": 2, "num_key_value_heads": 2, "head_dim": 16,
    "qk_norm": True,
}


def _dense_args(tmp_path, out_name="out", **train_overrides):
    args = VeOmniArguments()
    args.model.config_overrides = dict(DENSE_TOY)
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.train.output_dir = str(tmp_path / out_name)
    args.train.micro_batch_size = 2
    args.train.train_steps = 4
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 1
    args.train.resilience_retry_base_s = 0.001
    for k, v in train_overrides.items():
        setattr(args.train, k, v)
    return args


def _train_with_loss_log(args, data_path_writer=None):
    """Run a TextTrainer recording the bit pattern of every synced loss;
    returns (ctl, {step: loss_hex}, trainer)."""
    from veomni_tpu.trainer import TextTrainer
    from veomni_tpu.trainer.callbacks import Callback

    trainer = TextTrainer(args)
    losses = {}

    class Rec(Callback):
        def on_step_end(self, t, state):
            if state.synced:
                # replayed (post-rollback) steps overwrite: last wins
                losses[state.global_step] = float(state.metrics["loss"]).hex()

    trainer.callbacks.append(Rec())
    ctl = trainer.train()
    return ctl, losses, trainer


def _tree_bits_equal(a, b):
    import jax

    la, ta = jax.tree.flatten(a)
    lb, tb = jax.tree.flatten(b)
    assert ta == tb
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# fault plan grammar + retry unit behavior
# ---------------------------------------------------------------------------

def test_fault_plan_grammar_env_and_file(tmp_path):
    from veomni_tpu.resilience import faults

    os.environ["VEOMNI_FAULT_PLAN"] = json.dumps(
        [{"point": "ckpt.save", "mode": "exception", "hit": 2, "times": 2,
          "message": "boom"}]
    )
    assert faults.arm_from_env()
    assert faults.fault_point("ckpt.save") is None          # hit 1: unarmed
    for _ in range(2):                                       # hits 2-3 fire
        with pytest.raises(faults.InjectedFault, match="boom"):
            faults.fault_point("ckpt.save")
    assert faults.fault_point("ckpt.save") is None           # hit 4: window past
    assert [a.hit for a in faults.fired_faults()] == [2, 3]
    # injected faults are OSErrors: the retry layer's default classification
    assert issubclass(faults.InjectedFault, OSError)

    # @file indirection + nan mode returns an action instead of raising
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps([{"point": "step.loss", "mode": "nan"}]))
    os.environ["VEOMNI_FAULT_PLAN"] = "@" + str(plan_file)
    assert faults.arm_from_env()
    act = faults.fault_point("step.loss")
    assert act is not None and act.mode == "nan" and act.hit == 1
    assert faults.fault_point("step.loss") is None

    with pytest.raises(ValueError, match="unknown fault mode"):
        faults.configure_faults([{"point": "x", "mode": "explode"}])
    with pytest.raises(ValueError, match="missing 'point'"):
        faults.configure_faults([{"mode": "nan"}])
    faults.disarm_faults()
    assert faults.fault_point("ckpt.save") is None
    assert faults.fired_faults() == []


def test_retry_deterministic_backoff_and_exhaustion():
    from veomni_tpu.resilience.retry import RetryPolicy, retry_call

    delays, calls = [], []

    def flaky(fail_times):
        calls.append(1)
        if len(calls) <= fail_times:
            raise OSError(f"transient {len(calls)}")
        return "ok"

    policy = RetryPolicy(retries=3, base_delay_s=0.5, max_delay_s=1.5)
    assert retry_call(flaky, 2, policy=policy, sleep=delays.append) == "ok"
    assert delays == [0.5, 1.0]  # deterministic: base * 2**attempt, no jitter
    assert policy.delay(5) == 1.5  # capped

    calls.clear()
    with pytest.raises(OSError, match="transient 4"):  # original, not laundered
        retry_call(flaky, 99, policy=policy, sleep=lambda _: None)
    assert len(calls) == 4  # 1 + 3 retries

    # non-I/O errors are NOT retried
    def bug():
        calls.append(1)
        raise ValueError("schema mismatch")

    calls.clear()
    with pytest.raises(ValueError):
        retry_call(bug, policy=policy, sleep=lambda _: None)
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# device-side non-finite skip in the jitted train step
# ---------------------------------------------------------------------------

def test_train_step_device_side_skip(monkeypatch):
    import jax.numpy as jnp
    import optax

    from veomni_tpu.train import build_train_state, build_train_step

    # the test re-steps from the SAME state object; donation would delete it
    monkeypatch.setenv("VEOMNI_DONATE_STATE", "0")

    def loss_fn(params, micro):
        loss = (params["w"] * micro["x"]).sum() * micro["scale"][0]
        return loss, {"ntokens": jnp.int32(micro["x"].size)}

    opt = optax.adam(0.1)
    state0 = build_train_state({"w": jnp.ones((4,), jnp.float32)}, opt)
    step = build_train_step(loss_fn, opt, None, skip_nonfinite=True)

    def batch(scale):
        return {"x": jnp.ones((1, 4), jnp.float32),
                "scale": jnp.full((1, 1), scale, jnp.float32)}

    bad_state, bad_metrics = step(state0, batch(float("nan")))
    assert not bool(bad_metrics["step_ok"])
    assert not np.isfinite(float(bad_metrics["loss"]))
    # params AND optimizer state untouched by the non-finite update
    assert _tree_bits_equal(bad_state.params, state0.params)
    assert _tree_bits_equal(bad_state.opt_state, state0.opt_state)

    good_state, good_metrics = step(state0, batch(1.0))
    assert bool(good_metrics["step_ok"])
    assert not _tree_bits_equal(good_state.params, state0.params)

    # ungated build: the same bad batch poisons params (documents the knob)
    step_raw = build_train_step(loss_fn, opt, None, skip_nonfinite=False)
    raw_state, raw_metrics = step_raw(state0, batch(float("nan")))
    assert not bool(raw_metrics["step_ok"])  # flag still reported
    assert not np.isfinite(np.asarray(raw_state.params["w"])).all()


# ---------------------------------------------------------------------------
# supervisor escalation: NaN-skip, rollback + bit-exact replay, abort
# ---------------------------------------------------------------------------

def test_nan_skip_counts_anomaly_and_completes(tmp_path):
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, resilience_rollback_after=10)
    configure_faults([{"point": "step.loss", "mode": "nan", "hit": 2}])
    ctl, losses, trainer = _train_with_loss_log(args)
    trainer.checkpointer.close()
    assert ctl.global_step == 4
    assert ctl.resilience["anomalies"] == 1
    assert ctl.resilience["anomaly_steps"] == [2]
    assert ctl.resilience["rollbacks"] == 0
    assert all(np.isfinite(float.fromhex(h)) for h in losses.values())


def test_rollback_replays_bit_exact(tmp_path):
    """Two consecutive anomalies at steps 4-5 -> rollback to the step-4
    checkpoint, cursor-exact iterator replay; the final params and the
    replayed per-step losses must be BIT-identical to an uninterrupted run."""
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")

    ctl_a, losses_a, trainer_a = _train_with_loss_log(
        _dense_args(tmp_path, "clean", train_steps=6, save_steps=2)
    )
    import jax

    ref_params = jax.tree.map(np.asarray, trainer_a.train_state.params)
    trainer_a.checkpointer.close()
    destroy_parallel_state()

    configure_faults([{"point": "step.loss", "mode": "nan", "hit": 4, "times": 2}])
    ctl_b, losses_b, trainer_b = _train_with_loss_log(
        _dense_args(tmp_path, "faulty", train_steps=6, save_steps=2,
                    resilience_rollback_after=2)
    )
    assert ctl_b.global_step == 6
    assert ctl_b.resilience["rollbacks"] == 1
    assert ctl_b.resilience["anomalies"] == 2
    assert ctl_b.resilience["anomaly_steps"] == [4, 5]
    assert _tree_bits_equal(
        ref_params, jax.tree.map(np.asarray, trainer_b.train_state.params)
    )
    assert losses_a == losses_b  # incl. replayed steps 5-6 (last-wins)
    trainer_b.checkpointer.close()


def test_rollback_without_checkpoint_is_impossible(tmp_path):
    from veomni_tpu.resilience import RollbackImpossible
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, save_steps=0, resilience_rollback_after=2)
    configure_faults([{"point": "step.loss", "mode": "nan", "hit": 2, "times": 2}])
    with pytest.raises(RollbackImpossible):
        _train_with_loss_log(args)


def test_anomaly_budget_aborts(tmp_path):
    from veomni_tpu.resilience import AnomalyBudgetExceeded
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, train_steps=8, resilience_anomaly_budget=2,
                       resilience_rollback_after=10)
    configure_faults([{"point": "step.loss", "mode": "nan", "hit": 2, "times": 6}])
    with pytest.raises(AnomalyBudgetExceeded):
        _train_with_loss_log(args)


# ---------------------------------------------------------------------------
# checkpoint I/O faults: retried saves/restores, exhaustion, async eviction
# ---------------------------------------------------------------------------

def test_ckpt_save_fault_survived_within_retry_budget(tmp_path):
    from veomni_tpu.resilience.faults import configure_faults, fired_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, save_steps=2, resilience_io_retries=3)
    configure_faults([{"point": "ckpt.save", "mode": "exception", "hit": 1,
                       "times": 2}])
    ctl, losses, trainer = _train_with_loss_log(args)
    trainer.checkpointer.close()
    assert ctl.global_step == 4
    assert len(fired_faults()) == 2  # two failed attempts, third succeeded
    ckpts = trainer.checkpointer.list_steps()
    assert ckpts == [2, 4]


def test_ckpt_save_retry_exhaustion_aborts_run(tmp_path):
    from veomni_tpu.resilience.faults import InjectedFault, configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, save_steps=2, resilience_io_retries=1)
    configure_faults([{"point": "ckpt.save", "mode": "exception", "times": 20}])
    with pytest.raises(InjectedFault):
        _train_with_loss_log(args)


def test_ckpt_restore_fault_survived_within_retry_budget(tmp_path):
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.resilience.faults import configure_faults, fired_faults
    from veomni_tpu.trainer import TextTrainer

    _write_data(tmp_path / "data.jsonl")
    ctl, _, trainer = _train_with_loss_log(_dense_args(tmp_path, save_steps=2))
    trainer.checkpointer.close()
    destroy_parallel_state()

    configure_faults([{"point": "ckpt.restore", "mode": "exception", "hit": 1}])
    trainer2 = TextTrainer(_dense_args(tmp_path))
    restored, extra = trainer2.try_resume()
    assert restored and int(extra["global_step"]) == 4
    assert len(fired_faults()) == 1
    trainer2.checkpointer.close()


def test_async_save_error_surfaced_and_evicted(tmp_path):
    """check_for_errors-style probe at the step boundary: a failed async
    commit raises at wait(), and the step leaves the dedupe set so a later
    save() re-dispatches instead of silently skipping."""
    import jax.numpy as jnp

    from veomni_tpu.checkpoint import build_checkpointer

    ck = build_checkpointer(str(tmp_path / "ck"), async_save=True)
    state = {"w": jnp.arange(4, dtype=jnp.float32)}
    ck.save(1, state, extra_state={"global_step": 1})
    ck.wait()
    assert ck.list_steps() == [1]

    # simulate an async commit failure of a dispatched step 2
    ck._saved_steps.add(2)
    ck._inflight_step = 2
    ck._ckptr.check_for_errors = lambda: (_ for _ in ()).throw(IOError("commit failed"))
    with pytest.raises(IOError, match="commit failed"):
        ck.wait()
    assert 2 not in ck._saved_steps  # evicted: not silently lost

    del ck._ckptr.check_for_errors  # commit thread healthy again
    ck.save(2, state, extra_state={"global_step": 2})  # NOT dedupe-skipped
    ck.wait()
    assert ck.list_steps() == [1, 2]
    ck.close()


def test_extra_state_precedes_payload_commit(tmp_path):
    """The train_state dir rename is the commit marker; the JSON sidecars a
    committed checkpoint needs must already be on disk when it appears —
    a crash can never yield a committed step missing its cursor metadata."""
    import jax.numpy as jnp

    from veomni_tpu.checkpoint import Checkpointer
    from veomni_tpu.resilience.faults import InjectedFault, configure_faults

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False, io_retries=0)
    configure_faults([{"point": "ckpt.save", "mode": "exception"}])
    with pytest.raises(InjectedFault):
        ck.save(3, {"w": jnp.zeros(2)}, extra_state={"global_step": 3},
                rank_state={"dataloader": {"cursor": 7}})
    step_dir = tmp_path / "ck" / "global_step_3"
    assert (step_dir / "extra_state.json").exists()
    assert (step_dir / "extra_state_rank0.json").exists()
    assert not (step_dir / "train_state").exists()
    assert ck.list_steps() == []  # uncommitted: invisible to resume
    ck.close()


# ---------------------------------------------------------------------------
# data-fetch faults: streaming retry + watchdog on a stalled loop
# ---------------------------------------------------------------------------

def test_streaming_fetch_fault_survived_by_retry(tmp_path):
    from veomni_tpu.data.streaming import StreamingShardDataset
    from veomni_tpu.resilience.faults import configure_faults, fired_faults

    shard_dir = tmp_path / "shards"
    shard_dir.mkdir()
    rows = [{"i": i} for i in range(10)]
    with open(shard_dir / "00.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    configure_faults([{"point": "data.fetch", "mode": "exception", "hit": 3,
                       "times": 2}])
    ds = StreamingShardDataset(str(shard_dir), shuffle=False, retry_base_s=0.001)
    got = [r["i"] for r in ds]
    assert got == list(range(10))  # nothing dropped, order preserved
    assert len(fired_faults()) == 2


def test_watchdog_fires_on_stalled_loop_and_run_completes(tmp_path):
    """A bounded hang at data.fetch stalls the loop past the watchdog
    deadline: stacks are dumped (stall counted) but the run still finishes —
    no unbounded hang, no spurious kill."""
    from veomni_tpu.resilience.faults import configure_faults

    _write_data(tmp_path / "data.jsonl")
    args = _dense_args(tmp_path, resilience_watchdog_s=0.25, prefetch_depth=1)
    # the LAST fetch of the run: nothing queued behind it hides the stall
    configure_faults([{"point": "data.fetch", "mode": "hang", "hit": 4,
                       "seconds": 1.5}])
    ctl, losses, trainer = _train_with_loss_log(args)
    trainer.checkpointer.close()
    assert ctl.global_step == 4
    assert ctl.resilience["watchdog_stalls"] >= 1


def test_watchdog_unit_dump_names_threads():
    from veomni_tpu.utils.helper import Watchdog

    dumps = []
    wd = Watchdog(0.1, on_stall=dumps.append, description="unit").start()
    try:
        time.sleep(0.35)
    finally:
        wd.stop()
    assert wd.stall_count >= 1 and dumps
    assert "MainThread" in dumps[0] and "test_watchdog_unit" in dumps[0]
    # petting resets the deadline
    wd2 = Watchdog(0.25, on_stall=dumps.append).start()
    try:
        for _ in range(4):
            time.sleep(0.1)
            wd2.pet()
        assert wd2.stall_count == 0
    finally:
        wd2.stop()


# ---------------------------------------------------------------------------
# real-process preemption/crash tests (subprocess: signals need a process)
# ---------------------------------------------------------------------------

_DRIVER = """\
import json, os, sys, time

cfg = json.load(open(sys.argv[1]))
sys.path.insert(0, cfg["repo"])

from veomni_tpu.arguments import VeOmniArguments
from veomni_tpu.trainer import TextTrainer
from veomni_tpu.trainer.callbacks import Callback

args = VeOmniArguments()
args.model.config_overrides = cfg["toy"]
args.data.train_path = cfg["data"]
args.data.data_type = "pretokenized"
args.data.max_seq_len = 64
t = args.train
t.output_dir = cfg["out"]
t.micro_batch_size = 2
t.train_steps = cfg["train_steps"]
t.save_steps = cfg.get("save_steps", 0)
t.async_save = cfg.get("async_save", False)
t.ckpt_verify = cfg.get("ckpt_verify", "size")
t.data_skip_budget = cfg.get("data_skip_budget", 0)
t.lr_decay_style = cfg.get("lr_decay_style", "cosine")
if cfg.get("dataset_type"):
    args.data.dataset_type = cfg["dataset_type"]
t.lr = 1e-3
t.bf16 = False
t.save_hf_weights = False
t.log_steps = 1

trainer = TextTrainer(args)


class Rec(Callback):
    def on_step_end(self, tr, state):
        if state.synced:
            with open(cfg["loss_log"], "a") as f:
                f.write(json.dumps({
                    "step": state.global_step,
                    "loss_hex": float(state.metrics["loss"]).hex(),
                }) + "\\n")
        # AFTER CheckpointCallback in the list: by marker time the step's
        # save has been dispatched
        if cfg.get("marker_at") and state.global_step == cfg["marker_at"]:
            with open(cfg["marker"], "w") as f:
                f.write(str(state.global_step))
        if cfg.get("step_sleep"):
            time.sleep(cfg["step_sleep"])


trainer.callbacks.append(Rec())
ctl = trainer.train()
trainer.checkpointer.close()
res = {"global_step": ctl.global_step, "preempted": ctl.preempted,
       "resilience": ctl.resilience}
if hasattr(trainer.dataset, "state_dict"):
    res["dataset_state"] = trainer.dataset.state_dict()
with open(cfg["result"], "w") as f:
    json.dump(res, f)
"""

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_driver(tmp_path, cfg, extra_env=None):
    driver = tmp_path / "driver.py"
    driver.write_text(_DRIVER)
    cfg_path = tmp_path / f"cfg_{os.path.basename(cfg['loss_log'])}.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ, JAX_PLATFORMS="cpu", VEOMNI_LOG_LEVEL="WARNING")
    env.pop("VEOMNI_FAULT_PLAN", None)
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, str(driver), str(cfg_path)],
        env=env, cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _base_cfg(tmp_path, out_name, loss_log, **over):
    cfg = {
        "repo": _REPO,
        "toy": DENSE_TOY,
        "data": str(tmp_path / "data.jsonl"),
        "out": str(tmp_path / out_name),
        "loss_log": str(tmp_path / loss_log),
        "result": str(tmp_path / (loss_log + ".result.json")),
        "marker": str(tmp_path / (loss_log + ".marker")),
        "train_steps": 8,
    }
    cfg.update(over)
    return cfg


def _wait_for(path, proc, timeout=180.0):
    t0 = time.monotonic()
    while not os.path.exists(path):
        if proc.poll() is not None:
            out, err = proc.communicate()
            raise AssertionError(
                f"driver exited rc={proc.returncode} before {path}:\n{err[-2000:]}"
            )
        if time.monotonic() - t0 > timeout:
            proc.kill()
            raise AssertionError(f"timed out waiting for {path}")
        time.sleep(0.05)


def _read_losses(path):
    out = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            out[rec["step"]] = rec["loss_hex"]  # replayed steps: last wins
    return out


def test_sigterm_graceful_checkpoint_exit0_and_resume(tmp_path):
    """SIGTERM mid-run: the loop finishes the in-flight step, takes one
    final synchronous checkpoint, and exits 0; a restart resumes from
    exactly that step."""
    _write_data(tmp_path / "data.jsonl")
    cfg = _base_cfg(tmp_path, "out", "leg1.jsonl",
                    train_steps=60, step_sleep=0.15, marker_at=2)
    proc = _spawn_driver(tmp_path, cfg)
    _wait_for(cfg["marker"], proc)
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=180)
    assert proc.returncode == 0, f"expected clean exit, rc={proc.returncode}:\n{err[-2000:]}"

    result = json.load(open(cfg["result"]))
    stopped_at = result["global_step"]
    assert result["preempted"] and 2 <= stopped_at < 60
    step_dir = os.path.join(cfg["out"], "checkpoints", f"global_step_{stopped_at}")
    assert os.path.isdir(os.path.join(step_dir, "train_state"))  # committed
    assert os.path.exists(os.path.join(step_dir, "extra_state.json"))

    # restart: auto-resume picks up at stopped_at and continues
    cfg2 = _base_cfg(tmp_path, "out", "leg2.jsonl", train_steps=stopped_at + 2)
    proc2 = _spawn_driver(tmp_path, cfg2)
    out, err = proc2.communicate(timeout=300)
    assert proc2.returncode == 0, err[-2000:]
    result2 = json.load(open(cfg2["result"]))
    assert not result2["preempted"] and result2["global_step"] == stopped_at + 2
    leg2 = _read_losses(cfg2["loss_log"])
    assert min(leg2) == stopped_at + 1  # no step re-run, none skipped


def test_sigkill_mid_async_save_resume_bit_exact(tmp_path):
    """Crash consistency: SIGKILL the trainer right as the step-4 async save
    is in flight, restart, and the resumed loss trajectory must be BIT-exact
    vs an uninterrupted run — whether the kill landed before or after the
    async commit (uncommitted debris is cleaned, committed state resumes)."""
    _write_data(tmp_path / "data.jsonl")

    ref_cfg = _base_cfg(tmp_path, "ref_out", "ref.jsonl",
                        save_steps=4, async_save=True)
    proc = _spawn_driver(tmp_path, ref_cfg)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]
    ref = _read_losses(ref_cfg["loss_log"])
    assert sorted(ref) == list(range(1, 9))

    kill_cfg = _base_cfg(tmp_path, "kill_out", "kill1.jsonl",
                         save_steps=4, async_save=True, marker_at=4)
    proc = _spawn_driver(tmp_path, kill_cfg)
    _wait_for(kill_cfg["marker"], proc)
    proc.kill()  # SIGKILL: no handlers, no cleanup — a real crash
    proc.communicate(timeout=60)
    assert proc.returncode == -signal.SIGKILL
    assert not os.path.exists(kill_cfg["result"])

    resume_cfg = _base_cfg(tmp_path, "kill_out", "kill2.jsonl",
                           save_steps=4, async_save=True)
    proc = _spawn_driver(tmp_path, resume_cfg)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]
    result = json.load(open(resume_cfg["result"]))
    assert result["global_step"] == 8
    leg2 = _read_losses(resume_cfg["loss_log"])
    assert max(leg2) == 8
    for step, hexloss in leg2.items():
        assert ref[step] == hexloss, (
            f"step {step}: resumed loss {hexloss} != uninterrupted {ref[step]}"
        )


# ---------------------------------------------------------------------------
# integrity: manifest roundtrip + verify-mode matrix (resilience/integrity.py)
# ---------------------------------------------------------------------------

def _make_ckpt_tree(root):
    ts = root / "train_state"
    ts.mkdir(parents=True)
    (ts / "arr0.bin").write_bytes(bytes(range(256)) * 8)  # largest file
    (ts / "nested").mkdir()
    (ts / "nested" / "arr1.bin").write_bytes(b"hello world" * 10)
    (root / "extra_state.json").write_text('{"global_step": 3}')
    (root / "extra_state_rank0.json").write_text('{"dataloader": {}}')


def test_manifest_roundtrip_and_verify_matrix(tmp_path):
    from veomni_tpu.resilience import integrity

    step_dir = tmp_path / "global_step_3"
    _make_ckpt_tree(step_dir)
    integrity.write_manifest(str(step_dir))
    doc = integrity.read_manifest(str(step_dir))
    assert doc["version"] == integrity.MANIFEST_VERSION
    # payload subtree (incl. nested dirs) + both extra-state sidecars
    assert set(doc["files"]) == {
        os.path.join("train_state", "arr0.bin"),
        os.path.join("train_state", "nested", "arr1.bin"),
        "extra_state.json", "extra_state_rank0.json",
    }
    # off -> no report (unverified, not verified-clean); size/full pass
    assert integrity.verify_manifest(str(step_dir), mode="off") is None
    for mode in ("size", "full"):
        rep = integrity.verify_manifest(str(step_dir), mode=mode)
        assert rep.passed and rep.total == 4 and rep.problems == []
        assert "OK" in rep.summary()
    with pytest.raises(ValueError, match="unknown verify mode"):
        integrity.verify_manifest(str(step_dir), mode="paranoid")

    # BITFLIP keeps the size: invisible to "size", caught only by "full"
    payload = step_dir / "train_state" / "arr0.bin"
    raw = bytearray(payload.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    payload.write_bytes(bytes(raw))
    assert integrity.verify_manifest(str(step_dir), mode="size").passed
    rep = integrity.verify_manifest(str(step_dir), mode="full")
    assert [(p.path, p.kind) for p in rep.problems] == [
        (os.path.join("train_state", "arr0.bin"), "mismatch")]
    assert "CORRUPT" in rep.summary()

    # TRUNCATION: already caught by "size", classified as truncated
    raw2 = payload.read_bytes()
    payload.write_bytes(raw2[: len(raw2) // 2])
    rep = integrity.verify_manifest(str(step_dir), mode="size")
    assert [(p.path, p.kind) for p in rep.problems] == [
        (os.path.join("train_state", "arr0.bin"), "truncated")]

    # MISSING file
    payload.unlink()
    rep = integrity.verify_manifest(str(step_dir), mode="size")
    assert [(p.path, p.kind) for p in rep.problems] == [
        (os.path.join("train_state", "arr0.bin"), "missing")]

    # an unreadable or absent manifest is UNVERIFIABLE (None), never corrupt
    (step_dir / integrity.MANIFEST_NAME).write_text("{not json")
    assert integrity.verify_manifest(str(step_dir), mode="full") is None
    (step_dir / integrity.MANIFEST_NAME).unlink()
    assert integrity.verify_manifest(str(step_dir), mode="full") is None


def test_corrupt_fault_mode_truncate_and_bitflip(tmp_path):
    from veomni_tpu.resilience import faults

    d = tmp_path / "gen"
    d.mkdir()
    (d / "a.bin").write_bytes(b"x" * 10)
    (d / "b.bin").write_bytes(bytes(range(100)))

    # default target = LARGEST file under the context dir; bitflip keeps size
    faults.configure_faults([{"point": "ckpt.manifest", "mode": "corrupt"}])
    act = faults.fault_point("ckpt.manifest", context={"dir": str(d)})
    assert act is not None and act.mode == "corrupt"
    assert act.target == str(d / "b.bin")
    assert (d / "b.bin").stat().st_size == 100
    assert (d / "b.bin").read_bytes()[50] == 50 ^ 0xFF  # middle byte flipped
    assert (d / "a.bin").read_bytes() == b"x" * 10      # untouched

    # truncate op; context names the file directly
    faults.configure_faults([{"point": "data.record", "mode": "corrupt",
                              "op": "truncate"}])
    shard = tmp_path / "shard.jsonl"
    shard.write_bytes(b"y" * 64)
    act = faults.fault_point("data.record", context={"file": str(shard)})
    assert act.target == str(shard) and shard.stat().st_size == 32

    # glob-resolved explicit target + pinned offset
    faults.configure_faults([{"point": "ckpt.manifest", "mode": "corrupt",
                              "file": "*.bin", "offset": 0}])
    act = faults.fault_point("ckpt.manifest", context={"dir": str(d)})
    assert act.target == str(d / "a.bin")  # first sorted match
    assert (d / "a.bin").read_bytes()[0] == ord("x") ^ 0xFF

    with pytest.raises(ValueError, match="unknown corrupt op"):
        faults.configure_faults([{"point": "ckpt.manifest", "mode": "corrupt",
                                  "op": "melt"}])


# ---------------------------------------------------------------------------
# integrity: checkpointer quarantine + multi-generation restore fallback
# ---------------------------------------------------------------------------

def _corrupt_payload(step_dir, op="truncate"):
    """Damage the largest payload file of a committed generation in place."""
    best, best_size = None, -1
    for dirpath, _dirs, files in os.walk(os.path.join(step_dir, "train_state")):
        for f in files:
            full = os.path.join(dirpath, f)
            size = os.path.getsize(full)
            if size > best_size:
                best, best_size = full, size
    with open(best, "r+b") as f:
        if op == "truncate":
            f.truncate(best_size // 2)
        else:
            f.seek(best_size // 2)
            b = f.read(1)
            f.seek(best_size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
    return best


def test_ckpt_quarantine_and_multi_generation_fallback(tmp_path):
    import jax
    import jax.numpy as jnp

    from veomni_tpu.checkpoint import build_checkpointer
    from veomni_tpu.observability.metrics import get_registry
    from veomni_tpu.resilience import CheckpointCorruptError

    reg = get_registry()
    q0 = reg.counter("integrity.ckpt_quarantined").value
    f0 = reg.counter("integrity.ckpt_fallbacks").value

    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            verify_mode="size")
    state = None
    for step in (1, 2, 3):
        state = {"w": jnp.full((128,), float(step), jnp.float32)}
        ck.save(step, state, extra_state={"global_step": step})
    assert ck.list_steps() == [1, 2, 3]
    for step in (1, 2, 3):  # sync saves wrote their manifests immediately
        assert os.path.exists(os.path.join(
            ck.ckpt_dir, f"global_step_{step}", "manifest.json"))

    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), state)

    # newest TWO generations rot: restore quarantines both, lands on step 1
    _corrupt_payload(os.path.join(ck.ckpt_dir, "global_step_3"))
    _corrupt_payload(os.path.join(ck.ckpt_dir, "global_step_2"))
    restored, extra = ck.load(abstract)
    assert int(extra["global_step"]) == 1
    assert float(np.asarray(restored["w"])[0]) == 1.0
    assert ck.list_steps() == [1] and ck.latest_step() == 1
    assert os.path.isdir(os.path.join(ck.ckpt_dir, "global_step_3.corrupt"))
    assert os.path.isdir(os.path.join(ck.ckpt_dir, "global_step_2.corrupt"))
    assert not os.path.isdir(os.path.join(ck.ckpt_dir, "global_step_3"))
    assert reg.counter("integrity.ckpt_quarantined").value - q0 == 2
    assert reg.counter("integrity.ckpt_fallbacks").value - f0 == 2

    # the last generation rots too: clean abort with actionable guidance
    _corrupt_payload(os.path.join(ck.ckpt_dir, "global_step_1"))
    with pytest.raises(CheckpointCorruptError, match="no trustworthy state"):
        ck.load(abstract)
    assert ck.list_steps() == []
    ck.close()


def test_resave_supersedes_quarantined_step_same_process(tmp_path):
    """A quarantine must not block a later legitimate save() of the same
    step IN THE SAME PROCESS (the supervisor-rollback timeline: quarantine
    step N, restore older, train forward past N again): the re-save must
    dispatch a fresh generation — not be deduped as "already dispatched" —
    and that generation must be offered by list_steps/latest_step again."""
    import jax
    import jax.numpy as jnp

    from veomni_tpu.checkpoint import build_checkpointer
    from veomni_tpu.resilience import CheckpointCorruptError

    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            verify_mode="size")
    for step in (1, 2):
        ck.save(step, {"w": jnp.full((64,), float(step), jnp.float32)},
                extra_state={"global_step": step})
    _corrupt_payload(os.path.join(ck.ckpt_dir, "global_step_2"))
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        {"w": jnp.zeros((64,), jnp.float32)})
    restored, extra = ck.load(abstract)  # quarantines 2, falls back to 1
    assert int(extra["global_step"]) == 1 and ck.latest_step() == 1

    # the run trains forward and saves step 2 again: fresh healthy bytes
    ck.save(2, {"w": jnp.full((64,), 2.0, jnp.float32)},
            extra_state={"global_step": 2})
    assert ck.list_steps() == [1, 2] and ck.latest_step() == 2
    assert os.path.isdir(os.path.join(ck.ckpt_dir, "global_step_2"))
    assert os.path.isdir(os.path.join(ck.ckpt_dir, "global_step_2.corrupt"))
    restored2, extra2 = ck.load(abstract)  # the new generation verifies
    assert int(extra2["global_step"]) == 2
    assert float(np.asarray(restored2["w"])[0]) == 2.0
    ck.close()


def test_resave_after_failed_quarantine_rename_clears_corpse(tmp_path, monkeypatch):
    """If the quarantine rename itself fails (EBUSY/ESTALE on the flaky
    shared fs this layer targets), the corrupt dir stays at the live path.
    A later superseding save() of that step must clear the corpse (rename
    retry, then deletion) instead of dispatching Orbax into the existing
    dir and dying on an unretried 'destination already exists'."""
    import jax
    import jax.numpy as jnp

    from veomni_tpu.checkpoint import build_checkpointer

    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            verify_mode="size")
    for step in (1, 2):
        ck.save(step, {"w": jnp.full((64,), float(step), jnp.float32)},
                extra_state={"global_step": step})
    _corrupt_payload(os.path.join(ck.ckpt_dir, "global_step_2"))

    # every .corrupt rename fails; Orbax's own commit renames stay live
    real_rename = os.rename

    def flaky_rename(src, dst, *a, **kw):
        if ".corrupt" in str(dst):
            raise OSError("ESTALE: simulated shared-fs rename failure")
        return real_rename(src, dst, *a, **kw)

    monkeypatch.setattr(os, "rename", flaky_rename)

    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        {"w": jnp.zeros((64,), jnp.float32)})
    restored, extra = ck.load(abstract)  # quarantine rename fails in-flight
    assert int(extra["global_step"]) == 1
    # the corpse still occupies the live path, excluded only in-memory
    assert os.path.isdir(os.path.join(ck.ckpt_dir, "global_step_2"))
    assert ck.latest_step() == 1

    # superseding save: rename retry fails again -> deletion fallback
    ck.save(2, {"w": jnp.full((64,), 2.0, jnp.float32)},
            extra_state={"global_step": 2})
    assert ck.list_steps() == [1, 2]
    restored2, extra2 = ck.load(abstract)
    assert int(extra2["global_step"]) == 2
    assert float(np.asarray(restored2["w"])[0]) == 2.0
    ck.close()


def test_ckpt_verify_mode_gates_bitflip_detection(tmp_path):
    import jax
    import jax.numpy as jnp

    from veomni_tpu.checkpoint import build_checkpointer

    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            verify_mode="full")
    state = {"w": jnp.arange(1024, dtype=jnp.float32)}
    ck.save(1, state, extra_state={"global_step": 1})
    ck.save(2, state, extra_state={"global_step": 2})
    _corrupt_payload(os.path.join(ck.ckpt_dir, "global_step_2"), op="bitflip")

    # a size-mode verify misses the same-size bitflip...
    ck_size = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                                 verify_mode="size")
    rep = ck_size.verify_step(2)
    assert rep is not None and rep.passed

    # ...the full-mode gate catches it, quarantines, falls back to step 1
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), state)
    restored, extra = ck.load(abstract)
    assert int(extra["global_step"]) == 1
    assert os.path.isdir(os.path.join(ck.ckpt_dir, "global_step_2.corrupt"))

    # off-mode never verifies; bogus mode rejected at construction
    ck_off = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                                verify_mode="off")
    assert ck_off.verify_step(1) is None
    with pytest.raises(ValueError, match="unknown ckpt verify mode"):
        build_checkpointer(str(tmp_path / "x"), verify_mode="paranoid")
    for c in (ck, ck_size, ck_off):
        c.close()


def test_quarantined_dirs_age_out_beyond_max_to_keep(tmp_path):
    import jax.numpy as jnp

    from veomni_tpu.checkpoint import build_checkpointer

    ck = build_checkpointer(str(tmp_path / "ck"), async_save=False,
                            max_to_keep=2)
    # three pre-existing corpses (incl. a rename-collision suffix)
    for name in ("global_step_1.corrupt", "global_step_2.corrupt",
                 "global_step_3.corrupt.1"):
        d = tmp_path / "ck" / name / "train_state"
        d.mkdir(parents=True)
        (d / "junk.bin").write_bytes(b"z" * 8)
    ck.save(10, {"w": jnp.zeros(4)}, extra_state={"global_step": 10})
    corpses = sorted(d for d in os.listdir(tmp_path / "ck")
                     if ".corrupt" in d)
    # newest max_to_keep corpses stay for post-mortem, the oldest is reaped
    assert corpses == ["global_step_2.corrupt", "global_step_3.corrupt.1"]
    assert ck.list_steps() == [10]
    ck.close()


# ---------------------------------------------------------------------------
# integrity: streaming shard provenance + poison-record skip budget
# ---------------------------------------------------------------------------

def test_shard_decode_errors_carry_provenance(tmp_path):
    from veomni_tpu.data.streaming import _open_shard
    from veomni_tpu.resilience import ShardRecordError

    shard = tmp_path / "00.jsonl"
    shard.write_text('{"i": 0}\n{oops not json\n{"i": 2}\n')
    reader = _open_shard(str(shard))
    assert reader.read(0) == {"i": 0}
    with pytest.raises(ShardRecordError) as ei:
        reader.read(1)
    assert ei.value.shard == str(shard) and ei.value.record == 1
    assert "00.jsonl" in str(ei.value) and "record 1" in str(ei.value)
    assert reader.read(2) == {"i": 2}  # neighbors unaffected

    # tar member rot: same provenance contract, member named in the detail
    import io
    import tarfile

    tar_path = tmp_path / "01.tar"
    with tarfile.open(tar_path, "w") as tf:
        for name, payload in (("s0.json", b'{"i": 0}'), ("s1.json", b"{rot")):
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))
    treader = _open_shard(str(tar_path))
    assert treader.read(0) == {"i": 0}
    with pytest.raises(ShardRecordError) as ei:
        treader.read(1)
    assert ei.value.record == 1 and "member .json" in str(ei.value)


def _poison_shard_dir(tmp_path, n=10, poison=(4,), name="shards"):
    shard_dir = tmp_path / name
    shard_dir.mkdir(exist_ok=True)
    lines = ["{rot}" if i in poison else json.dumps({"i": i})
             for i in range(n)]
    (shard_dir / "00.jsonl").write_text("\n".join(lines) + "\n")
    return shard_dir


def test_poison_skip_budget_sequential_and_fail_fast(tmp_path):
    from veomni_tpu.data.streaming import StreamingShardDataset
    from veomni_tpu.resilience import ShardRecordError

    shard_dir = _poison_shard_dir(tmp_path, n=10, poison=(4,))

    # budget 0 (the default): fail FAST with shard+record provenance
    ds0 = StreamingShardDataset(str(shard_dir), shuffle=False,
                                retry_base_s=0.001)
    with pytest.raises(ShardRecordError) as ei:
        list(ds0)
    assert ei.value.record == 4 and "00.jsonl" in str(ei.value)
    assert "skip budget exhausted" in str(ei.value)

    # budget 1: the poisoned record is dropped, order otherwise preserved
    ds = StreamingShardDataset(str(shard_dir), shuffle=False,
                               retry_base_s=0.001, skip_budget=1)
    got = [r["i"] for r in ds]
    assert got == [i for i in range(10) if i != 4]
    assert ds.state_dict()["skipped"] == [["00.jsonl", 4]]

    # epoch 2 re-skips the same record WITHOUT consuming fresh budget
    got2 = [r["i"] for r in ds]
    assert got2 == got and len(ds.state_dict()["skipped"]) == 1

    # two poisons against a budget of one: exhaustion carries the history
    shard_dir2 = _poison_shard_dir(tmp_path, n=10, poison=(2, 7), name="s2")
    ds2 = StreamingShardDataset(str(shard_dir2), shuffle=False,
                                retry_base_s=0.001, skip_budget=1)
    with pytest.raises(ShardRecordError) as ei:
        list(ds2)
    assert ei.value.record == 7 and "already skipped" in str(ei.value)


def test_poison_skip_replay_across_state_roundtrip(tmp_path):
    from veomni_tpu.data.streaming import StreamingShardDataset

    shard_dir = _poison_shard_dir(tmp_path, n=12, poison=(2, 7))

    def build():
        return StreamingShardDataset(str(shard_dir), shuffle=True, seed=5,
                                     retry_base_s=0.001, skip_budget=2)

    ref = build()
    ref_rows = [r["i"] for r in ref]
    assert len(ref_rows) == 10 and len(ref.state_dict()["skipped"]) == 2

    # consume part of the epoch, snapshot mid-stream, resume in a FRESH
    # dataset: the combined row sequence and the final skip history must be
    # identical to the uninterrupted epoch
    a = build()
    it = iter(a)
    first = [next(it)["i"] for _ in range(4)]
    snap = a.state_dict()
    b = build()
    b.load_state_dict(snap)
    rest = [r["i"] for r in b]
    assert first + rest == ref_rows
    assert b.state_dict()["skipped"] == ref.state_dict()["skipped"]


def test_poison_getitem_substitutes_deterministically(tmp_path):
    from veomni_tpu.data.streaming import StreamingShardDataset
    from veomni_tpu.resilience import ShardRecordError

    shard_dir = _poison_shard_dir(tmp_path, n=6, poison=(3,))
    ds = StreamingShardDataset(str(shard_dir), shuffle=False,
                               retry_base_s=0.001, skip_budget=1)
    assert len(ds) == 6
    # linear access substitutes the NEXT healthy record for the poisoned one
    # (batch shapes must stay full), stable across repeated access
    assert ds[3]["i"] == 4 and ds[3]["i"] == 4
    assert ds[2]["i"] == 2 and ds[4]["i"] == 4
    assert ds.state_dict()["skipped"] == [["00.jsonl", 3]]

    # the substitution survives a state roundtrip
    ds2 = StreamingShardDataset(str(shard_dir), shuffle=False,
                                retry_base_s=0.001, skip_budget=1)
    ds2.load_state_dict(ds.state_dict())
    assert ds2[3]["i"] == 4

    # budget 0: the same access fails fast instead of substituting
    ds3 = StreamingShardDataset(str(shard_dir), shuffle=False,
                                retry_base_s=0.001)
    with pytest.raises(ShardRecordError):
        ds3[3]


def test_validate_hook_feeds_skip_budget(tmp_path):
    from veomni_tpu.data.streaming import StreamingShardDataset
    from veomni_tpu.resilience import ShardRecordError

    shard_dir = tmp_path / "vshards"
    shard_dir.mkdir()
    with open(shard_dir / "00.jsonl", "w") as f:
        for i in range(6):
            f.write(json.dumps({"i": i}) + "\n")

    def validate(row):
        return row["i"] != 2

    ds = StreamingShardDataset(str(shard_dir), shuffle=False,
                               retry_base_s=0.001, skip_budget=1,
                               validate=validate)
    assert [r["i"] for r in ds] == [0, 1, 3, 4, 5]
    ds0 = StreamingShardDataset(str(shard_dir), shuffle=False,
                                retry_base_s=0.001, validate=validate)
    with pytest.raises(ShardRecordError, match="validation hook"):
        list(ds0)


def test_retry_counters_and_exhaustion_log():
    import logging

    from veomni_tpu.observability.metrics import get_registry
    from veomni_tpu.resilience.retry import RetryPolicy, retry_call

    reg = get_registry()
    a0 = reg.counter("retry.attempts").value
    e0 = reg.counter("retry.exhausted").value

    def doomed():
        raise OSError("disk on fire")

    records = []
    handler = logging.Handler()
    handler.emit = records.append
    target = logging.getLogger("veomni_tpu.resilience.retry")
    target.addHandler(handler)
    try:
        with pytest.raises(OSError):
            retry_call(doomed, policy=RetryPolicy(retries=2, base_delay_s=0.5),
                       sleep=lambda _: None, description="probe")
    finally:
        target.removeHandler(handler)
    assert reg.counter("retry.attempts").value - a0 == 2
    assert reg.counter("retry.exhausted").value - e0 == 1
    final = [r.getMessage() for r in records
             if "exhausted" in r.getMessage()]
    # evidence the retries happened rides the final-failure line
    assert final and "3 attempt(s)" in final[0]
    assert "total backoff" in final[0]


# ---------------------------------------------------------------------------
# integrity: real-process drills (acceptance criteria)
# ---------------------------------------------------------------------------

def test_subprocess_corrupt_ckpt_quarantine_fallback_bit_exact(tmp_path):
    """A corrupt-mode fault plan flips bytes in the newest committed
    generation right after its manifest is written; the resumed run must
    quarantine it, restore the previous generation, and replay to the end
    with a loss trajectory BIT-exact vs an uncorrupted control run."""
    _write_data(tmp_path / "data.jsonl")

    # constant LR: the cosine default bakes train_steps into every update,
    # and the three legs train different horizons
    # control: uninterrupted 8-step run over the same data/seed
    ctl_cfg = _base_cfg(tmp_path, "ictl_out", "ictl.jsonl", save_steps=2,
                        lr_decay_style="constant")
    proc = _spawn_driver(tmp_path, ctl_cfg)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]
    ref = _read_losses(ctl_cfg["loss_log"])
    assert sorted(ref) == list(range(1, 9))

    # leg 1: checkpoints at steps 2 and 4; the ckpt.manifest corrupt fault
    # (hit 2 = the step-4 manifest) bitflips the step-4 payload AFTER its
    # digests were recorded — the storage-rot timeline
    leg1_cfg = _base_cfg(tmp_path, "ivic_out", "ivic1.jsonl",
                         train_steps=4, save_steps=2,
                         lr_decay_style="constant")
    plan = [{"point": "ckpt.manifest", "mode": "corrupt", "hit": 2,
             "op": "bitflip"}]
    proc = _spawn_driver(tmp_path, leg1_cfg,
                         extra_env={"VEOMNI_FAULT_PLAN": json.dumps(plan)})
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]
    ck_dir = os.path.join(leg1_cfg["out"], "checkpoints")
    assert os.path.isdir(os.path.join(ck_dir, "global_step_4"))  # committed

    # leg 2: resume under full verification — step 4 quarantined, step 2
    # restored, steps 3-8 replayed
    leg2_cfg = _base_cfg(tmp_path, "ivic_out", "ivic2.jsonl",
                         save_steps=0, ckpt_verify="full",
                         lr_decay_style="constant")
    proc = _spawn_driver(tmp_path, leg2_cfg)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]
    result = json.load(open(leg2_cfg["result"]))
    assert result["global_step"] == 8
    assert os.path.isdir(os.path.join(ck_dir, "global_step_4.corrupt"))
    assert not os.path.isdir(os.path.join(ck_dir, "global_step_4"))
    leg2 = _read_losses(leg2_cfg["loss_log"])
    assert sorted(leg2) == list(range(3, 9))  # resumed from step 2
    for step, hexloss in leg2.items():
        assert ref[step] == hexloss, (
            f"step {step}: post-fallback loss {hexloss} != control {ref[step]}"
        )


def test_subprocess_data_skip_budget_across_resume_and_exhaustion(tmp_path):
    """With ``train.data_skip_budget=1`` a poisoned streaming record is
    skipped deterministically across a save/restore boundary (trajectory
    bit-exact vs an uninterrupted run over the same poisoned corpus, skip
    recorded in the restored rank state); with the default budget of 0 the
    same corpus fails fast with shard+record provenance."""
    # sized to the packing collator's demand-driven offer: with the pinned
    # 4-device topology below it requests samples_per_micro_batch*local_mb
    # = 64 raw samples per batch, so 64 records = every record (incl. the
    # poison) is offered from step 1 on — and a smaller corpus would starve
    # the offer loop outright
    shard_dir = tmp_path / "stream_shards"
    shard_dir.mkdir()
    rng = np.random.default_rng(0)
    poison_idx = 7
    with open(shard_dir / "00.jsonl", "w") as f:
        for i in range(64):
            if i == poison_idx:
                f.write("{this is not json\n")
                continue
            f.write(json.dumps({
                "input_ids": rng.integers(
                    0, 256, int(rng.integers(16, 80))).tolist(),
            }) + "\n")

    # constant LR: the cosine default bakes train_steps into every update,
    # and the legs train different horizons. The device topology is pinned
    # (not inherited from the pytest process) so batch assembly — and with
    # it which records each step consumes — is identical across legs
    # however the suite is invoked.
    xla4 = {"XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    common = dict(dataset_type="streaming", data_skip_budget=1,
                  lr_decay_style="constant")

    ctl = _base_cfg(tmp_path, "sctl_out", "sctl.jsonl", save_steps=2, **common)
    ctl["data"] = str(shard_dir)
    proc = _spawn_driver(tmp_path, ctl, extra_env=xla4)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]
    ref = _read_losses(ctl["loss_log"])
    assert sorted(ref) == list(range(1, 9))
    ctl_result = json.load(open(ctl["result"]))
    assert ctl_result["dataset_state"]["skipped"] == [["00.jsonl", poison_idx]]

    leg1 = _base_cfg(tmp_path, "svic_out", "svic1.jsonl",
                     train_steps=4, save_steps=2, **common)
    leg1["data"] = str(shard_dir)
    proc = _spawn_driver(tmp_path, leg1, extra_env=xla4)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]

    leg2 = _base_cfg(tmp_path, "svic_out", "svic2.jsonl", save_steps=0,
                     **common)
    leg2["data"] = str(shard_dir)
    proc = _spawn_driver(tmp_path, leg2, extra_env=xla4)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err[-2000:]
    result = json.load(open(leg2["result"]))
    assert result["global_step"] == 8
    # the restored run carries the identical skip record
    assert result["dataset_state"]["skipped"] == [["00.jsonl", poison_idx]]
    leg2_losses = _read_losses(leg2["loss_log"])
    assert sorted(leg2_losses) == list(range(5, 9))  # resumed from step 4
    for step, hexloss in leg2_losses.items():
        assert ref[step] == hexloss, (
            f"step {step}: post-resume loss {hexloss} != control {ref[step]}"
        )

    # budget exhaustion: same corpus, budget 0 -> fast failure w/ provenance
    fail = _base_cfg(tmp_path, "sfail_out", "sfail.jsonl",
                     dataset_type="streaming", data_skip_budget=0)
    fail["data"] = str(shard_dir)
    proc = _spawn_driver(tmp_path, fail, extra_env=xla4)
    out, err = proc.communicate(timeout=300)
    assert proc.returncode != 0
    assert "00.jsonl" in err and f"record {poison_idx}" in err
    assert "skip budget exhausted" in err
