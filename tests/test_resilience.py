"""Adversarial resilience tests (VERDICT r4 weak #8): capacity-overflow
TRAINING behavior and dataloader resume across a topology change."""

import json
import os

import numpy as np
import pytest

from veomni_tpu.arguments import VeOmniArguments


def _write_data(path, n=96, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            f.write(json.dumps({
                "input_ids": rng.integers(0, vocab, int(rng.integers(16, 80))).tolist(),
            }) + "\n")


def _args(tmp_path, **overrides):
    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen3_moe", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
        "qk_norm": True, "num_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 32, **overrides,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 4
    args.train.lr = 1e-3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 1
    return args


def test_capacity_overflow_training_stays_finite(tmp_path):
    """A drastically undersized expert capacity (most tokens dropped) must
    degrade throughput, not stability: finite loss/grad at every step."""
    from veomni_tpu.trainer import TextTrainer

    _write_data(tmp_path / "data.jsonl")
    args = _args(tmp_path, moe_capacity_factor=0.25)
    trainer = TextTrainer(args)
    losses = []

    from veomni_tpu.trainer.callbacks import Callback

    class Rec(Callback):
        def on_step_end(self, t, state):
            if state.synced:
                losses.append(float(state.metrics["loss"]))
                assert np.isfinite(state.metrics["grad_norm"])

    trainer.callbacks.append(Rec())
    ctl = trainer.train()
    assert ctl.global_step == 4
    assert all(np.isfinite(l) for l in losses) and len(losses) == 4
    trainer.checkpointer.close()


def test_resume_after_topology_change_warns_and_continues(tmp_path):
    """A checkpoint whose per-rank extra state doesn't cover this rank
    (process count changed between save and resume) must warn about the
    dataloader cursor and still restore the train state + continue."""
    from veomni_tpu.trainer import TextTrainer

    _write_data(tmp_path / "data.jsonl")
    args = _args(tmp_path)
    args.train.save_steps = 2
    trainer = TextTrainer(args)
    trainer.train()
    trainer.checkpointer.close()

    # simulate "saved by a different topology": this rank's extra-state file
    # is absent, another rank's is present
    step_dir = os.path.join(args.train.output_dir, "checkpoints", "global_step_4")
    os.rename(
        os.path.join(step_dir, "extra_state_rank0.json"),
        os.path.join(step_dir, "extra_state_rank7.json"),
    )

    args2 = _args(tmp_path)
    args2.train.train_steps = 6
    trainer2 = TextTrainer(args2)
    import logging

    records = []
    handler = logging.Handler()
    handler.emit = records.append
    target = logging.getLogger("veomni_tpu.checkpoint.checkpointer")
    target.addHandler(handler)
    try:
        restored, extra = trainer2.try_resume()
    finally:
        target.removeHandler(handler)
    assert restored
    assert any("topology" in r.getMessage() for r in records)
    # training continues from the restored params
    ctl = trainer2.train()
    assert ctl.global_step == 6
    assert np.isfinite(ctl.metrics["loss"])
    trainer2.checkpointer.close()
