"""Top-k distillation loss: chunked op parity + trainer e2e.

Reference semantics: ``veomni/ops/kernels/cross_entropy/chunk_topk_distill.py``
(forward KL on the teacher's top-k support; log_probs/entropy shared with the
chunk_logprobs path; mass terms metrics-only).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.ops.cross_entropy import IGNORE_INDEX, _topk_distill_chunked


def _dense_reference(hidden, kernel, labels, t_ids, t_lp, temperature=1.0,
                     clamp=None):
    """Unchunked direct computation of all five outputs."""
    logits = (hidden.astype(jnp.float32) @ kernel.astype(jnp.float32))
    if temperature != 1.0:
        logits = logits / temperature
    valid = labels != IGNORE_INDEX
    lab = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    gold = jnp.take_along_axis(logp, lab[:, None], 1)[:, 0]
    p = jnp.exp(logp)
    ent = -(p * logp).sum(-1)
    s_lp = jnp.take_along_axis(logp, t_ids, 1)
    t32 = t_lp.astype(jnp.float32)
    if clamp is not None:
        s_lp = jnp.maximum(s_lp, clamp)
        t32 = jnp.maximum(t32, clamp)
    pt = jnp.exp(t32)
    dist = (pt * (t32 - s_lp)).sum(-1)
    z = jnp.zeros_like(gold)
    raw_logp = jax.nn.log_softmax(
        hidden.astype(jnp.float32) @ kernel.astype(jnp.float32), axis=-1
    )
    raw_gold = jnp.take_along_axis(raw_logp, lab[:, None], 1)[:, 0]
    return {
        "nll": jnp.where(valid, -raw_gold, z),
        "log_probs": jnp.where(valid, gold, z),
        "entropy": jnp.where(valid, ent, z),
        "distill": jnp.where(valid, dist, z),
        "student_mass": jax.lax.stop_gradient(
            jnp.where(valid, jnp.exp(s_lp).sum(-1), z)
        ),
        "teacher_mass": jax.lax.stop_gradient(jnp.where(valid, pt.sum(-1), z)),
    }


def _make_inputs(t=37, h=16, v=64, k=4, seed=0):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.normal(size=(t, h)), jnp.float32)
    kernel = jnp.asarray(rng.normal(size=(h, v)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, t), jnp.int32)
    labels = labels.at[::7].set(IGNORE_INDEX)  # sprinkle ignored positions
    t_ids = jnp.asarray(
        np.stack([rng.choice(v, k, replace=False) for _ in range(t)]), jnp.int32
    )
    # a proper sub-distribution: softmax logprobs restricted to k slots
    raw = rng.normal(size=(t, k))
    t_lp = jnp.asarray(raw - np.log(np.exp(raw).sum(-1, keepdims=True)) - 0.3,
                       jnp.float32)
    return hidden, kernel, labels, t_ids, t_lp


@pytest.mark.parametrize("chunk", [8, 64])  # 37 % 8 != 0 exercises padding
def test_distill_chunked_matches_dense(chunk):
    hidden, kernel, labels, t_ids, t_lp = _make_inputs()
    got = _topk_distill_chunked(
        hidden, kernel, labels, t_ids, t_lp, chunk_size=chunk
    )
    want = _dense_reference(hidden, kernel, labels, t_ids, t_lp)
    for name in want:
        np.testing.assert_allclose(
            np.asarray(got[name]), np.asarray(want[name]), rtol=1e-5,
            atol=1e-5, err_msg=name,
        )
    # sign contracts (reference docstring): logp <= 0, entropy/KL/mass >= 0
    assert float(jnp.max(got["log_probs"])) <= 1e-6
    assert float(jnp.min(got["entropy"])) >= -1e-6
    assert float(jnp.min(got["distill"])) >= -1e-5


def test_distill_grads_match_dense_and_mass_detached():
    hidden, kernel, labels, t_ids, t_lp = _make_inputs()

    def total(fn):
        def f(h, w):
            out = fn(h, w)
            # mass terms are stop_gradient'ed; including them must not
            # perturb the gradient of the differentiable outputs
            return (out["distill"].sum() + 0.1 * out["log_probs"].sum()
                    + out["student_mass"].sum())
        return f

    g_chunk = jax.grad(total(
        lambda h, w: _topk_distill_chunked(h, w, labels, t_ids, t_lp,
                                           chunk_size=8)), argnums=(0, 1)
    )(hidden, kernel)
    g_dense = jax.grad(total(
        lambda h, w: _dense_reference(h, w, labels, t_ids, t_lp)),
        argnums=(0, 1)
    )(hidden, kernel)
    for gc, gd in zip(g_chunk, g_dense):
        np.testing.assert_allclose(np.asarray(gc), np.asarray(gd),
                                   rtol=1e-4, atol=1e-5)
    # ignored positions carry zero hidden-gradient
    ignored = np.asarray(labels) == IGNORE_INDEX
    assert float(jnp.abs(g_chunk[0][ignored]).max()) == 0.0


def test_distill_temperature_and_clamp():
    hidden, kernel, labels, t_ids, t_lp = _make_inputs()
    for kw in ({"temperature": 2.0}, {"log_prob_min_clamp": -1.5}):
        got = _topk_distill_chunked(
            hidden, kernel, labels, t_ids, t_lp, chunk_size=16,
            **kw,
        )
        want = _dense_reference(
            hidden, kernel, labels, t_ids, t_lp,
            temperature=kw.get("temperature", 1.0),
            clamp=kw.get("log_prob_min_clamp"),
        )
        np.testing.assert_allclose(
            np.asarray(got["distill"]), np.asarray(want["distill"]),
            rtol=1e-5, atol=1e-5,
        )
    # perfectly matching teacher ==> zero KL on the support
    logits = hidden @ kernel
    logp = jax.nn.log_softmax(logits, -1)
    ids = jnp.argsort(-logits, axis=-1)[:, :4].astype(jnp.int32)
    perfect = jnp.take_along_axis(logp, ids, 1)
    out = _topk_distill_chunked(hidden, kernel, labels, ids, perfect,
                                chunk_size=16)
    np.testing.assert_allclose(np.asarray(out["distill"]), 0.0, atol=1e-5)


def test_distill_collator_ragged_teacher():
    """Rows with fewer teacher columns than distill_topk (or fewer teacher
    tokens than input tokens) fill with zero-weight slots, not a crash."""
    from veomni_tpu.trainer.distill_trainer import DistillCollator

    col = DistillCollator(seq_len=16, micro_batch_size=1, topk=8)
    batch = col([{
        "input_ids": list(range(10)),
        "teacher_topk_ids": [[1, 2]] * 6,          # 2 cols < topk, 6 tok < 10
        "teacher_topk_log_probs": [[-0.5, -1.0]] * 6,
    }])
    assert batch["teacher_topk_ids"].shape == (1, 16, 8)
    # present slots kept, absent slots carry ~zero probability mass
    assert batch["teacher_topk_log_probs"][0, 0, 0] == -0.5
    assert np.exp(batch["teacher_topk_log_probs"][0, 0, 7]) == 0.0
    assert np.exp(batch["teacher_topk_log_probs"][0, 9, 0]) == 0.0
    with pytest.raises(ValueError, match="shape mismatch"):
        col([{
            "input_ids": [1, 2, 3],
            "teacher_topk_ids": [[1]] * 3,
            "teacher_topk_log_probs": [[-0.5, -1.0]] * 3,
        }])


def test_distill_trainer_e2e(tmp_path):
    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.trainer.distill_trainer import DistillTrainer

    rng = np.random.default_rng(0)
    v, k = 128, 4
    with open(tmp_path / "distill.jsonl", "w") as f:
        for _ in range(32):
            n = int(rng.integers(8, 24))
            lp = rng.normal(size=(n, k))
            lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True)) - 0.2
            f.write(json.dumps({
                "input_ids": rng.integers(0, v, n).tolist(),
                "teacher_topk_ids": rng.integers(0, v, (n, k)).tolist(),
                "teacher_topk_log_probs": lp.tolist(),
            }) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen2", "vocab_size": v, "hidden_size": 32,
        "intermediate_size": 64, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 8,
        "attention_bias": True,
    }
    args.data.train_path = str(tmp_path / "distill.jsonl")
    args.data.data_type = "distill"
    args.data.max_seq_len = 32
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.distill_topk = k
    args.train.distill_kl_coef = 0.5
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = False
    args.train.log_steps = 100
    trainer = DistillTrainer(args)
    ctl = trainer.train()
    assert ctl.global_step == 3
    assert np.isfinite(ctl.metrics["loss"])
    assert np.isfinite(ctl.metrics["distill_kl"])
    assert 0.0 < ctl.metrics["teacher_mass"] <= 1.0 + 1e-5
    trainer.checkpointer.close()
