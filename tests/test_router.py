"""Scale-out router: affinity, parity, QoS-at-the-front-door, failure.

The load-bearing guarantees, layered on the engine's own:

* **Single-replica transparency** — a router over ONE replica is
  behavior-identical to the bare engine: token-exact outputs, identical
  shed/deadline semantics (a representative slice of the test_serving /
  test_serve_qos contracts driven through the router).
* **Fleet parity** — shared-prefix traffic fanned over 2–4 replicas still
  matches ``greedy_generate`` per request exactly; affinity and spill
  change WHERE a request runs, never WHAT it generates.
* **Failure sheds, never corrupts** — killing a replica mid-storm leaves
  survivors token-exact with zero leaked blocks, and every request that
  was on the dead replica reaches a terminal status (re-dispatched and
  completed, or ``cancelled``) — nothing hangs.
* **Elasticity is free** — added replicas share the compiled-program
  bundle (zero new traces) and drain out with no lost or duplicated ids.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.models import decode as decode_mod
from veomni_tpu.models.decode import greedy_generate
from veomni_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    Request,
    SamplingParams,
)
from veomni_tpu.serving.replica import STATE_DETACHED, STATE_DRAINING
from veomni_tpu.serving.router import Router, RouterConfig

QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)


@pytest.fixture(scope="module")
def qwen3():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


def _prompts(lengths, seed=0, vocab=128):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, vocab, n)] for n in lengths]


def _shared_prefix_prompts(n, prefix_len=16, tail=8, seed=0, groups=2):
    """``n`` prompts drawn from ``groups`` distinct shared prefixes with
    random tails — the workload affinity routing exists for."""
    rng = np.random.default_rng(seed)
    prefixes = [[int(t) for t in rng.integers(1, 128, prefix_len)]
                for _ in range(groups)]
    return [prefixes[i % groups]
            + [int(t) for t in rng.integers(1, 128, tail)]
            for i in range(n)]


def _pool_identity(eng):
    """The no-leak identity: every non-cached block on the free list, every
    cached block refcount-0, nothing still attributed to a sequence."""
    bm = eng.blocks
    assert bm.num_used == 0
    assert bm.num_free_uncached + bm.num_cached == bm.num_blocks - 1
    if eng.prefix_cache is not None:
        assert all(bm.refcount(b) == 0 for b in eng.prefix_cache._by_block)


def _greedy_refs(params, cfg, prompts, n_new):
    return {tuple(p): greedy_generate(params, cfg, p,
                                      max_new_tokens=n_new)[len(p):]
            for p in prompts}


# ---------------------------------------------------------- affinity + spill
def test_affinity_key_deterministic_and_block_aligned(qwen3):
    """The affinity key hashes the LEADING full blocks only: same prefix
    -> same key regardless of tail; different prefix -> (a.s.) different
    key; sub-block prompts key on the whole prompt."""
    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ), RouterConfig(replicas=2, affinity_blocks=2))
    prefix = list(range(1, 17))  # two full 8-token blocks
    k1 = r._affinity_key(prefix + [99, 98, 97])
    k2 = r._affinity_key(prefix + [55])
    k3 = r._affinity_key(prefix)
    assert k1 == k2 == k3
    assert r._affinity_key([7] + prefix) != k1
    # short prompts: whole-prompt key, still deterministic
    assert r._affinity_key([1, 2, 3]) == r._affinity_key([1, 2, 3])
    assert r._affinity_key([1, 2, 3]) != r._affinity_key([1, 2, 4])
    # rendezvous target is a pure function of (key, live set)
    live = r.live_replicas()
    assert r._affinity_target(k1, live).rid == r._affinity_target(
        k1, list(reversed(live))).rid


def test_rendezvous_stability_under_membership_change(qwen3):
    """Removing one replica only moves the keys it owned; keys owned by
    survivors keep their target (the property that keeps caches warm
    through elastic resizes)."""
    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ), RouterConfig(replicas=3))
    live = r.live_replicas()
    keys = list(range(200))
    before = {k: r._affinity_target(k, live).rid for k in keys}
    gone = live[0].rid
    survivors = [h for h in live if h.rid != gone]
    for k in keys:
        after = r._affinity_target(k, survivors).rid
        if before[k] != gone:
            assert after == before[k]


def test_spill_threshold_and_park(qwen3):
    """Affinity yields to the least-loaded replica past the queue-depth
    threshold; with EVERY live replica past it the router parks (QoS
    back-pressure) instead of blind fan-out."""
    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=1, block_size=8, max_model_len=64,
    ), RouterConfig(replicas=2, spill_queue_depth=2))
    h0, h1 = r.live_replicas()
    assert not r._past_threshold(h0)
    # same-prefix requests all map to one replica; its queue crossing the
    # threshold forces spills to the sibling
    prompts = _shared_prefix_prompts(8, prefix_len=16, tail=4, seed=1,
                                     groups=1)
    for p in prompts:
        r.submit(Request(prompt_ids=p,
                         sampling=SamplingParams(max_new_tokens=2)))
    r._dispatch()
    d0, d1 = h0.queue_depth() + len(h0.assigned), \
        h1.queue_depth() + len(h1.assigned)
    assert d0 > 0 and d1 > 0, (d0, d1)  # spill engaged both replicas
    assert r._spill_total > 0
    # both replicas now past threshold -> the rest parks at the router
    assert len(r._queue) > 0
    assert r.run()  # drains clean


# ------------------------------------------------------------------- parity
def test_single_replica_router_matches_bare_engine(qwen3):
    """The representative serving slice through a 1-replica router:
    token-exact with the bare engine, same ids, same finish reasons."""
    params, cfg = qwen3
    prompts = _prompts((5, 12, 9, 17), seed=2)
    ec = EngineConfig(num_slots=2, block_size=8, max_model_len=64)
    reqs = lambda: [Request(prompt_ids=list(p),  # noqa: E731
                            sampling=SamplingParams(max_new_tokens=6))
                    for p in prompts]
    eng = InferenceEngine(params, cfg, ec)
    r = Router(params, cfg, ec, RouterConfig(replicas=1))
    eng_outs = eng.run(reqs())
    rout_outs = r.run(reqs())
    assert sorted(eng_outs) == sorted(rout_outs)
    for rid, o in eng_outs.items():
        assert rout_outs[rid].token_ids == o.token_ids
        assert rout_outs[rid].finish_reason == o.finish_reason
    _pool_identity(r.live_replicas()[0].engine)


def test_single_replica_router_shed_semantics(qwen3):
    """QoS moved up to the router: the bounded queue and validation raise/
    shed exactly like the bare engine's submit (same error messages, same
    terminal statuses), so fronting one engine changes nothing."""
    params, cfg = qwen3
    ec = EngineConfig(num_slots=1, block_size=8, max_model_len=32,
                      queue_bound=2)
    r = Router(params, cfg, ec, RouterConfig(replicas=1))
    with pytest.raises(ValueError, match="empty prompt"):
        r.submit(Request(prompt_ids=[]))
    with pytest.raises(ValueError, match="max_new_tokens"):
        r.submit(Request(prompt_ids=[1],
                         sampling=SamplingParams(max_new_tokens=0)))
    with pytest.raises(ValueError, match="max_model_len"):
        r.submit(Request(prompt_ids=[1] * 30,
                         sampling=SamplingParams(max_new_tokens=8)))
    with pytest.raises(ValueError, match="unknown priority class"):
        r.submit(Request(prompt_ids=[1, 2],
                         sampling=SamplingParams(max_new_tokens=1),
                         priority="vip"))
    ids = [r.submit(Request(prompt_ids=[1, 2, 3],
                            sampling=SamplingParams(max_new_tokens=2)))
           for _ in range(5)]
    with pytest.raises(ValueError, match="duplicate"):
        r.submit(Request(prompt_ids=[1], request_id=ids[0]))
    outs = r.run()
    statuses = [outs[i].finish_reason for i in ids]
    assert statuses.count("rejected") == 3  # queue_bound=2 + 5 submits
    assert all(s in ("rejected", "length") for s in statuses)
    assert r.metrics()["rejected"] == 3.0


def test_router_shared_prefix_parity_across_fleet_sizes(qwen3):
    """Shared-prefix traffic over 2..4 replicas: every request is
    token-exact with isolated greedy generation, wherever affinity or
    spill landed it, and every pool drains leak-free."""
    params, cfg = qwen3
    prompts = _shared_prefix_prompts(10, prefix_len=16, tail=6, seed=3,
                                     groups=3)
    refs = _greedy_refs(params, cfg, prompts, 6)
    for n in (2, 4):
        r = Router(params, cfg, EngineConfig(
            num_slots=2, block_size=8, max_model_len=64,
        ), RouterConfig(replicas=n, spill_queue_depth=2))
        outs = r.run([Request(prompt_ids=list(p),
                              sampling=SamplingParams(max_new_tokens=6))
                      for p in prompts])
        assert len(outs) == len(prompts)
        for o in outs.values():
            assert o.token_ids == refs[tuple(o.prompt_ids)], o.request_id
        for h in r.live_replicas():
            _pool_identity(h.engine)
        # affinity concentrated each prefix group: the fleet-aggregate hit
        # rate stays warm instead of diluting N ways
        assert r.metrics()["prefix_hit_rate"] > 0


# -------------------------------------------------------------- router QoS
def test_router_qos_no_starvation_under_parked_backlog(qwen3):
    """With every replica past the spill threshold the router parks and
    ITS stride picker decides dispatch order: an interactive arrival
    overtakes a parked batch backlog, and batch still gets its weighted
    share — no starvation at the front door."""
    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=1, block_size=8, max_model_len=64,
        classes="interactive:4,batch:1",
    ), RouterConfig(replicas=2, spill_queue_depth=1))
    for i, p in enumerate(_prompts((8,) * 8, seed=4)):
        r.submit(Request(prompt_ids=p, priority="batch",
                         sampling=SamplingParams(max_new_tokens=2)))
    r._dispatch()  # fill both replicas past the threshold; rest parks
    assert len(r._queue) > 0
    inter = r.submit(Request(prompt_ids=_prompts((8,), seed=5)[0],
                             priority="interactive",
                             sampling=SamplingParams(max_new_tokens=2)))
    order = []
    orig = r._dispatch_to

    def spy(item, h):
        order.append(item.request.request_id)
        orig(item, h)

    r._dispatch_to = spy
    outs = r.run()
    assert outs[inter].finish_reason == "length"
    # the late interactive request dispatched ahead of the parked batch
    # backlog (stride weight 4:1), but batch was NOT starved out
    assert order.index(inter) < len(order) - 1
    assert all(o.finish_reason == "length" for o in outs.values())


# ----------------------------------------------------------------- failure
def test_replica_kill_mid_storm_sheds_never_corrupts(qwen3):
    """Mid-storm kill: survivors stay token-exact and leak-free; every
    request that was on the dead replica reaches a terminal status —
    re-dispatched (nothing streamed yet) or ``cancelled`` (tokens already
    delivered) — and nothing hangs."""
    params, cfg = qwen3
    prompts = _shared_prefix_prompts(12, prefix_len=16, tail=6, seed=6,
                                     groups=4)
    refs = _greedy_refs(params, cfg, prompts, 8)
    r = Router(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ), RouterConfig(replicas=3, spill_queue_depth=2))
    ids = [r.submit(Request(prompt_ids=list(p),
                            sampling=SamplingParams(max_new_tokens=8)))
           for p in prompts]
    for _ in range(4):  # let the storm develop: prefills + some decode
        r.step()
    victim = max(r.live_replicas(),
                 key=lambda h: len(h.assigned))  # kill the busiest
    stranded = set(victim.assigned)
    r.kill_replica(victim.rid, reason="drill")
    outs = r.run()
    assert sorted(outs) == sorted(ids)  # nothing lost, nothing duplicated
    for rid in ids:
        o = outs[rid]
        assert o.finished and o.finish_reason, rid  # terminal, never hung
        if o.finish_reason == "length":
            assert o.token_ids == refs[tuple(o.prompt_ids)], rid
        else:  # only the dead replica's in-flight work may cancel
            assert o.finish_reason == "cancelled" and rid in stranded
    for h in r.live_replicas():
        _pool_identity(h.engine)  # zero leaked blocks on survivors
    assert len(r.live_replicas()) == 2
    doc = r.debug_doc()
    assert [x["rid"] for x in doc["retired"]] == [victim.rid]
    assert doc["retired"][0]["fail_reason"]


def test_router_stalls_loudly_with_no_live_replicas(qwen3):
    """With resurrection disabled (max_respawns=0), losing every replica
    still fails loudly — but only AFTER every queued request got a
    terminal REJECTED output, so a run()/pop_output caller is never left
    blocking on a request that can no longer be served."""
    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=1, block_size=8, max_model_len=32,
    ), RouterConfig(replicas=2, max_respawns=0))
    rid = r.submit(Request(prompt_ids=[1, 2, 3],
                           sampling=SamplingParams(max_new_tokens=2)))
    for h in list(r.live_replicas()):
        r.kill_replica(h.rid)
    with pytest.raises(RuntimeError, match="no live replicas"):
        r.step()
    out = r.pop_output(rid)
    assert out is not None and out.finished
    assert out.finish_reason == "rejected"
    assert not r.has_work  # nothing left parked or in flight


# -------------------------------------------------------------- elasticity
def test_live_add_remove_no_lost_or_duplicated_ids(qwen3):
    """Grow 2->3 mid-traffic, then drain one replica out: every id
    submitted before, during and after the resize reaches exactly one
    terminal output; the drained replica leaves only once empty."""
    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ), RouterConfig(replicas=2, spill_queue_depth=1))
    mk = lambda p: Request(prompt_ids=list(p),  # noqa: E731
                           sampling=SamplingParams(max_new_tokens=4))
    prompts = _shared_prefix_prompts(9, prefix_len=8, tail=6, seed=7,
                                     groups=3)
    ids = [r.submit(mk(p)) for p in prompts[:3]]
    r.step()
    added = r.add_replica()
    ids += [r.submit(mk(p)) for p in prompts[3:6]]
    r.step()
    victim = r.live_replicas()[0]
    r.remove_replica(victim.rid)
    assert victim.state == STATE_DRAINING
    with pytest.raises(ValueError, match="not live"):
        r.remove_replica(victim.rid)
    ids += [r.submit(mk(p)) for p in prompts[6:]]
    outs = r.run()
    assert sorted(outs) == sorted(ids)
    assert all(o.finish_reason == "length" for o in outs.values())
    assert victim.state == STATE_DETACHED
    assert not victim.assigned and not victim.engine.has_work
    _pool_identity(victim.engine)
    assert added.rid in r.replicas
    # can't drain the fleet to zero
    last_live = r.live_replicas()
    while len(last_live) > 1:
        r.remove_replica(last_live[0].rid)
        r.step()
        last_live = r.live_replicas()
    with pytest.raises(ValueError, match="last live replica"):
        r.remove_replica(last_live[0].rid)


def test_add_replica_shares_programs_zero_new_traces(qwen3):
    """The compile-count gate for elasticity: serving through a replica
    added at runtime must not add a single trace — it shares the fleet's
    program bundle."""
    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ), RouterConfig(replicas=2))
    prompts = _prompts((5, 9, 12, 7), seed=8)
    r.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=4))
           for p in prompts])  # warm the shared bundle across bucket shapes
    base = dict(decode_mod.TRACE_COUNTS)
    h = r.add_replica()
    assert h.engine.programs is r._programs
    r.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=4))
           for p in _prompts((6, 10, 11, 8), seed=9)])
    assert dict(decode_mod.TRACE_COUNTS) == base


def test_publish_weights_versioning(qwen3):
    """New replicas serve the latest published version immediately;
    existing replicas keep theirs until the rolling publish (driven by
    ``step()``) reaches them — never mid-stream, always via the drain
    fence (docs/serving.md "Versioned weight publication")."""
    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=1, block_size=8, max_model_len=32,
    ), RouterConfig(replicas=2))
    old = {h.rid for h in r.live_replicas()}
    assert all(h.weights_version == "v0" for h in r.live_replicas())
    p2 = jax.tree_util.tree_map(lambda x: x, params)
    assert r.publish_weights(p2, "v1") == "v1"
    h = r.add_replica()
    assert h.weights_version == "v1"
    # snapshot BEFORE any step(): the roll is lazy, nothing swapped yet
    assert all(x.weights_version == "v0"
               for x in r.live_replicas() if x.rid in old)
    assert r.debug_doc()["weights_version"] == "v1"
    assert r.publish_in_progress
    # an idle fleet converges through step() alone (has_work holds the
    # pump open while any serving replica is off the latest version)
    deadline = time.perf_counter() + 30.0
    while r.has_work and time.perf_counter() < deadline:
        r.step()
    assert not r.publish_in_progress
    assert all(x.weights_version == "v1" for x in r.live_replicas())
    doc = r.debug_doc()
    assert doc["publishes"] == 1 and not doc["publish_in_progress"]
    health = r.health()
    assert health["weights_version"] == "v1"
    assert set(health["replica_weights"].values()) == {"v1"}


# ----------------------------------------------------------- observability
def test_router_metrics_and_debug_surface(qwen3):
    """serve.router.* gauges/counters and /debug/router reflect dispatch
    reality; the debug snapshot is safe to read from another thread while
    the pump runs."""
    from veomni_tpu.observability.metrics import get_registry

    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ), RouterConfig(replicas=2))
    reqs = [Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=3))
            for p in _prompts((6, 9, 12), seed=10)]
    stop = threading.Event()
    seen = []

    def scrape():
        while not stop.is_set():
            seen.append(r.debug_doc())

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    try:
        outs = r.run(reqs)
    finally:
        stop.set()
        t.join(timeout=5)
    assert len(outs) == 3
    reg = get_registry()
    assert reg.counter("serve.router.requests").value >= 3
    assert reg.counter("serve.router.dispatched").value >= 3
    assert reg.gauge("serve.router.replicas_live").value == 2
    # per-replica engine metrics carry the instance label, so two engines
    # do not clobber one shared gauge family
    labelled = [n for n, _ in reg.items_snapshot()
                if n.startswith("serve.r0.") or n.startswith("serve.r1.")]
    assert labelled
    doc = r.debug_doc()
    assert {x["rid"] for x in doc["replicas"]} == {"r0", "r1"}
    assert sum(x["dispatched"] for x in doc["replicas"]) >= 3
    assert seen  # concurrent scraper observed snapshots without crashing


def test_router_deadline_and_cancel_paths(qwen3):
    """Deadlines expire both at the router (parked) and on replicas with
    the clock backdated to router intake; cancel reaches a request
    wherever it currently lives."""
    params, cfg = qwen3
    r = Router(params, cfg, EngineConfig(
        num_slots=1, block_size=8, max_model_len=64,
    ), RouterConfig(replicas=2, spill_queue_depth=1))
    # park a deadline-carrying request behind a saturating backlog
    for p in _prompts((8,) * 6, seed=11):
        r.submit(Request(prompt_ids=p,
                         sampling=SamplingParams(max_new_tokens=2)))
    r._dispatch()
    victim = r.submit(Request(prompt_ids=[1, 2, 3], deadline_s=30.0,
                              sampling=SamplingParams(max_new_tokens=2)))
    item = r._items[victim]
    assert item.phase == "queued"  # parked at the router
    item.submit_time -= 60.0  # deadline elapsed while parked
    cancel_me = r.submit(Request(
        prompt_ids=[4, 5, 6], sampling=SamplingParams(max_new_tokens=2)))
    assert r.cancel(cancel_me)
    assert not r.cancel(cancel_me)  # already terminal
    assert not r.cancel("req-nope")
    outs = r.run()
    assert outs[victim].finish_reason == "deadline"
    assert outs[victim].deadline_missed
    assert outs[cancel_me].finish_reason == "cancelled"
    assert r.metrics()["deadline_misses"] >= 1.0
