"""KV-cache greedy decode vs full-prefix rescoring parity.

The cache path (models/decode.py) reimplements the layer walk; these tests
anchor it to the training forward (models/transformer.py forward_logits) on
the dialect extremes: qwen3 (GQA+qk_norm), gemma3-style (sandwich norms,
sliding windows, dual rope, embed_scale, softcap), and qwen3_moe."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.models.decode import greedy_generate, supports_cached_decode
from veomni_tpu.models.transformer import forward_logits


def _rescoring_generate(params, cfg, prompt, n, eos_id=-1):
    ids = list(prompt)
    total = len(ids) + n
    for _ in range(n):
        tokens = np.zeros((1, total), np.int32)
        tokens[0, : len(ids)] = ids
        pos = np.arange(total)[None]
        seg = (np.arange(total) < len(ids)).astype(np.int32)[None]
        logits = forward_logits(
            params, cfg, jnp.asarray(tokens), jnp.asarray(pos), jnp.asarray(seg)
        )
        nxt = int(jnp.argmax(logits[0, len(ids) - 1]))
        ids.append(nxt)
        if nxt == eos_id:
            break
    return ids


CONFIGS = {
    "qwen3": dict(
        model_type="qwen3", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, qk_norm=True,
    ),
    "gemma3ish": dict(
        model_type="gemma3", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, qk_norm=True,
        sandwich_norms=True, sliding_window=8,
        layer_types=["sliding_attention", "full_attention"] * 2,
        rope_local_base_freq=10000.0,
        query_pre_attn_scalar=16, final_logit_softcap=30.0,
    ),
    "qwen3_moe": dict(
        model_type="qwen3_moe", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, qk_norm=True, num_experts=4,
        num_experts_per_tok=2, moe_intermediate_size=32,
    ),
    # gpt_oss-style: learned attention sinks + alternating sliding windows —
    # covers the sink softmax-denominator math duplicated between
    # _cache_attend and the training attention impls
    "gpt_oss_ish": dict(
        model_type="gpt_oss", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, attention_sinks=True,
        attention_bias=True, o_bias=True, sliding_window=8,
        layer_types=["sliding_attention", "full_attention"] * 2,
        hidden_act="gpt_oss_glu",
    ),
}


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_cached_decode_matches_rescoring(name):
    cfg = TransformerConfig(dtype=jnp.float32, **CONFIGS[name])
    assert supports_cached_decode(cfg)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    prompt = list(np.random.default_rng(0).integers(1, 128, 9))
    got = greedy_generate(params, cfg, prompt, max_new_tokens=6)
    want = _rescoring_generate(params, cfg, prompt, 6)
    assert got == want, (got, want)


def test_cached_decode_rejects_mla():
    cfg = TransformerConfig(
        model_type="deepseek_v3", vocab_size=64, hidden_size=64,
        num_hidden_layers=1, num_attention_heads=4,
        kv_lora_rank=16, qk_nope_head_dim=8, qk_rope_head_dim=8,
        v_head_dim=8,
    )
    assert not supports_cached_decode(cfg)


def test_sampling_decode_valid_and_greedy_consistent():
    """temperature=0 sampling path == greedy; temperature>0 with top_k
    produces in-vocab tokens and is reproducible per seed."""
    cfg = TransformerConfig(dtype=jnp.float32, **CONFIGS["qwen3"])
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    prompt = list(np.random.default_rng(1).integers(1, 128, 7))
    greedy = greedy_generate(params, cfg, prompt, max_new_tokens=5)
    greedy2 = greedy_generate(params, cfg, prompt, max_new_tokens=5,
                              temperature=0.0)
    assert greedy == greedy2
    s1 = greedy_generate(params, cfg, prompt, max_new_tokens=5,
                         temperature=0.8, top_k=10, seed=3)
    s2 = greedy_generate(params, cfg, prompt, max_new_tokens=5,
                         temperature=0.8, top_k=10, seed=3)
    assert s1 == s2  # per-seed reproducible
    assert all(0 <= t < 128 for t in s1[len(prompt):])
    # top_k > vocab clamps to the vocab (HF generate semantics) instead of
    # raising inside lax.top_k
    s3 = greedy_generate(params, cfg, prompt, max_new_tokens=5,
                         temperature=0.8, top_k=10_000, seed=3)
    assert all(0 <= t < 128 for t in s3[len(prompt):])


def test_nucleus_sampling():
    """top_p semantics: a vanishing nucleus collapses to greedy (the top-1
    token always survives the filter); top_p=1.0 keeps the full
    distribution (token-identical to not passing top_p); sampled tokens
    stay in-vocab and per-seed reproducible."""
    cfg = TransformerConfig(dtype=jnp.float32, **CONFIGS["qwen3"])
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    prompt = list(np.random.default_rng(4).integers(1, 128, 8))
    greedy = greedy_generate(params, cfg, prompt, max_new_tokens=5)
    tiny_p = greedy_generate(params, cfg, prompt, max_new_tokens=5,
                             temperature=0.8, top_p=1e-6, seed=3)
    assert tiny_p == greedy
    full_p = greedy_generate(params, cfg, prompt, max_new_tokens=5,
                             temperature=0.8, top_k=10, top_p=1.0, seed=3)
    no_p = greedy_generate(params, cfg, prompt, max_new_tokens=5,
                           temperature=0.8, top_k=10, seed=3)
    assert full_p == no_p
    s1 = greedy_generate(params, cfg, prompt, max_new_tokens=5,
                         temperature=0.9, top_p=0.7, seed=5)
    s2 = greedy_generate(params, cfg, prompt, max_new_tokens=5,
                         temperature=0.9, top_p=0.7, seed=5)
    assert s1 == s2
    assert all(0 <= t < 128 for t in s1[len(prompt):])


def test_per_slot_sample_tokens_matches_scalar_semantics():
    """The serving engine's vectorized sampler: greedy rows == argmax
    regardless of batch-mates; per-row top_k<=0 / top_p>=1 keep everything;
    a tiny top_p collapses a sampled row to its argmax."""
    from veomni_tpu.models.decode import sample_tokens

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(4, 32)), jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4))
    out = sample_tokens(
        logits, keys,
        jnp.asarray([0.0, 0.0, 0.8, 0.9], jnp.float32),
        jnp.asarray([0, 5, 0, 3], jnp.int32),
        jnp.asarray([1.0, 1.0, 1e-6, 0.9], jnp.float32),
    )
    out = np.asarray(out)
    am = np.asarray(jnp.argmax(logits, axis=-1))
    assert out[0] == am[0] and out[1] == am[1]  # temperature<=0 -> greedy
    assert out[2] == am[2]  # vanishing nucleus -> argmax survives alone
    assert 0 <= out[3] < 32
    # per-row keys: the same row resamples identically under the same key
    out2 = np.asarray(sample_tokens(
        logits, keys,
        jnp.asarray([0.0, 0.0, 0.8, 0.9], jnp.float32),
        jnp.asarray([0, 5, 0, 3], jnp.int32),
        jnp.asarray([1.0, 1.0, 1e-6, 0.9], jnp.float32),
    ))
    assert (out == out2).all()


def test_prompt_length_bucketing_keeps_compiles_flat():
    """Distinct prompt lengths inside one power-of-two bucket must reuse the
    SAME prefill/decode compilation (each retrace costs 20-40s on TPU) and
    still match full-prefix rescoring exactly."""
    from veomni_tpu.models import decode as decode_mod

    cfg = TransformerConfig(dtype=jnp.float32, **CONFIGS["qwen3"])
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    prompt = list(np.random.default_rng(2).integers(1, 128, 9))

    base = dict(decode_mod.TRACE_COUNTS)
    outs = {}
    # lengths 5/6/7 share the prompt bucket (16) AND the cache bucket
    # (5+6..7+6 <= 16): zero extra compiles after the first
    for n in (5, 6, 7):
        outs[n] = greedy_generate(params, cfg, prompt[:n], max_new_tokens=6)
    assert decode_mod.TRACE_COUNTS["prefill"] - base["prefill"] == 1
    assert decode_mod.TRACE_COUNTS["decode"] - base["decode"] == 1
    for n in (5, 6, 7):  # bucketing must not change the tokens
        assert outs[n] == _rescoring_generate(params, cfg, prompt[:n], 6)
