"""Flight recorder + request tracing + automatic post-mortems (ISSUE 6).

Acceptance contract: the always-on event ring is bounded and alloc-light
(disabled = one attribute check; enabled = one bounded append, no per-event
allocation beyond the record); a fault-injected hang and a supervisor abort
each yield a ``postmortem-<rank>.json`` whose ring holds the step/checkpoint/
supervisor events leading up to the failure plus all-thread stacks
(subprocess drills); ``scripts/postmortem.py`` merges two rank files into one
monotonic timeline; a preempted request's timeline shows
admitted→preempted→re-admitted with ``serve.queue_wait_s``/``serve.tpot_s``
recorded while greedy parity stays token-exact; ``/debug/*`` endpoints serve
the live views; and every metric family emitted at runtime is documented in
docs/observability.md (doc-drift gate).
"""

import importlib.util
import json
import logging
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from veomni_tpu.observability.flight_recorder import (
    FlightRecorder,
    get_flight_recorder,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    path = os.path.join(_REPO, "scripts", name)
    spec = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------------ recorder
def test_flight_ring_overflow_keeps_tail_and_counts_drops():
    rec = FlightRecorder(max_events=8)
    for i in range(20):
        rec.record("step.end", cid=str(i))
    assert len(rec) == 8
    assert rec.dropped == 12
    # the TAIL survives (the seconds before a failure, not the start of run)
    cids = [ev[2] for ev in rec.events()]
    assert cids == [str(i) for i in range(12, 20)]
    # resize preserves what fits
    rec.configure(max_events=4)
    assert [ev[2] for ev in rec.events()] == ["16", "17", "18", "19"]


def test_flight_recorder_disabled_and_alloc_discipline():
    rec = FlightRecorder(max_events=0)
    assert not rec.enabled
    for _ in range(100):
        rec.record("step.end", cid="1", a=1)  # no-op: nothing retained
    assert len(rec) == 0 and rec.dropped == 0
    # re-enable: recording resumes into the (bounded) ring
    rec.configure(max_events=16)
    rec.record("a")
    rec.record("b", cid="7", x=1)
    evs = rec.events()
    assert len(evs) == 2
    # the record IS the allocation: a 4-tuple, payload None when no kwargs
    assert isinstance(evs[0], tuple) and len(evs[0]) == 4
    assert evs[0][3] is None
    assert evs[1][3] == {"x": 1}
    # enabled-path is a single bounded append: ring never exceeds maxlen
    for i in range(100):
        rec.record("c", cid=str(i))
    assert len(rec) == 16


def test_postmortem_dump_is_self_contained(tmp_path):
    rec = FlightRecorder(max_events=32)
    rec.configure(dump_dir=str(tmp_path))
    rec.record("step.dispatch", cid="3")
    rec.record("ckpt.commit", cid="2")
    path = rec.dump("unit-test", extra={"global_step": 3})
    assert path == str(tmp_path / "postmortem-0.json")
    doc = json.load(open(path))
    assert doc["schema"] == 1 and doc["reason"] == "unit-test"
    assert doc["rank"] == 0 and doc["global_step"] == 3
    kinds = [e["kind"] for e in doc["events"]]
    assert kinds == ["step.dispatch", "ckpt.commit"]
    # the three sidecars that make the artifact self-contained
    assert isinstance(doc["metrics"], dict)
    assert isinstance(doc["spans"], list)
    assert "MainThread" in doc["thread_stacks"]
    # anchor pair lets scripts/postmortem.py map onto a wall axis
    assert doc["anchor"]["wall_time_s"] > 0 and doc["anchor"]["perf_ns"] > 0
    # dump never raises, even with junk payloads
    rec.record("weird", cid="x", obj=object())
    assert rec.dump("again") is not None


# ------------------------------------------------- spans drop-counter satellite
class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


def test_span_ring_drop_counter_and_one_time_warning():
    from veomni_tpu.observability import spans as spans_mod
    from veomni_tpu.observability.metrics import get_registry
    from veomni_tpu.observability.spans import (
        disable_spans,
        dropped_events,
        dump_chrome_trace,
        enable_spans,
        span,
    )

    was = spans_mod.spans_enabled()
    spans_mod.clear_events()
    base = get_registry().counter("span.dropped").value
    cap = _Capture()
    root = logging.getLogger("veomni_tpu")
    root.addHandler(cap)
    try:
        enable_spans(max_events=4)
        for _ in range(10):
            with span("tiny.phase"):
                pass
        assert dropped_events() == 6
        assert get_registry().counter("span.dropped").value - base == 6
        warns = [r for r in cap.records
                 if "dropped" in r.getMessage() and "ring" in r.getMessage()]
        assert len(warns) == 1, "drop warning must fire exactly once"
        assert len(spans_mod.live_span_events()) == 4  # ring stayed bounded
    finally:
        root.removeHandler(cap)
        spans_mod.clear_events()
        enable_spans(max_events=100_000)  # restore the module default
        if not was:
            disable_spans()


# ------------------------------------------------------------ request tracing
QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)


@pytest.fixture(scope="module")
def qwen3():
    import jax
    import jax.numpy as jnp

    from veomni_tpu.models import TransformerConfig, build_foundation_model

    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


def _prompts(lengths, seed=0, vocab=128):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, vocab, n)] for n in lengths]


def test_request_timeline_across_forced_preemption(qwen3, tmp_path):
    """The acceptance gate: a pool too small for the load forces preemption;
    the preempted request's timeline shows admitted→preempted→re-admitted,
    queue-wait/TPOT land in the histograms AND on the RequestOutput, and
    greedy parity stays token-exact with tracing on (it always is)."""
    from veomni_tpu.models.decode import greedy_generate
    from veomni_tpu.observability.metrics import get_registry
    from veomni_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        Request,
        SamplingParams,
    )

    params, cfg = qwen3
    reg = get_registry()
    wait_base = reg.histogram("serve.queue_wait_s").count
    tpot_base = reg.histogram("serve.tpot_s").count
    prompts = _prompts((9, 11, 7), seed=1)
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, num_blocks=8,
    ))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(max_new_tokens=10)))
           for p in prompts]
    outs = eng.run()
    assert eng.scheduler.preemption_count > 0
    for rid, p in zip(ids, prompts):
        want = greedy_generate(params, cfg, p, max_new_tokens=10)[len(p):]
        assert outs[rid].token_ids == want  # parity with tracing enabled

    preempted = [rid for rid in ids if outs[rid].preemptions > 0]
    assert preempted, "drill config no longer forces a preemption"
    for rid in preempted:
        tl = eng.tracer.get(rid)
        stages = tl.stages
        # admitted -> ... -> preempted -> ... -> admitted (again) -> finished
        i_adm = stages.index("admitted")
        i_pre = stages.index("preempted", i_adm)
        i_readm = stages.index("admitted", i_pre)
        assert stages.index("finished", i_readm) > i_readm
        assert tl.preemptions == outs[rid].preemptions
        # a re-admission closed a second wait segment
        assert len(tl.wait_segments) == tl.preemptions + 1
        assert outs[rid].queue_wait_s == pytest.approx(tl.queue_wait_s)
        # one slot residency per admission
        assert len(tl.slot_segments) == tl.preemptions + 1
    # every finished request observed a wait; each re-admission adds one
    n_req = len(ids)
    n_preempt = sum(outs[rid].preemptions for rid in ids)
    assert reg.histogram("serve.queue_wait_s").count - wait_base == (
        n_req + n_preempt)
    assert reg.histogram("serve.tpot_s").count - tpot_base == sum(
        1 for rid in ids if len(outs[rid].token_ids) > 1)
    for rid in ids:
        assert outs[rid].tpot_s is None or outs[rid].tpot_s > 0

    # chrome export: one track per slot + a waiting track, request hops
    # visible as multiple X segments
    trace_path = str(tmp_path / "requests.json")
    n = eng.tracer.dump_chrome_trace(trace_path)
    doc = json.load(open(trace_path))
    events = doc["traceEvents"]
    tids = {e["args"]["name"] for e in events if e.get("name") == "thread_name"}
    assert tids == {"slot-0", "slot-1", "slot-2", "waiting"}
    xs = [e for e in events if e.get("ph") == "X"]
    assert len(xs) == n and n >= n_req + 2 * n_preempt
    segs = [e for e in xs if e["name"] == preempted[0] and e["cat"] == "serve"]
    assert len(segs) == outs[preempted[0]].preemptions + 1
    # ...and it merges with the host-span traces in the same viewer
    merge = _load_script("merge_chrome_trace.py")
    assert len(merge.merge_traces([trace_path])) == len(events)


def test_debug_endpoints_flight_and_requests(qwen3):
    from veomni_tpu.observability import MetricsExporter
    from veomni_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        Request,
        SamplingParams,
    )

    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
    ))
    eng.run([Request(prompt_ids=_prompts((9,), seed=7)[0],
                     sampling=SamplingParams(max_new_tokens=4))])
    get_flight_recorder().record("unit.flight", cid="42")
    exp = MetricsExporter(port=0, requests_fn=eng.tracer.snapshot)
    port = exp.start()
    try:
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/flight?n=5", timeout=10).read())
        assert doc["rank"] == 0 and len(doc["events"]) <= 5
        assert any(e["kind"] == "unit.flight" and e.get("cid") == "42"
                   for e in doc["events"])
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/requests", timeout=10).read())
        assert doc["num_slots"] == 2 and doc["inflight"] == []
        assert doc["finished"][0]["tokens"] == 4
        stages = [m["stage"] for m in doc["finished"][0]["timeline"]]
        assert stages[0] == "queued" and stages[-1] == "finished"
    finally:
        exp.stop()


# -------------------------------------------------------------- fleet merging
def _doctor_rank(src, dst, rank, skew_ns):
    """Clone a dump as another rank whose monotonic epoch differs by
    ``skew_ns`` (exactly what two real processes look like)."""
    doc = json.load(open(src))
    doc["rank"] = rank
    doc["anchor"] = dict(doc["anchor"], perf_ns=doc["anchor"]["perf_ns"] + skew_ns)
    doc["events"] = [dict(e, ts_ns=e["ts_ns"] + skew_ns) for e in doc["events"]]
    json.dump(doc, open(dst, "w"))


def test_postmortem_merge_two_ranks_monotonic(tmp_path):
    rec = FlightRecorder(max_events=32)
    rec.configure(dump_dir=str(tmp_path))
    for i in range(6):
        rec.record("step.end", cid=str(i))
        time.sleep(0.002)
    p0 = rec.dump("drill")
    p1 = str(tmp_path / "postmortem-1.json")
    _doctor_rank(p0, p1, rank=1, skew_ns=123_456_789_000)
    pm = _load_script("postmortem.py")
    merged = pm.merge_dumps([p0, p1])
    walls = [e["wall_s"] for e in merged["events"]]
    assert walls == sorted(walls), "merged fleet timeline must be monotonic"
    assert len(walls) == 12
    # despite wildly different monotonic epochs, the anchor mapping
    # interleaves the two ranks rather than concatenating them
    ranks_in_order = [e["rank"] for e in merged["events"]]
    assert ranks_in_order != sorted(ranks_in_order)
    text = pm.format_timeline(merged, tail=4)
    assert "rank0" in text and "rank1" in text and "step.end" in text


# --------------------------------------------------------- subprocess drills
_DRIVER = """\
import json, os, sys

cfg = json.load(open(sys.argv[1]))
sys.path.insert(0, cfg["repo"])

from veomni_tpu.arguments import VeOmniArguments
from veomni_tpu.trainer import TextTrainer

args = VeOmniArguments()
args.model.config_overrides = cfg["toy"]
args.data.train_path = cfg["data"]
args.data.data_type = "pretokenized"
args.data.max_seq_len = 64
t = args.train
t.output_dir = cfg["out"]
t.micro_batch_size = 2
t.train_steps = cfg["train_steps"]
t.save_steps = cfg.get("save_steps", 0)
t.async_save = False
t.lr = 1e-3
t.bf16 = False
t.save_hf_weights = False
t.log_steps = 1
t.resilience_watchdog_s = cfg.get("watchdog_s", 0.0)
t.resilience_anomaly_budget = cfg.get("anomaly_budget", 8)
t.resilience_rollback_after = cfg.get("rollback_after", 3)

trainer = TextTrainer(args)
res = {"error": ""}
try:
    ctl = trainer.train()
    res["global_step"] = ctl.global_step
    res["resilience"] = ctl.resilience
except Exception as e:
    res["error"] = type(e).__name__
finally:
    trainer.checkpointer.close()
with open(cfg["result"], "w") as f:
    json.dump(res, f)
"""

DENSE_TOY = {
    "model_type": "qwen3", "vocab_size": 256, "hidden_size": 64,
    "intermediate_size": 128, "num_hidden_layers": 2,
    "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
    "qk_norm": True,
}


def _write_data(path, n=96, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            f.write(json.dumps({
                "input_ids": rng.integers(
                    0, vocab, int(rng.integers(16, 80))).tolist(),
            }) + "\n")


def _run_driver(tmp_path, cfg, fault_plan, timeout=240):
    driver = tmp_path / "driver.py"
    driver.write_text(_DRIVER)
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ, JAX_PLATFORMS="cpu", VEOMNI_LOG_LEVEL="WARNING",
               VEOMNI_FAULT_PLAN=json.dumps(fault_plan))
    p = subprocess.run(
        [sys.executable, str(driver), str(cfg_path)],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=timeout,
    )
    assert os.path.exists(cfg["result"]), (
        f"driver died rc={p.returncode}:\n{p.stderr[-3000:]}"
    )
    return json.load(open(cfg["result"]))


def test_postmortem_drill_fault_hang_watchdog(tmp_path):
    """Acceptance drill 1: a ``step.loss`` hang (PR 3 fault plan) stalls the
    loop past the watchdog deadline; the watchdog fire auto-dumps
    ``postmortem-0.json`` whose ring shows the hang step dispatched but never
    ended, with the earlier checkpoint commit and all-thread stacks — then
    scripts/postmortem.py merges it with a second rank file into one
    monotonic fleet timeline."""
    _write_data(tmp_path / "data.jsonl")
    cfg = {
        "repo": _REPO, "toy": DENSE_TOY,
        "data": str(tmp_path / "data.jsonl"),
        "out": str(tmp_path / "out"),
        "result": str(tmp_path / "result.json"),
        "train_steps": 5, "save_steps": 2, "watchdog_s": 1.0,
    }
    res = _run_driver(tmp_path, cfg, [
        {"point": "step.loss", "mode": "hang", "hit": 4, "seconds": 5.0},
    ])
    assert res["error"] == "" and res["global_step"] == 5
    assert res["resilience"]["watchdog_stalls"] >= 1

    pm_path = os.path.join(cfg["out"], "postmortem-0.json")
    assert os.path.exists(pm_path), "watchdog fire must auto-dump"
    # the re-arming dog can fire again on a slow post-hang step and dump
    # recovered state — dump ROTATION (not test deadlines) is what keeps the
    # hang-time artifact: scan canonical + .1/.2 for the mid-hang dump whose
    # ring shows the hang step dispatched but never ended
    doc = None
    for cand in (pm_path, f"{pm_path}.1", f"{pm_path}.2"):
        if not os.path.exists(cand):
            continue
        d = json.load(open(cand))
        kinds = {(e["kind"], e.get("cid", "")) for e in d["events"]}
        if ("step.dispatch", "4") in kinds and ("step.end", "4") not in kinds:
            doc, pm_path = d, cand
            break
    assert doc is not None, \
        "no dump (canonical or rotated) captured the mid-hang state"
    assert doc["reason"].startswith("watchdog:")
    events = doc["events"]
    by_kind_cid = {(e["kind"], e.get("cid", "")) for e in events}
    assert ("step.end", "3") in by_kind_cid  # ...while earlier steps closed
    # the checkpoint machinery's history rode along
    assert ("ckpt.save", "2") in by_kind_cid
    assert ("ckpt.commit", "2") in by_kind_cid
    # the injected fault is legible (a drill must not read as organic rot)
    assert ("fault.injected", "step.loss") in by_kind_cid
    assert "Thread" in doc["thread_stacks"]
    assert doc["metrics"].get("ckpt.saves", 0) >= 1

    # fleet merge through the real CLI (two ranks -> one monotonic timeline)
    p1 = str(tmp_path / "postmortem-1.json")
    _doctor_rank(pm_path, p1, rank=1, skew_ns=7_000_000_000)
    merged_path = str(tmp_path / "merged.json")
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "postmortem.py"),
         pm_path, p1, "--json", merged_path],
        capture_output=True, text=True, cwd=_REPO, timeout=60,
    )
    assert p.returncode == 0, p.stderr
    assert "rank0" in p.stdout and "rank1" in p.stdout
    merged = json.load(open(merged_path))
    walls = [e["wall_s"] for e in merged["events"]]
    assert walls == sorted(walls) and len(walls) == 2 * len(events)


def test_postmortem_drill_supervisor_abort(tmp_path):
    """Acceptance drill 2: injected NaNs blow the anomaly budget; the
    AnomalyBudgetExceeded escaping train() auto-dumps a post-mortem whose
    ring carries the anomaly escalation."""
    _write_data(tmp_path / "data.jsonl")
    cfg = {
        "repo": _REPO, "toy": DENSE_TOY,
        "data": str(tmp_path / "data.jsonl"),
        "out": str(tmp_path / "out"),
        "result": str(tmp_path / "result.json"),
        "train_steps": 8, "anomaly_budget": 2, "rollback_after": 10,
    }
    res = _run_driver(tmp_path, cfg, [
        {"point": "step.loss", "mode": "nan", "hit": 1, "times": 5},
    ])
    assert res["error"] == "AnomalyBudgetExceeded"

    pm_path = os.path.join(cfg["out"], "postmortem-0.json")
    assert os.path.exists(pm_path), "abort must auto-dump"
    doc = json.load(open(pm_path))
    assert doc["reason"] == "exception:AnomalyBudgetExceeded"
    events = doc["events"]
    anomalies = [e for e in events if e["kind"] == "supervisor.anomaly"]
    assert len(anomalies) >= 3  # the escalation history, not just the raise
    verdicts = [e.get("cid") for e in events
                if e["kind"] == "supervisor.verdict"]
    assert "abort" in verdicts
    assert "anomaly budget exceeded" in doc["error"]
    assert "Thread" in doc["thread_stacks"]


# -------------------------------------------------------------- doc drift
def test_every_emitted_metric_family_is_documented():
    """Doc-drift gate: a metric family emitted at runtime that is absent
    from docs/observability.md fails CI — new metrics can't ship
    undocumented.

    Since ISSUE 13 the scan lives in the static-analysis framework
    (``veomni_tpu/analysis/drift.py``: AST instrument-creation call sites,
    the same sanity-pinned family list, plus the analysis-subtree pin) —
    this test keeps its name and CI behavior by delegating to that pass,
    so a regression fails here exactly like it did in PR 6."""
    from veomni_tpu.analysis import drift
    from veomni_tpu.analysis.core import RepoIndex

    index = RepoIndex.load(_REPO)
    sanity = [f for f in drift.sanity(index) if f.rule == "drift/scan-sanity"]
    assert not sanity, "\n".join(f.format() for f in sanity)
    findings = drift.metric_findings(index)
    assert not findings, (
        "metric families emitted at runtime but absent from "
        "docs/observability.md:\n"
        + "\n".join(f.format() for f in findings)
        + "\n— document them (metric reference tables) or stop emitting them"
    )
