"""Qwen3-VL parity vs HF transformers (tiny config, random weights).

Same oracle pattern as test_qwen2_5_vl.py: build a tiny
``Qwen3VLForConditionalGeneration``, save HF-format safetensors, import into
our model, and assert identical vision features (main + deepstack taps) and
loss on inputs with text + two differently-sized images — exercising the
learnable pos-embed bilinear interpolation, interleaved mrope, per-frame
attention segmentation, and the deepstack residual injection into the first
K decoder layers.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

IMG_ID, VID_ID, VSTART_ID = 9, 10, 8


def _tiny_hf_model(tmp_path):
    import torch
    from transformers.models.qwen3_vl import (
        Qwen3VLConfig, Qwen3VLForConditionalGeneration,
    )

    cfg = Qwen3VLConfig(
        text_config=dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=4,
            num_attention_heads=4,
            num_key_value_heads=2,
            head_dim=16,
            max_position_embeddings=512,
            rope_theta=10000.0,
            rope_scaling={"rope_type": "default", "mrope_section": [2, 3, 3],
                          "mrope_interleaved": True},
            tie_word_embeddings=False,
        ),
        vision_config=dict(
            depth=4,
            hidden_size=32,
            intermediate_size=64,
            num_heads=2,
            in_channels=3,
            patch_size=2,
            temporal_patch_size=2,
            spatial_merge_size=2,
            out_hidden_size=64,
            num_position_embeddings=16,  # 4x4 grid -> real interpolation
            deepstack_visual_indexes=[0, 2],
        ),
        image_token_id=IMG_ID,
        video_token_id=VID_ID,
        vision_start_token_id=VSTART_ID,
    )
    torch.manual_seed(0)
    model = Qwen3VLForConditionalGeneration(cfg).eval()
    out = tmp_path / "hf_ckpt"
    model.save_pretrained(out, safe_serialization=True)
    return model, cfg, str(out)


def _vision_inputs(rng, grids, patch_dim):
    n = sum(t * h * w for t, h, w in grids)
    pixel_values = rng.standard_normal((n, patch_dim)).astype(np.float32)
    return pixel_values, np.asarray(grids, np.int64)


@pytest.fixture(scope="module")
def hf_and_ours(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("q3vl")
    hf_model, hf_cfg, ckpt = _tiny_hf_model(tmp_path)

    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(ckpt, dtype="float32")
    params = model.load_hf(ckpt)
    return hf_model, hf_cfg, model, params


GRIDS = [(1, 4, 6), (2, 6, 4)]  # image + 2-frame video (per-frame segments)


def _metadata_and_px(cfg, pixel_values, pad=8):
    from veomni_tpu.models.qwen3_vl import vision_metadata

    meta = vision_metadata(GRIDS, cfg.vision,
                           n_pad_patches=pixel_values.shape[0] + pad)
    px = np.zeros((pixel_values.shape[0] + pad, pixel_values.shape[1]),
                  np.float32)
    px[: pixel_values.shape[0]] = pixel_values
    return meta, px


def test_vision_tower_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    rng = np.random.default_rng(0)
    pixel_values, grid_thw = _vision_inputs(rng, GRIDS, cfg.vision.patch_dim)

    with torch.no_grad():
        ref, ref_deepstack = hf_model.model.visual(
            torch.from_numpy(pixel_values), torch.from_numpy(grid_thw)
        )

    from veomni_tpu.models.qwen3_vl import vision_forward

    meta, px = _metadata_and_px(cfg, pixel_values)
    got, got_deep = vision_forward(
        params["vision_tower"], cfg.vision, jnp.asarray(px),
        jnp.asarray(meta["pos_hw"]), jnp.asarray(meta["pos_interp_idx"]),
        jnp.asarray(meta["pos_interp_w"]), jnp.asarray(meta["seg_full"]),
        dtype=jnp.float32,
    )
    mask = np.asarray(meta["merged_mask"])
    np.testing.assert_allclose(
        np.asarray(got)[mask], ref.numpy(), rtol=2e-4, atol=2e-4
    )
    assert got_deep.shape[0] == len(ref_deepstack)
    for k, rd in enumerate(ref_deepstack):
        np.testing.assert_allclose(
            np.asarray(got_deep[k])[mask], rd.numpy(), rtol=2e-4, atol=2e-4
        )


def test_mrope_position_ids_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    rng = np.random.default_rng(1)

    from veomni_tpu.models.qwen3_vl import (
        mrope_position_ids, split_video_grids,
    )

    image_grid = [GRIDS[0]]
    video_grid = [GRIDS[1]]
    split = split_video_grids(video_grid)
    n_img = [t * (h // 2) * (w // 2) for t, h, w in image_grid]
    n_vid = [t * (h // 2) * (w // 2) for t, h, w in split]

    ids = [VSTART_ID] + [IMG_ID] * n_img[0] + list(rng.integers(11, 256, 5))
    for nm in n_vid:  # timestamp-text then frame, per HF chat format
        ids += list(rng.integers(11, 256, 2)) + [VSTART_ID] + [VID_ID] * nm
    ids += list(rng.integers(11, 256, 7))
    input_ids = np.asarray([ids], np.int64)

    ref_pos, _ = hf_model.model.get_rope_index(
        torch.from_numpy(input_ids),
        image_grid_thw=torch.as_tensor(image_grid),
        video_grid_thw=torch.as_tensor(video_grid),
    )
    got = mrope_position_ids(input_ids, image_grid + split, cfg)  # [B,3,S]
    np.testing.assert_array_equal(got[0], ref_pos[:, 0].numpy())


def test_full_loss_parity(hf_and_ours):
    import torch

    hf_model, hf_cfg, model, params = hf_and_ours
    cfg = model.config
    n_merged = [t * (h // 2) * (w // 2) for t, h, w in GRIDS]
    rng = np.random.default_rng(2)
    pixel_values, grid_thw = _vision_inputs(rng, GRIDS, cfg.vision.patch_dim)

    ids = [VSTART_ID] + [IMG_ID] * n_merged[0] + list(rng.integers(11, 256, 5))
    ids += [VSTART_ID] + [IMG_ID] * n_merged[1] + list(rng.integers(11, 256, 6))
    input_ids = np.asarray([ids], np.int64)
    labels = input_ids.copy()
    labels[:, : n_merged[0] + 1] = -100  # mask the first image span

    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.from_numpy(input_ids),
            labels=torch.from_numpy(labels),
            pixel_values=torch.from_numpy(pixel_values),
            image_grid_thw=torch.from_numpy(grid_thw),
        )
    ref_loss = float(ref.loss)

    from veomni_tpu.models.qwen3_vl import mrope_position_ids

    meta, px = _metadata_and_px(cfg, pixel_values, pad=0)
    pos = mrope_position_ids(input_ids, GRIDS, cfg)
    shifted = np.full_like(labels, -100)
    shifted[:, :-1] = labels[:, 1:]
    batch = {
        "input_ids": jnp.asarray(input_ids, jnp.int32),
        "labels": jnp.asarray(shifted, jnp.int32),
        "position_ids": jnp.asarray(pos, jnp.int32),
        "segment_ids": jnp.ones_like(jnp.asarray(input_ids, jnp.int32)),
        "pixel_values": jnp.asarray(px),
        "vis_pos_hw": jnp.asarray(meta["pos_hw"]),
        "vis_pos_interp_idx": jnp.asarray(meta["pos_interp_idx"]),
        "vis_pos_interp_w": jnp.asarray(meta["pos_interp_w"]),
        "vis_seg_full": jnp.asarray(meta["seg_full"]),
        "vis_merged_mask": jnp.asarray(meta["merged_mask"]),
    }
    loss_sum, metrics = model.loss_fn(params, batch)
    got_loss = float(loss_sum) / float(metrics["ntokens"])
    np.testing.assert_allclose(got_loss, ref_loss, rtol=2e-4)


def test_hf_export_roundtrip(hf_and_ours, tmp_path):
    """Our params -> HF safetensors -> reload into a fresh HF model: the
    exported checkpoint must produce the identical loss."""
    import torch
    from transformers.models.qwen3_vl import Qwen3VLForConditionalGeneration

    hf_model, hf_cfg, model, params = hf_and_ours
    out = tmp_path / "export"
    model.family.save_hf_checkpoint(params, model.config, str(out))

    reloaded = Qwen3VLForConditionalGeneration.from_pretrained(
        str(out), config=hf_cfg, torch_dtype=torch.float32
    ).eval()
    with torch.no_grad():
        for (n1, p1), (n2, p2) in zip(
            sorted(hf_model.named_parameters()),
            sorted(reloaded.named_parameters()),
        ):
            assert n1 == n2
            np.testing.assert_allclose(
                p1.numpy(), p2.numpy(), rtol=1e-6, atol=1e-6,
            )


def test_qwen3_vl_moe_loss_parity(tmp_path):
    """MoE variant: fused-chunked expert import + loss parity vs HF."""
    import torch
    from transformers.models.qwen3_vl_moe import (
        Qwen3VLMoeConfig, Qwen3VLMoeForConditionalGeneration,
    )

    cfg_hf = Qwen3VLMoeConfig(
        text_config=dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            moe_intermediate_size=32,
            num_experts=4,
            num_experts_per_tok=2,
            norm_topk_prob=True,
            router_aux_loss_coef=0.0,
            output_router_logits=False,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            head_dim=16,
            max_position_embeddings=512,
            rope_theta=10000.0,
            rope_scaling={"rope_type": "default", "mrope_section": [2, 3, 3],
                          "mrope_interleaved": True},
            tie_word_embeddings=False,
        ),
        vision_config=dict(
            depth=2,
            hidden_size=32,
            intermediate_size=64,
            num_heads=2,
            in_channels=3,
            patch_size=2,
            temporal_patch_size=2,
            spatial_merge_size=2,
            out_hidden_size=64,
            num_position_embeddings=16,
            deepstack_visual_indexes=[0],
        ),
        image_token_id=IMG_ID,
        video_token_id=VID_ID,
        vision_start_token_id=VSTART_ID,
    )
    torch.manual_seed(0)
    hf_model = Qwen3VLMoeForConditionalGeneration(cfg_hf).eval()
    ckpt = tmp_path / "hf_moe"
    hf_model.save_pretrained(ckpt, safe_serialization=True)

    from veomni_tpu.models import build_foundation_model

    model = build_foundation_model(str(ckpt), dtype="float32")
    assert model.config.model_type == "qwen3_vl_moe"
    assert model.config.text.num_experts == 4
    params = model.load_hf(str(ckpt))

    grids = [(1, 4, 4)]
    n_merged = [t * (h // 2) * (w // 2) for t, h, w in grids]
    rng = np.random.default_rng(3)
    cfg = model.config
    pixel_values, grid_thw = _vision_inputs(rng, grids, cfg.vision.patch_dim)
    ids = [VSTART_ID] + [IMG_ID] * n_merged[0] + list(rng.integers(11, 256, 9))
    input_ids = np.asarray([ids], np.int64)
    labels = input_ids.copy()

    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.from_numpy(input_ids),
            labels=torch.from_numpy(labels),
            pixel_values=torch.from_numpy(pixel_values),
            image_grid_thw=torch.from_numpy(grid_thw),
        )
    ref_loss = float(ref.loss)

    from veomni_tpu.models.qwen3_vl import mrope_position_ids, vision_metadata

    meta = vision_metadata(grids, cfg.vision, n_pad_patches=pixel_values.shape[0])
    pos = mrope_position_ids(input_ids, grids, cfg)
    shifted = np.full_like(labels, -100)
    shifted[:, :-1] = labels[:, 1:]
    batch = {
        "input_ids": jnp.asarray(input_ids, jnp.int32),
        "labels": jnp.asarray(shifted, jnp.int32),
        "position_ids": jnp.asarray(pos, jnp.int32),
        "segment_ids": jnp.ones_like(jnp.asarray(input_ids, jnp.int32)),
        "pixel_values": jnp.asarray(pixel_values),
        "vis_pos_hw": jnp.asarray(meta["pos_hw"]),
        "vis_pos_interp_idx": jnp.asarray(meta["pos_interp_idx"]),
        "vis_pos_interp_w": jnp.asarray(meta["pos_interp_w"]),
        "vis_seg_full": jnp.asarray(meta["seg_full"]),
        "vis_merged_mask": jnp.asarray(meta["merged_mask"]),
    }
    loss_sum, metrics = model.loss_fn(params, batch)
    got_loss = float(loss_sum) / float(metrics["ntokens"])
    np.testing.assert_allclose(got_loss, ref_loss, rtol=3e-4)

    # export round-trip: fused-chunked gate_up reassembled correctly
    out = tmp_path / "export_moe"
    model.family.save_hf_checkpoint(params, cfg, str(out))
    reloaded = Qwen3VLMoeForConditionalGeneration.from_pretrained(
        str(out), config=cfg_hf, torch_dtype=torch.float32
    ).eval()
    with torch.no_grad():
        for (n1, p1), (n2, p2) in zip(
            sorted(hf_model.named_parameters()),
            sorted(reloaded.named_parameters()),
        ):
            assert n1 == n2
            np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-6, atol=1e-6)


def test_qwen3_vl_trainer_e2e(tmp_path):
    """Full trainer drive through the qwen3_vl data path: images ->
    merge-block patches + interp plan -> interleaved mrope -> deepstack
    train steps (loss finite, checkpoint written, HF export reimports)."""
    import json

    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer import VLMTrainer

    rng = np.random.default_rng(0)
    rows = []
    for i in range(24):
        rows.append({
            "input_ids": rng.integers(11, 256, int(rng.integers(8, 24))).tolist(),
            # 8x8 or 12x8 pixels -> 4x4 / 6x4 patch grids (patch 2, merge 2)
            "images": [rng.random((8 + 4 * (i % 2), 8, 3)).tolist()],
        })
    with open(tmp_path / "data.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "qwen3_vl",
        "vocab_size": 256,
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "head_dim": 16,
        "rope_scaling": {"rope_type": "default", "mrope_section": [2, 3, 3]},
        "vision": {
            "depth": 2, "hidden_size": 32, "intermediate_size": 64,
            "num_heads": 2, "patch_size": 2, "spatial_merge_size": 2,
            "out_hidden_size": 64, "num_position_embeddings": 16,
            "deepstack_visual_indexes": [0],
        },
        "image_token_id": 9, "video_token_id": 10,
        "vision_start_token_id": 8,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.data.data_type = "pretokenized"
    args.data.max_seq_len = 64
    args.data.max_patches = 256
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    destroy_parallel_state()
    try:
        trainer = VLMTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 3
        assert np.isfinite(ctl.metrics["loss"])
        trainer.checkpointer.close()
        import os

        hf_dir = os.path.join(args.train.output_dir, "hf_ckpt")
        assert os.path.exists(os.path.join(hf_dir, "model.safetensors"))
        from veomni_tpu.models import build_foundation_model

        m2 = build_foundation_model(hf_dir, dtype="float32")
        m2.load_hf(hf_dir)
    finally:
        destroy_parallel_state()
