"""Wan T2V DiT: structural self-tests.

No diffusers oracle is available in this environment (the reference wraps
``diffusers.WanTransformer3DModel``), so these tests pin the architecture's
own contract: shape/adaLN/rope behavior, checkpoint round-trip through the
diffusers-format key layout, and a full DiTTrainer drive.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veomni_tpu.models.wan import (
    WanConfig, hf_to_params, init_params, loss_fn, params_to_hf, rope_3d,
    wan_forward,
)

TINY = dict(
    patch_size=(1, 2, 2),
    num_attention_heads=2,
    attention_head_dim=24,  # t/h/w rope split 8/8/8
    in_channels=4,
    out_channels=4,
    text_dim=32,
    freq_dim=32,
    ffn_dim=96,
    num_layers=2,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def model():
    cfg = WanConfig(**TINY)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_forward_shape_and_determinism(model):
    cfg, params = model
    rng = np.random.default_rng(0)
    lat = jnp.asarray(rng.standard_normal((2, 4, 2, 8, 8)), jnp.float32)
    t = jnp.asarray([100.0, 700.0], jnp.float32)
    text = jnp.asarray(rng.standard_normal((2, 5, 32)), jnp.float32)
    out = wan_forward(params, cfg, lat, t, text)
    assert out.shape == lat.shape
    out2 = wan_forward(params, cfg, lat, t, text)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # timestep conditioning changes the output (adaLN path live)
    out3 = wan_forward(params, cfg, lat, t * 0.1, text)
    assert np.abs(np.asarray(out) - np.asarray(out3)).max() > 1e-6
    # text conditioning changes the output (cross-attention live)
    out4 = wan_forward(params, cfg, lat, t, text * -1.0)
    assert np.abs(np.asarray(out) - np.asarray(out4)).max() > 1e-6


def test_rope_split():
    cfg = WanConfig(**TINY)
    cos, sin = rope_3d(cfg, 2, 4, 4)
    assert cos.shape == (1, 32, 24)
    # temporal-axis angles identical across (h, w) within a frame
    c = np.asarray(cos).reshape(2, 4, 4, 24)
    np.testing.assert_array_equal(
        c[1, :, :, :8], np.broadcast_to(c[1, 0, 0, :8], (4, 4, 8))
    )
    # height-axis angles identical across w
    np.testing.assert_array_equal(
        c[0, 1, :, 8:16], np.broadcast_to(c[0, 1, 0, 8:16], (4, 8))
    )
    # width-axis angles identical across h
    np.testing.assert_array_equal(
        c[0, :, 1, 16:24], np.broadcast_to(c[0, 0, 1, 16:24], (4, 8))
    )


def test_loss_and_grads_finite(model):
    cfg, params = model
    rng = np.random.default_rng(1)
    batch = {
        "latents": jnp.asarray(rng.standard_normal((2, 4, 2, 8, 8)), jnp.float32),
        "timestep": jnp.asarray([10.0, 500.0], jnp.float32),
        "text_states": jnp.asarray(rng.standard_normal((2, 5, 32)), jnp.float32),
        "target": jnp.asarray(rng.standard_normal((2, 4, 2, 8, 8)), jnp.float32),
    }

    def scalar(p):
        l, _ = loss_fn(p, cfg, batch)
        return l

    loss, grads = jax.value_and_grad(scalar)(params)
    assert np.isfinite(float(loss))
    flat = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    # every parameter receives gradient (cross-attn, adaLN tables, rope paths)
    assert all(np.abs(np.asarray(g)).max() > 0 for g in flat)


def test_checkpoint_roundtrip(model, tmp_path):
    from safetensors.flax import save_file

    cfg, params = model
    tensors = params_to_hf(params, cfg)
    save_file({k: jnp.asarray(v) for k, v in tensors.items()},
              str(tmp_path / "model.safetensors"))
    with open(tmp_path / "config.json", "w") as f:
        json.dump({"_class_name": "WanTransformer3DModel"}, f)
    reloaded = hf_to_params(str(tmp_path), cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, reloaded,
    )


def test_wan_trainer_e2e(tmp_path):
    from veomni_tpu.arguments import VeOmniArguments
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.trainer.dit_trainer import DiTTrainer

    rng = np.random.default_rng(0)
    with open(tmp_path / "data.jsonl", "w") as f:
        for _ in range(16):
            f.write(json.dumps({
                "latents": rng.standard_normal((4, 2, 8, 8)).tolist(),
                "text_states": rng.standard_normal((5, 32)).tolist(),
            }) + "\n")

    args = VeOmniArguments()
    args.model.config_overrides = {
        "model_type": "wan_t2v", **{k: v for k, v in TINY.items() if k != "dtype"},
        "latent_shape": (4, 2, 8, 8), "text_len": 5,
    }
    args.data.train_path = str(tmp_path / "data.jsonl")
    args.train.output_dir = str(tmp_path / "out")
    args.train.micro_batch_size = 2
    args.train.train_steps = 3
    args.train.bf16 = False
    args.train.async_save = False
    args.train.save_hf_weights = True
    args.train.log_steps = 100
    destroy_parallel_state()
    try:
        trainer = DiTTrainer(args)
        ctl = trainer.train()
        assert ctl.global_step == 3
        assert np.isfinite(ctl.metrics["loss"])
        trainer.checkpointer.close()
        import os

        hf_dir = os.path.join(args.train.output_dir, "hf_ckpt")
        assert os.path.exists(
            os.path.join(hf_dir, "diffusion_pytorch_model.safetensors")
        )
        # diffusers-format reload
        from veomni_tpu.models import build_foundation_model

        m2 = build_foundation_model(hf_dir, dtype="float32")
        m2.load_hf(hf_dir)
    finally:
        destroy_parallel_state()
