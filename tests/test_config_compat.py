"""Reference-schema YAML compatibility (VERDICT r4 #6: a VeOmni recipe drops
in). Reference: ``veomni/arguments/arguments_types.py:465-526,1440``."""

import glob
import os

import pytest

from veomni_tpu.arguments import VeOmniArguments, parse_args

REFERENCE_YAML = """
model:
  model_path: Some-Model-Base
  ops_implementation:
    attn_implementation: flash_attention_2
    cross_entropy_loss_implementation: chunk_loss
    rms_norm_implementation: eager
  lora_config:
    rank: 64
    alpha: 32
    lora_modules: [q_proj, v_proj]
data:
  train_path: corpus
  data_type: conversation
  max_seq_len: 2048
  train_size: 750000
  datasets_type: iterable
  dataloader:
    type: native
    drop_last: true
train:
  accelerator:
    ulysses_size: 2
    ep_size: 4
    dp_shard_size: 8
    fsdp_config:
      fsdp_mode: fsdp2
      reshard_after_forward: true
      mixed_precision:
        enable: true
        param_dtype: bfloat16
    offload_config:
      enable_activation: true
  gradient_checkpointing:
    enable: true
    enable_reentrant: false
  global_batch_size: 64
  micro_batch_size: 1
  max_steps: 500
  dyn_bsz: true
  freeze_vit: true
  vit_lr: 1.0e-5
  bsz_warmup_ratio: 0.007
  init_device: meta
  empty_cache_steps: 500
  optimizer:
    type: adamw
    lr: 1.0e-4
    lr_decay_style: cosine
    lr_warmup_ratio: 0.01
    weight_decay: 0.1
    max_grad_norm: 1.0
  checkpoint:
    output_dir: run_out
    manager: dcp
    save_steps: 100
    save_hf_weights: true
  wandb:
    enable: true
    project: VeOmni
    name: my_run
  profile:
    enable: true
    start_step: 3
    end_step: 5
    record_shapes: true
dpo_config:
  beta: 0.25
  loss_type: sigmoid
"""


def test_reference_recipe_translates(tmp_path):
    p = tmp_path / "ref.yaml"
    p.write_text(REFERENCE_YAML)
    a = parse_args(VeOmniArguments, [str(p)])

    # accelerator block -> flat parallel sizes
    assert a.train.ulysses_parallel_size == 2
    assert a.train.expert_parallel_size == 4
    assert a.train.data_parallel_shard_size == 8
    assert a.train.data_parallel_mode == "fsdp"
    # mixed precision / offload / gradient checkpointing
    assert a.train.bf16 is True
    assert a.train.gradient_checkpointing_policy == "offload"
    assert a.train.enable_gradient_checkpointing is True
    # optimizer flatten
    assert a.train.optimizer == "adamw"
    assert a.train.lr == pytest.approx(1e-4)
    assert a.train.lr_decay_style == "cosine"
    assert a.train.weight_decay == pytest.approx(0.1)
    # checkpoint block (dcp -> orbax)
    assert a.train.output_dir == "run_out"
    assert a.train.ckpt_manager == "orbax"
    assert a.train.save_steps == 100
    # wandb / profile
    assert a.train.use_wandb is True and a.train.wandb_project == "VeOmni"
    assert a.train.enable_profiling is True and a.train.profile_end_step == 5
    # cross-section moves
    assert a.train.train_steps == 500
    assert a.data.dyn_bsz is True
    assert "^vision_tower" in a.model.freeze_modules
    assert a.train.module_lr_scales["^vision_tower"] == pytest.approx(0.1)
    # lora_config + ops impls
    assert a.model.lora["rank"] == 64 and a.model.lora["alpha"] == 32
    assert a.model.attn_implementation == "auto"
    assert a.model.ops_implementation == {
        "fused_linear_cross_entropy": "xla_chunked",
        "rms_norm": "xla",
    }
    # data block
    assert a.data.dataset_type == "iterable"
    assert a.data.dataloader_type == "native"
    # top-level dpo_config
    assert a.train.dpo_beta == pytest.approx(0.25)


def test_native_schema_keeps_typo_safety(tmp_path):
    p = tmp_path / "native.yaml"
    p.write_text("train:\n  learning_rate: 1e-4\n")  # typo for lr
    with pytest.raises(AttributeError, match="learning_rate"):
        parse_args(VeOmniArguments, [str(p)])


def test_native_flat_keys_survive_translator(tmp_path):
    """A native scalar that collides with a reference block name (optimizer)
    must pass through untouched."""
    p = tmp_path / "native.yaml"
    p.write_text("train:\n  optimizer: muon\n  lr: 3.0e-4\n")
    a = parse_args(VeOmniArguments, [str(p)])
    assert a.train.optimizer == "muon"
    assert a.train.lr == pytest.approx(3e-4)


def test_native_ops_implementation_not_translated(tmp_path):
    p = tmp_path / "native.yaml"
    p.write_text(
        "model:\n  ops_implementation:\n    rms_norm: xla\n"
    )
    a = parse_args(VeOmniArguments, [str(p)])
    assert a.model.ops_implementation == {"rms_norm": "xla"}


@pytest.mark.skipif(
    not os.path.isdir("/root/reference/configs"),
    reason="reference recipes not present",
)
def test_all_reference_recipes_parse():
    paths = sorted(
        glob.glob("/root/reference/configs/**/*.yaml", recursive=True)
    )
    assert len(paths) >= 30
    for p in paths:
        parse_args(VeOmniArguments, [p])  # must not raise
