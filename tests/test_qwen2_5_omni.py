"""Qwen2.5-Omni thinker parity vs HF transformers (tiny config).

Reference capability: veomni/models/transformers/qwen2_5_omni/ (training the
thinker: audio encoder + vision tower + LM). Oracle style of
test_qwen2_5_vl.py: build a tiny HF thinker, export, import, compare.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

IMG_ID, VID_ID, VSTART_ID = 9, 10, 8
AUD_ID, ASTART_ID, AEND_ID = 5, 6, 7


def _tiny_hf_thinker(tmp_path):
    import torch
    from transformers import (
        Qwen2_5OmniThinkerConfig, Qwen2_5OmniThinkerForConditionalGeneration,
    )

    cfg = Qwen2_5OmniThinkerConfig(
        audio_config=dict(
            num_mel_bins=16, d_model=32, encoder_layers=2,
            encoder_attention_heads=2, encoder_ffn_dim=64, n_window=8,
            max_source_positions=64, output_dim=64,
        ),
        vision_config=dict(
            depth=2, hidden_size=32, intermediate_size=64, num_heads=2,
            in_channels=3, patch_size=2, temporal_patch_size=2,
            spatial_merge_size=2, window_size=8, fullatt_block_indexes=[1],
            out_hidden_size=64,
        ),
        text_config=dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            rope_theta=10000.0, tie_word_embeddings=False,
            rope_scaling={"type": "default", "mrope_section": [2, 3, 3]},
        ),
        audio_token_index=AUD_ID, image_token_index=IMG_ID,
        video_token_index=VID_ID, vision_start_token_id=VSTART_ID,
        audio_start_token_id=ASTART_ID, audio_end_token_id=AEND_ID,
        position_id_per_seconds=25,
    )
    torch.manual_seed(0)
    model = Qwen2_5OmniThinkerForConditionalGeneration(cfg).eval()
    out = tmp_path / "hf_thinker"
    model.save_pretrained(out, safe_serialization=True)
    return model, str(out)


@pytest.fixture(scope="module")
def hf_and_ours(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("q25omni")
    hf_model, ckpt = _tiny_hf_thinker(tmp_path)

    from veomni_tpu.models import build_foundation_model

    # audio static slot: 32 mel frames (= 2 chunks of 2*n_window=16)
    model = build_foundation_model(ckpt, dtype="float32", audio_max_frames=32)
    params = model.load_hf(ckpt)
    return hf_model, model, params


def test_audio_encoder_parity(hf_and_ours):
    import torch

    hf_model, model, params = hf_and_ours
    acfg = model.config.audio
    t_mel = acfg.max_frames
    rng = np.random.default_rng(0)
    mel = rng.standard_normal((1, acfg.num_mel_bins, t_mel)).astype(np.float32)

    with torch.no_grad():
        ref = hf_model.audio_tower(
            torch.from_numpy(mel[0]),
            feature_lens=torch.tensor([t_mel]),
            aftercnn_lens=torch.tensor([t_mel // 2]),
        ).last_hidden_state.numpy()

    from veomni_tpu.models.qwen2_5_omni import audio_encoder_forward

    got = audio_encoder_forward(
        params["audio_tower"], acfg,
        jnp.asarray(mel.transpose(0, 2, 1)), dtype=jnp.float32,
    )
    np.testing.assert_allclose(np.asarray(got)[0], ref, rtol=2e-4, atol=2e-4)


def test_thinker_loss_parity(hf_and_ours):
    import torch

    hf_model, model, params = hf_and_ours
    cfg = model.config
    acfg, vcfg = cfg.audio, cfg.vision
    rng = np.random.default_rng(1)

    # one audio (32 mel frames -> 8 tokens) + one image (4x4 grid -> 4 merged)
    t_mel = acfg.max_frames
    n_audio_tok = acfg.tokens_per_audio
    grids = [(1, 4, 4)]
    n_merged = 4
    mel = rng.standard_normal((1, acfg.num_mel_bins, t_mel)).astype(np.float32)
    patch_dim = vcfg.patch_dim
    pixel_values = rng.standard_normal((16, patch_dim)).astype(np.float32)

    ids = (
        [ASTART_ID] + [AUD_ID] * n_audio_tok + [AEND_ID]
        + list(rng.integers(11, 256, 4))
        + [VSTART_ID] + [IMG_ID] * n_merged
        + list(rng.integers(11, 256, 6))
    )
    input_ids = np.asarray([ids], np.int64)
    labels = input_ids.copy()
    labels[:, : n_audio_tok + 2] = -100

    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.from_numpy(input_ids),
            labels=torch.from_numpy(labels),
            input_features=torch.from_numpy(mel),
            feature_attention_mask=torch.ones(1, t_mel, dtype=torch.bool),
            pixel_values=torch.from_numpy(pixel_values),
            image_grid_thw=torch.tensor(grids),
        )
    ref_loss = float(ref.loss)

    from veomni_tpu.models.qwen2_5_vl import mrope_position_ids, vision_metadata

    meta = vision_metadata(grids, vcfg, n_pad_patches=pixel_values.shape[0])
    pos = mrope_position_ids(input_ids, grids, cfg)
    shifted = np.full_like(labels, -100)
    shifted[:, :-1] = labels[:, 1:]
    batch = {
        "input_ids": jnp.asarray(input_ids, jnp.int32),
        "labels": jnp.asarray(shifted, jnp.int32),
        "position_ids": jnp.asarray(pos, jnp.int32),
        "segment_ids": jnp.ones_like(jnp.asarray(input_ids, jnp.int32)),
        "pixel_values": jnp.asarray(pixel_values)[jnp.asarray(meta["patch_gather"])],
        "vis_pos_hw": jnp.asarray(meta["pos_hw"]),
        "vis_seg_window": jnp.asarray(meta["seg_window"]),
        "vis_seg_full": jnp.asarray(meta["seg_full"]),
        "vis_reverse": jnp.asarray(meta["reverse"]),
        "vis_merged_mask": jnp.asarray(meta["merged_mask"]),
        "audio_features": jnp.asarray(mel.transpose(0, 2, 1)),
        "audio_mask": jnp.ones((1,), bool),
    }
    loss_sum, metrics = model.loss_fn(params, batch)
    got_loss = float(loss_sum) / float(metrics["ntokens"])
    np.testing.assert_allclose(got_loss, ref_loss, rtol=2e-4)


def test_hf_export_roundtrip(hf_and_ours, tmp_path):
    hf_model, model, params = hf_and_ours
    out = str(tmp_path / "export")
    model.save_hf(out, params)

    from veomni_tpu.models import build_foundation_model

    cfg = model.config
    model2 = build_foundation_model(
        config=cfg,
    )
    params2 = model2.family.hf_to_params(out, cfg)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(params2),
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(pa))
