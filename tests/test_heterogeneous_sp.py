"""Per-module heterogeneous SP: towers at sp=1 inside an LM at ulysses/cp>1
must reproduce the unsharded math exactly (reference sp_gather_seqs /
use_parallel_state scoping, sequence_parallel/data.py:149-298).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

TEXT = dict(model_type="qwen2", vocab_size=600, hidden_size=64,
            intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, attention_bias=True,
            dtype=jnp.float32)
VISION = dict(image_size=28, patch_size=7, hidden_size=32, intermediate_size=64,
              num_hidden_layers=2, num_attention_heads=2, spatial_merge_size=2)
AUDIO = dict(n_mels=16, max_frames=32, subsample=4, hidden_size=32,
             intermediate_size=64, num_hidden_layers=2, num_attention_heads=2)


def _cfg():
    from veomni_tpu.models.omni import OmniConfig

    return OmniConfig(
        text=dict(TEXT), vision=dict(VISION), audio=dict(AUDIO),
        image_token_id=510, audio_token_id=511,
    )


def _batch(cfg, bsz=4, seq=64):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 500, (bsz, seq)).astype(np.int32)
    tpi = cfg.vision.tokens_per_image
    tpa = cfg.audio.tokens_per_audio
    # one image + one audio per row, placeholder runs at fixed offsets
    for b in range(bsz):
        ids[b, 2:2 + tpi] = 510
        ids[b, 4 + tpi:4 + tpi + tpa] = 511
    return {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(ids),
        "position_ids": jnp.broadcast_to(jnp.arange(seq), (bsz, seq)),
        "segment_ids": jnp.ones((bsz, seq), jnp.int32),
        "pixel_patches": jnp.asarray(
            rng.random((bsz, 1, (28 // 7) ** 2, 7 * 7 * 3)), jnp.float32),
        "image_mask": jnp.ones((bsz, 1), bool),
        "audio_features": jnp.asarray(rng.random((bsz, 1, 32, 16)), jnp.float32),
        "audio_mask": jnp.ones((bsz, 1), bool),
    }


def _loss_and_gnorm(layout):
    from veomni_tpu.models.omni import (
        abstract_omni_params, init_omni_params, omni_loss_fn,
    )
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state

    cfg = _cfg()
    destroy_parallel_state()
    ps = init_parallel_state(**layout)
    with use_parallel_state(ps):
        params = init_omni_params(jax.random.PRNGKey(0), cfg)
        batch = _batch(cfg)
        # LM batch tensors sequence-sharded; tower slots replicated
        seq_sharding = ps.sharding(ps.dp_axes, ps.sp_axes)
        batch = {
            k: jax.device_put(
                v,
                seq_sharding if np.ndim(v) == 2 and v.shape[-1] == 64
                else ps.sharding(ps.dp_axes),
            )
            for k, v in batch.items()
        }

        def norm_loss(p, b):
            loss_sum, metrics = omni_loss_fn(p, cfg, b)
            return loss_sum / jnp.maximum(metrics["ntokens"], 1)

        loss, grads = jax.jit(jax.value_and_grad(norm_loss))(params, batch)
        gnorm = jax.jit(optax.global_norm)(grads)
        out = float(loss), float(gnorm)
    destroy_parallel_state()
    return out


@pytest.mark.parametrize(
    "layout",
    [dict(ulysses_size=2, dp_shard_size=2), dict(cp_size=2, dp_shard_size=2)],
    ids=["ulysses2", "cp2"],
)
def test_towers_sp1_lm_sp2_matches_unsharded(layout):
    base = _loss_and_gnorm(dict(dp_shard_size=4))
    het = _loss_and_gnorm(layout)
    np.testing.assert_allclose(het[0], base[0], rtol=2e-5)
    np.testing.assert_allclose(het[1], base[1], rtol=2e-4)
