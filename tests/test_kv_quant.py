"""Quantized serving tier: int8 KV blocks + int8 decode weights.

The contract under test has two halves. Quantization changes VALUES, so
token-exact parity with the f32 engine is replaced by the fixed-seed
quality gate (``tests/tools/quality_gate.py``: bounded perplexity delta +
top-k overlap). It must NOT change STRUCTURE, so every exact identity of
the unquantized engine — no-leak block accounting, free+cached == pool,
deterministic replay, preemption-recompute self-parity, CoW isolation,
spec-decode self-parity, bucket-bounded compile counts — is asserted
bit-for-bit on the quantized engine across the same hard drill matrix the
f32 engine earns its keep on.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veomni_tpu.models import TransformerConfig, build_foundation_model
from veomni_tpu.models import decode as decode_mod
from veomni_tpu.ops.quantization import (
    DECODE_QUANT_KEYS,
    QuantizedKV,
    QuantizedWeight,
    decode_dot,
    dequantize_rows,
    kv_block_nbytes,
    make_kv_pool,
    quantize_decode_params,
    quantize_rows,
    quantize_weight,
)
from veomni_tpu.serving import EngineConfig, InferenceEngine, Request, SamplingParams

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tools"))
from quality_gate import (  # noqa: E402
    PPL_REL_DELTA_BOUND,
    TOPK_OVERLAP_BOUND,
    assert_quality_gate,
)

QWEN3 = dict(
    model_type="qwen3", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True,
)
GPT_OSS_ISH = dict(
    model_type="gpt_oss", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=4, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, attention_sinks=True,
    attention_bias=True, o_bias=True, sliding_window=8,
    layer_types=["sliding_attention", "full_attention"] * 2,
    hidden_act="gpt_oss_glu",
)
QWEN3_MOE = dict(
    model_type="qwen3_moe", vocab_size=128, hidden_size=64,
    intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, head_dim=16, qk_norm=True, num_experts=4,
    num_experts_per_tok=2, moe_intermediate_size=32,
)

#: the three shipped quantization modes: KV-only, weights-only, both
MODES = [("int8", "none"), ("none", "int8"), ("int8", "int8")]


@pytest.fixture(scope="module")
def qwen3():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3)
    model = build_foundation_model(config=cfg)
    return model.family.init_params(jax.random.PRNGKey(0), cfg), cfg


def _prompts(lengths, seed=0, vocab=128):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, vocab, n)] for n in lengths]


def _drill(params, cfg, prompts, max_new_tokens=6, **ec):
    """One staggered-arrival drill: first wave, two ticks, second wave,
    drain. Returns (engine, per-request token lists in submit order)."""
    eng = InferenceEngine(params, cfg, EngineConfig(**ec))
    ids = [eng.submit(Request(prompt_ids=p,
                              sampling=SamplingParams(
                                  max_new_tokens=max_new_tokens)))
           for p in prompts[:2]]
    for _ in range(2):
        eng.step()
    ids += [eng.submit(Request(prompt_ids=p,
                               sampling=SamplingParams(
                                   max_new_tokens=max_new_tokens)))
            for p in prompts[2:]]
    outs = eng.run()
    return eng, [outs[rid].token_ids for rid in ids]


def _assert_no_leak(eng):
    """The exact structural identities quantization must not disturb."""
    bm = eng.blocks
    assert bm.num_used == 0
    assert bm.num_free_uncached + bm.num_cached == bm.num_blocks - 1


# ------------------------------------------------------------------ unit layer
def test_quantize_rows_roundtrip_and_zero_rows():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 5, 16)), jnp.float32)
    q, s = quantize_rows(x)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert q.shape == x.shape and s.shape == x.shape[:-1]
    # symmetric absmax over the last dim: error bounded by half an LSB of
    # the per-row scale
    err = np.abs(np.asarray(dequantize_rows(q, s) - x))
    bound = np.asarray(s)[..., None] * 0.5 + 1e-7
    assert (err <= bound).all()
    # zero rows round-trip to EXACT zeros (fresh pool contract)
    zq, zs = quantize_rows(jnp.zeros((4, 16)))
    assert not np.asarray(zq).any() and not np.asarray(zs).any()
    assert not np.asarray(dequantize_rows(zq, zs)).any()


def test_quantized_kv_indexer_and_cow_copy_is_bit_exact():
    pool = make_kv_pool((2, 4, 8, 2, 16), "int8", jnp.float32)
    assert isinstance(pool, QuantizedKV)
    rows = jnp.asarray(np.random.default_rng(1).normal(size=(8, 2, 16)),
                       jnp.float32)
    # float write quantizes on the way in (prefill scatter / decode append)
    p1 = pool.at[0, 1].set(rows)
    q, s = quantize_rows(rows)
    assert (np.asarray(p1.data[0, 1]) == np.asarray(q)).all()
    assert (np.asarray(p1.scale[0, 1]) == np.asarray(s)).all()
    # QuantizedKV write copies payload + sidecar bit-exactly (the CoW path
    # must never re-quantize: that would compound rounding per copy)
    p2 = p1.at[0, 2].set(p1[0, 1])
    assert (np.asarray(p2.data[0, 2]) == np.asarray(p2.data[0, 1])).all()
    assert (np.asarray(p2.scale[0, 2]) == np.asarray(p2.scale[0, 1])).all()
    # the logical surface the serving paths rely on
    assert p2.shape == (2, 4, 8, 2, 16) and p2.ndim == 5
    assert p2[0].shape == (4, 8, 2, 16)


def test_kv_pool_nbytes_accounting_matches_sizing_primitive():
    shape = (2, 5, 8, 2, 16)  # [L, NB, BS, hkv, d]
    qpool = make_kv_pool(shape, "int8", jnp.float32)
    fpool = make_kv_pool(shape, "none", jnp.float32)
    # the live pool reports payload + sidecar; the pre-allocation sizing
    # primitive (k + v, per block) must agree exactly with 2x pool / NB
    assert qpool.nbytes == int(qpool.data.nbytes) + int(qpool.scale.nbytes)
    for pool, mode in ((qpool, "int8"), (fpool, "none")):
        per_block = kv_block_nbytes(2, 8, 2, 16, kv_quant=mode,
                                    dtype_bytes=4)
        assert 2 * pool.nbytes == per_block * shape[1], mode
    with pytest.raises(NotImplementedError):
        make_kv_pool(shape, "fp8", jnp.float32)
    with pytest.raises(ValueError):
        make_kv_pool(shape, "int4", jnp.float32)


def test_quantize_decode_params_structure_and_dispatch(qwen3):
    params, cfg = qwen3
    qp = quantize_decode_params(params)
    layers = qp["layers"]
    for name in DECODE_QUANT_KEYS & set(layers):
        assert isinstance(layers[name], QuantizedWeight), name
        assert layers[name].data.dtype == jnp.int8
        # scale keeps the leading layer axis so lax.scan slices both
        assert layers[name].scale.shape[0] == layers[name].data.shape[0]
    # everything outside the eligible set is the SAME object — embeddings,
    # norms, biases and the lm head stay full-width, bit-identical
    for name, w in params["layers"].items():
        if name not in DECODE_QUANT_KEYS or isinstance(w, dict):
            assert qp["layers"][name] is w, name
    assert qp["embed_tokens"] is params["embed_tokens"]
    assert qp["norm"] is params["norm"]
    # type-based registry dispatch: dense -> xla, QuantizedWeight -> xla_q8,
    # and the q8 product stays within the per-channel rounding envelope
    w = params["layers"]["q_proj"]
    x = jnp.asarray(np.random.default_rng(2).normal(size=(3, w.shape[1])),
                    jnp.float32)
    dense = decode_dot(x, w[0])
    quant = decode_dot(x[None], quantize_decode_params(params)["layers"]
                       ["q_proj"][0])[0]
    assert np.allclose(np.asarray(dense), np.asarray(quant),
                       atol=0.05, rtol=0.05)


def test_moe_experts_and_shared_experts_stay_unquantized():
    cfg = TransformerConfig(dtype=jnp.float32, **QWEN3_MOE)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    qp = quantize_decode_params(params)
    for seg in ("layers", "dense_layers"):
        tree = params.get(seg)
        if not isinstance(tree, dict):
            continue
        for name, w in tree.items():
            if isinstance(w, dict) or getattr(w, "ndim", 0) != 3:
                # expert stacks (4-D, grouped-GEMM) and nested subtrees
                # (shared_experts) pass through untouched
                assert qp[seg][name] is w, (seg, name)


# ------------------------------------------------------------- config surface
def test_engine_config_validation_and_fp8_scaffold(qwen3):
    params, cfg = qwen3
    with pytest.raises(ValueError, match="kv_quant"):
        EngineConfig(kv_quant="int4")
    with pytest.raises(ValueError, match="weight_quant"):
        EngineConfig(weight_quant="fp8")
    # fp8 KV is a declared-but-unshipped storage mode: the config accepts
    # it, the pool allocation refuses it loudly at engine construction
    with pytest.raises(NotImplementedError, match="fp8"):
        InferenceEngine(params, cfg, EngineConfig(
            num_slots=2, block_size=8, max_model_len=64, kv_quant="fp8",
        ))


# ------------------------------------------------------------- quality gate
@pytest.mark.parametrize("kv_quant,weight_quant", MODES)
def test_quality_gate_bounds(qwen3, kv_quant, weight_quant):
    """The shipping gate: fixed-seed teacher-forced perplexity delta and
    top-k overlap vs the f32 path, through the REAL paged serving path."""
    params, cfg = qwen3
    stats = assert_quality_gate(params, cfg, kv_quant=kv_quant,
                                weight_quant=weight_quant, block_size=8)
    assert stats["ppl_ref"] > 0 and stats["ppl_quant"] > 0
    assert stats["ppl_rel_delta"] <= PPL_REL_DELTA_BOUND
    assert stats["topk_overlap"] >= TOPK_OVERLAP_BOUND


def test_quality_gate_catches_scale_corruption(qwen3):
    """The gate is not a rubber stamp: inflating one projection's stored
    scales (a wrong-axis / wrong-constant quantization bug) must blow
    through the bounds it certifies the real modes against."""
    from veomni_tpu.serving import quality

    params, cfg = qwen3
    qp = quantize_decode_params(params)
    broken = dict(qp, layers=dict(
        qp["layers"],
        down_proj=QuantizedWeight(qp["layers"]["down_proj"].data,
                                  qp["layers"]["down_proj"].scale * 4.0),
    ))
    corpus = quality.fixed_corpus(cfg.vocab_size)
    nll_ref, nll_bad, overlaps = [], [], []
    for toks in corpus:
        ref = quality.teacher_forced_logits(params, cfg, toks, block_size=8)
        bad = quality.teacher_forced_logits(broken, cfg, toks, block_size=8)
        nll_ref.append(np.log(quality._ppl(ref, toks)))
        nll_bad.append(np.log(quality._ppl(bad, toks)))
        overlaps.append(quality._topk_overlap(ref, bad, 8))
    ppl_ref = float(np.exp(np.mean(nll_ref)))
    ppl_bad = float(np.exp(np.mean(nll_bad)))
    delta = abs(ppl_bad - ppl_ref) / ppl_ref
    # a 4x scale blowup on one projection must trip at least one bound
    assert (delta > PPL_REL_DELTA_BOUND
            or float(np.mean(overlaps)) < TOPK_OVERLAP_BOUND), (
        delta, float(np.mean(overlaps)))


# ------------------------------------------------------------- drill matrix
@pytest.mark.parametrize("kv_quant,weight_quant", MODES)
def test_quant_engine_staggered_identities(qwen3, kv_quant, weight_quant):
    """Staggered arrivals + prefix cache + chunked prefill through a
    quantized engine: full token counts, deterministic replay (fresh engine,
    same config -> bit-identical streams), exact no-leak identities."""
    params, cfg = qwen3
    prompts = _prompts((5, 9, 17, 12), seed=0)
    ec = dict(num_slots=2, block_size=8, max_model_len=64,
              prefix_cache=True, prefill_chunk=8,
              kv_quant=kv_quant, weight_quant=weight_quant)
    eng, toks = _drill(params, cfg, prompts, **ec)
    assert all(len(t) == 6 for t in toks)
    assert all(0 <= x < cfg.vocab_size for t in toks for x in t)
    _assert_no_leak(eng)
    # determinism: quantize-on-write is a pure function of the written rows
    eng2, toks2 = _drill(params, cfg, prompts, **ec)
    assert toks == toks2
    _assert_no_leak(eng2)


def test_quant_engine_preemption_recompute_self_parity(qwen3):
    """A pool too small for the load forces preemption; recompute through
    quantized blocks must resume every stream exactly where a roomy
    quantized engine would have taken it — rounding is deterministic, so
    recompute parity is still an EXACT identity, not a gated one."""
    params, cfg = qwen3
    prompts = _prompts((9, 11, 7), seed=1)
    roomy = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, kv_quant="int8",
    ))
    want = {}
    for p in prompts:
        rid = roomy.submit(Request(prompt_ids=p,
                                   sampling=SamplingParams(max_new_tokens=10)))
        want[tuple(p)] = roomy.run()[rid].token_ids
    tight = InferenceEngine(params, cfg, EngineConfig(
        num_slots=3, block_size=8, max_model_len=40, num_blocks=8,
        kv_quant="int8",
    ))
    ids = [tight.submit(Request(prompt_ids=p,
                                sampling=SamplingParams(max_new_tokens=10)))
           for p in prompts]
    outs = tight.run()
    assert tight.scheduler.preemption_count > 0
    for rid, p in zip(ids, prompts):
        assert outs[rid].token_ids == want[tuple(p)]
    _assert_no_leak(tight)


def test_quant_engine_cow_mid_block_isolation(qwen3):
    """CoW divergence on quantized blocks: the private copy is bit-exact
    (never re-quantized), the shared cached block is never corrupted, and
    the cache accounting matches the f32 engine's exactly."""
    params, cfg = qwen3
    rng = np.random.default_rng(12)
    base = [int(t) for t in rng.integers(1, cfg.vocab_size, 16)]
    diverged = base[:12] + [int(t) for t in rng.integers(1, 128, 4)]
    ec = EngineConfig(num_slots=2, block_size=8, max_model_len=64,
                      prefix_cache=True, kv_quant="int8")
    eng = InferenceEngine(params, cfg, ec)
    r1 = eng.submit(Request(prompt_ids=base,
                            sampling=SamplingParams(max_new_tokens=5)))
    first = eng.run()[r1].token_ids
    assert eng.blocks.cow_count == 0
    r2 = eng.submit(Request(prompt_ids=base,
                            sampling=SamplingParams(max_new_tokens=5)))
    r3 = eng.submit(Request(prompt_ids=diverged,
                            sampling=SamplingParams(max_new_tokens=5)))
    outs = eng.run()
    assert eng.blocks.cow_count == 1
    assert outs[r2].cached_tokens == 15  # P-1, same as the f32 engine
    assert outs[r3].cached_tokens == 8
    # cached replay == fresh computation: the CoW'd quantized block holds
    # exactly what a fresh prefill would have written
    assert outs[r2].token_ids == first
    # and a third replay still matches (the shared block is uncorrupted)
    r4 = eng.submit(Request(prompt_ids=base,
                            sampling=SamplingParams(max_new_tokens=5)))
    assert eng.run()[r4].token_ids == first
    fresh = InferenceEngine(params, cfg, ec)
    rd = fresh.submit(Request(prompt_ids=diverged,
                              sampling=SamplingParams(max_new_tokens=5)))
    assert fresh.run()[rd].token_ids == outs[r3].token_ids
    _assert_no_leak(eng)


def test_quant_engine_spec_decode_rollback_self_parity(qwen3):
    """Draft-then-verify over quantized blocks: the verify step scores
    against the same quantized rows the one-token path writes, so spec
    decoding stays EXACTLY lossless vs the non-spec quantized engine —
    including across rollback — and rollback leaves no block behind."""
    params, cfg = qwen3
    prompts = _prompts((9, 13, 5), seed=2)
    base_ec = dict(num_slots=2, block_size=8, max_model_len=64,
                   prefix_cache=True, kv_quant="int8", weight_quant="int8")
    plain = InferenceEngine(params, cfg, EngineConfig(**base_ec))
    want = {}
    for p in prompts:
        rid = plain.submit(Request(prompt_ids=p,
                                   sampling=SamplingParams(max_new_tokens=8)))
        want[tuple(p)] = plain.run()[rid].token_ids
    spec = InferenceEngine(params, cfg, EngineConfig(
        spec_k=3, spec_draft="ngram", **base_ec,
    ))
    ids = [spec.submit(Request(prompt_ids=p,
                               sampling=SamplingParams(max_new_tokens=8)))
           for p in prompts]
    outs = spec.run()
    for rid, p in zip(ids, prompts):
        assert outs[rid].token_ids == want[tuple(p)]
    m = spec.metrics()
    assert m["spec_proposed"] > 0  # the draft path actually engaged
    _assert_no_leak(spec)


@pytest.mark.parametrize("spec", ["gpt_oss_ish", "qwen3_moe"])
def test_quant_engine_dialect_identities_and_gate(spec):
    """The dialect extremes (sinks + alternating sliding windows; MoE MLP
    segments with unquantized expert stacks) through the fully quantized
    engine: deterministic replay, no-leak identities, quality gate green."""
    conf = {"gpt_oss_ish": GPT_OSS_ISH, "qwen3_moe": QWEN3_MOE}[spec]
    cfg = TransformerConfig(dtype=jnp.float32, **conf)
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)
    prompts = _prompts((9, 13, 5, 11), seed=6)
    ec = dict(num_slots=2, block_size=8, max_model_len=64,
              prefix_cache=True, prefill_chunk=8,
              kv_quant="int8", weight_quant="int8")
    eng, toks = _drill(params, cfg, prompts, **ec)
    eng2, toks2 = _drill(params, cfg, prompts, **ec)
    assert toks == toks2 and all(len(t) == 6 for t in toks)
    _assert_no_leak(eng)
    _assert_no_leak(eng2)
    assert_quality_gate(params, cfg, kv_quant="int8", weight_quant="int8",
                        block_size=8)


# ---------------------------------------------------------- compile counting
def test_quant_engine_compile_count_bounded(qwen3):
    """The q8 gather-attend is one more program per bucket, not per
    request: the quantized engine's decode compiles stay inside the same
    table-width bucket bound as f32, and re-running inside known buckets
    adds ZERO compiles."""
    params, cfg = qwen3
    eng = InferenceEngine(params, cfg, EngineConfig(
        num_slots=2, block_size=8, max_model_len=64,
        kv_quant="int8", weight_quant="int8",
    ))
    base = dict(decode_mod.TRACE_COUNTS)
    first = _prompts((5, 9, 17, 21, 33, 7), seed=3)
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=5))
             for p in first])
    delta = decode_mod.TRACE_COUNTS["paged_decode"] - base["paged_decode"]
    assert 1 <= delta <= 4, delta  # table-width buckets {1,2,4,8}
    mid = dict(decode_mod.TRACE_COUNTS)
    more = _prompts((6, 10, 18, 22, 34, 8, 12, 30), seed=4)
    eng.run([Request(prompt_ids=p, sampling=SamplingParams(max_new_tokens=5))
             for p in more])
    assert decode_mod.TRACE_COUNTS["paged_decode"] == mid["paged_decode"]
    _assert_no_leak(eng)


# ------------------------------------------------------------- capacity claim
def test_quant_capacity_ratio_at_fixed_pool_bytes(qwen3):
    """The headline: at the f32 pool's exact byte budget, int8 blocks fit
    >= 1.8x the max-length sequences — computed from the LIVE pools'
    nbytes (payload + sidecar) via the same devmem gauges scripts/serve.py
    exports, never from f32 math."""
    params, cfg = qwen3
    ec = dict(num_slots=2, block_size=8, max_model_len=64)
    f32 = InferenceEngine(params, cfg, EngineConfig(**ec))
    q8 = InferenceEngine(params, cfg, EngineConfig(kv_quant="int8", **ec))
    cap_f, cap_q = f32.kv_capacity(), q8.kv_capacity()
    # the gauges report the ACTUAL quantized footprint
    assert cap_q["pool_bytes"] == q8.k_pool.nbytes + q8.v_pool.nbytes
    assert cap_q["block_bytes"] < cap_f["block_bytes"]
    # and agree with the pre-allocation sizing primitive
    assert cap_q["block_bytes"] == kv_block_nbytes(
        cfg.num_hidden_layers, 8, cfg.num_key_value_heads, cfg.head_dim,
        kv_quant="int8")
    per_seq = cap_f["blocks_per_max_len_seq"]
    q_blocks_in_f32_budget = cap_f["pool_bytes"] // cap_q["block_bytes"]
    q_seqs = (q_blocks_in_f32_budget - 1) // per_seq  # block 0 reserved
    ratio = q_seqs / max(1.0, cap_f["max_concurrent_seqs"])
    assert ratio >= 1.8, (ratio, cap_f, cap_q)
