"""Long-context dryrun: 32k-64k-token train step on an 8-device virtual mesh.

Mirrors the reference's 128k@SP8 datapoint (BASELINE.md): ulysses x ring-CP
sequence parallelism + chunked-MLP (ChunkMBS) + remat, one REAL executed
train step per point plus XLA's compile-time memory analysis per device.

Run: python scripts/long_context_dryrun.py [--seq 32768 65536] [--sp u2cp4]
Prints one JSON line per point; paste the table into BENCH_NOTES.md.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from veomni_tpu.utils.testing import force_cpu_devices  # noqa: E402


def run_point(seq_len: int, layout: dict, *, hidden=512, layers=2,
              vocab=16384, remat_policy="dots", chunk_mbs=2,
              compile_only=False):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.optim import build_optimizer
    from veomni_tpu.parallel import init_parallel_state, use_parallel_state
    from veomni_tpu.parallel.parallel_state import destroy_parallel_state
    from veomni_tpu.train import build_train_state, build_train_step
    from veomni_tpu.train.train_step import resolve_state_shardings

    destroy_parallel_state()
    ps = init_parallel_state(**layout)
    with use_parallel_state(ps):
        cfg = TransformerConfig(
            model_type="qwen3",
            vocab_size=vocab,
            hidden_size=hidden,
            intermediate_size=hidden * 3,
            num_hidden_layers=layers,
            num_attention_heads=16,
            num_key_value_heads=8,
            head_dim=hidden // 16,
            qk_norm=True,
            rope_theta=1e6,
            max_position_embeddings=131072,
            dtype=jnp.float32,  # CPU mesh; dtype is layout-neutral here
            remat=True,
            remat_policy=remat_policy,
            chunk_mbs=chunk_mbs,
        )
        model = build_foundation_model(config=cfg)
        plan = model.get_parallel_plan()
        opt = build_optimizer(model.abstract(), lr=1e-4)

        def make_state(rng):
            return build_train_state(model.family.init_params(rng, cfg), opt)

        abs_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        shardings = resolve_state_shardings(abs_state, plan, ps)
        state = jax.jit(make_state, out_shardings=shardings)(jax.random.PRNGKey(0))

        keys = ("input_ids", "labels", "position_ids", "segment_ids")
        bsh = {k: NamedSharding(ps.mesh, P(None, ps.dp_axes, ps.sp_axes))
               for k in keys}
        step = build_train_step(
            model.loss_fn, opt, ps, state_shardings=shardings,
            batch_shardings=bsh,
        )

        rng = np.random.default_rng(0)
        # batch dim must cover the dp axes (fsdp8 layout: 8-way dp shard
        # needs 8 rows; sp layouts keep dp=1 and shard the sequence)
        ids = rng.integers(0, vocab, (1, max(ps.dp_size, 1), seq_len))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(seq_len), ids.shape).copy(), jnp.int32),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        batch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}

        lowered = step.lower(state, batch)
        t0 = time.perf_counter()
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        mem = compiled.memory_analysis()

        if compile_only:
            # the 64k x 8-virtual-device EXECUTION needs >100G host RAM
            # (XLA:CPU materializes every buffer; OOM-killed, r5 notes) —
            # the per-device memory analysis is the long-context datapoint
            loss, step_s = float("nan"), float("nan")
        else:
            t0 = time.perf_counter()
            state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])
            step_s = time.perf_counter() - t0

        n_dev = len(jax.devices())
        point = {
            "seq_len": seq_len,
            "layout": {k: v for k, v in layout.items() if v > 1},
            "remat": remat_policy,
            "chunk_mbs": chunk_mbs,
            "hidden": hidden,
            "layers": layers,
            "loss": None if loss != loss else round(loss, 4),
            "compile_s": round(compile_s, 1),
            "step_s": None if step_s != step_s else round(step_s, 1),
            # per-device activation/temp memory is THE long-context number
            "temp_MiB_per_dev": round(mem.temp_size_in_bytes / n_dev / 2**20, 1),
            "args_MiB_per_dev": round(mem.argument_size_in_bytes / n_dev / 2**20, 1),
        }
    destroy_parallel_state()
    return point


LAYOUTS = {
    "u2cp4": dict(ulysses_size=2, cp_size=4, dp_shard_size=1),
    "cp8": dict(cp_size=8, dp_shard_size=1),
    "u4cp2": dict(ulysses_size=4, cp_size=2, dp_shard_size=1),
    "fsdp8": dict(dp_shard_size=8),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, nargs="+", default=[32768, 65536])
    ap.add_argument("--sp", default="u2cp4", choices=sorted(LAYOUTS))
    ap.add_argument("--remat", default="dots")
    ap.add_argument("--chunk_mbs", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--compile_only", action="store_true")
    args = ap.parse_args()

    if len(args.seq) > 1:
        # one seq per process: a second AOT lower/compile/call cycle in the
        # same process trips a JAX const-args miscount ("compiled for N
        # inputs but called with N-2") after the parallel-state rebuild
        import subprocess

        for seq in args.seq:
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--seq", str(seq), "--sp", args.sp,
                   "--remat", args.remat, "--chunk_mbs", str(args.chunk_mbs),
                   "--hidden", str(args.hidden), "--layers", str(args.layers)] \
                  + (["--compile_only"] if args.compile_only else [])
            subprocess.run(cmd, check=False)
        return

    force_cpu_devices(8)
    import jax

    # reruns of the same points skip the multi-minute XLA:CPU compiles
    jax.config.update("jax_compilation_cache_dir", "/tmp/veomni_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    point = run_point(
        args.seq[0], LAYOUTS[args.sp], remat_policy=args.remat,
        chunk_mbs=args.chunk_mbs, hidden=args.hidden, layers=args.layers,
        compile_only=args.compile_only,
    )
    print(json.dumps(point), flush=True)


if __name__ == "__main__":
    main()
