"""Minimal CLI driver for the continuous-batching inference engine.

Operates on token ids (tokenization is out of scope for the driver): either
a stream of synthetic random-prompt requests (``--synthetic N``) or explicit
comma-separated prompts (``--prompt-ids 5,17,3`` repeatable). Streams every
token event to stdout as it lands and prints the engine metrics at the end.

By default builds a tiny random-weight qwen3-style model (engine plumbing
demo / CPU smoke); ``--preset`` switches to a bench-scale model on the real
accelerator.

Run:
  python scripts/serve.py --synthetic 8 --max-new 32
  python scripts/serve.py --prompt-ids 1,2,3 --prompt-ids 4,5 \
      --temperature 0.8 --top-p 0.9
  python scripts/serve.py --requests-json mixed_traffic.json

``--requests-json`` takes a JSON list of request objects carrying the
per-request QoS surface: ``{"prompt_ids": [...], "priority":
"interactive"|"batch", "tenant": "...", "deadline_s": 2.5,
"max_new_tokens": 32, "temperature": 0.0, ...}`` (every field except
``prompt_ids`` optional, ``-`` reads stdin). Requests load-shed by the
bounded queue (``--queue-bound``) or cancelled past their deadline come
back as distinct terminal statuses in the final JSON — the driver never
waits on tokens a shed request will not produce.

Env knobs (flags win): VEOMNI_SERVE_SLOTS, VEOMNI_SERVE_BLOCK,
VEOMNI_SERVE_MAX_LEN, VEOMNI_SERVE_LOG_STEPS, VEOMNI_SERVE_PREFIX_CACHE
(1 default; 0 disables prompt-block sharing), VEOMNI_SERVE_PREFILL_CHUNK
(tokens prefilled per engine tick, 0 = whole prompt at once),
VEOMNI_SERVE_SPEC_K (draft-then-verify speculation: max drafted tokens per
slot per tick, 0 = off) with VEOMNI_SERVE_SPEC_DRAFT selecting the drafting
strategy (`ngram` prompt-lookup default, `off` disables),
VEOMNI_SERVE_QUEUE_BOUND (max waiting requests before submissions are
load-shed with a terminal "rejected" status; 0 = unbounded),
VEOMNI_SERVE_KV_QUANT (KV block storage: `none` default | `int8` —
int8 blocks + f32 scale sidecar, ~4x concurrent sequences per pool byte
at f32, quality-gated), VEOMNI_SERVE_WEIGHT_QUANT (decode weight
storage: `none` default | `int8` per-channel, dequantized in-kernel),
VEOMNI_SERVE_CLASSES (QoS classes "name:weight,..." highest priority
first; a single class restores plain FIFO), VEOMNI_SERVE_TENANT_INFLIGHT
(per-tenant waiting+running cap, 0 = uncapped),
VEOMNI_SERVE_REPLICAS (``--replicas N``: N > 1 serves through the
scale-out router — prefix-affinity dispatch over N data-parallel engine
replicas sharing one compiled-program bundle, QoS admission at the
router, per-replica ``serve.rK.*`` metrics and a per-replica status
census in the final JSON; 1 = the bare engine, byte-identical to the
seed driver), VEOMNI_SERVE_OUT (post-mortem dump dir, default CWD; when
set, router pump workers also heartbeat there as heartbeat-<rid>.json).
Self-healing fleet knobs (router mode, docs/serving.md):
VEOMNI_SERVE_STALL_S (per-replica step() deadline before a replica is
declared wedged and its pump thread abandoned, default 60, 0 disables),
VEOMNI_SERVE_MAX_RESPAWNS (respawn budget per replica lineage before
permanent retirement, default 2, 0 disables resurrection),
VEOMNI_SERVE_PROBATION (clean completions a respawned replica must serve
on spill traffic before rejoining affinity rotation, default 2),
VEOMNI_SERVE_MIN_LIVE (live-replica floor under which /healthz answers
503, default 1).
Live weight publication (docs/serving.md "Versioned weight
publication"): ``--publish-from <step dir>`` (VEOMNI_SERVE_PUBLISH_FROM)
loads a committed checkpoint generation through the integrity gate
(VEOMNI_SERVE_PUBLISH_VERIFY: off|size|full, default size — corrupt or
uncommitted generations are refused before any live buffer is touched)
and hot-publishes it: router mode rolls the fleet replica-by-replica
after the first token lands (drain -> in-place swap -> prefix-cache
flush, zero new traces); bare-engine mode swaps in place before serving.
VEOMNI_SERVE_PUBLISH_VERSION tags the published version (default: the
step dir's basename). /healthz and /debug/router report the fleet
weights version, per-replica versions and publish-in-progress.
VEOMNI_METRICS_PORT
serves Prometheus /metrics + /healthz while the pump runs (healthz carries
rejected/deadline-miss counts); /debug/requests
rows carry each request's cached_tokens, /debug/router the router's
replica census, and /debug/fleet the collective
census of the engine's compiled programs (docs/observability.md).
VEOMNI_FAULT_PLAN arms the serving fault points (serve.admit /
serve.prefill / serve.decode_tick, docs/resilience.md) for overload and
stall drills.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_model(args):
    import jax
    import jax.numpy as jnp

    from veomni_tpu.models import TransformerConfig, build_foundation_model

    if args.preset:
        from bench import bench_config

        cfg = bench_config(preset=args.preset)
    else:  # tiny random demo model
        cfg = TransformerConfig(
            model_type="qwen3", vocab_size=256, hidden_size=64,
            intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            qk_norm=True, dtype=jnp.float32,
        )
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(args.seed), cfg)
    return params, cfg


def _ckpt_params_loader(step_dir):
    """Restore the params subtree of a trainer checkpoint generation.

    Abstract target comes from on-disk metadata (same idiom as
    merge_checkpoint_to_hf.py), so the loader needs no knowledge of the
    optimizer that produced the checkpoint. This orbax version has no
    partial restore, so optimizer moments are materialized then dropped —
    budget host RAM accordingly for big models.
    """
    import jax
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(step_dir), "train_state")
    ckptr = ocp.StandardCheckpointer()
    meta = ckptr.metadata(path)
    # older orbax returns the tree metadata directly; newer wraps it
    meta = getattr(meta, "item_metadata", meta)
    target = jax.tree.map(
        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype),
        {"params": meta["params"], "opt_state": meta["opt_state"],
         "step": meta["step"]},
    )
    return ckptr.restore(path, target)["params"]


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--prompt-ids", action="append", default=[],
                    help="comma-separated token ids; repeatable")
    ap.add_argument("--synthetic", type=int, default=0,
                    help="also enqueue N random prompts")
    ap.add_argument("--synthetic-len", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=1.0)
    ap.add_argument("--eos-id", type=int, default=-1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--preset", default="",
                    help="bench.py BENCH_PRESETS model instead of the tiny demo")
    ap.add_argument("--slots", type=int,
                    default=int(os.environ.get("VEOMNI_SERVE_SLOTS", 4)))
    ap.add_argument("--block-size", type=int,
                    default=int(os.environ.get("VEOMNI_SERVE_BLOCK", 16)))
    ap.add_argument("--max-model-len", type=int,
                    default=int(os.environ.get("VEOMNI_SERVE_MAX_LEN", 2048)))
    ap.add_argument("--log-steps", type=int,
                    default=int(os.environ.get("VEOMNI_SERVE_LOG_STEPS", 0)))
    ap.add_argument("--prefix-cache", type=int, choices=(0, 1),
                    default=int(os.environ.get("VEOMNI_SERVE_PREFIX_CACHE",
                                               1)),
                    help="share prompt KV blocks across requests (radix "
                         "prefix cache; 0 restores exclusive blocks)")
    ap.add_argument("--prefill-chunk", type=int,
                    default=int(os.environ.get("VEOMNI_SERVE_PREFILL_CHUNK",
                                               0)),
                    help="max tokens prefilled per engine tick (0 = whole "
                         "prompt at once; bounds how long a long arrival "
                         "stalls running decodes)")
    ap.add_argument("--spec-k", type=int,
                    default=int(os.environ.get("VEOMNI_SERVE_SPEC_K", 0)),
                    help="speculative decoding: max drafted tokens per "
                         "slot per tick, verified in one batched step "
                         "(0 = off; token-exact either way)")
    ap.add_argument("--spec-draft",
                    default=os.environ.get("VEOMNI_SERVE_SPEC_DRAFT",
                                           "ngram"),
                    help="drafting strategy registry impl (`ngram` "
                         "prompt-lookup, `off`)")
    ap.add_argument("--kv-quant", choices=("none", "int8", "fp8"),
                    default=os.environ.get("VEOMNI_SERVE_KV_QUANT", "none"),
                    help="KV block storage mode: int8 stores blocks as "
                         "int8 + f32 scale sidecar (~4x concurrent "
                         "sequences per pool byte at f32; NOT bit-exact — "
                         "ships under the fixed-seed quality gate)")
    ap.add_argument("--weight-quant", choices=("none", "int8"),
                    default=os.environ.get("VEOMNI_SERVE_WEIGHT_QUANT",
                                           "none"),
                    help="decode-path weight storage: int8 per-channel, "
                         "dequantized in-kernel (decode_matmul/xla_q8)")
    ap.add_argument("--queue-bound", type=int,
                    default=int(os.environ.get("VEOMNI_SERVE_QUEUE_BOUND",
                                               0)),
                    help="max waiting requests before submissions are "
                         "load-shed (terminal 'rejected' status; 0 = "
                         "unbounded)")
    ap.add_argument("--classes",
                    default=os.environ.get("VEOMNI_SERVE_CLASSES",
                                           "interactive:4,batch:1"),
                    help="QoS classes 'name:weight,...', highest priority "
                         "first; a single class restores plain FIFO")
    ap.add_argument("--tenant-inflight", type=int,
                    default=int(os.environ.get("VEOMNI_SERVE_TENANT_INFLIGHT",
                                               0)),
                    help="per-tenant waiting+running cap (0 = uncapped)")
    ap.add_argument("--replicas", type=int,
                    default=int(os.environ.get("VEOMNI_SERVE_REPLICAS", 1)),
                    help="N > 1 serves through the scale-out router over N "
                         "data-parallel engine replicas (prefix-affinity "
                         "dispatch, QoS at the router); 1 = bare engine")
    ap.add_argument("--priority", default="interactive",
                    help="QoS class for CLI-built requests")
    ap.add_argument("--tenant", default="",
                    help="tenant id for CLI-built requests")
    ap.add_argument("--deadline-s", type=float, default=0.0,
                    help="end-to-end deadline for CLI-built requests "
                         "(0 = none)")
    ap.add_argument("--requests-json", default="",
                    help="JSON list of request objects (prompt_ids + "
                         "optional priority/tenant/deadline_s/"
                         "max_new_tokens/temperature/top_k/top_p/eos_id/"
                         "seed); '-' reads stdin")
    ap.add_argument("--publish-from",
                    default=os.environ.get("VEOMNI_SERVE_PUBLISH_FROM", ""),
                    help="checkpoint step dir (global_step_N) to hot-"
                         "publish: integrity-gated load, then rolling "
                         "in-place swap mid-serve (router mode) or a "
                         "pre-serve swap (bare engine)")
    ap.add_argument("--publish-version",
                    default=os.environ.get("VEOMNI_SERVE_PUBLISH_VERSION",
                                           ""),
                    help="version tag for the published weights "
                         "(default: the step dir's basename)")
    ap.add_argument("--publish-verify", choices=("off", "size", "full"),
                    default=os.environ.get("VEOMNI_SERVE_PUBLISH_VERIFY",
                                           "size"),
                    help="manifest verification mode for --publish-from "
                         "(docs/resilience.md; corrupt generations are "
                         "refused before any buffer is touched)")
    args = ap.parse_args()

    import numpy as np

    from veomni_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        Request,
        SamplingParams,
    )

    # VEOMNI_FAULT_PLAN: serving drills (serve.admit / serve.prefill /
    # serve.decode_tick) arm exactly like the trainer's
    from veomni_tpu.resilience.faults import arm_from_env

    arm_from_env()

    if args.replicas < 1:
        ap.error("--replicas must be >= 1")
    params, cfg = _build_model(args)
    ecfg = EngineConfig(
        num_slots=args.slots, block_size=args.block_size,
        max_model_len=args.max_model_len, log_every_steps=args.log_steps,
        prefix_cache=bool(args.prefix_cache),
        prefill_chunk=args.prefill_chunk,
        spec_k=args.spec_k, spec_draft=args.spec_draft,
        classes=args.classes, queue_bound=args.queue_bound,
        tenant_max_inflight=args.tenant_inflight,
        kv_quant=args.kv_quant, weight_quant=args.weight_quant,
    )
    router = None
    if args.replicas > 1:
        from veomni_tpu.serving import Router, RouterConfig

        # self-healing knobs (docs/serving.md "Self-healing fleet"):
        # wedge deadline, respawn budget, probation length, and the live
        # floor under which /healthz flips 503. Heartbeats only when the
        # operator chose an artifact dir — the CLI default CWD ('.')
        # would litter launch directories with heartbeat files.
        router = Router(params, cfg, ecfg, RouterConfig(
            replicas=args.replicas,
            replica_stall_s=float(
                os.environ.get("VEOMNI_SERVE_STALL_S", 60.0)),
            max_respawns=int(
                os.environ.get("VEOMNI_SERVE_MAX_RESPAWNS", 2)),
            probation_requests=int(
                os.environ.get("VEOMNI_SERVE_PROBATION", 2)),
            min_live=int(os.environ.get("VEOMNI_SERVE_MIN_LIVE", 1)),
            heartbeat_dir=os.environ.get("VEOMNI_SERVE_OUT", ""),
        ))
        # any replica describes the per-replica pool; all are identical
        first = next(iter(router.replicas.values())).engine
        driver, cap_engine = router, first
    else:
        driver = cap_engine = InferenceEngine(params, cfg, ecfg)
    # startup echo of the quant tier next to the capacity it buys: the
    # operator sees the storage mode AND the "users that fit" figure the
    # quantized pool actually provides, before any request lands
    cap = cap_engine.kv_capacity()
    print(json.dumps({
        "kv_quant": args.kv_quant, "weight_quant": args.weight_quant,
        "replicas": args.replicas,
        "kv_pool_bytes": cap["pool_bytes"],
        "kv_block_bytes": cap["block_bytes"],
        "kv_max_concurrent_seqs": cap["max_concurrent_seqs"],
    }), flush=True)
    # --publish-from: load THROUGH the integrity gate before serving a
    # single token, so a corrupt/uncommitted generation fails fast here
    # with an actionable error instead of mid-serve. The actual swap is
    # deferred: router mode rolls it after the first token lands (the
    # hot-publish path this flag exists to exercise); bare-engine mode
    # swaps in place right away (the engine refuses swaps while busy).
    publish_params = None
    publish_version = ""
    if args.publish_from:
        from veomni_tpu.resilience.integrity import CheckpointCorruptError
        from veomni_tpu.serving import load_published_params

        try:
            publish_params = load_published_params(
                args.publish_from, _ckpt_params_loader,
                verify_mode=args.publish_verify)
        except CheckpointCorruptError as e:
            raise SystemExit(
                f"--publish-from refused by integrity gate: {e}")
        publish_version = args.publish_version or os.path.basename(
            os.path.normpath(args.publish_from))
    if publish_params is not None and router is None:
        info = driver.swap_weights(publish_params)
        print(json.dumps({"publish": publish_version, "mode": "pre-serve",
                          **info}), flush=True)
        publish_params = None  # consumed
    # VEOMNI_METRICS_PORT: Prometheus /metrics + /healthz + /debug/flight +
    # /debug/requests (per-request timelines) for the pump loop (the engine
    # feeds the same registry the trainer exports through)
    from veomni_tpu.observability.exporter import maybe_start_from_env
    from veomni_tpu.observability.flight_recorder import (
        configure_flight_recorder,
    )

    # post-mortems (watchdog / crash) land somewhere deliberate, not
    # whatever CWD the operator launched from
    configure_flight_recorder(
        dump_dir=os.environ.get("VEOMNI_SERVE_OUT", ".")
    )
    from veomni_tpu.observability.metrics import get_registry

    # the exporter's HTTP thread must NOT read live scheduler internals the
    # pump loop mutates (unlocked cross-thread read — the lock-discipline
    # audit in docs/static-analysis.md): the engine publishes these as
    # thread-safe registry gauges after every tick, so health reads those
    if router is not None:
        # router mode: engine gauges carry the serve.rK.* instance label;
        # the health doc reads the router-level aggregates instead, and
        # /debug/requests merges every replica's (thread-safe) tracer.
        # Tracer list captured at startup — the CLI never resizes the fleet
        tracers = [h.engine.tracer for h in router.replicas.values()]

        def _requests_fn():
            doc = {"inflight": [], "finished": []}
            for t in tracers:
                snap = t.snapshot()
                doc["inflight"].extend(snap.get("inflight", ()))
                doc["finished"].extend(snap.get("finished", ()))
            return doc

        def _health_fn():
            # router.health() is a thread-safe snapshot read: healthy
            # flips False — exporter answers 503 — while the live count
            # sits under min_live, and recovers when respawns land
            doc = router.health()
            reg = get_registry()
            doc["rejected"] = reg.counter("serve.router.rejected").value
            doc["deadline_cancelled"] = reg.counter(
                "serve.router.deadline_cancelled").value
            return doc

        exporter = maybe_start_from_env(
            health_fn=_health_fn, requests_fn=_requests_fn,
            memory_fn=cap_engine.kv_capacity, router_fn=router.debug_doc)
    else:
        exporter = maybe_start_from_env(health_fn=lambda: {
            "healthy": True,
            "queue_depth": get_registry().gauge("serve.queue_depth").value,
            "num_running": get_registry().gauge("serve.num_running").value,
            # overload outcomes (thread-safe registry counters, same rule):
            # a probe sees shedding/deadline pressure without log scraping
            "rejected": get_registry().counter("serve.rejected").value,
            "deadline_misses":
                get_registry().counter("serve.deadline_misses").value,
        }, requests_fn=driver.tracer.snapshot,
            # /debug/memory gains the KV pool capacity document (pool bytes
            # + estimated max-concurrent sequences) next to the buffer
            # census
            memory_fn=driver.kv_capacity)

    sampling = SamplingParams(
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        max_new_tokens=args.max_new, eos_id=args.eos_id, seed=args.seed,
    )
    cli_deadline = args.deadline_s if args.deadline_s > 0 else None
    prompts = [[int(t) for t in s.split(",")] for s in args.prompt_ids]
    rng = np.random.default_rng(args.seed)
    prompts += [
        [int(t) for t in rng.integers(1, cfg.vocab_size, args.synthetic_len)]
        for _ in range(args.synthetic)
    ]
    reqs = [Request(prompt_ids=p, sampling=sampling, priority=args.priority,
                    tenant=args.tenant, deadline_s=cli_deadline)
            for p in prompts]
    if args.requests_json:
        if args.requests_json == "-":
            docs = json.load(sys.stdin)
        else:
            with open(args.requests_json) as f:
                docs = json.load(f)
        for d in docs:
            # same convention as --deadline-s: absent falls back to the
            # CLI default, <= 0 means "no deadline" (an explicit 0 in the
            # JSON opts OUT of the CLI default rather than setting an
            # instantly-expired deadline)
            if d.get("deadline_s") is None:
                dl = cli_deadline
            else:
                dl = float(d["deadline_s"])
                dl = dl if dl > 0 else None
            reqs.append(Request(
                prompt_ids=[int(t) for t in d["prompt_ids"]],
                sampling=SamplingParams(
                    temperature=float(d.get("temperature",
                                            args.temperature)),
                    top_k=int(d.get("top_k", args.top_k)),
                    top_p=float(d.get("top_p", args.top_p)),
                    max_new_tokens=int(d.get("max_new_tokens",
                                             args.max_new)),
                    eos_id=int(d.get("eos_id", args.eos_id)),
                    seed=int(d.get("seed", args.seed)),
                ),
                request_id=str(d.get("request_id", "")),
                priority=str(d.get("priority", args.priority)),
                tenant=str(d.get("tenant", args.tenant)),
                deadline_s=dl,
            ))
    if not reqs:
        ap.error("nothing to do: pass --prompt-ids, --synthetic N "
                 "and/or --requests-json")
    try:
        for ev in driver.generate(reqs):
            line = {"request_id": ev.request_id, "index": ev.index,
                    "token": ev.token}
            if ev.finished:
                line["finished"] = ev.finish_reason
            print(json.dumps(line), flush=True)
            if publish_params is not None:
                # router mode: fire the rolling publish once the fleet
                # is demonstrably serving (first token landed). step()
                # drains each replica and swaps in place from here on;
                # generate() keeps pumping until the fleet converges.
                router.publish_weights(publish_params, publish_version)
                print(json.dumps({"publish": publish_version,
                                  "mode": "rolling"}), flush=True)
                publish_params = None
        outs = driver.run()  # no-op drain; collects final outputs
    except BaseException as e:
        # same contract as trainer.train(): a pump that dies mid-decode
        # leaves its request/event history in a post-mortem, not in the void
        from veomni_tpu.observability.flight_recorder import dump_postmortem

        extra = {"error": str(e)[:2000]}
        try:
            # a pool/allocator blowup gets the buffer + cost censuses:
            # what held HBM and which program asked for more
            from veomni_tpu.observability.devmem import attach_oom_extra

            attach_oom_extra(e, extra)
        except Exception as forensic_err:  # even the import must be safe
            extra["oom_report_error"] = str(forensic_err)
        dump_postmortem(f"exception:{type(e).__name__}", extra=extra)
        raise
    print(json.dumps({"metrics": driver.metrics()}), flush=True)
    if exporter is not None:
        exporter.stop()
    # terminal-status census first: shed/expired requests are reported
    # DISTINCTLY (they produced no final token event to learn it from)
    by_status = {"ok": 0, "rejected": 0, "deadline": 0, "cancelled": 0}
    for o in outs.values():
        key = o.finish_reason if o.finish_reason in by_status else "ok"
        by_status[key] += 1
    census = {
        "completed": by_status["ok"],
        "rejected": by_status["rejected"],
        "deadline_cancelled": by_status["deadline"],
        "cancelled": by_status["cancelled"],
        "deadline_missed": sum(1 for o in outs.values()
                               if o.deadline_missed),
    }
    if router is not None:
        # per-replica rollup in the same census line: where the traffic
        # actually landed (dispatch/redispatch counts, terminal states)
        census["replicas"] = [h.status_doc()
                              for h in router.replicas.values()]
        census["replicas_retired"] = [h.status_doc()
                                      for h in router.retired]
    print(json.dumps(census), flush=True)
    for rid in sorted(outs):
        o = outs[rid]
        line = {
            "request_id": rid, "tokens": o.token_ids,
            "finish_reason": o.finish_reason,
            "ttft_s": round(o.ttft_s, 4) if o.ttft_s is not None else None,
            "cached_tokens": o.cached_tokens,
            "spec_accepted_tokens": o.spec_accepted_tokens,
            # quant tier echoed per request: a scraped response line is
            # self-describing about whether it came off a quantized engine
            "kv_quant": args.kv_quant,
            "weight_quant": args.weight_quant,
        }
        if o.deadline_missed:
            line["deadline_missed"] = True
        print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
