"""Merge multiple chrome-trace JSON files (e.g. per-host jax.profiler dumps,
or the observability span tracer's ``dump_chrome_trace`` output) into one
timeline, offsetting pids so hosts don't collide.

Reference capability: ``scripts/profile/merge_chrome_trace.py``.
Our ProfileCallback writes traces under
``<output_dir>/profile/plugins/profile/<run>/*.trace.json.gz``; the span
tracer writes via ``observability.spans.dump_chrome_trace`` (pid = rank).

Usage:
  python scripts/merge_chrome_trace.py out.json trace_host0.json.gz trace_host1.json.gz
"""

import gzip
import json
import sys


def load(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        data = json.load(f)
    return data.get("traceEvents", data) if isinstance(data, dict) else data


def merge_traces(paths):
    """Concatenate trace events, remapping pids monotonically: every input's
    pids are offset past the previous inputs' maximum, so host i+1's
    processes always sort after host i's and never collide."""
    merged = []
    pid_base = 0
    for i, path in enumerate(paths):
        events = load(path)
        max_pid = 0
        for ev in events:
            ev = dict(ev)
            if isinstance(ev.get("pid"), int):
                max_pid = max(max_pid, ev["pid"])
                ev["pid"] += pid_base
            # tag host in the process names so the viewer groups clearly
            if ev.get("name") == "process_name" and "args" in ev:
                ev["args"] = dict(ev["args"])
                ev["args"]["name"] = f"host{i}/{ev['args'].get('name', '')}"
            merged.append(ev)
        pid_base += max_pid + 1
    return merged


def main():
    if len(sys.argv) < 3:
        raise SystemExit(__doc__)
    out, inputs = sys.argv[1], sys.argv[2:]
    merged = merge_traces(inputs)
    with open(out, "w") as f:
        json.dump({"traceEvents": merged}, f)
    print(f"merged {len(inputs)} traces, {len(merged)} events -> {out}")


if __name__ == "__main__":
    main()
