"""Deterministic tier-1 test-file sharding: run 1/N of the suite per box.

The full tier-1 suite now exceeds its 870s budget on a 2-core box
(ROADMAP.md), so CI runs it staged: ``--shard K/N`` selects a stable
subset of ``tests/test_*.py`` such that the N shards partition the suite
exactly (every file in exactly one shard) and membership is STABLE under
file additions — assignment is ``crc32(filename) % N``, not positional, so
adding ``test_new.py`` never reshuffles which shard runs ``test_serving.py``
(a reshuffle would make cross-shard timing history useless).

Default action runs pytest on the shard with the tier-1 flags; ``--list``
prints the files instead (for drivers that own the pytest invocation).
Arguments after ``--`` pass through to pytest IN ADDITION to the tier-1
flags (they must never silently drop ``-m 'not slow'`` or the plugin
disables — that would blow the very budget this script exists to fix);
``--bare`` replaces the defaults entirely for drivers that own the flags.

Usage (docs/testing.md "Sharded tier-1"):
  JAX_PLATFORMS=cpu python scripts/tier1_shard.py --shard 1/2
  JAX_PLATFORMS=cpu python scripts/tier1_shard.py --shard 2/2 -- -x
  python scripts/tier1_shard.py --shard 1/3 --list
"""

import argparse
import glob
import os
import re
import subprocess
import sys
import zlib

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the tier-1 invocation's pytest flags (mirror ROADMAP.md's verify line;
#: plugin disables keep the 2-core box deterministic)
DEFAULT_PYTEST_ARGS = [
    "-q", "-m", "not slow", "--continue-on-collection-errors",
    "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
]


def parse_shard(text):
    m = re.fullmatch(r"(\d+)/(\d+)", text.strip())
    if not m:
        raise ValueError(f"--shard must be K/N (e.g. 1/2), got {text!r}")
    k, n = int(m.group(1)), int(m.group(2))
    if not (1 <= k <= n):
        raise ValueError(f"--shard K/N needs 1 <= K <= N, got {k}/{n}")
    return k, n


def shard_files(files, k, n):
    """The K-th (1-based) of N shards over ``files``. Stable: a file's
    shard depends only on its basename, never on its neighbors."""
    return [
        f for f in sorted(files)
        if zlib.crc32(os.path.basename(f).encode()) % n == k - 1
    ]


def discover(tests_dir=None):
    tests_dir = tests_dir or os.path.join(_REPO, "tests")
    return sorted(glob.glob(os.path.join(tests_dir, "test_*.py")))


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    passthrough = []
    if "--" in argv:
        i = argv.index("--")
        argv, passthrough = argv[:i], argv[i + 1:]
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--shard", required=True, help="K/N, 1-based")
    ap.add_argument("--list", action="store_true",
                    help="print the shard's files instead of running pytest")
    ap.add_argument("--bare", action="store_true",
                    help="drop the tier-1 default pytest flags (pass your "
                         "own after --)")
    ap.add_argument("--tests-dir", default="",
                    help="test directory (default: <repo>/tests)")
    args = ap.parse_args(argv)
    k, n = parse_shard(args.shard)
    files = shard_files(discover(args.tests_dir or None), k, n)
    if args.list:
        for f in files:
            print(f)
        return 0
    if not files:
        print(f"shard {k}/{n}: no test files assigned", file=sys.stderr)
        return 0
    base = [] if args.bare else DEFAULT_PYTEST_ARGS
    cmd = [sys.executable, "-m", "pytest", *base, *passthrough, *files]
    print(f"shard {k}/{n}: {len(files)} files", file=sys.stderr)
    return subprocess.call(cmd, cwd=_REPO)


if __name__ == "__main__":
    sys.exit(main())
