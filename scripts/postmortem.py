"""Merge rank-local flight-recorder dumps into one ordered fleet view.

Each rank's failure artifact (``postmortem-<rank>.json``, written by
``observability/flight_recorder.py`` on watchdog fire / supervisor abort /
uncaught exception / SIGTERM) timestamps its events with that process's
monotonic clock — incomparable across hosts. Every dump therefore carries a
paired anchor (wall time + perf counter at dump time); this tool maps each
event onto the shared wall axis via

    wall(event) = anchor.wall_time_s - (anchor.perf_ns - event.ts_ns) / 1e9

and prints one merged, monotonically ordered timeline with per-rank
provenance, plus a per-rank header (reason, event count, drops, in-flight
requests). ``--json`` additionally writes the merged document for tooling.

Usage:
  python scripts/postmortem.py postmortem-0.json postmortem-1.json
  python scripts/postmortem.py out/postmortem-*.json --json merged.json --tail 80
"""

import argparse
import json


def load_dump(path):
    with open(path) as f:
        doc = json.load(f)
    for key in ("rank", "anchor", "events"):
        if key not in doc:
            raise ValueError(f"{path}: not a flight-recorder dump (no {key!r})")
    return doc


def _wall(anchor, ts_ns):
    return anchor["wall_time_s"] - (anchor["perf_ns"] - ts_ns) / 1e9


def merge_dumps(paths):
    """Load + merge dumps; returns ``{"ranks": [...], "events": [...]}`` with
    events carrying ``wall_s`` (shared axis) and ``rank``, sorted ascending —
    i.e. one monotonic fleet timeline."""
    ranks = []
    merged = []
    for path in paths:
        doc = load_dump(path)
        anchor = doc["anchor"]
        ranks.append({
            "path": path,
            "rank": doc["rank"],
            "reason": doc.get("reason", ""),
            "events": len(doc["events"]),
            "dropped": doc.get("dropped", 0),
            # numerics observatory provenance (when the tier was armed):
            # the first non-finite group this rank's anomaly re-run named
            "numerics": doc.get("numerics"),
        })
        for ev in doc["events"]:
            merged.append({
                "wall_s": _wall(anchor, ev["ts_ns"]),
                "rank": doc["rank"],
                "kind": ev["kind"],
                "cid": ev.get("cid", ""),
                "payload": ev.get("payload"),
            })
    merged.sort(key=lambda e: e["wall_s"])
    return {"ranks": ranks, "events": merged}


def format_timeline(doc, tail=0):
    """Human-readable fleet view: header per rank, then the ordered events
    (``--tail N`` keeps only the last N — the seconds before the failure)."""
    lines = []
    for r in sorted(doc["ranks"], key=lambda r: r["rank"]):
        lines.append(
            f"# rank {r['rank']}: {r['reason'] or '<no reason>'} — "
            f"{r['events']} events ({r['dropped']} dropped) [{r['path']}]"
        )
        prov = (r.get("numerics") or {}).get("provenance") or {}
        first = prov.get("first_nonfinite")
        if first:
            layer = (f" layer {first['layer']}"
                     if first.get("layer") is not None else "")
            lines.append(
                f"# rank {r['rank']} numerics: first non-finite = "
                f"{first['kind']} {first['group']}{layer} at step "
                f"{prov.get('step')} "
                f"({int(first.get('nonfinite_count', 0))} elements"
                f"{', injected drill' if prov.get('injected') else ''})"
            )
    events = doc["events"]
    if tail > 0:
        skipped = max(0, len(events) - tail)
        if skipped:
            lines.append(f"# ... {skipped} earlier events elided (--tail)")
        events = events[-tail:]
    t0 = events[0]["wall_s"] if events else 0.0
    for ev in events:
        extra = ""
        if ev["cid"]:
            extra += f" cid={ev['cid']}"
        if ev["payload"]:
            extra += " " + json.dumps(ev["payload"], sort_keys=True,
                                      default=str)
        lines.append(
            f"[+{ev['wall_s'] - t0:10.4f}s] rank{ev['rank']} "
            f"{ev['kind']}{extra}"
        )
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dumps", nargs="+", help="postmortem-<rank>.json files")
    ap.add_argument("--json", default="",
                    help="also write the merged document here")
    ap.add_argument("--tail", type=int, default=0,
                    help="print only the last N merged events")
    args = ap.parse_args()
    doc = merge_dumps(args.dumps)
    print(format_timeline(doc, tail=args.tail))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f)
        print(f"# merged {len(args.dumps)} dumps, {len(doc['events'])} "
              f"events -> {args.json}")


if __name__ == "__main__":
    main()
