"""Offline conversion: orbax train-state checkpoint -> HF safetensors.

Reference: ``scripts/merge_dcp_to_hf.py`` + ``dcp_to_torch_state_dict``
(``checkpoint/dcp_checkpointer.py:859``) — consolidate a sharded training
checkpoint into an inference-ready HF directory without running the trainer.

Usage:
  python scripts/merge_checkpoint_to_hf.py \
      --ckpt_dir output/run/checkpoints [--step N] \
      --config <dir with config.json or inline overrides JSON> \
      --out_dir output/run/hf_merged [--platform cpu]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt_dir", required=True)
    ap.add_argument("--step", type=int, default=None)
    ap.add_argument("--config", required=True,
                    help="HF config dir, or a JSON string of config overrides")
    ap.add_argument("--out_dir", required=True)
    ap.add_argument("--platform", default="cpu",
                    help="jax platform to restore on (cpu avoids TPU claims)")
    args = ap.parse_args()

    import re

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from veomni_tpu.models import build_foundation_model
    from veomni_tpu.models.auto import build_config

    # single config-resolution path (handles VLM/composite model_types too)
    if os.path.isdir(args.config):
        with open(os.path.join(args.config, "config.json")) as f:
            hf = json.load(f)
        mt = hf.get("model_type", "")
        if mt == "slot_vlm":  # generic VLM composite: no config_from_hf
            config = build_config(mt, text=hf.get("text_config", hf))
        else:
            # delegate to auto's per-family config_from_hf dispatch so
            # vision/audio sub-configs and token ids survive the round-trip
            config = build_foundation_model(config_path=args.config).config
    else:
        overrides = json.loads(args.config)
        mt = overrides.pop("model_type", "")
        if not mt:
            raise SystemExit(
                "inline --config JSON must include model_type (silent "
                "llama-family fallback would mis-map family-specific tensors)"
            )
        config = build_config(mt, **overrides)
    model = build_foundation_model(config=config)

    # read-only step discovery (no Checkpointer: avoid mkdir/threads)
    if args.step is not None:
        step = args.step
    else:
        steps = sorted(
            int(m.group(1))
            for d in (os.listdir(args.ckpt_dir) if os.path.isdir(args.ckpt_dir) else [])
            if (m := re.match(r"^global_step_(\d+)$", d))
        )
        step = steps[-1] if steps else None
    if step is None:
        raise SystemExit(f"no checkpoints under {args.ckpt_dir}")

    # Restore with an abstract target built from on-disk metadata (works
    # without knowing the optimizer that produced the checkpoint). NOTE: this
    # orbax version has no partial/placeholder restore, so optimizer moments
    # (~2x params bytes) are materialized too — budget host RAM accordingly.
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(args.ckpt_dir), f"global_step_{step}", "train_state")
    ckptr = ocp.StandardCheckpointer()
    meta = ckptr.metadata(path).item_metadata
    target = jax.tree.map(
        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype),
        {"params": meta["params"], "opt_state": meta["opt_state"], "step": meta["step"]},
    )
    restored = ckptr.restore(path, target)
    model.save_hf(args.out_dir, params=restored["params"])
    print(f"merged step {step} -> {args.out_dir}")


if __name__ == "__main__":
    main()
