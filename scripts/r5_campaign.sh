#!/bin/bash
# r5 chip campaign: knock until the relay grants, then run the ladder in
# subprocess mode (hang costs one config), then the pallas probe, then a
# final validation run of bench.py's exact default config.
# Single chip claimant by construction: every stage is sequential.
set -u
cd "$(dirname "$0")/.."
LOG=bench_logs/r5_campaign.log
echo "=== campaign start $(date -u +%H:%M:%S) ===" >> "$LOG"

# 1. knock: in-process backend-init retry; exits 0 on the first grant
#    (claim released at exit). Bounded by the caller's timeout.
python - >> "$LOG" 2>&1 <<'EOF'
import sys
sys.path.insert(0, ".")
from bench import _wait_for_backend
n = _wait_for_backend(retry_s=120.0)
print(f"KNOCK OK: {n} chip(s)", flush=True)
EOF
[ $? -ne 0 ] && { echo "knock failed, aborting" >> "$LOG"; exit 1; }

# 2. the ladder (subprocess mode; pallas configs last)
VEOMNI_XLA_PERF_FLAGS=0 SWEEP_SUBPROCESS=1 SWEEP_CONFIG_TIMEOUT_S=1500 \
SWEEP_STEPS=8 SWEEP_CONFIGS='[
  [4096,4,"xla_twopass","ctx"],
  [2048,8,"xla_twopass","ctx"],
  [4096,8,"xla_twopass","ctx"],
  [2048,2,"xla_twopass","dots"],
  [2048,4,"xla_twopass","ctx","qwen3_1p7b","muon"],
  [4096,2,"xla_twopass","ctx","qwen3_1p7b","muon"],
  [2048,8,"xla","ctx"],
  [2048,8,"pallas_flash","ctx"],
  [4096,4,"pallas_flash","ctx"]]' \
  python scripts/mfu_sweep.py >> "$LOG" 2>&1

# 3. pallas silicon probe (watchdogged stages)
timeout 1800 python scripts/pallas_probe.py >> "$LOG" 2>&1
echo "pallas_probe exit: $?" >> "$LOG"

# 4. validate the round-end bench default end-to-end
BENCH_WATCHDOG_S=1500 timeout 1800 python bench.py >> "$LOG" 2>&1
echo "bench exit: $?" >> "$LOG"
echo "=== campaign done $(date -u +%H:%M:%S) ===" >> "$LOG"
