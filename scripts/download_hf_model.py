"""Download an HF model snapshot (reference: ``scripts/download_hf_model.py``).

Usage: python scripts/download_hf_model.py --repo_id Qwen/Qwen3-8B --local_dir ./qwen3-8b
Optionally restrict to weights/config only with --weights_only.
"""

import argparse


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--repo_id", required=True)
    p.add_argument("--local_dir", required=True)
    p.add_argument("--revision", default=None)
    p.add_argument("--weights_only", action="store_true",
                   help="only *.safetensors / *.json / tokenizer files")
    args = p.parse_args()

    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # huggingface_hub isn't a hard dependency
        raise SystemExit(
            "huggingface_hub is required for downloads: pip install huggingface_hub"
        ) from e

    allow = (
        ["*.safetensors", "*.json", "tokenizer*", "*.model", "*.jinja"]
        if args.weights_only else None
    )
    path = snapshot_download(
        args.repo_id, local_dir=args.local_dir, revision=args.revision,
        allow_patterns=allow,
    )
    print(path)


if __name__ == "__main__":
    main()
