"""Offline condition-model cache for DiT training.

Reference: ``veomni/trainer/dit_trainer.py:168-595`` runs the frozen
condition models (VAE + text encoder) inline on GPU; the TPU design keeps
the train step pure DiT and feeds it pre-computed rows — this script is the
producer. It walks a jsonl of {"image": path | array, "caption": str} rows
and writes the trainer's row format:

  wan/qwen_image/flux:  {"latents": [...], "text_states": [[...], ...]}
  slot-dit (cond_dim):  {"latents": [...], "cond": [...]}  (--cond_dim N
                        mean-pools the text states into one [N] vector)

Encoders (all frozen, run on CPU via torch — no TPU claim):
  * text: any HF T5/CLIP encoder (``--text_encoder google/t5-v1_1-base``)
  * vae:  a diffusers AutoencoderKL if the package+weights are available
          (``--vae <dir>``); otherwise ``--pixel_latents`` area-downsamples
          pixels into the latent grid — a stand-in that keeps the pipeline
          runnable end-to-end where no VAE weights exist (tests, smoke).

Usage:
  python scripts/cache_dit_conditions.py --in data.jsonl --out cached.jsonl \
      --latent_shape 16,8,8 --text_encoder google/t5-v1_1-base --text_len 64
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_image(spec) -> np.ndarray:
    if isinstance(spec, str):
        from PIL import Image

        return np.asarray(Image.open(spec).convert("RGB"), np.float32) / 255.0
    arr = np.asarray(spec, np.float32)
    return arr / 255.0 if arr.max() > 1.5 else arr


def _pixel_latents(img: np.ndarray, shape) -> np.ndarray:
    """Area-downsample pixels into [C, H, W] (or [C, F, H, W]) — the
    VAE-free fallback encoder."""
    c = shape[0]
    h, w = shape[-2], shape[-1]
    ys = np.linspace(0, img.shape[0] - 1, h).astype(np.int64)
    xs = np.linspace(0, img.shape[1] - 1, w).astype(np.int64)
    small = img[ys][:, xs]  # [h, w, 3]
    reps = int(np.ceil(c / 3))
    lat = np.tile(small.transpose(2, 0, 1), (reps, 1, 1))[:c]
    lat = (lat - 0.5) * 2.0
    if len(shape) == 4:  # video latent: single frame broadcast
        lat = np.repeat(lat[:, None], shape[1], axis=1)
    return lat


def build_text_encoder(name: str, text_len: int):
    import torch
    from transformers import AutoModel, AutoTokenizer

    tok = AutoTokenizer.from_pretrained(name)
    model = AutoModel.from_pretrained(name)
    enc = getattr(model, "encoder", model)
    enc.eval()

    @torch.no_grad()
    def encode(caption: str) -> np.ndarray:
        ids = tok(caption, return_tensors="pt", truncation=True,
                  max_length=text_len, padding="max_length")
        out = enc(input_ids=ids["input_ids"],
                  attention_mask=ids["attention_mask"])
        return out.last_hidden_state[0].float().numpy()

    return encode


def build_vae(vae_dir: str):
    try:
        import torch
        from diffusers import AutoencoderKL
    except ImportError:
        return None
    vae = AutoencoderKL.from_pretrained(vae_dir)
    vae.eval()

    @torch.no_grad()
    def encode(img: np.ndarray) -> np.ndarray:
        x = torch.from_numpy(img.transpose(2, 0, 1))[None] * 2.0 - 1.0
        lat = vae.encode(x).latent_dist.mode()[0]
        return (lat * vae.config.scaling_factor).float().numpy()

    return encode


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--in", dest="inp", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--latent_shape", required=True,
                    help="C,H,W or C,F,H,W (comma separated)")
    ap.add_argument("--text_encoder", default="")
    ap.add_argument("--text_len", type=int, default=64)
    ap.add_argument("--vae", default="")
    ap.add_argument("--pixel_latents", action="store_true",
                    help="VAE-free fallback latent encoder")
    ap.add_argument("--caption_key", default="caption")
    ap.add_argument("--image_key", default="image")
    ap.add_argument("--cond_dim", type=int, default=0,
                    help="emit a pooled 'cond' [N] vector instead of "
                         "'text_states' (slot-dit row format)")
    args = ap.parse_args()

    shape = tuple(int(x) for x in args.latent_shape.split(","))
    vae = build_vae(args.vae) if args.vae else None
    if vae is None and not args.pixel_latents:
        raise SystemExit(
            "no VAE available: pass --vae <diffusers dir> or opt into the "
            "--pixel_latents fallback explicitly"
        )
    text = build_text_encoder(args.text_encoder, args.text_len) \
        if args.text_encoder else None

    n = 0
    with open(args.inp) as f_in, open(args.out, "w") as f_out:
        for line in f_in:
            row = json.loads(line)
            img = _load_image(row[args.image_key])
            lat = vae(img) if vae is not None else _pixel_latents(img, shape)
            out = {"latents": np.asarray(lat, np.float32).tolist()}
            if text is not None:
                states = text(row.get(args.caption_key, ""))
                if args.cond_dim:
                    pooled = states.mean(0)
                    cond = np.zeros(args.cond_dim, np.float32)
                    n_c = min(args.cond_dim, len(pooled))
                    cond[:n_c] = pooled[:n_c]
                    out["cond"] = cond.tolist()
                else:
                    out["text_states"] = states.tolist()
            elif args.cond_dim:
                out["cond"] = np.zeros(args.cond_dim, np.float32).tolist()
            f_out.write(json.dumps(out) + "\n")
            n += 1
    print(f"cached {n} rows -> {args.out}")


if __name__ == "__main__":
    main()
