"""MFU ladder sweep on the live chip: one process, many configs.

Runs the bench train step (qwen3-0.6B-class dense) across micro-batch /
seq-len / remat / attention-impl combinations and prints one JSON line per
config. Used to pick bench.py defaults; results recorded in BENCH_NOTES.md.

Single process on purpose: the axon TPU chip claim is exclusive, and a
killed TPU process can wedge it (memory notes) — never run this under
`timeout`, never run two at once.
"""

import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_one(*, seq_len, micro_bs, steps, remat, remat_policy="nothing",
            attn="xla", model_overrides=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veomni_tpu.models import TransformerConfig, build_foundation_model
    from veomni_tpu.optim import build_lr_scheduler, build_optimizer
    from veomni_tpu.ops.kernel_registry import KERNEL_REGISTRY
    from veomni_tpu.parallel import use_parallel_state
    from veomni_tpu.parallel.parallel_state import get_parallel_state
    from veomni_tpu.train import build_train_state, build_train_step
    from veomni_tpu.train.train_step import resolve_state_shardings
    from veomni_tpu.utils.count_flops import FlopsCounter
    from veomni_tpu.utils.device import get_device_peak_flops

    ps = get_parallel_state()
    n_chips = jax.device_count()
    KERNEL_REGISTRY.pin("attention", attn)

    with use_parallel_state(ps):
        cfg = TransformerConfig(**{
            **dict(
                model_type="qwen3",
                vocab_size=151936,
                hidden_size=1024,
                intermediate_size=3072,
                num_hidden_layers=28,
                num_attention_heads=16,
                num_key_value_heads=8,
                head_dim=128,
                qk_norm=True,
                tie_word_embeddings=True,
                max_position_embeddings=131072,
                rope_theta=1e6,
                dtype=jnp.bfloat16,
                remat=remat,
                remat_policy=remat_policy,
            ),
            **(model_overrides or {}),
        })
        model = build_foundation_model(config=cfg)
        plan = model.get_parallel_plan()
        opt = build_optimizer(model.abstract(), lr=build_lr_scheduler(lr=1e-4, train_steps=1000))

        def make_state(rng):
            return build_train_state(model.family.init_params(rng, cfg), opt)

        abs_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        shardings = resolve_state_shardings(abs_state, plan, ps)
        state = jax.jit(make_state, out_shardings=shardings)(jax.random.PRNGKey(0))

        keys = ("input_ids", "labels", "position_ids", "segment_ids")
        batch_shardings = {
            k: NamedSharding(ps.mesh, P(None, ps.dp_axes, ps.sp_axes)) for k in keys
        }
        step = build_train_step(
            model.loss_fn, opt, ps,
            state_shardings=shardings, batch_shardings=batch_shardings,
        )
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, micro_bs, seq_len))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(seq_len), ids.shape).copy(), jnp.int32
            ),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        batch = {k: jax.device_put(v, batch_shardings[k]) for k, v in batch.items()}

        state, metrics = step(state, batch)
        _ = float(metrics["loss"])  # axon: host fetch is the only true sync

        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        _ = float(metrics["loss"])
        dt = time.perf_counter() - t0

        tokens = micro_bs * seq_len * steps
        tok_s = tokens / dt / n_chips
        flops = FlopsCounter.from_config(cfg).batch_flops(
            micro_bs * seq_len, seq_len
        ) * steps
        mfu = 100.0 * flops / dt / (get_device_peak_flops() * n_chips)
        del state, step, batch
        gc.collect()
        return {"seq": seq_len, "mb": micro_bs, "remat": remat,
                "policy": remat_policy, "attn": attn,
                "tok_s_chip": round(tok_s, 1), "mfu": round(mfu, 2)}


def main():
    platform = os.environ.get("SWEEP_PLATFORM", "")
    if platform:  # CPU smoke testing (axon overrides env vars; use config)
        import jax

        jax.config.update("jax_platforms", platform)
    import jax

    from veomni_tpu.parallel import init_parallel_state

    init_parallel_state()
    print(json.dumps({"devices": [str(d) for d in jax.devices()]}), flush=True)

    configs = json.loads(os.environ.get("SWEEP_CONFIGS", "[]")) or [
        # seq 2048 ladder: micro-batch x remat x attention impl
        dict(seq_len=2048, micro_bs=2, steps=5, remat=True),
        dict(seq_len=2048, micro_bs=4, steps=5, remat=True),
        dict(seq_len=2048, micro_bs=8, steps=5, remat=True),
        dict(seq_len=2048, micro_bs=4, steps=5, remat=False),
        dict(seq_len=2048, micro_bs=8, steps=5, remat=False),
        dict(seq_len=2048, micro_bs=8, steps=5, remat=True, remat_policy="dots"),
        # seq 4096+: chunked attention
        dict(seq_len=4096, micro_bs=4, steps=5, remat=True, attn="xla_chunked"),
        dict(seq_len=4096, micro_bs=4, steps=5, remat=False, attn="xla_chunked"),
        dict(seq_len=8192, micro_bs=2, steps=4, remat=True, attn="xla_chunked"),
        dict(seq_len=16384, micro_bs=1, steps=4, remat=True, attn="xla_chunked"),
        dict(seq_len=32768, micro_bs=1, steps=3, remat=True, attn="xla_chunked"),
    ]
    for c in configs:
        try:
            res = run_one(**c)
            print(json.dumps(res), flush=True)
        except Exception as e:
            print(json.dumps({"config": c, "error": str(e)[:400]}), flush=True)
            gc.collect()


if __name__ == "__main__":
    main()
