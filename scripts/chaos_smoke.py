"""Tier-1 chaos smoke: the self-healing fleet survives a seeded storm.

Runs the bench's chaos soak leg (``bench.run_serve_open_loop_bench`` with
``chaos_seed``) on the tiny CPU model: a fixed-seed deterministic fault
schedule — replica kill + hang/delay/exception across the serve fault
points — fires over a 3-replica self-healing router while an open-loop
Poisson storm replays, then the same storm replays fault-free. The plan
also schedules one mid-storm weight publish, so the drill covers the
rolling hot-swap path under fire. Exits 0 only when every fleet
invariant holds on both runs (no lost/duplicated request ids, zero
leaked KV blocks per survivor, fleet restored to full live count, fleet
converged to the published weights version) and chaos goodput stays
>= 70% of the fault-free replay.

Budgeted for CI: one rate, a small storm, aggressive (sub-second) wedge
deadlines — the whole drill finishes in well under a minute on CPU.
Invoked by ``scripts/tier1.sh`` before the shard loop; the fixed seed
means a failure here replays bit-for-bit with the same command.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# fixed: a failing run replays bit-for-bit. Seed 11's schedule is known
# to land a hang whose victim survives long enough to be declared WEDGED
# (other seeds' kills can absorb the hanging replica first), so this
# smoke pins the full detect -> abandon -> respawn -> probation path.
SEED = 11


def main() -> int:
    import jax
    import jax.numpy as jnp

    import bench
    from veomni_tpu.models import TransformerConfig, build_foundation_model

    cfg = TransformerConfig(
        model_type="qwen3", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, qk_norm=True,
        dtype=jnp.float32,
    )
    model = build_foundation_model(config=cfg)
    params = model.family.init_params(jax.random.PRNGKey(0), cfg)

    # absolute arrival rate, NOT a capacity multiple: the tiny CPU model
    # absorbs the whole storm in ~0.1s at measured capacity, which makes
    # the 2s chaos hang dominate any goodput ratio. 2.5 req/s spreads 16
    # requests over ~6s so the ratio measures healing, not storm length.
    r = bench.run_serve_open_loop_bench(
        num_slots=2, block_size=8, n_requests=16, prompt_lens=(8, 12),
        max_new_tokens=6, arrival_rates=(2.5,), seed=SEED,
        chaos_seed=SEED, chaos_stall_s=0.5, chaos_publishes=1,
        _model=(params, cfg),
    )
    c = r["chaos"]
    line = {
        "metric": "chaos_smoke",
        "seed": c["seed"],
        "replicas": c["replicas"],
        "ok": c["ok"],
        "goodput_ratio": round(c["goodput_ratio"], 4),
        "wedged": c["chaos"]["wedged"],
        "respawns": c["chaos"]["respawns"],
        "probation_passed": c["chaos"]["probation_passed"],
        "lost_ids": c["chaos"]["lost_ids"],
        "leaked_blocks": c["chaos"]["leaked_blocks"],
        "restored": c["chaos"]["restored"],
        "publishes": c["chaos"]["publishes"],
        "published_versions": c["chaos"]["published_versions"],
        "version_converged": c["chaos"]["version_converged"],
        "fault_free_quiet": (c["fault_free"]["wedged"] == 0
                             and c["fault_free"]["respawns"] == 0),
        "plan": c["plan"],
    }
    print("CHAOS_SMOKE " + json.dumps(line), flush=True)
    if not c["ok"]:
        print("CHAOS_SMOKE FAILED: invariants or goodput floor violated",
              file=sys.stderr)
        return 1
    if not line["fault_free_quiet"]:
        # the fault-free replay must never trip the wedge detector: a
        # wedge there means the stall deadline is mis-tuned, and every
        # chaos verdict on top of it is noise
        print("CHAOS_SMOKE FAILED: fault-free replay tripped self-healing",
              file=sys.stderr)
        return 1
    if c["chaos"]["wedged"] < 1:
        # seed 11 is chosen to wedge; zero wedges means the detector (or
        # the schedule's determinism) regressed, not that the fleet got
        # lucky
        print("CHAOS_SMOKE FAILED: expected >= 1 wedge from this seed",
              file=sys.stderr)
        return 1
    if c["chaos"]["publishes"] != 1 or not c["chaos"]["version_converged"]:
        # the plan schedules exactly one mid-storm publish; the fleet
        # must end the drill serving that version everywhere
        print("CHAOS_SMOKE FAILED: mid-storm publish did not converge",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
