"""Trim an HF safetensors checkpoint to its first N decoder layers.

Reference capability: ``scripts/trim_safetensor_layers.py`` — produce a
small real-weights model (e.g. deepseek 5-layer) to exercise streamed
weight loading without the full checkpoint. This version streams tensor by
tensor (numpy; peak RAM = one tensor), rewrites the weight index, and
patches ``num_hidden_layers`` (+ ``first_k_dense_replace`` /
``layer_types`` when present) in config.json.

Usage:
  python scripts/trim_safetensor_layers.py --model_dir IN --out_dir OUT --num_layers 4
"""

import argparse
import json
import os
import re
import shutil

import numpy as np
from safetensors import safe_open
from safetensors.numpy import save_file

_LAYER_RE = re.compile(r"(^|\.)layers\.(\d+)\.")


def layer_id(key: str):
    m = _LAYER_RE.search(key)
    return int(m.group(2)) if m else None


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_dir", required=True)
    p.add_argument("--out_dir", required=True)
    p.add_argument("--num_layers", type=int, required=True)
    p.add_argument("--max_shard_gb", type=float, default=4.0)
    args = p.parse_args()

    os.makedirs(args.out_dir, exist_ok=True)
    shards = sorted(
        f for f in os.listdir(args.model_dir) if f.endswith(".safetensors")
    )
    if not shards:
        raise SystemExit(f"no safetensors in {args.model_dir}")

    budget = int(args.max_shard_gb * 1024 ** 3)
    out_idx, weight_map = 1, {}
    current, current_bytes = {}, 0
    n_out = 0

    def flush():
        nonlocal current, current_bytes, out_idx
        if not current:
            return
        name = f"model-trimmed-{out_idx:05d}.safetensors"
        save_file(current, os.path.join(args.out_dir, name))
        for k in current:
            weight_map[k] = name
        out_idx += 1
        current, current_bytes = {}, 0

    for shard in shards:
        with safe_open(os.path.join(args.model_dir, shard), framework="np") as f:
            for key in f.keys():
                lid = layer_id(key)
                if lid is not None and lid >= args.num_layers:
                    continue
                t = f.get_tensor(key)
                current[key] = np.ascontiguousarray(t)
                current_bytes += t.nbytes
                n_out += 1
                if current_bytes >= budget:
                    flush()
    flush()

    with open(os.path.join(args.out_dir, "model.safetensors.index.json"), "w") as f:
        json.dump({"metadata": {}, "weight_map": weight_map}, f, indent=2)

    cfg_path = os.path.join(args.model_dir, "config.json")
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            cfg = json.load(f)

        def patch(c):
            if "num_hidden_layers" in c:
                c["num_hidden_layers"] = min(c["num_hidden_layers"], args.num_layers)
            if "first_k_dense_replace" in c:
                c["first_k_dense_replace"] = min(
                    c["first_k_dense_replace"], args.num_layers
                )
            if isinstance(c.get("layer_types"), list):
                c["layer_types"] = c["layer_types"][: args.num_layers]
            for sub in ("text_config", "thinker_config"):
                if isinstance(c.get(sub), dict):
                    patch(c[sub])

        patch(cfg)
        with open(os.path.join(args.out_dir, "config.json"), "w") as f:
            json.dump(cfg, f, indent=2)

    for asset in ("tokenizer.json", "tokenizer_config.json", "generation_config.json",
                  "special_tokens_map.json", "vocab.json", "merges.txt"):
        src = os.path.join(args.model_dir, asset)
        if os.path.exists(src):
            shutil.copy2(src, args.out_dir)

    print(f"wrote {n_out} tensors in {out_idx - 1} shards to {args.out_dir}")


if __name__ == "__main__":
    main()
