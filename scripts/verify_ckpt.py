"""Operator CLI: verify checkpoint-generation integrity manifests.

Walks a checkpoint directory (the trainer's ``<output_dir>/checkpoints``),
prints a per-generation VerifyReport — OK / CORRUPT (with the per-file
missing/truncated/mismatch classification) / UNVERIFIABLE (no manifest) /
UNCOMMITTED — plus any already-quarantined ``*.corrupt`` corpses, and exits
non-zero if anything is corrupt. Pure stdlib + ``resilience/integrity.py``:
no JAX backend is touched, so it is safe to run next to a live job.

Run:
  python scripts/verify_ckpt.py /path/to/output_dir/checkpoints
  python scripts/verify_ckpt.py --mode size /path/to/checkpoints
  python scripts/verify_ckpt.py --step 1200 /path/to/checkpoints
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from veomni_tpu.resilience.integrity import (  # noqa: E402
    MANIFEST_NAME,
    QUARANTINE_DIR_RE,
    STEP_DIR_RE,
    VERIFY_MODES,
    is_committed_dir,
    verify_manifest,
)

_STEP_RE = STEP_DIR_RE
_CORRUPT_RE = QUARANTINE_DIR_RE


def verify_tree(ckpt_dir: str, mode: str, step: int = -1):
    """Returns (rows: [(step, status, detail)], corpses: [dirname],
    n_corrupt). Newest generation first — that is the one ``latest_step()``
    would hand a resuming run."""
    steps, corpses = [], []
    for d in sorted(os.listdir(ckpt_dir)):
        m = _STEP_RE.match(d)
        if m:
            steps.append(int(m.group(1)))
        elif _CORRUPT_RE.match(d):
            corpses.append(d)
    if step >= 0:
        steps = [s for s in steps if s == step]
    rows = []
    n_corrupt = 0
    for s in sorted(steps, reverse=True):
        step_dir = os.path.join(ckpt_dir, f"global_step_{s}")
        if not is_committed_dir(step_dir):
            rows.append((s, "UNCOMMITTED", "no train_state payload (crashed "
                         "save debris; startup cleanup removes this)"))
            continue
        report = verify_manifest(step_dir, mode=mode)
        if report is None:
            rows.append((s, "UNVERIFIABLE", f"no readable {MANIFEST_NAME} "
                         "(pre-integrity checkpoint, or crash before the "
                         "manifest write)"))
        elif report.passed:
            rows.append((s, "OK", report.summary()))
        else:
            n_corrupt += 1
            rows.append((s, "CORRUPT", report.summary()))
    return rows, corpses, n_corrupt


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("ckpt_dir", help="directory holding global_step_N generations")
    ap.add_argument("--mode", default="full", choices=[m for m in VERIFY_MODES if m != "off"],
                    help="size = existence+bytes; full = re-digest every file (default)")
    ap.add_argument("--step", type=int, default=-1,
                    help="verify only this generation (default: all)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.ckpt_dir):
        print(f"error: {args.ckpt_dir} is not a directory", file=sys.stderr)
        return 2
    rows, corpses, n_corrupt = verify_tree(args.ckpt_dir, args.mode, args.step)
    if not rows and not corpses:
        print(f"{args.ckpt_dir}: no checkpoint generations found")
        return 2
    for s, status, detail in rows:
        print(f"global_step_{s}: {status}\n    {detail}")
    for d in sorted(corpses):
        print(f"{d}: QUARANTINED (left on disk for post-mortem; aged out "
              "beyond max_ckpt_to_keep)")
    print(f"\n{len(rows)} generation(s) checked (mode={args.mode}): "
          f"{n_corrupt} corrupt, {len(corpses)} previously quarantined")
    return 1 if n_corrupt else 0


if __name__ == "__main__":
    raise SystemExit(main())
