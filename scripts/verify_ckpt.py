"""Operator CLI: verify checkpoint-generation integrity manifests.

Walks a checkpoint directory (the trainer's ``<output_dir>/checkpoints``),
prints a per-generation VerifyReport — OK / CORRUPT (with the per-file
missing/truncated/mismatch classification) / UNVERIFIABLE (no manifest) /
UNCOMMITTED — plus each generation's saved topology (mesh axis sizes, world
size, jax versions — recorded even under ``ckpt_verify=off``) and any
already-quarantined ``*.corrupt`` corpses, and exits non-zero if anything is
corrupt. With ``--target-world-size`` every generation additionally gets an
``ELASTIC-OK`` / ``INCOMPATIBLE`` / ``UNKNOWN`` verdict: could a run on that
many processes restore it (same topology, or a data-parallel resize whose
per-rank cursor sidecars are complete and mergeable)? Exit codes: 1 =
corruption found, 3 = intact but elastically incompatible with the target
world size (so a scripted pre-resize gate can fail on either). Pure stdlib +
``resilience/integrity.py`` + ``resilience/elastic.py``: no JAX backend is
touched, so it is safe to run next to a live job.

Run:
  python scripts/verify_ckpt.py /path/to/output_dir/checkpoints
  python scripts/verify_ckpt.py --mode size /path/to/checkpoints
  python scripts/verify_ckpt.py --step 1200 /path/to/checkpoints
  python scripts/verify_ckpt.py --target-world-size 8 /path/to/checkpoints
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from veomni_tpu.resilience.elastic import classify_restore  # noqa: E402
from veomni_tpu.resilience.integrity import (  # noqa: E402
    MANIFEST_NAME,
    QUARANTINE_DIR_RE,
    STEP_DIR_RE,
    VERIFY_MODES,
    is_committed_dir,
    list_rank_sidecars,
    read_topology,
    verify_manifest,
)

_STEP_RE = STEP_DIR_RE
_CORRUPT_RE = QUARANTINE_DIR_RE


def _topology_line(topo) -> str:
    if not topo:
        return "topology: unrecorded (pre-elastic checkpoint)"
    mesh = topo.get("mesh") or {}
    mesh_s = (
        "x".join(f"{k}={v}" for k, v in mesh.items()) if mesh else "unknown"
    )
    return (
        f"topology: world_size={topo.get('world_size', '?')} "
        f"devices={topo.get('device_count', '?')} mesh[{mesh_s}] "
        f"jax={topo.get('jax', '?')}/{topo.get('jaxlib', '?')}"
    )


def _elastic_verdict(step_dir: str, topo, target_world: int) -> str:
    rank_files = list_rank_sidecars(step_dir)
    verdict, reason = classify_restore(
        topo, target_world, rank_files=rank_files or None
    )
    label = {
        "ok": "ELASTIC-OK", "elastic": "ELASTIC-OK",
        "incompatible": "INCOMPATIBLE", "unknown": "UNKNOWN",
    }[verdict]
    return f"{label} for world_size={target_world}: {reason}"


def verify_tree(ckpt_dir: str, mode: str, step: int = -1,
                target_world: int = 0):
    """Returns (rows: [(step, status, [detail lines])], corpses: [dirname],
    n_corrupt, n_incompatible). Newest generation first — that is the one
    ``latest_step()`` would hand a resuming run."""
    steps, corpses = [], []
    for d in sorted(os.listdir(ckpt_dir)):
        m = _STEP_RE.match(d)
        if m:
            steps.append(int(m.group(1)))
        elif _CORRUPT_RE.match(d):
            corpses.append(d)
    if step >= 0:
        steps = [s for s in steps if s == step]
    rows = []
    n_corrupt = 0
    n_incompatible = 0
    for s in sorted(steps, reverse=True):
        step_dir = os.path.join(ckpt_dir, f"global_step_{s}")
        if not is_committed_dir(step_dir):
            rows.append((s, "UNCOMMITTED", ["no train_state payload (crashed "
                         "save debris; startup cleanup removes this)"]))
            continue
        topo = read_topology(step_dir)
        detail = []
        report = verify_manifest(step_dir, mode=mode)
        if report is None:
            rows.append((s, "UNVERIFIABLE", detail))
            detail.append(
                f"no readable {MANIFEST_NAME} with digests (pre-integrity "
                "checkpoint, ckpt_verify=off at save time, or crash before "
                "the manifest write)"
            )
        elif report.passed:
            rows.append((s, "OK", detail))
            detail.append(report.summary())
        else:
            n_corrupt += 1
            rows.append((s, "CORRUPT", detail))
            detail.append(report.summary())
        detail.append(_topology_line(topo))
        if target_world > 0:
            verdict_line = _elastic_verdict(step_dir, topo, target_world)
            if verdict_line.startswith("INCOMPATIBLE"):
                n_incompatible += 1
            detail.append(verdict_line)
    return rows, corpses, n_corrupt, n_incompatible


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("ckpt_dir", help="directory holding global_step_N generations")
    ap.add_argument("--mode", default="full", choices=[m for m in VERIFY_MODES if m != "off"],
                    help="size = existence+bytes; full = re-digest every file (default)")
    ap.add_argument("--step", type=int, default=-1,
                    help="verify only this generation (default: all)")
    ap.add_argument("--target-world-size", type=int, default=0,
                    help="also print an ELASTIC-OK/INCOMPATIBLE verdict per "
                         "generation: could a run on this many processes "
                         "restore it (train.ckpt_elastic)?")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.ckpt_dir):
        print(f"error: {args.ckpt_dir} is not a directory", file=sys.stderr)
        return 2
    rows, corpses, n_corrupt, n_incompat = verify_tree(
        args.ckpt_dir, args.mode, args.step, args.target_world_size
    )
    if not rows and not corpses:
        print(f"{args.ckpt_dir}: no checkpoint generations found")
        return 2
    for s, status, detail in rows:
        print(f"global_step_{s}: {status}")
        for line in detail:
            print(f"    {line}")
    for d in sorted(corpses):
        print(f"{d}: QUARANTINED (left on disk for post-mortem; aged out "
              "beyond max_ckpt_to_keep)")
    tail = f"{n_corrupt} corrupt, {len(corpses)} previously quarantined"
    if args.target_world_size > 0:
        tail += (f", {n_incompat} elastically incompatible with "
                 f"world_size={args.target_world_size}")
    print(f"\n{len(rows)} generation(s) checked (mode={args.mode}): {tail}")
    if n_corrupt:
        return 1
    # a scripted pre-resize gate must be able to fail on incompatibility
    # alone (distinct code: 3 = intact but not restorable at that world)
    if args.target_world_size > 0 and n_incompat:
        return 3
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
