"""Evidence artifact for the comm/compute-overlap story (VERDICT r3 #8).

Thin CLI over ``veomni_tpu/utils/overlap_evidence.py`` (the census itself is
a first-class API, regression-gated by ``tests/test_async_ulysses.py``).
This script produces the human-readable artifact:

1. jit a sharded train step on an 8-device CPU mesh with ``--xla_dump_to``
   and report (a) every async collective start/done pair in the *scheduled*
   HLO with the compute placed inside the window (TPU dumps), (b) the
   backend-neutral dependency census — overlappable collective/compute
   pairs — for BOTH the monolithic and the chunked async Ulysses path, so
   the pipeline's structural win is visible off-TPU too;
2. measure the async trainer-loop win: wall-clock per step with a device
   fetch every step (log_steps=1) vs amortized fetch (log_steps=50).

Usage:  python scripts/overlap_evidence.py [out_dir]
Writes a summary to stdout — paste into BENCH_NOTES.md.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DUMP = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(prefix="hlo_dump_")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_dump_to={DUMP} --xla_dump_hlo_pass_re=scheduling|latency"
)

from veomni_tpu.utils.testing import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import time  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_cpu_enable_async_dispatch", False)

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from veomni_tpu.models import TransformerConfig, build_foundation_model  # noqa: E402
from veomni_tpu.optim import build_lr_scheduler, build_optimizer  # noqa: E402
from veomni_tpu.parallel import init_parallel_state, use_parallel_state  # noqa: E402
from veomni_tpu.parallel.parallel_state import destroy_parallel_state  # noqa: E402
from veomni_tpu.train import build_train_state, build_train_step  # noqa: E402
from veomni_tpu.train.train_step import resolve_state_shardings  # noqa: E402
from veomni_tpu.utils.overlap_evidence import (  # noqa: E402
    analyze_scheduled_dump,
    collective_bytes_census,
    compiled_hlo_text,
    overlap_report,
)


def _build_step(ulysses_async_chunks: int):
    destroy_parallel_state()
    ps = init_parallel_state(ulysses_size=2, dp_shard_size=4)
    cfg = TransformerConfig(
        model_type="qwen3", vocab_size=512, hidden_size=128,
        intermediate_size=256, num_hidden_layers=2, num_attention_heads=8,
        num_key_value_heads=4, head_dim=16, qk_norm=True, dtype=jnp.float32,
        ulysses_async_chunks=ulysses_async_chunks,
    )
    with use_parallel_state(ps):
        model = build_foundation_model(config=cfg)
        plan = model.get_parallel_plan()
        opt = build_optimizer(model.abstract(),
                              lr=build_lr_scheduler(lr=1e-3, train_steps=100))

        def make_state(rng):
            return build_train_state(model.family.init_params(rng, cfg), opt)

        abs_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        shardings = resolve_state_shardings(abs_state, plan, ps)
        state = jax.jit(make_state, out_shardings=shardings)(jax.random.PRNGKey(0))
        keys = ("input_ids", "labels", "position_ids", "segment_ids")
        bsh = {k: NamedSharding(ps.mesh, P(None, ps.dp_axes, ps.sp_axes))
               for k in keys}
        step = build_train_step(model.loss_fn, opt, ps,
                                state_shardings=shardings, batch_shardings=bsh)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, 4, 64))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(64), ids.shape).copy(), jnp.int32),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        batch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    return ps, step, state, batch


def main():
    # build + execute the MONOLITHIC step first: at this point the
    # --xla_dump_to dir contains only its program, so the scheduled-dump
    # census below can't conflate it with the chunked compile
    ps, step, state, batch = _build_step(1)
    with use_parallel_state(ps):
        state, metrics = step(state, batch)  # compile + dump
        _ = float(metrics["loss"])

        # async-loop win: fetch-every-step vs fetch-every-50
        def run(n, fetch_every):
            nonlocal state
            t0 = time.perf_counter()
            for i in range(n):
                state, m = step(state, batch)
                if (i + 1) % fetch_every == 0:
                    _ = float(m["loss"])
            _ = float(m["loss"])
            return (time.perf_counter() - t0) / n

        per_step_sync = run(50, 1)
        per_step_async = run(50, 50)

    # scheduled-dump census BEFORE any other compile lands in DUMP: the
    # pairs reported here are the monolithic step's and nothing else's
    pairs = analyze_scheduled_dump(DUMP)

    # dependency census for both Ulysses paths (backend-neutral evidence);
    # the monolithic step above is reused, only the chunked one compiles.
    # The toy head layout (hq=8, hkv=4, u=2) clamps the pipeline to K=2 —
    # label what actually ran.
    with use_parallel_state(ps):
        rep = overlap_report(compiled_hlo_text(step, state, batch))
    print(f"dependency census [monolithic]: {rep.describe()}")
    ps2, step2, state2, batch2 = _build_step(2)
    with use_parallel_state(ps2):
        rep = overlap_report(compiled_hlo_text(step2, state2, batch2))
    print(f"dependency census [async_chunked(K=2)]: {rep.describe()}")

    overlapped = [p for p in pairs if p.overlapped]
    print(f"HLO dump: {DUMP}")
    print(f"async collective pairs in scheduled HLO (monolithic step): "
          f"{len(pairs)}; "
          f"with compute scheduled inside the start->done window: {len(overlapped)}")
    for p in pairs[:12]:
        print(f"  {p.name:40s} window={p.window_lines:4d} lines, "
              f"compute ops inside={p.compute_inside}")
    if not pairs:
        # XLA:CPU lowers collectives synchronously — no start/done pairs
        # exist off-TPU (the latency-hiding scheduler is a TPU pass). Report
        # the GSPMD-inserted collective census of the compiled step instead:
        # these are exactly the ops the TPU scheduler overlaps. (The same
        # census now runs LIVE on every instrumented compile — the
        # comm.{site}.{bucket}.* gauges, observability/comm.py — this
        # script stays the human-readable offline artifact.)
        census: dict = {}
        for fname in os.listdir(DUMP):
            if "step_fn" not in fname or "after_optimizations.txt" not in fname:
                continue
            with open(os.path.join(DUMP, fname)) as f:
                for op, rec in collective_bytes_census(f.read()).items():
                    agg = census.setdefault(op, {"count": 0, "bytes": 0.0})
                    agg["count"] += rec["count"]
                    agg["bytes"] += rec["bytes"]
        print("CPU backend lowers collectives synchronously; GSPMD-inserted "
              "collectives in the compiled train step (what the TPU "
              "latency-hiding scheduler overlaps):")
        for op, rec in sorted(census.items()):
            print(f"  {op:20s} {rec['count']:4d}  "
                  f"{rec['bytes'] / 1e6:10.3f} MB/device")
    print(f"step time, fetch every step:  {per_step_sync * 1e3:.2f} ms")
    print(f"step time, fetch every 50:    {per_step_async * 1e3:.2f} ms")
    print(f"async-loop win: {(per_step_sync / per_step_async - 1) * 100:.1f}%")


if __name__ == "__main__":
    main()
