"""Evidence artifact for the comm/compute-overlap story (VERDICT r3 #8).

The reference hand-overlaps Ulysses a2a with compute
(``veomni/distributed/sequence_parallel/async_ulysses.py:48-506``); our
design delegates overlap to XLA's scheduler (utils/xla_flags.py). This
script produces the checkable artifact:

1. jit a sharded train step on an 8-device CPU mesh with ``--xla_dump_to``,
   parse the *scheduled* HLO, and report every async collective pair
   (``*-start``/``*-done``) together with how many real compute ops the
   scheduler placed between start and done — nonzero gaps = the compiler is
   hiding collective latency behind compute (the capability async_ulysses
   implements by hand);
2. measure the async trainer-loop win: wall-clock per step with a device
   fetch every step (log_steps=1) vs amortized fetch (log_steps=50).

Usage:  python scripts/overlap_evidence.py [out_dir]
Writes a summary to stdout — paste into BENCH_NOTES.md.
"""

import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DUMP = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(prefix="hlo_dump_")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_dump_to={DUMP} --xla_dump_hlo_pass_re=scheduling|latency"
    + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
    + " --xla_cpu_collective_call_terminate_timeout_seconds=600"
)

from veomni_tpu.utils.testing import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import time  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_cpu_enable_async_dispatch", False)

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from veomni_tpu.models import TransformerConfig, build_foundation_model  # noqa: E402
from veomni_tpu.optim import build_lr_scheduler, build_optimizer  # noqa: E402
from veomni_tpu.parallel import init_parallel_state, use_parallel_state  # noqa: E402
from veomni_tpu.train import build_train_state, build_train_step  # noqa: E402
from veomni_tpu.train.train_step import resolve_state_shardings  # noqa: E402

COMPUTE_OPS = ("fusion", "dot", "convolution", "custom-call")


def analyze_dump(dump_dir: str):
    """Parse scheduled HLO: for each async collective start/done pair, count
    compute ops scheduled between them."""
    pairs = []
    for fname in sorted(os.listdir(dump_dir)):
        if "after_scheduling" not in fname and "latency" not in fname:
            continue
        if not fname.endswith(".txt"):
            continue
        with open(os.path.join(dump_dir, fname)) as f:
            lines = f.readlines()
        open_starts = {}
        for i, line in enumerate(lines):
            m = re.search(r"%(\S*?(all-gather|all-reduce|reduce-scatter|"
                          r"all-to-all|collective-permute)\S*start\S*) =", line)
            if m:
                open_starts[m.group(1).rstrip(",")] = i
                continue
            m = re.search(r"(all-gather|all-reduce|reduce-scatter|all-to-all|"
                          r"collective-permute)\S*done", line)
            if m and open_starts:
                # attribute to the most recent unmatched start of that type
                key = next(
                    (k for k in reversed(list(open_starts))
                     if m.group(1) in k), None,
                )
                if key is None:
                    continue
                start_i = open_starts.pop(key)
                gap_ops = sum(
                    1 for ln in lines[start_i + 1: i]
                    if any(f" {op}(" in ln or f"= {op}" in ln for op in COMPUTE_OPS)
                )
                pairs.append((key.split(".")[0], i - start_i, gap_ops))
    return pairs


def main():
    ps = init_parallel_state(ulysses_size=2, dp_shard_size=4)
    with use_parallel_state(ps):
        cfg = TransformerConfig(
            model_type="qwen3", vocab_size=512, hidden_size=128,
            intermediate_size=256, num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=32, qk_norm=True, dtype=jnp.float32,
        )
        model = build_foundation_model(config=cfg)
        plan = model.get_parallel_plan()
        opt = build_optimizer(model.abstract(),
                              lr=build_lr_scheduler(lr=1e-3, train_steps=100))

        def make_state(rng):
            return build_train_state(model.family.init_params(rng, cfg), opt)

        abs_state = jax.eval_shape(make_state, jax.random.PRNGKey(0))
        shardings = resolve_state_shardings(abs_state, plan, ps)
        state = jax.jit(make_state, out_shardings=shardings)(jax.random.PRNGKey(0))
        keys = ("input_ids", "labels", "position_ids", "segment_ids")
        bsh = {k: NamedSharding(ps.mesh, P(None, ps.dp_axes, ps.sp_axes))
               for k in keys}
        step = build_train_step(model.loss_fn, opt, ps,
                                state_shardings=shardings, batch_shardings=bsh)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, 4, 64))
        batch = {
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(ids, jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(64), ids.shape).copy(), jnp.int32),
            "segment_ids": jnp.ones(ids.shape, jnp.int32),
        }
        batch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
        state, metrics = step(state, batch)  # compile + dump
        _ = float(metrics["loss"])

        # async-loop win: fetch-every-step vs fetch-every-50
        def run(n, fetch_every):
            nonlocal state
            t0 = time.perf_counter()
            for i in range(n):
                state, m = step(state, batch)
                if (i + 1) % fetch_every == 0:
                    _ = float(m["loss"])
            _ = float(m["loss"])
            return (time.perf_counter() - t0) / n

        per_step_sync = run(50, 1)
        per_step_async = run(50, 50)

    pairs = analyze_dump(DUMP)
    overlapped = [p for p in pairs if p[2] > 0]
    print(f"HLO dump: {DUMP}")
    print(f"async collective pairs in scheduled HLO: {len(pairs)}; "
          f"with compute scheduled inside the start->done window: {len(overlapped)}")
    for name, span, gap in pairs[:12]:
        print(f"  {name:40s} window={span:4d} lines, compute ops inside={gap}")
    if not pairs:
        # XLA:CPU lowers collectives synchronously — no start/done pairs
        # exist off-TPU (the latency-hiding scheduler is a TPU pass). Report
        # the GSPMD-inserted collective census of the compiled step instead:
        # these are exactly the ops the TPU scheduler overlaps.
        census: dict = {}
        for fname in os.listdir(DUMP):
            if "step_fn" not in fname or "after_optimizations.txt" not in fname:
                continue
            with open(os.path.join(DUMP, fname)) as f:
                text = f.read()
            for op in ("all-gather", "all-reduce", "reduce-scatter",
                       "all-to-all", "collective-permute"):
                census[op] = census.get(op, 0) + len(
                    re.findall(rf"= \S* {op}\(|{op}\.", text)
                )
        print("CPU backend lowers collectives synchronously; GSPMD-inserted "
              "collectives in the compiled train step (what the TPU "
              "latency-hiding scheduler overlaps):")
        for op, n in sorted(census.items()):
            print(f"  {op:20s} {n}")
    print(f"step time, fetch every step:  {per_step_sync * 1e3:.2f} ms")
    print(f"step time, fetch every 50:    {per_step_async * 1e3:.2f} ms")
    print(f"async-loop win: {(per_step_sync / per_step_async - 1) * 100:.1f}%")


if __name__ == "__main__":
    main()
