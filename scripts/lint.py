#!/usr/bin/env python
"""graftlint CLI — run the repo's static-analysis passes (no JAX backend).

Usage:
    python scripts/lint.py                  # human output, exit 1 on findings
    python scripts/lint.py --json out.json  # CI artifact (also - for stdout)
    python scripts/lint.py --rule drift     # one pass family
    python scripts/lint.py --list-rules
    python scripts/lint.py --raw            # include allowlisted findings

Exit codes: 0 clean, 1 findings, 2 internal error. The tier-1 runner
(scripts/tier1.sh) runs this BEFORE the pytest shards: it finishes in
seconds because nothing here imports jax — `veomni_tpu.analysis` is
import-light by design, and this script asserts that property so a future
import can't silently turn the lint stage into a backend init.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--json", metavar="PATH",
                    help="write findings as JSON to PATH ('-' for stdout)")
    ap.add_argument("--rule", help="run only rules under this prefix "
                    "(pass family or full rule id)")
    ap.add_argument("--raw", action="store_true",
                    help="also show allowlist-suppressed findings")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--root", default=_REPO)
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    from veomni_tpu.analysis import get_passes, run_lint

    if args.list_rules:
        for p in get_passes():
            print(f"{p.name:<18} {p.description}")
        return 0

    result = run_lint(args.root, rules=args.rule)
    dt = time.perf_counter() - t0

    # the whole point of the fast lint stage: no backend, ever
    assert "jax" not in sys.modules, (
        "graftlint imported jax — the lint stage must stay backend-free"
    )

    if args.json:
        doc = {
            "ok": result.ok,
            "elapsed_s": round(dt, 3),
            "suppressed": result.suppressed,
            "findings": [f.to_doc() for f in result.findings],
        }
        if args.raw:
            doc["raw_findings"] = [f.to_doc() for f in result.raw_findings]
        payload = json.dumps(doc, indent=2)
        if args.json == "-":
            print(payload)
        else:
            parent = os.path.dirname(args.json)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(args.json, "w") as f:
                f.write(payload + "\n")

    shown = result.findings if not args.raw else result.raw_findings
    for f in shown:
        print(f.format())
    status = "clean" if result.ok else f"{len(result.findings)} finding(s)"
    print(
        f"graftlint: {status} ({result.suppressed} allowlisted, "
        f"{dt:.2f}s, no JAX)", file=sys.stderr,
    )
    return 0 if result.ok else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # pragma: no cover - CI wants a distinct code
        print(f"graftlint: internal error: {e}", file=sys.stderr)
        raise SystemExit(2)
