"""Pallas-on-silicon probe (VERDICT r4 item 2).

Round-1 observed Pallas kernels HANG at execution on the axon relay (even a
trivial VMEM copy), so `supports_pallas()` gates them off there. The r5 relay
is new infrastructure (remote AOT compile); this probe re-tests each kernel
in a watchdogged step so a hang produces a logged timeout instead of a wedged
process: trivial copy -> flash fwd -> flash fwd+bwd -> grouped_gemm fwd+bwd,
tiny shapes first, numerics vs the XLA reference impl each time.

Run:  timeout 1800 python scripts/pallas_probe.py   (one chip claimant only)
Each stage prints one JSON line; paste into BENCH_NOTES.md.
"""

import json
import os
import sys
import threading
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("VEOMNI_AXON_PALLAS", "1")  # bypass the r1 gate

STAGE_TIMEOUT_S = float(os.environ.get("PALLAS_PROBE_STAGE_S", 240))


def _stage(name, fn):
    """Run fn under a watchdog thread; a hang beyond STAGE_TIMEOUT_S aborts
    the whole process (exit 7) after logging — matching the r1 failure mode
    where the hung kernel never returns and the process must die anyway."""
    done = threading.Event()
    result = {}

    def _watch():
        if not done.wait(STAGE_TIMEOUT_S):
            print(json.dumps({"stage": name, "ok": False,
                              "error": f"HANG >{int(STAGE_TIMEOUT_S)}s"}),
                  flush=True)
            os._exit(7)

    threading.Thread(target=_watch, daemon=True).start()
    try:
        result = fn() or {}
        result = {"stage": name, "ok": True, **result}
    except Exception as e:
        result = {"stage": name, "ok": False,
                  "error": f"{type(e).__name__}: {e}"[:400]}
        traceback.print_exc(file=sys.stderr)
    done.set()
    print(json.dumps(result), flush=True)
    return result.get("ok", False)


def stage_platform():
    import jax

    d = jax.devices()[0]
    return {"device": str(d), "platform": getattr(d, "platform", "?"),
            "kind": getattr(d, "device_kind", "?")}


def stage_trivial_copy():
    """The r1 hang reproducer: a VMEM identity kernel."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
    interpret = jax.devices()[0].platform == "cpu"
    y = pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)
    ok = bool(jnp.allclose(y, x * 2.0))
    return {"numerics": ok}


def _attn_inputs(b=1, s=512, hq=4, hkv=2, d=128):
    import jax
    import jax.numpy as jnp

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, hkv, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, hkv, d), jnp.bfloat16)
    seg = jnp.ones((b, s), jnp.int32)
    return q, k, v, seg


def stage_flash_fwd():
    import jax.numpy as jnp

    from veomni_tpu.ops.attention import _attention_xla
    from veomni_tpu.ops.pallas.flash_attention import flash_attention

    q, k, v, seg = _attn_inputs()
    out = flash_attention(q, k, v, segment_ids=seg, causal=True)
    ref = _attention_xla(q, k, v, segment_ids=seg, causal=True)
    err = float(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)).max())
    return {"max_abs_err_vs_xla": err, "numerics": err < 2e-2}


def stage_flash_bwd():
    import jax
    import jax.numpy as jnp

    from veomni_tpu.ops.attention import _attention_xla
    from veomni_tpu.ops.pallas.flash_attention import flash_attention

    q, k, v, seg = _attn_inputs()

    def loss_pl(q, k, v):
        return flash_attention(q, k, v, segment_ids=seg, causal=True).astype(
            jnp.float32).sum()

    def loss_xla(q, k, v):
        return _attention_xla(q, k, v, segment_ids=seg, causal=True).astype(
            jnp.float32).sum()

    g_pl = jax.grad(loss_pl, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    errs = [float(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max())
            for a, b in zip(g_pl, g_ref)]
    # dv accumulates S bf16 products: 1e-1 abs is the right bf16 bound
    return {"max_abs_err_dq_dk_dv": errs, "numerics": max(errs) < 1e-1}


def stage_grouped_gemm():
    import jax
    import jax.numpy as jnp

    from veomni_tpu.ops.pallas.grouped_gemm import pallas_group_gemm as grouped_gemm

    g, m, k_, n = 4, 512, 256, 256
    ks = jax.random.split(jax.random.PRNGKey(1), 2)
    lhs = jax.random.normal(ks[0], (m, k_), jnp.bfloat16)
    rhs = jax.random.normal(ks[1], (g, k_, n), jnp.bfloat16)
    sizes = jnp.array([128, 128, 128, 128], jnp.int32)

    def ref(lhs, rhs):
        return jax.lax.ragged_dot(lhs, rhs, sizes)

    out = grouped_gemm(lhs, rhs, sizes)
    expect = ref(lhs, rhs)
    err = float(jnp.abs(out.astype(jnp.float32) - expect.astype(jnp.float32)).max())

    def loss(lhs, rhs):
        return grouped_gemm(lhs, rhs, sizes).astype(jnp.float32).sum()

    gl, gr = jax.grad(loss, argnums=(0, 1))(lhs, rhs)

    def loss_ref(lhs, rhs):
        return ref(lhs, rhs).astype(jnp.float32).sum()

    rl, rr = jax.grad(loss_ref, argnums=(0, 1))(lhs, rhs)
    gerr = max(
        float(jnp.abs(gl.astype(jnp.float32) - rl.astype(jnp.float32)).max()),
        float(jnp.abs(gr.astype(jnp.float32) - rr.astype(jnp.float32)).max()),
    )
    return {"max_abs_err_fwd": err, "max_abs_err_grad": gerr,
            "numerics": err < 2e-2 and gerr < 5e-2}


def stage_flash_ab_steptime(s=2048, reps=20):
    """A/B step time pallas vs xla_twopass on a mid-size attention call."""
    import time

    import jax
    import jax.numpy as jnp

    from veomni_tpu.ops.attention import _attention_xla_twopass
    from veomni_tpu.ops.pallas.flash_attention import flash_attention

    q, k, v, seg = _attn_inputs(b=4, s=s, hq=16, hkv=8, d=128)
    out = {}
    for name, fn in (("pallas", flash_attention), ("xla_twopass", _attention_xla_twopass)):
        f = jax.jit(lambda q, k, v, fn=fn: fn(q, k, v, segment_ids=seg, causal=True))
        r = f(q, k, v)
        _ = jax.device_get(r.astype(jnp.float32).sum())  # sync (relay-safe)
        t0 = time.perf_counter()
        for _ in range(reps):
            r = f(q, k, v)
        _ = jax.device_get(r.astype(jnp.float32).sum())
        out[f"{name}_ms"] = round((time.perf_counter() - t0) / reps * 1e3, 3)
    return out


def main():
    stages = [
        ("platform", stage_platform),
        ("trivial_copy", stage_trivial_copy),
        ("flash_fwd", stage_flash_fwd),
        ("flash_bwd", stage_flash_bwd),
        ("grouped_gemm", stage_grouped_gemm),
        ("flash_ab_steptime", stage_flash_ab_steptime),
    ]
    for name, fn in stages:
        if not _stage(name, fn):
            # numerics failures continue (informative); only exceptions in
            # the FIRST pallas stage mean "pallas dead here" — keep going
            # anyway: later stages are independently informative
            pass


if __name__ == "__main__":
    main()
