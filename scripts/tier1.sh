#!/usr/bin/env bash
# Default tier-1 entry point (ROADMAP.md "Tier-1 verify").
#
# The full suite exceeds a single 870s invocation on a 2-core box, so this
# runs it as N deterministic shards (scripts/tier1_shard.py: crc32-stable
# file partition) SEQUENTIALLY, each under its own timeout, and merges the
# passed-dot counts into the one DOTS_PASSED line drivers grep for. A shard
# that times out or fails makes the whole run fail (worst rc wins), but the
# later shards still run — a hang in shard 1 must not hide shard 2's result.
#
# Knobs (env):
#   TIER1_SHARDS         shard count (default 5; 4 stopped fitting the
#                        per-shard budget when the scale-out router tier
#                        grew the suite — shard 1/4 hit 870s)
#   TIER1_SHARD_TIMEOUT  per-shard budget in seconds (default 870, the
#                        ROADMAP's historical single-run budget)
#   TIER1_LOG_DIR        where per-shard logs land (default /tmp)
#
# Usage (docs/testing.md "Sharded tier-1"):
#   bash scripts/tier1.sh
#   TIER1_SHARDS=3 TIER1_SHARD_TIMEOUT=600 bash scripts/tier1.sh
set -u -o pipefail

cd "$(dirname "$0")/.."

SHARDS="${TIER1_SHARDS:-5}"
SHARD_TIMEOUT="${TIER1_SHARD_TIMEOUT:-870}"
LOG_DIR="${TIER1_LOG_DIR:-/tmp}"
mkdir -p "$LOG_DIR"

total_dots=0
rc=0

# Fast static-analysis stage (graftlint, docs/static-analysis.md): AST-only,
# never initializes a JAX backend, finishes in seconds. Runs BEFORE the
# pytest shards so a trace-purity / lock-discipline / doc-drift violation
# fails tier-1 without waiting out two ~870s shards; the shards still run so
# a lint failure never hides a test regression (worst rc wins, same policy
# as a failing shard). --json artifact lands next to the shard logs for CI.
lint_log="$LOG_DIR/_t1_lint.log"
timeout -k 5 120 python scripts/lint.py --json "$LOG_DIR/_t1_lint.json" \
  2>&1 | tee "$lint_log"
lint_rc=${PIPESTATUS[0]}
echo "LINT rc=${lint_rc}"
if [ "$lint_rc" -ne 0 ]; then
  rc=$lint_rc
fi
# Bounded chaos smoke (scripts/chaos_smoke.py, docs/testing.md): the
# fixed-seed self-healing fleet drill — kill + hang + delay/exception over
# 3 replicas, fleet invariants + goodput floor checked against a fault-free
# replay. ~50s on CPU; the 120s timeout is headroom, not budget. Runs
# before the shard loop for the same reason lint does: a broken resurrect
# path fails fast, and a smoke failure never hides a shard regression.
chaos_log="$LOG_DIR/_t1_chaos.log"
timeout -k 5 120 env JAX_PLATFORMS=cpu python scripts/chaos_smoke.py \
  2>&1 | tee "$chaos_log"
chaos_rc=${PIPESTATUS[0]}
echo "CHAOS_SMOKE rc=${chaos_rc}"
if [ "$chaos_rc" -ne 0 ] && [ "$rc" -eq 0 ]; then
  rc=$chaos_rc
fi
for k in $(seq 1 "$SHARDS"); do
  log="$LOG_DIR/_t1_shard${k}of${SHARDS}.log"
  rm -f "$log"
  timeout -k 10 "$SHARD_TIMEOUT" env JAX_PLATFORMS=cpu \
    python scripts/tier1_shard.py --shard "$k/$SHARDS" 2>&1 | tee "$log"
  shard_rc=${PIPESTATUS[0]}
  # pytest's -q progress lines are runs of [.FEsx] (with an optional
  # percentage suffix); count the dots = passed tests, same recipe the
  # single-invocation verify line used
  dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$log" | tr -cd . | wc -c)
  echo "SHARD_DOTS ${k}/${SHARDS}=${dots} rc=${shard_rc}"
  total_dots=$((total_dots + dots))
  if [ "$shard_rc" -ne 0 ] && [ "$rc" -eq 0 ]; then
    rc=$shard_rc
  fi
done
echo "DOTS_PASSED=${total_dots}"
exit "$rc"
