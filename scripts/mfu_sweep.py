"""MFU ladder: sweep attention impl x micro-batch x remat on the real chip.

Config entries: [seq_len, micro_bs, attention_impl, remat_policy] with two
optional trailing fields [, preset [, optimizer]] — preset one of
bench.BENCH_PRESETS (default qwen3_0p6b), optimizer passed to
build_optimizer (default adamw; "muon" fits the 1p7b preset on one v5e).

Run:  python scripts/mfu_sweep.py            # full ladder
      SWEEP_CONFIGS='[[4096,8,"xla","dots"],
                      [2048,4,"xla_twopass","ctx","qwen3_1p7b","muon"]]' \
          python scripts/mfu_sweep.py

Modes:
  in-process (default): one backend init for the whole ladder — fastest,
      but a hung remote execution (observed: sweep-1's seq32k point sat
      >25 min asleep) strands every remaining config.
  SWEEP_SUBPROCESS=1: each config runs in its own python subprocess with a
      SWEEP_CONFIG_TIMEOUT_S kill budget (default 1500s) — a hang costs one
      config. Pays one chip claim (~25-45s when the relay is healthy) per
      config; the claim risk of killing a hung child is confined to a
      config that was already lost.

Appends one JSON line per config to stdout; the best config should become
bench.py's default (see BENCH_NOTES.md for the recorded ladder).
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


DEFAULT = [
    # [seq_len, micro_bs, attention_impl, remat_policy] — the r5 ladder:
    # ctx policy (the only one that fits beside f32 AdamW state on one
    # v5e at real batch sizes, see docs/performance.md) + impl A/B
    [2048, 8, "xla_twopass", "ctx"],
    [4096, 4, "xla_twopass", "ctx"],
    [4096, 8, "xla_twopass", "ctx"],
    [2048, 2, "xla_twopass", "dots"],
    [2048, 8, "xla", "ctx"],
    [2048, 4, "xla_twopass", "ctx", "qwen3_1p7b", "muon"],
    [4096, 2, "xla_twopass", "ctx", "qwen3_1p7b", "muon"],
    [2048, 8, "pallas_flash", "ctx"],
]

_CHILD = """
import json, os, sys
sys.path.insert(0, {root!r})
from bench import run_bench
r = run_bench({seq}, {mb}, {steps}, attention_impl={attn!r},
              remat_policy={remat!r}, preset={preset!r}, optimizer={opt!r})
print("SWEEPRESULT " + json.dumps(r), flush=True)
"""


def _norm(seq_len, micro_bs, attn, remat, preset, opt):
    return dict(seq_len=seq_len, micro_bs=micro_bs, attention=attn,
                remat_policy=remat, preset=preset, optimizer=opt)


def _error_record(base, msg: str) -> dict:
    import re

    msg = re.sub(r"\x1b\[[0-9;]*m", "", msg)  # strip ANSI
    oom = re.search(r"Ran out of memory.*?hbm capacity by [0-9.]+\w", msg)
    return {**base, "error": oom.group(0) if oom else msg[-600:]}


def _run_subprocess(seq_len, micro_bs, steps, attn, remat, preset, opt):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = _CHILD.format(root=root, seq=seq_len, mb=micro_bs, steps=steps,
                         attn=attn, remat=remat, preset=preset, opt=opt)
    base = _norm(seq_len, micro_bs, attn, remat, preset, opt)
    timeout = float(os.environ.get("SWEEP_CONFIG_TIMEOUT_S", 1500))
    try:
        p = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"").decode() if isinstance(e.stderr, bytes)
                else (e.stderr or ""))[-300:]
        return {**base, "error": f"HANG >{int(timeout)}s (killed); {tail}"}
    for line in p.stdout.splitlines():
        if line.startswith("SWEEPRESULT "):
            return json.loads(line[len("SWEEPRESULT "):])
    return _error_record(base, p.stderr or p.stdout or f"exit {p.returncode}")


def main():
    from veomni_tpu.utils.xla_flags import apply_performance_flags

    apply_performance_flags()
    configs = json.loads(os.environ.get("SWEEP_CONFIGS", "null")) or DEFAULT
    steps = int(os.environ.get("SWEEP_STEPS", 8))
    use_subprocess = os.environ.get("SWEEP_SUBPROCESS") == "1"
    results = []
    for seq_len, micro_bs, attn, remat, *extra in configs:
        preset = extra[0] if extra else "qwen3_0p6b"
        opt = extra[1] if len(extra) > 1 else "adamw"
        if use_subprocess:
            r = _run_subprocess(int(seq_len), int(micro_bs), steps,
                                attn, remat, preset, opt)
        else:
            from bench import run_bench

            try:
                r = run_bench(int(seq_len), int(micro_bs), steps,
                              attention_impl=attn, remat_policy=remat,
                              preset=preset, optimizer=opt)
            except Exception as e:  # OOM etc: record and continue the ladder
                r = _error_record(
                    _norm(seq_len, micro_bs, attn, remat, preset, opt), str(e)
                )
        results.append(r)
        print(json.dumps(r), flush=True)
    ok = [r for r in results if "mfu" in r]
    if ok:
        best = max(ok, key=lambda r: r["mfu"])
        print("BEST:", json.dumps(best), flush=True)


if __name__ == "__main__":
    main()
