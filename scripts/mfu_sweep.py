"""MFU ladder: sweep attention impl x micro-batch x remat on the real chip.

Config entries: [seq_len, micro_bs, attention_impl, remat_policy] with two
optional trailing fields [, preset [, optimizer]] — preset one of
bench.BENCH_PRESETS (default qwen3_0p6b), optimizer passed to
build_optimizer (default adamw; "muon" fits the 1p7b preset on one v5e).

Run:  python scripts/mfu_sweep.py            # full ladder
      SWEEP_CONFIGS='[[4096,8,"xla","dots"],
                      [2048,4,"xla_twopass","ctx","qwen3_1p7b","muon"]]' \
          python scripts/mfu_sweep.py

Appends one JSON line per config to stdout; the best config should become
bench.py's default (see BENCH_NOTES.md for the recorded ladder).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import run_bench  # noqa: E402


DEFAULT = [
    # [seq_len, micro_bs, attention_impl, remat_policy] — the VERDICT ladder:
    # seq 2k -> 32k x attention impl x remat x micro-bs (xla_twopass is the
    # measured-best attention on the relay-attached v5e, BENCH_NOTES r2)
    [2048, 8, "xla_twopass", "dots"],
    [4096, 8, "xla_twopass", "dots"],
    [4096, 8, "xla_twopass", "nothing"],
    [4096, 16, "xla_twopass", "dots"],
    [4096, 8, "xla", "dots"],
    [4096, 8, "pallas_flash", "dots"],
    [8192, 4, "xla_twopass", "dots"],
    [16384, 2, "xla_twopass", "dots"],
    [32768, 1, "xla_twopass", "dots"],
]


def main():
    from veomni_tpu.utils.xla_flags import apply_performance_flags

    apply_performance_flags()
    configs = json.loads(os.environ.get("SWEEP_CONFIGS", "null")) or DEFAULT
    steps = int(os.environ.get("SWEEP_STEPS", 8))
    results = []
    for seq_len, micro_bs, attn, remat, *extra in configs:
        preset = extra[0] if extra else "qwen3_0p6b"
        opt = extra[1] if len(extra) > 1 else "adamw"
        try:
            r = run_bench(int(seq_len), int(micro_bs), steps,
                          attention_impl=attn, remat_policy=remat,
                          preset=preset, optimizer=opt)
        except Exception as e:  # OOM etc: record and continue the ladder
            import re

            msg = re.sub(r"\x1b\[[0-9;]*m", "", str(e))  # strip ANSI
            oom = re.search(r"Ran out of memory.*?hbm capacity by [0-9.]+\w", msg)
            r = {"seq_len": seq_len, "micro_bs": micro_bs, "attention": attn,
                 "remat_policy": remat, "preset": preset, "optimizer": opt,
                 "error": oom.group(0) if oom else msg[:600]}
        results.append(r)
        print(json.dumps(r), flush=True)
    ok = [r for r in results if "mfu" in r]
    if ok:
        best = max(ok, key=lambda r: r["mfu"])
        print("BEST:", json.dumps(best), flush=True)


if __name__ == "__main__":
    main()
