"""Merge per-rank run artifacts into one cluster timeline + fleet verdict.

Every rank of a run leaves three artifact families in its output dir:
``metrics_rank<R>.jsonl`` (one line per sync step, wall-stamped),
``heartbeat-<R>.json`` (last progress marker, rewritten in place —
``observability/fleet.py``) and ``postmortem-<R>.json`` (flight-recorder
dump with a wall/perf anchor pair — ``observability/flight_recorder.py``).
Each alone is rank-local; this tool merges all three onto one shared wall
axis (post-mortem events via the PR 6 anchor-pair mapping, reused from
``scripts/postmortem.py``) and prints:

1. a per-rank summary — last metrics step, heartbeat age + phase,
   post-mortem reason;
2. a fleet verdict — which rank's heartbeat is stalest, which rank's last
   progress step lags the fleet, and (when the skew exchange ran) which
   rank the live telemetry already named slowest;
3. the merged, monotonically ordered timeline (``--tail N`` for the last
   N events).

One invocation answers "which rank is slow / wedged and what was it doing"
— the artifact five wedged-relay bench rounds (BENCH_r01–r05) never had.

Usage:
  python scripts/fleet.py OUTPUT_DIR [--tail 80] [--json merged.json]
  python scripts/fleet.py out/ --now 1754300000   # pin "now" (tests)
"""

import argparse
import importlib.util
import json
import os
import re
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

_METRICS_RE = re.compile(r"^metrics_rank(\d+)\.jsonl$")
_POSTMORTEM_RE = re.compile(r"^postmortem-(\d+)\.json$")

#: metrics-JSONL keys carried into timeline events (the full line is huge)
_METRIC_KEYS = ("loss", "goodput_pct", "mfu_pct", "fleet.step_time_skew_s",
                "fleet.slowest_rank", "comm_est_frac")


def _load_postmortem_module():
    """The anchor-pair merge lives in scripts/postmortem.py (PR 6); scripts/
    is not a package, so load the sibling file directly."""
    spec = importlib.util.spec_from_file_location(
        "veomni_postmortem_cli", os.path.join(_HERE, "postmortem.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def collect_artifacts(dirpath):
    """-> (metrics_files {rank: path}, heartbeat dir, postmortem paths)."""
    metrics = {}
    postmortems = []
    for name in sorted(os.listdir(dirpath)):
        m = _METRICS_RE.match(name)
        if m:
            metrics[int(m.group(1))] = os.path.join(dirpath, name)
            continue
        if _POSTMORTEM_RE.match(name):
            postmortems.append(os.path.join(dirpath, name))
    return metrics, dirpath, postmortems


def merge_fleet(dirpath, now=None):
    """Merge one output dir's rank artifacts. Returns ``{"ranks": {...},
    "events": [...], "verdict": {...}}`` with events sorted ascending on
    the shared wall axis (``wall_s``) — one monotonic cluster timeline."""
    now = time.time() if now is None else now
    metrics_files, hb_dir, pm_paths = collect_artifacts(dirpath)
    events = []
    ranks = {}

    def rankdoc(r):
        # trainer ranks are ints; serving router pump heartbeats carry
        # their replica id (e.g. "r0") as the rank
        key = int(r) if str(r).lstrip("-").isdigit() else str(r)
        return ranks.setdefault(key, {"rank": key})

    # 1. metrics JSONL: already wall-stamped per line
    for rank, path in metrics_files.items():
        last = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue  # torn final line of a killed run
                payload = {k: doc[k] for k in _METRIC_KEYS if k in doc}
                payload["step"] = doc.get("step", 0)
                events.append({
                    "wall_s": float(doc.get("ts", 0.0)),
                    "rank": doc.get("rank", rank),
                    "kind": "metrics",
                    "payload": payload,
                })
                last = doc
        if last is not None:
            rankdoc(rank).update({
                "last_metrics_step": last.get("step", 0),
                "last_metrics_age_s": max(0.0, now - last.get("ts", now)),
            })

    # 2. heartbeats: freshness is the wedged-rank signal
    from veomni_tpu.observability.fleet import read_heartbeats

    for hb in read_heartbeats(hb_dir):
        rank = hb.get("rank", -1)
        wall = float(hb.get("wall_time_s", 0.0))
        events.append({
            "wall_s": wall, "rank": rank, "kind": "heartbeat",
            "payload": {"global_step": hb.get("global_step", 0),
                        "phase": hb.get("phase", "")},
        })
        rankdoc(rank).update({
            "heartbeat_age_s": max(0.0, now - wall),
            "heartbeat_step": hb.get("global_step", 0),
            "heartbeat_phase": hb.get("phase", ""),
        })

    # 3. post-mortems: anchor-pair merge (scripts/postmortem.py)
    if pm_paths:
        pm = _load_postmortem_module().merge_dumps(pm_paths)
        events.extend(pm["events"])
        for r in pm["ranks"]:
            rankdoc(r["rank"])["postmortem_reason"] = r["reason"]

    events.sort(key=lambda e: e["wall_s"])

    # fleet verdict: name the laggard instead of making the operator diff
    verdict = {}
    with_hb = [r for r in ranks.values() if "heartbeat_age_s" in r]
    if with_hb:
        stalest = max(with_hb, key=lambda r: r["heartbeat_age_s"])
        verdict["stalest_rank"] = stalest["rank"]
        verdict["stalest_age_s"] = stalest["heartbeat_age_s"]
        laggard = min(with_hb, key=lambda r: r.get("heartbeat_step", 0))
        verdict["lagging_rank"] = laggard["rank"]
        verdict["lagging_step"] = laggard.get("heartbeat_step", 0)
    # the live skew telemetry's own last word, if any rank exported it
    slowest = [e["payload"]["fleet.slowest_rank"] for e in events
               if e["kind"] == "metrics"
               and "fleet.slowest_rank" in e["payload"]]
    if slowest:
        verdict["telemetry_slowest_rank"] = int(slowest[-1])
    order = sorted(ranks, key=lambda r: (isinstance(r, str), r))
    return {"ranks": [ranks[r] for r in order], "events": events,
            "verdict": verdict}


def format_fleet(doc, tail=0):
    lines = []
    for r in doc["ranks"]:
        bits = [f"rank {r['rank']}:"]
        if "last_metrics_step" in r:
            bits.append(f"metrics@step {r['last_metrics_step']} "
                        f"({r['last_metrics_age_s']:.1f}s ago)")
        if "heartbeat_age_s" in r:
            bits.append(f"heartbeat {r['heartbeat_age_s']:.1f}s old "
                        f"(step {r.get('heartbeat_step', 0)}, "
                        f"{r.get('heartbeat_phase', '')})")
        if "postmortem_reason" in r:
            bits.append(f"postmortem: {r['postmortem_reason']}")
        lines.append("# " + " ".join(bits))
    v = doc["verdict"]
    if v:
        parts = []
        if "stalest_rank" in v:
            parts.append(f"stalest heartbeat: rank {v['stalest_rank']} "
                         f"({v['stalest_age_s']:.1f}s)")
        if "lagging_rank" in v:
            parts.append(f"least progress: rank {v['lagging_rank']} "
                         f"(step {v['lagging_step']})")
        if "telemetry_slowest_rank" in v:
            parts.append(
                f"telemetry slowest: rank {v['telemetry_slowest_rank']}")
        lines.append("# VERDICT — " + "; ".join(parts))
    events = doc["events"]
    if tail > 0:
        skipped = max(0, len(events) - tail)
        if skipped:
            lines.append(f"# ... {skipped} earlier events elided (--tail)")
        events = events[-tail:]
    t0 = events[0]["wall_s"] if events else 0.0
    for ev in events:
        extra = ""
        if ev.get("cid"):
            extra += f" cid={ev['cid']}"
        if ev.get("payload"):
            extra += " " + json.dumps(ev["payload"], sort_keys=True,
                                      default=str)
        lines.append(f"[+{ev['wall_s'] - t0:10.4f}s] rank{ev['rank']} "
                     f"{ev['kind']}{extra}")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", help="run output dir (metrics_rank*.jsonl + "
                                "heartbeat-*.json + postmortem-*.json)")
    ap.add_argument("--tail", type=int, default=0,
                    help="print only the last N merged events")
    ap.add_argument("--json", default="",
                    help="also write the merged document here")
    ap.add_argument("--now", type=float, default=0.0,
                    help="wall time to age heartbeats against (default: "
                         "actual now; pin for reproducible output)")
    args = ap.parse_args()
    doc = merge_fleet(args.dir, now=args.now or None)
    print(format_fleet(doc, tail=args.tail))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, default=str)
        print(f"# merged {len(doc['ranks'])} ranks, {len(doc['events'])} "
              f"events -> {args.json}")


if __name__ == "__main__":
    main()
