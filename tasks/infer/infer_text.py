"""Interactive/greedy text generation entry.

Reference: ``tasks/infer/infer_text.py:26-49`` — single-process inference
(serving at scale is explicitly out of scope for the reference too; RL
rollout integrates external engines). Greedy decode with a jitted
fixed-shape step (KV-cache-free re-scoring for simplicity at small lengths).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp

from veomni_tpu.arguments import VeOmniArguments, parse_args
from veomni_tpu.models import build_foundation_model, build_tokenizer
from veomni_tpu.models.transformer import forward_logits


def generate(model, params, input_ids, max_new_tokens: int = 64,
             eos_id: int = -1, temperature: float = 0.0, top_k: int = 0,
             seed: int = 0):
    """Generation: KV-cache scan decode where the dialect supports it
    (models/decode.py — the TPU equivalent of HF generate()'s cache,
    greedy or temperature/top-k sampling), else the fixed-window greedy
    rescoring fallback (MLA/DSA/hybrid families)."""
    from veomni_tpu.models.decode import greedy_generate, supports_cached_decode

    if supports_cached_decode(model.config):
        return greedy_generate(params, model.config, input_ids,
                               max_new_tokens=max_new_tokens, eos_id=eos_id,
                               temperature=temperature, top_k=top_k,
                               seed=seed)
    cfg = model.config
    ids = list(map(int, input_ids))
    total = len(ids) + max_new_tokens

    @jax.jit
    def score(tokens, length):
        pos = jnp.arange(total)
        logits = forward_logits(
            params, cfg, tokens[None], pos[None],
            jnp.where(jnp.arange(total) < length, 1, 0)[None],
        )
        return logits[0, length - 1]

    tokens = jnp.zeros((total,), jnp.int32).at[: len(ids)].set(jnp.asarray(ids))
    for step in range(max_new_tokens):
        length = len(ids)
        nxt = int(jnp.argmax(score(tokens, length)))
        ids.append(nxt)
        tokens = tokens.at[length].set(nxt)
        if nxt == eos_id:
            break
    return ids


def main():
    args = parse_args(VeOmniArguments)
    m, t = args.model, args.train
    if t.platform:
        jax.config.update("jax_platforms", t.platform)
    config = None
    if not m.config_path:
        from veomni_tpu.models.auto import build_config

        overrides = dict(m.config_overrides)
        config = build_config(overrides.pop("model_type", ""), **overrides)
    model = build_foundation_model(
        m.config_path or None, config=config, weights_path=m.model_path or None
    )
    if model.params is None:
        model.init(jax.random.PRNGKey(0))
    tokenizer = build_tokenizer(m.tokenizer_path) if m.tokenizer_path else None
    print("enter prompt (ctrl-d to exit):")
    for line in sys.stdin:
        prompt = line.strip()
        if not prompt:
            continue
        ids = tokenizer(prompt)["input_ids"] if tokenizer else [int(x) for x in prompt.split()]
        out = generate(
            model, model.params, ids,
            eos_id=tokenizer.eos_token_id if tokenizer else -1,
            temperature=float(os.environ.get("INFER_TEMPERATURE", 0)),
            top_k=int(os.environ.get("INFER_TOP_K", 0)),
        )
        print(tokenizer.decode(out) if tokenizer else out)


if __name__ == "__main__":
    main()
