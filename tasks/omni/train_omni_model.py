"""Omni (audio+vision+text) training entry point.

Reference: ``tasks/omni/train_omni_model.py`` — the reference's fully linear
trainer-free script; here the same library calls are wrapped by OmniTrainer.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from veomni_tpu.arguments import VeOmniArguments, parse_args, save_args
from veomni_tpu.trainer.omni_trainer import OmniTrainer


def main():
    from veomni_tpu.utils.xla_flags import apply_performance_flags

    apply_performance_flags()
    args = parse_args(VeOmniArguments)
    save_args(args, args.train.output_dir)
    trainer = OmniTrainer(args)
    trainer.train()


if __name__ == "__main__":
    main()
