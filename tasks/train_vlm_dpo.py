"""Multimodal DPO training entry point (reference: text_dpo pipeline x
multimodal chat template)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from veomni_tpu.arguments import VeOmniArguments, parse_args, save_args
from veomni_tpu.trainer.dpo_trainer import VLMDPOTrainer


def main():
    from veomni_tpu.utils.xla_flags import apply_performance_flags

    apply_performance_flags()
    args = parse_args(VeOmniArguments)
    save_args(args, args.train.output_dir)
    trainer = VLMDPOTrainer(args)
    trainer.train()


if __name__ == "__main__":
    main()
