"""Text RL (PPO-style) post-training entry point.

Reference: ``tasks/train_text_rl.py`` — rollouts come from an external
engine; this consumes (prompt, response, advantage, old_logprobs) rows.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from veomni_tpu.arguments import VeOmniArguments, parse_args, save_args
from veomni_tpu.trainer.rl_trainer import BaseRLTrainer


def main():
    from veomni_tpu.utils.xla_flags import apply_performance_flags

    apply_performance_flags()
    args = parse_args(VeOmniArguments)
    save_args(args, args.train.output_dir)
    trainer = BaseRLTrainer(args)
    trainer.train()


if __name__ == "__main__":
    main()
